"""OSD daemon — mirror of src/osd/OSD.{h,cc} + src/ceph_osd.cc.

Structure mirrored from the reference (§3.3 of SURVEY.md):

- **Boot** (src/ceph_osd.cc:120): mount the object store, bind the
  messenger, announce to the monitors with MOSDBoot, subscribe to osdmap
  updates (the reference's `osd->init()` → `_send_boot`).
- **Map handling** (OSD::handle_osd_map → consume_map): full maps and
  incrementals advance the in-memory OSDMap; every PG whose acting set we
  appear in is created/advanced through a new peering interval.
- **Dispatch** (OSD::ms_fast_dispatch, OSD.cc:7244): backend sub-ops are
  fast-dispatched straight into the owning PG's backend (the reference
  bypasses the dispatch queue for exactly these); client MOSDOps are
  queued through the mClock/WPQ OpScheduler (enqueue_op/dequeue_op,
  OSD.cc:9431,9491) and run by the op worker.
- **Heartbeats** (handle_osd_ping OSD.cc:5463, heartbeat_check :5834):
  periodic MOSDPing to every up peer; peers that miss
  `osd_heartbeat_grace` seconds of replies are reported to the monitors
  with MOSDFailure, where the failure-quorum logic decides
  (OSDMonitor.cc:2791 prepare_failure).
- Cluster sends are ordered per peer through a single drain task — the
  per-connection ordering the reference gets from its one writer thread
  per AsyncConnection.
"""

from __future__ import annotations

import asyncio
import random
import time

from ..common import tracer as tracer_mod
from ..common.clog import ClusterLogClient
from ..common.config import Config
from ..common.log import dout
from ..common.perf_counters import PerfCountersBuilder
from ..common.tracer import Tracer, null_span
from ..mon.client import MonClient
from ..mon.monmap import MonMap
from ..msg.message import Message
from ..msg.messages import (
    MBackfillReserve,
    MOSDBoot,
    MOSDECSubOpRead,
    MOSDECSubOpReadReply,
    MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
    MOSDFailure,
    MOSDMap,
    MOSDOp,
    MOSDOpReply,
    MOSDPGLog,
    MOSDPGNotify,
    MOSDPGPull,
    MOSDPGPush,
    MOSDPGPushReply,
    MOSDPGQuery,
    MOSDPing,
    MOSDRepOp,
    MOSDRepOpReply,
    MOSDRepScrub,
    MOSDRepScrubMap,
    MWatchNotify,
    MMgrMap,
    MMgrReport,
    OSDOp,
    PgId,
    ReqId,
)
from ..msg.messenger import Connection, Dispatcher, Messenger, Policy
from .osdmap import PG_NONE, OSDMap, advance_map
from .pg import PG
from .reserver import Reserver
from .scheduler import SchedClass, WorkItem, make_scheduler

# Messages owned by a PG's backend (fast-dispatched, OSD.cc:7244).
BACKEND_MSGS = (
    MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
    MOSDECSubOpRead,
    MOSDECSubOpReadReply,
    MOSDRepOp,
    MOSDRepOpReply,
    MOSDPGPush,
    MOSDPGPushReply,
    MOSDPGPull,
)
PEERING_MSGS = (MOSDPGQuery, MOSDPGNotify, MOSDPGLog)
SCRUB_MSGS = (MOSDRepScrub, MOSDRepScrubMap)

# laggy detection's absolute RTT floor (ISSUE 17): below this a peer is
# never laggy no matter how it compares to the median — an all-local toy
# mesh has microsecond RTTs where relative inflation is pure noise
LAGGY_RTT_FLOOR = 0.010


class OSD(Dispatcher):
    def __init__(
        self,
        whoami: int,
        monmap: MonMap,
        conf: Config | None = None,
        store=None,
        addr: str = "127.0.0.1:0",
        auth=None,  # CephxAuth; built from the `keyring` option when unset
    ):
        self.whoami = whoami
        self.monmap = monmap
        self.conf = conf or Config({"name": f"osd.{whoami}"})
        if store is not None:
            self.store = store
        else:
            from ..os.bluestore import make_store

            self.store = make_store(self.conf)
        self._bind_addr = addr
        if auth is None and self.conf.get("keyring"):
            from ..auth.cephx import CephxAuth
            from ..auth.keyring import KeyRing

            auth = CephxAuth.for_daemon(
                f"osd.{whoami}", KeyRing.load(self.conf.get("keyring"))
            )
        msgr_kw = dict(
            crc_data=self.conf.get("ms_crc_data"),
            inject_socket_failures=self.conf.get("ms_inject_socket_failures"),
            inject_internal_delays=self.conf.get("ms_inject_internal_delays"),
            dispatch_throttle_bytes=self.conf.get("ms_dispatch_throttle_bytes"),
            auth=auth,
            secure=self.conf.get("ms_secure"),
            compress=self.conf.get("ms_compress"),
            stack=self.conf.get("ms_type"),
        )
        self.msgr = Messenger(f"osd.{whoami}", **msgr_kw)
        self.msgr.default_policy = Policy.lossless_peer()
        self.monc = MonClient(
            f"osd.{whoami}",
            monmap,
            msgr=Messenger(f"osd.{whoami}", **msgr_kw),
        )
        # the ms_inject_* fault knobs are runtime-mutable (the chaos
        # harness arms them mid-run via injectargs/config set): changes
        # must reach BOTH live messengers, not just the next boot
        def _apply_ms_inject(name: str, v) -> None:
            for m in (self.msgr, self.monc.msgr):
                if name == "ms_inject_socket_failures":
                    m.inject_socket_failures = int(v)
                else:
                    m.inject_internal_delays = float(v)

        self.conf.add_observer(
            ["ms_inject_socket_failures", "ms_inject_internal_delays"],
            _apply_ms_inject,
        )
        self.osdmap = OSDMap()
        self.pgs: dict[tuple[int, int], PG] = {}
        # op scheduler: the osd_mclock_* dmClock triples come from the
        # option table (they were declared runtime-mutable since PR 1 but
        # never read — the ISSUE 12 config-coherence pass caught the
        # drift); any knob changing re-derives all three profiles live
        def _mclock_profiles() -> dict:
            from .scheduler import ClientProfile

            return {
                SchedClass.CLIENT: ClientProfile(
                    reservation=self.conf.get("osd_mclock_client_res"),
                    weight=self.conf.get("osd_mclock_client_wgt"),
                    limit=self.conf.get("osd_mclock_client_lim"),
                ),
                SchedClass.RECOVERY: ClientProfile(
                    reservation=self.conf.get("osd_mclock_recovery_res"),
                    weight=self.conf.get("osd_mclock_recovery_wgt"),
                    limit=self.conf.get("osd_mclock_recovery_lim"),
                ),
            }

        self.sched = make_scheduler(
            self.conf.get("osd_op_queue"), profiles=_mclock_profiles()
        )

        def _apply_mclock(_n=None, _v=None) -> None:
            # update_profile, NOT a raw profiles.update(): the class's
            # tag chain must restart — a reservation of 0 stores
            # last.r = inf, and without the reset a later nonzero
            # reservation would compute max(now, inf + 1/res) forever
            if hasattr(self.sched, "update_profile"):
                for klass, prof in _mclock_profiles().items():
                    self.sched.update_profile(klass, prof)

        self.conf.add_observer(
            [
                f"osd_mclock_{lane}_{knob}"
                for lane in ("client", "recovery")
                for knob in ("res", "wgt", "lim")
            ],
            _apply_mclock,
        )
        self._sched_kick = asyncio.Event()
        b = PerfCountersBuilder(f"osd.{whoami}")
        for c in ("op", "op_r", "op_w", "op_in_bytes", "op_out_bytes",
                  "recovery_ops", "heartbeat_failures", "backfill_pushes",
                  # gray-failure tolerance (ISSUE 17): ops shed at
                  # admission / sub-reads shed shard-side after the
                  # deadline, and the hedged-read ledger (issued /
                  # joined-the-decode-set / budget-denied)
                  "op_deadline_shed", "subread_deadline_shed",
                  "ec_hedge_reads", "ec_hedge_wins", "ec_hedge_denied"):
            b.add_u64_counter(c)
        # latency distributions (PerfHistogram; the reference's
        # op_latency / op_w_latency_in_bytes_histogram family): log2
        # buckets so the prometheus export is a real histogram, not an
        # average that hides the tail
        b.add_histogram("op_latency", "client op dispatch->reply (s)")
        b.add_histogram_2d(
            "op_size_latency", "op payload bytes x dispatch->reply (s)"
        )
        b.add_histogram("ec_encode_latency", "EC encode launch->reap (s)")
        b.add_histogram("ec_decode_latency", "EC reconstruct decode (s)")
        # heartbeat ping + EC sub-read round-trips, aggregate; per-peer
        # osd_heartbeat_rtt_osd_<N> twins are declared lazily on first
        # sample (ensure_histogram) since peer membership is an osdmap
        # fact.  The osd_ prefix puts the scrape family at
        # ceph_tpu_osd_heartbeat_rtt_* — the name the docs index.
        b.add_histogram("osd_heartbeat_rtt", "peer ping/sub-read rtt (s)")
        self.perf = b.create_perf_counters()
        self.clog: list[str] = []
        # structured cluster-log client (ISSUE 16): batching + dedup +
        # rate limit in front of monc.send_log; every load-bearing
        # transition (DEGRADED/heal, HBM pressure, storm engage/shed/
        # disengage, scrub found/repaired) lands here, and the asok's
        # mutating commands audit through it
        self.clogc = ClusterLogClient(f"osd.{whoami}", send=self.monc.send_log)
        # last-seen transition state for the beacon-driven clog diffs
        self._clog_degraded = False
        self._clog_hbm_stage = 0
        self._pushed_config: set[str] = set()  # mon-managed option names
        # backfill reservation slots (AsyncReserver pair, OSDService):
        # local = backfills this OSD primaries, remote = slots granted to
        # other primaries targeting this OSD; both bound by
        # osd_max_backfills (runtime-mutable via the config push path).
        self.local_reserver = Reserver(
            lambda: self.conf.get("osd_max_backfills")
        )
        self.remote_reserver = Reserver(
            lambda: self.conf.get("osd_max_backfills")
        )
        # internal (OSD-as-client) reads for COPY_FROM source fetches
        self._internal_tid = 0
        self._internal_reads: dict[int, object] = {}
        # op tracking (TrackedOp.h OpTracker; dumped via the admin socket)
        from ..common.op_tracker import OpTracker

        # workload attribution (ISSUE 10): per-pool / per-client ops,
        # bytes and log2 latency histograms sampled on the op reply and
        # recovery paths; shipped in the status blob for the mgr iostat
        # module to merge into cluster-wide rates
        from ..common.io_accounting import IOAccountant

        self.io_accountant = IOAccountant()
        self.op_tracker = OpTracker(
            history_size=self.conf.get("osd_op_history_size")
        )
        self.op_tracker.complaint_time = self.conf.get("osd_op_complaint_time")
        # runtime-mutable: resize the history ring on config push
        self.conf.add_observer(
            ["osd_op_history_size"],
            lambda _n, v: self.op_tracker.resize_history(int(v)),
        )
        self.conf.add_observer(
            ["osd_op_complaint_time"],
            lambda _n, v: setattr(self.op_tracker, "complaint_time", float(v)),
        )
        # span tracer threaded through the EC data path (common/tracer.py;
        # the reference's ZTracer/jaeger integration, dumped via the admin
        # socket's `dump_tracer`)
        self.tracer = Tracer(
            f"osd.{whoami}", enabled=self.conf.get("jaeger_tracing_enable")
        )
        # the option is runtime-mutable: flips must reach the live tracer
        self.conf.add_observer(
            ["jaeger_tracing_enable"],
            lambda _n, v: setattr(self.tracer, "enabled", bool(v)),
        )
        # budgeted trace sampling (ISSUE 10): head-sampling rate + span
        # retention budget, runtime-mutable via the same observer
        # pattern — what makes always-on tracing safe at harness scale
        self.tracer.configure_sampling(
            sample_rate=self.conf.get("op_trace_sample_rate"),
            budget_per_sec=self.conf.get("op_trace_budget_per_sec"),
        )
        self.conf.add_observer(
            ["op_trace_sample_rate"],
            lambda _n, v: self.tracer.configure_sampling(sample_rate=float(v)),
        )
        self.conf.add_observer(
            ["op_trace_budget_per_sec"],
            lambda _n, v: self.tracer.configure_sampling(
                budget_per_sec=float(v)
            ),
        )
        # incoming trace-carrying messages get a messenger hop span
        # parent-linked to the sender (tracer.py inject/extract)
        self.msgr.tracer = self.tracer
        # EC encode/decode launch aggregation: this OSD's PGs share the
        # process-wide aggregators; apply the daemon's config to them and
        # keep them in sync on runtime sets (all four options are
        # runtime=True)
        from ..codec.matrix_codec import (
            default_decode_aggregator,
            default_encode_aggregator,
            default_verify_aggregator,
        )

        self.encode_aggregator = default_encode_aggregator()
        self.encode_aggregator.configure(
            window=self.conf.get("ec_tpu_aggregate_window"),
            max_bytes=self.conf.get("ec_tpu_aggregate_max_bytes"),
        )
        self.conf.add_observer(
            ["ec_tpu_aggregate_window"],
            lambda _n, v: self.encode_aggregator.configure(window=int(v)),
        )
        self.conf.add_observer(
            ["ec_tpu_aggregate_max_bytes"],
            lambda _n, v: self.encode_aggregator.configure(max_bytes=int(v)),
        )
        self.decode_aggregator = default_decode_aggregator()
        self.decode_aggregator.configure(
            window=self.conf.get("ec_tpu_decode_aggregate_window"),
            max_bytes=self.conf.get("ec_tpu_decode_aggregate_max_bytes"),
        )
        self.conf.add_observer(
            ["ec_tpu_decode_aggregate_window"],
            lambda _n, v: self.decode_aggregator.configure(window=int(v)),
        )
        self.conf.add_observer(
            ["ec_tpu_decode_aggregate_max_bytes"],
            lambda _n, v: self.decode_aggregator.configure(max_bytes=int(v)),
        )
        self.verify_aggregator = default_verify_aggregator()
        self.verify_aggregator.configure(
            window=self.conf.get("ec_tpu_verify_aggregate_window"),
            max_bytes=self.conf.get("ec_tpu_verify_aggregate_max_bytes"),
        )
        self.conf.add_observer(
            ["ec_tpu_verify_aggregate_window"],
            lambda _n, v: self.verify_aggregator.configure(window=int(v)),
        )
        self.conf.add_observer(
            ["ec_tpu_verify_aggregate_max_bytes"],
            lambda _n, v: self.verify_aggregator.configure(max_bytes=int(v)),
        )
        # launch-scheduler QoS profiles (ISSUE 9): the nine
        # ec_tpu_sched_* knobs map onto the three lanes' dmClock
        # triples; any one changing re-derives all three profiles (the
        # mClockScheduler config-observer pattern, reapplied to the
        # device launch queue)
        from ..ops.launch_scheduler import launch_scheduler
        from .scheduler import ClientProfile

        def _apply_sched_profiles(_n=None, _v=None) -> None:
            launch_scheduler().configure(**{
                lane: ClientProfile(
                    reservation=self.conf.get(f"ec_tpu_sched_{lane}_res"),
                    weight=self.conf.get(f"ec_tpu_sched_{lane}_wgt"),
                    limit=self.conf.get(f"ec_tpu_sched_{lane}_lim"),
                )
                for lane in ("client", "recovery", "background")
            })

        _apply_sched_profiles()
        self.conf.add_observer(
            [
                f"ec_tpu_sched_{lane}_{knob}"
                for lane in ("client", "recovery", "background")
                for knob in ("res", "wgt", "lim")
            ],
            _apply_sched_profiles,
        )
        # backpressure bound: both aggregators share the knob (ISSUE 7),
        # runtime-mutable like the window/byte-budget settings
        def _apply_inflight(v: int) -> None:
            self.encode_aggregator.configure(inflight_max_bytes=int(v))
            self.decode_aggregator.configure(inflight_max_bytes=int(v))

        _apply_inflight(self.conf.get("ec_tpu_inflight_max_bytes"))
        self.conf.add_observer(
            ["ec_tpu_inflight_max_bytes"], lambda _n, v: _apply_inflight(v)
        )
        # depth-N async launch pipeline (ISSUE 11): every aggregator
        # shares the in-flight ring bound, runtime-mutable like the
        # aggregation knobs
        def _apply_pipeline_depth(v: int) -> None:
            self.encode_aggregator.configure(pipeline_depth=int(v))
            self.decode_aggregator.configure(pipeline_depth=int(v))
            self.verify_aggregator.configure(pipeline_depth=int(v))

        _apply_pipeline_depth(self.conf.get("ec_tpu_pipeline_depth"))
        self.conf.add_observer(
            ["ec_tpu_pipeline_depth"],
            lambda _n, v: _apply_pipeline_depth(v),
        )
        # super-launch fusion + bucketed pad specialization (ISSUE 18):
        # every aggregator shares both knobs, runtime-mutable; shrinking
        # the bucket budget trims the now-dead pooled shapes in place
        def _apply_fuse_windows(v: int) -> None:
            self.encode_aggregator.configure(fuse_max_windows=int(v))
            self.decode_aggregator.configure(fuse_max_windows=int(v))
            self.verify_aggregator.configure(fuse_max_windows=int(v))

        def _apply_pad_buckets(v: int) -> None:
            self.encode_aggregator.configure(pad_buckets=int(v))
            self.decode_aggregator.configure(pad_buckets=int(v))
            self.verify_aggregator.configure(pad_buckets=int(v))

        _apply_fuse_windows(self.conf.get("ec_tpu_fuse_max_windows"))
        self.conf.add_observer(
            ["ec_tpu_fuse_max_windows"],
            lambda _n, v: _apply_fuse_windows(v),
        )
        _apply_pad_buckets(self.conf.get("ec_tpu_pad_buckets"))
        self.conf.add_observer(
            ["ec_tpu_pad_buckets"],
            lambda _n, v: _apply_pad_buckets(v),
        )
        # on-device RMW delta path (ISSUE 18): process-wide arm bit the
        # EC backend consults before trying the zero-copy delta encode
        from . import ec_backend as ec_backend_mod

        ec_backend_mod.configure_rmw_delta(
            bool(self.conf.get("ec_tpu_rmw_delta"))
        )
        self.conf.add_observer(
            ["ec_tpu_rmw_delta"],
            lambda _n, v: ec_backend_mod.configure_rmw_delta(bool(v)),
        )
        # device-resident chunk cache bound (ISSUE 11): the process-wide
        # HBM cache degraded reads / RMW read legs consult before H2D
        from ..ops.device_cache import device_chunk_cache

        device_chunk_cache().configure(
            max_bytes=self.conf.get("ec_tpu_device_cache_bytes")
        )
        self.conf.add_observer(
            ["ec_tpu_device_cache_bytes"],
            lambda _n, v: device_chunk_cache().configure(max_bytes=int(v)),
        )
        # HBM mempool ledger (ISSUE 13): call-site debug sharding and
        # the residency target the pressure layer trims against, both
        # runtime-mutable through the same observer plumbing
        from ..common.mempool import ledger as hbm_ledger

        hbm_ledger().configure(
            debug=self.conf.get("ec_tpu_mempool_debug"),
            target_bytes=self.conf.get("ec_tpu_hbm_target_bytes"),
        )
        self.conf.add_observer(
            ["ec_tpu_mempool_debug"],
            lambda _n, v: hbm_ledger().configure(debug=bool(v)),
        )
        self.conf.add_observer(
            ["ec_tpu_hbm_target_bytes"],
            lambda _n, v: hbm_ledger().configure(target_bytes=int(v)),
        )
        # flight recorder ring capacity (ISSUE 8): runtime-mutable like
        # the aggregation knobs; resizing keeps the newest records
        from ..ops.flight_recorder import flight_recorder

        flight_recorder().configure(
            capacity=self.conf.get("ec_tpu_flight_records")
        )
        self.conf.add_observer(
            ["ec_tpu_flight_records"],
            lambda _n, v: flight_recorder().configure(capacity=int(v)),
        )
        # device-launch watchdog (ops/guard.py): per-launch deadline +
        # degraded-mode re-probe cadence, runtime-mutable
        from ..ops.guard import device_guard

        device_guard().configure(
            timeout_ms=self.conf.get("ec_tpu_launch_timeout_ms"),
            probe_interval_ms=self.conf.get("ec_tpu_probe_interval_ms"),
        )
        self.conf.add_observer(
            ["ec_tpu_launch_timeout_ms"],
            lambda _n, v: device_guard().configure(timeout_ms=int(v)),
        )
        self.conf.add_observer(
            ["ec_tpu_probe_interval_ms"],
            lambda _n, v: device_guard().configure(probe_interval_ms=int(v)),
        )
        # device-offload runtime riders (ISSUE 20): the csum/compress
        # service aggregators share the bluestore_csum_offload_window /
        # _max_bytes knobs, and the BlueStore arm bit is runtime-mutable
        # through the store's setter — all three options runtime=True
        from ..compressor.device import default_compress_aggregator
        from ..ops.checksum_offload import default_csum_aggregator

        self.csum_aggregator = default_csum_aggregator()
        self.compress_aggregator = default_compress_aggregator()

        def _apply_offload_window(v: int) -> None:
            self.csum_aggregator.configure(window=int(v))
            self.compress_aggregator.configure(window=int(v))

        def _apply_offload_max_bytes(v: int) -> None:
            self.csum_aggregator.configure(max_bytes=int(v))
            self.compress_aggregator.configure(max_bytes=int(v))

        _apply_offload_window(self.conf.get("bluestore_csum_offload_window"))
        _apply_offload_max_bytes(
            self.conf.get("bluestore_csum_offload_max_bytes")
        )
        self.conf.add_observer(
            ["bluestore_csum_offload_window"],
            lambda _n, v: _apply_offload_window(v),
        )
        self.conf.add_observer(
            ["bluestore_csum_offload_max_bytes"],
            lambda _n, v: _apply_offload_max_bytes(v),
        )
        if hasattr(self.store, "set_csum_offload"):
            self.conf.add_observer(
                ["bluestore_csum_offload"],
                lambda _n, v: self.store.set_csum_offload(bool(v)),
            )
        # sharded-dispatch policy (ISSUE 6): the process-wide mesh fan-out
        # knobs ride the same config/observer plumbing as the aggregators
        from ..parallel import dispatch as shard_dispatch

        shard_dispatch.configure(
            min_batch=self.conf.get("ec_tpu_shard_min_batch"),
            devices=self.conf.get("ec_tpu_shard_devices"),
        )
        self.conf.add_observer(
            ["ec_tpu_shard_min_batch"],
            lambda _n, v: shard_dispatch.configure(min_batch=int(v)),
        )
        self.conf.add_observer(
            ["ec_tpu_shard_devices"],
            lambda _n, v: shard_dispatch.configure(devices=int(v)),
        )
        # recovery-storm controller (ISSUE 15): the cross-PG wave
        # orchestrator — engages when a whole-OSD failure floods the
        # missing sets, batches reconstruction decodes into mesh-wide
        # waves, and adapts admission to the local client burn rate.
        # Constructed after the reservers/aggregators/accountant it
        # coordinates; its knobs are re-read per tick (plus a ceiling
        # observer), so runtime config sets land immediately.
        from .recovery_controller import RecoveryStormController

        self.recovery_storm = RecoveryStormController(self)
        self.admin_socket = None
        # periodic-scrub schedule: pgid -> last periodic scrub kickoff
        self._last_periodic_scrub: dict = {}
        # heartbeat state: peer -> last reply rx time
        self._hb_last_rx: dict[int, float] = {}
        self._hb_first_tx: dict[int, float] = {}
        self._reported_failed: set[int] = set()
        self._last_failure_report: dict[int, float] = {}
        # laggy-OSD detection (ISSUE 17): per-peer RTT EWMA fed by ping
        # replies AND EC sub-read round-trips; peers past the slow-factor
        # threshold are flagged laggy — alive but slow, the gray failure
        # the markdown path cannot see — reported non-fatally to the mon
        # and deprioritized as EC sub-read sources
        self._peer_rtt_ewma: dict[int, float] = {}
        self._laggy_peers: set[int] = set()
        self._laggy_reported: dict[int, float] = {}  # peer -> last report
        # ordered cluster sends: addr -> queue + drain task
        self._out_q: dict[str, asyncio.Queue] = {}
        self._out_tasks: dict[str, asyncio.Task] = {}
        self._tasks: list[asyncio.Task] = []
        self._running = False
        self.up = False
        self.mgr_addr = ""  # active mgr (from the mgrmap subscription)
        self._mgrmap_epoch = 0

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> None:
        """Boot sequence (ceph_osd.cc main → OSD::init)."""
        # preload codec plugins (global_init_preload_erasure_code,
        # src/global/global_init.cc:593; option global.yaml.in:2541)
        from ..codec.registry import instance as ec_registry
        from ..common.log import dout as _dout

        for name in self.conf.get("osd_erasure_code_plugins").split():
            try:
                ec_registry().load(name)
            except Exception as e:
                _dout("osd", 1, f"osd.{self.whoami}: preload {name} failed: {e}")
        # preload object classes (ClassHandler::open_all_classes via
        # osd_class_load_list; others load lazily on first CALL)
        from ..cls.objclass import load_class

        for name in self.conf.get("osd_op_class_load_list").split():
            try:
                load_class(name)
            except Exception as e:
                _dout("osd", 1, f"osd.{self.whoami}: cls {name} failed: {e}")
        self.store.mount()
        await self.msgr.bind(self._bind_addr)
        self.msgr.add_dispatcher_head(self)
        self.monc.on_osdmap = self._on_osdmap_msg
        self.monc.on_config = self._on_config_msg
        self._running = True
        self.monc.msgr.add_dispatcher_tail(self)  # mgrmap rides the mon conn
        await self.monc.subscribe("osdmap")
        await self.monc.subscribe("mgrmap")
        await self.monc.subscribe("config")
        await self._send_boot()
        await self._start_admin_socket()
        self._tasks.append(asyncio.create_task(self._op_worker()))
        self._tasks.append(asyncio.create_task(self._heartbeat_loop()))

    async def _start_admin_socket(self) -> None:
        """Daemon admin socket (AdminSocket::init): perf/config/trace/ops
        introspection, enabled by the `admin_socket` path option."""
        path = self.conf.get("admin_socket")
        if not path:
            return
        from ..common.admin_socket import AdminSocket

        sock = AdminSocket(path)
        # every MUTATING asok command lands on the audit channel (ISSUE
        # 16): injectargs fault arming, mark_unfound_lost, ... — the
        # operator's state-changing actions are part of the timeline
        sock.audit_cb = lambda prefix, cmd: self.cluster_log(
            "info",
            f"asok from='osd.{self.whoami}' cmd={prefix!r} "
            f"args={ {k: v for k, v in cmd.items() if k != 'prefix'} }",
            channel="audit",
        )
        # the OSD's encode/decode aggregators (the shared instances this
        # daemon configured at init) export their occupancy/launch-size
        # distributions alongside the daemon counters
        agg_perf = self.encode_aggregator.perf
        dec_perf = self.decode_aggregator.perf
        ver_perf = self.verify_aggregator.perf
        from ..ops import dispatch as ec_dispatch
        from ..ops.offload_runtime import (
            offload_perf_dump as _offload_perf_dump,
        )

        sock.register(
            "perf dump",
            lambda cmd: {
                **self.perf.dump(),
                "ec_aggregator": agg_perf.dump(),
                "ec_decode_aggregator": dec_perf.dump(),
                "ec_verify_aggregator": ver_perf.dump(),
                # process-wide launch counters incl. the sharded-launch /
                # devices-per-launch dimension and the launch-scheduler
                # per-class QoS counters (ops/dispatch.py)
                "ec_dispatch": ec_dispatch.perf_dump(),
                # offload-runtime service registry slice (ISSUE 20)
                "offload": _offload_perf_dump(),
            },
            "dump perf counters",
        )
        sock.register("config show", lambda cmd: self.conf.show(),
                      "dump current config")
        sock.register("config diff", lambda cmd: self.conf.diff(),
                      "config values differing from defaults")
        sock.register(
            "dump_tracer",
            lambda cmd: {
                "spans": self.tracer.export(),
                "sampling": self.tracer.sampling_stats(),
            },
            "dump collected trace spans (EC data path) + sampling stats",
        )
        sock.register(
            "dump_io_accounting",
            lambda cmd: {
                "pools": self.io_accountant.dump_pools(),
                "clients": self.io_accountant.dump_clients(),
                "totals": self.io_accountant.totals(),
            },
            "per-pool / per-client cumulative IO counters + latency "
            "histograms (the iostat module's per-OSD input)",
        )
        sock.register(
            "dump_tracing",
            lambda cmd: {"traces": self.tracer.export_traces()},
            "spans grouped per trace id (cross-daemon op traces; "
            "client/messenger/dispatch/encode/codec stages)",
        )
        sock.register(
            "dump_histograms",
            lambda cmd: {
                **self.perf.dump_histograms(),
                "ec_aggregator": agg_perf.dump_histograms(),
                "ec_decode_aggregator": dec_perf.dump_histograms(),
                "ec_verify_aggregator": ver_perf.dump_histograms(),
            },
            "log2-bucketed latency (and size x latency) histograms",
        )
        def _pg_for_cmd(cmd):
            if "pool" not in cmd or "ps" not in cmd:
                raise ValueError("command requires args: pool, ps")
            pg = self.pgs.get((int(cmd["pool"]), int(cmd["ps"])))
            if pg is None:
                raise ValueError(f"no pg {cmd.get('pool')}.{cmd.get('ps')} here")
            return pg

        sock.register(
            "dump_blocked_ops",
            lambda cmd: {
                "pgs": {
                    repr(pg.pgid): blocked
                    for pg in self.pgs.values()
                    if (blocked := pg.blocked_ops_summary())
                }
            },
            "ops queued behind recovery / promotion / flush, per PG "
            "(pairs with list_unfound for stuck-op diagnosis)",
        )
        sock.register(
            "list_unfound",
            lambda cmd: {"unfound": _pg_for_cmd(cmd).list_unfound()},
            "missing objects with no live source (args: pool, ps)",
        )
        sock.register(
            "mark_unfound_lost",
            lambda cmd: {
                "lost": _pg_for_cmd(cmd).mark_unfound_lost(
                    cmd.get("mode", "delete")
                )
            },
            "give up on unfound objects: delete + release waiters "
            "(args: pool, ps[, mode=delete])",
            mutating=True,
        )
        def _injectargs(cmd: dict) -> dict:
            """injectargs-style runtime fault arming: the harness and the
            tests drive the SAME process-global FaultInjector hooks the
            data path checks (common/fault_injector.py catalog).

            Forms: {point, error?, hits?} arms a counted errno fault;
            {point, one_in} arms a probabilistic fault
            (ms_inject_socket_failures semantics); {point, delay_ms}
            arms a LATENCY fault — the seam stays functionally correct
            but slow, the gray-failure shape (ISSUE 17); {clear: true,
            point?} disarms one point or everything; {conf: {name:
            value}} additionally applies runtime config sets (the
            classic `injectargs '--opt val'` use)."""
            from ..common.fault_injector import FAULT_POINTS, global_injector

            inj = global_injector()
            if cmd.get("clear"):
                inj.clear(cmd.get("point"))
            elif "point" in cmd:
                point = cmd["point"]
                if point not in FAULT_POINTS:
                    raise ValueError(f"unregistered fault point {point!r}")
                if "one_in" in cmd:
                    inj.inject_probabilistic(point, int(cmd["one_in"]))
                elif "delay_ms" in cmd:
                    # `who` ("osd.3") scopes the latency to one daemon:
                    # the injector is process-global, a gray failure is
                    # one slow daemon among healthy ones
                    inj.inject_delay(
                        point, float(cmd["delay_ms"]),
                        hits=int(cmd.get("hits", -1)),
                        who=str(cmd.get("who", "")),
                    )
                else:
                    inj.inject(
                        point, int(cmd.get("error", 5)),
                        hits=int(cmd.get("hits", -1)),
                    )
            for name, value in (cmd.get("conf") or {}).items():
                self.conf.set(name, value)
            return {
                "armed": sorted(
                    p for p in FAULT_POINTS if inj.armed(p)
                ),
            }

        sock.register(
            "injectargs",
            _injectargs,
            "arm/clear fault-injection points + runtime config sets "
            "(args: point, error, hits, one_in, delay_ms, who, clear, conf)",
            mutating=True,
        )
        def _dump_flight(cmd: dict) -> dict:
            # the launch flight recorder (ops/flight_recorder.py): the
            # per-launch timeline behind the ec_dispatch counters.
            # `reset: true` rebases the ring + utilization window so a
            # bench stage can measure its own occupancy.
            from ..ops.flight_recorder import flight_recorder

            fr = flight_recorder()
            if cmd.get("reset"):
                fr.reset()
                return {"reset": True}
            return fr.dump()

        sock.register(
            "dump_flight",
            _dump_flight,
            "per-launch flight records: queue-wait + h2d/kernel/d2h "
            "sub-spans, device width, fallback/degraded/throttle flags "
            "(args: reset; export with tools/trace_export.py)",
        )
        def _dump_mempools(cmd: dict) -> dict:
            # the HBM mempool ledger (common/mempool.py, ISSUE 13):
            # per-pool current/peak bytes+buffers, per-device breakdown,
            # pressure state, and (in ec_tpu_mempool_debug mode) the
            # per-call-site shards.  `reset_peaks: true` rebases the
            # peak gauges, like the reference's mempool peak reset.
            from ..common.mempool import ledger as _hbm

            if cmd.get("reset_peaks"):
                _hbm().reset_peaks()
                return {"reset_peaks": True}
            return _hbm().dump()

        sock.register(
            "dump_mempools",
            _dump_mempools,
            "HBM mempool ledger: per-pool current/peak bytes+buffers, "
            "per-device breakdown, pressure state, call-site shards in "
            "debug mode (args: reset_peaks)",
        )
        sock.register(
            "dump_recovery_storm",
            lambda cmd: {
                "status": self.recovery_storm.status(),
                "perf": self.recovery_storm.perf_dump(),
            },
            "recovery-storm controller state: whole-OSD rebuild bar, "
            "wave/shed/ramp counters, live wave size + burn rate "
            "(ISSUE 15)",
        )
        sock.register(
            "dump_historic_ops",
            lambda cmd: self.op_tracker.dump_historic(),
            "recently completed ops with events + per-stage durations "
            "(OpTracker)",
        )
        sock.register(
            "dump_historic_slow_ops",
            lambda cmd: self.op_tracker.dump_slow(),
            "slowest completed ops retained (OpTracker)",
        )
        sock.register(
            "dump_ops_in_flight",
            lambda cmd: {
                **self.op_tracker.dump_in_flight(),
                "pgs": {
                    repr(pg.pgid): sorted(
                        f"{c}:{t}" for c, t in pg._inflight_reqids
                    )
                    for pg in self.pgs.values()
                    if pg._inflight_reqids
                },
            },
            "in-flight client writes",
        )
        await sock.start()
        self.admin_socket = sock

    async def stop(self) -> None:
        try:
            # ship any batched clog entries before the messenger dies
            await asyncio.wait_for(self.clogc.flush(), timeout=0.5)
        except Exception as e:
            # best-effort: the mon may already be gone at shutdown
            dout("osd", 5, f"final clog flush failed: {e}")
        self._running = False
        for t in self._tasks + list(self._out_tasks.values()):
            t.cancel()
        self._tasks.clear()
        self._out_tasks.clear()
        if self.admin_socket is not None:
            await self.admin_socket.stop()
            self.admin_socket = None
        await self.msgr.shutdown()
        await self.monc.msgr.shutdown()
        self.store.umount()

    async def _send_boot(self) -> None:
        """MOSDBoot broadcast to every mon (OSD::_send_boot; only the
        Paxos leader acts on it)."""
        boot = MOSDBoot(osd=self.whoami, addr=self.msgr.addr, epoch=self.osdmap.epoch)
        for name in self.monmap.ranks:
            try:
                await self.monc.msgr.send_to(self.monmap.addrs[name], boot)
            except ConnectionError:
                continue

    async def wait_for_up(self, timeout: float = 5.0) -> None:
        deadline = time.monotonic() + timeout
        while not self.up:
            if time.monotonic() > deadline:
                raise TimeoutError(f"osd.{self.whoami} never marked up")
            await asyncio.sleep(0.01)

    # -- osdmap handling -------------------------------------------------------

    def _on_osdmap_msg(self, msg: MOSDMap) -> None:
        """OSD::handle_osd_map: apply full maps / incrementals in epoch
        order, then advance the PGs."""
        old_map = self.osdmap
        self.osdmap = advance_map(self.osdmap, msg)
        info = self.osdmap.osds.get(self.whoami)
        self.up = bool(info and info.up and info.addr == self.msgr.addr)
        # storm victim detection: an OSD leaving up+in across this
        # advance names the whole-OSD rebuild the controller conducts
        self.recovery_storm.note_osdmap(old_map, self.osdmap)
        self._advance_pgs()

    def _advance_pgs(self) -> None:
        """consume_map: create/advance every PG we participate in."""
        epoch = self.osdmap.epoch
        for pool in self.osdmap.pools.values():
            for ps in range(pool.pg_num):
                try:
                    _up, _upp, acting, _actp = self.osdmap.pg_to_up_acting_osds(
                        pool.id, ps
                    )
                except Exception:
                    continue
                key = (pool.id, ps)
                if self.whoami in acting:
                    pg = self.pgs.get(key)
                    if pg is None:
                        pg = self.pgs[key] = PG(
                            self, pool, ps, self.osdmap.erasure_code_profiles
                        )
                    else:
                        # Full-map decodes build fresh PgPool objects, and
                        # pool metadata mutates across epochs (cache-tier
                        # attach/overlay, target sizes): the PG must see
                        # the CURRENT pool, not its creation-time snapshot.
                        pg.pool = pool
                    pg.on_new_interval(epoch, acting)
                elif key in self.pgs:
                    # no longer in the acting set: drop the in-memory PG
                    # (data stays on disk, as the reference keeps strays)
                    del self.pgs[key]

    def _get_pg(self, pgid) -> PG | None:
        pg = self.pgs.get((pgid.pool, pgid.ps))
        if pg is not None:
            return pg
        # A peering message can arrive before our copy of the map does
        # (OSD::handle_pg_create path): create the PG shell on demand.
        pool = self.osdmap.pools.get(pgid.pool)
        if pool is None:
            return None
        try:
            _up, _upp, acting, _actp = self.osdmap.pg_to_up_acting_osds(
                pool.id, pgid.ps
            )
        except Exception:
            return None
        if self.whoami not in acting:
            return None
        pg = self.pgs[(pgid.pool, pgid.ps)] = PG(
            self, pool, pgid.ps, self.osdmap.erasure_code_profiles
        )
        pg.on_new_interval(self.osdmap.epoch, acting)
        return pg

    # -- mgr reporting ---------------------------------------------------------

    def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, MMgrMap):
            if msg.epoch > self._mgrmap_epoch:
                self._mgrmap_epoch = msg.epoch
                self.mgr_addr = msg.active_addr
            return True
        return False

    def ms_handle_reset(self, conn: Connection) -> None:
        """A client session died: its watches evaporate and pending
        notifies stop waiting on it (Watch::remove on session reset)."""
        for pg in self.pgs.values():
            pg.on_client_reset(conn)

    def _send_mgr_report(self) -> None:
        """Periodic perf/status report to the active mgr
        (MgrClient::send_report)."""
        import json

        if not self.mgr_addr:
            return
        # the encode/decode aggregators' occupancy/launch-size histograms
        # ride the report (namespaced), so the mgr prometheus scrape
        # exports them like any daemon counter — not just the local
        # admin socket
        perf = dict(self.perf.dump())
        for name, val in self.encode_aggregator.perf.dump().items():
            perf[f"ec_aggregator.{name}"] = val
        for name, val in self.decode_aggregator.perf.dump().items():
            perf[f"ec_decode_aggregator.{name}"] = val
        for name, val in self.verify_aggregator.perf.dump().items():
            perf[f"ec_verify_aggregator.{name}"] = val
        # trace-sampling counters (ISSUE 10): sampled/kept/dropped +
        # live knobs ride the report flat so the scrape carries
        # ceph_tpu_trace_* families (rate/budget/pending are gauges,
        # the rest monotonic counters — mgr/prometheus._perf_type)
        for name, val in self.tracer.sampling_stats().items():
            perf[f"trace.{name}"] = val
        # recovery-storm controller counters/gauges (ISSUE 15): the
        # ceph_tpu_recovery_storm_* scrape families — wave/shed/ramp
        # totals plus the live wave size, in-flight depth, engagement
        # flag and local burn rate
        for name, val in self.recovery_storm.perf_dump().items():
            perf[f"recovery_storm.{name}"] = val
        # launch counters incl. sharded launches / devices-per-launch
        # (ops/dispatch.py): flat scalars, so the mgr prometheus scrape
        # exports one ceph_tpu_ec_dispatch_* family per counter
        from ..ops import dispatch as ec_dispatch

        for name, val in ec_dispatch.perf_dump().items():
            perf[f"ec_dispatch.{name}"] = val
        # device-offload runtime services (ISSUE 20): one flat
        # <service>.<counter> slice per registered rider (csum, compress,
        # plus the EC trio), exported as ceph_tpu_offload_* families
        from ..ops.offload_runtime import offload_perf_dump

        for name, val in offload_perf_dump().items():
            perf[f"offload.{name}"] = val
        # launch-scheduler QoS counters under their canonical prometheus
        # prefix (ISSUE 9): aliases of the sched.* slice the dispatch
        # loop above just exported, re-namespaced so the scrape renders
        # ceph_tpu_ec_sched_<class>_<counter> families.  Copied from the
        # snapshot already in `perf` — a second perf_dump() here could
        # disagree with its own alias within one report
        for name, val in list(perf.items()):
            if name.startswith("ec_dispatch.sched."):
                perf["ec_sched." + name[len("ec_dispatch.sched."):]] = val
        # device-utilization accounting under its canonical prometheus
        # names (ISSUE 8): aliases of the flight-derived scalars the
        # perf_dump() loop above just computed — one utilization
        # snapshot per report, two export names
        perf["ec_device_busy_seconds"] = perf["ec_dispatch.device_busy_seconds"]
        perf["ec_device_occupancy"] = perf["ec_dispatch.device_occupancy"]
        status = _osd_status(self)
        self._clog_transitions(status)
        self._send_addr(
            self.mgr_addr,
            MMgrReport(
                daemon=f"osd.{self.whoami}",
                perf=json.dumps(perf).encode(),
                status=json.dumps(status).encode(),
            ),
        )

    # -- dispatch --------------------------------------------------------------

    def ms_can_fast_dispatch(self, msg: Message) -> bool:
        return isinstance(
            msg,
            BACKEND_MSGS
            + PEERING_MSGS
            + SCRUB_MSGS
            + (MOSDPing, MOSDOp, MBackfillReserve, MWatchNotify, MOSDOpReply),
        )

    def ms_fast_dispatch(self, conn: Connection, msg: Message) -> None:
        if isinstance(msg, MOSDPing):
            self._handle_ping(conn, msg)
            return
        if isinstance(msg, MOSDOp):
            if msg.reqid.client in self.osdmap.blocklist:
                # fenced client (OSDMap blocklist): its ops bounce with
                # -EBLOCKLISTED so in-flight writers cannot land bytes
                # after a failover fenced them (rbd-mirror / MDS eviction)
                from ..common.errs import ESHUTDOWN

                rep = MOSDOpReply(
                    reqid=msg.reqid,
                    result=-ESHUTDOWN,
                    outdata=[],
                    version=0,
                    epoch=self.osdmap.epoch,
                )

                async def _send(c=conn, r=rep):
                    try:
                        await c.send_message(r)
                    except ConnectionError:
                        pass

                asyncio.get_event_loop().create_task(_send())
                return
            self._enqueue_op(conn, msg)
            return
        if isinstance(msg, MBackfillReserve):
            self._handle_backfill_reserve(msg)
            return
        if isinstance(msg, MWatchNotify):
            pg = self._get_pg(msg.pgid)
            if pg is not None and msg.is_ack:
                pg.handle_watch_ack(msg)
            return
        if isinstance(msg, MOSDOpReply):
            # reply to an internal op (COPY_FROM source fetch)
            entry = self._internal_reads.pop(msg.reqid.tid, None)
            if entry is not None:
                cb, multi = entry
                if multi:
                    cb(msg.result, list(msg.outdata))
                else:
                    cb(msg.result, msg.outdata[0] if msg.outdata else b"")
            return
        pg = self._get_pg(msg.pgid)
        if pg is None:
            dout("osd", 5, f"osd.{self.whoami}: no pg for {msg.pgid}, dropping {msg!r}")
            return
        if isinstance(msg, PEERING_MSGS):
            pg.handle_peering_message(msg)
        elif isinstance(msg, SCRUB_MSGS):
            pg.handle_scrub_message(msg)
        else:
            pg.backend.handle_message(msg)

    def _handle_backfill_reserve(self, msg: MBackfillReserve) -> None:
        """Target side grants/releases remote slots; primary side routes
        replies to the PG (OSD::handle_pg_backfill_reserve)."""
        key = msg.pgid.key()
        if msg.op == MBackfillReserve.REQUEST:
            granted = self.remote_reserver.try_reserve(key)
            self.send_cluster(
                msg.from_osd,
                MBackfillReserve(
                    pgid=msg.pgid,
                    op=MBackfillReserve.GRANT
                    if granted
                    else MBackfillReserve.REJECT,
                    epoch=msg.epoch,
                    from_osd=self.whoami,
                ),
            )
        elif msg.op == MBackfillReserve.RELEASE:
            self.remote_reserver.release(key)
        else:  # GRANT / REJECT -> the requesting primary's PG
            pg = self._get_pg(msg.pgid)
            if pg is not None:
                pg.on_backfill_reserve(msg)

    # -- client ops through the scheduler --------------------------------------

    def _enqueue_op(self, conn: Connection, msg: MOSDOp) -> None:
        """enqueue_op (OSD.cc:9431): into the QoS scheduler."""
        from .pg import op_class_of

        cost = sum(len(op.data) for op in msg.ops) or 4096
        self.perf.inc("op")
        op_class = op_class_of(msg.ops)
        # OpTracker registration (OpRequest created at dispatch,
        # TrackedOp::mark_event through the pipeline) with the
        # attribution tags (ISSUE 10): pool, client, op class.
        # UNCONDITIONAL — trace sampling gates span retention only, so
        # a sampled-out op still ages into SLOW_OPS accounting.
        token = self.op_tracker.create(
            f"osd_op({msg.reqid.client}:{msg.reqid.tid} "
            f"{msg.pgid.pool}.{msg.pgid.ps} {msg.oid} "
            f"[{','.join(str(op.op) for op in msg.ops)}])",
            pool_id=msg.pgid.pool,
            client=msg.reqid.client,
            op_class=op_class,
        )
        # op span: child of the messenger hop span when the delivery is
        # being traced, else adopted from the message's remote context
        # (OpRequest's osd_trace in the reference)
        span = self.tracer.start_span(
            "osd:op",
            parent=tracer_mod.current_span(),
            remote=tracer_mod.extract(msg),
        )
        span.keyval("oid", msg.oid)
        span.keyval("reqid", lambda: msg.reqid.key())
        span.event("queued")

        def run() -> None:
            self.op_tracker.mark_event(token, "dequeued")
            span.event("dequeued")
            with tracer_mod.span_scope(span):
                self._do_dispatch_op(
                    conn, msg, token, span=span, cost=cost,
                    op_class=op_class,
                )

        self.sched.enqueue(
            WorkItem(
                run=run, klass=SchedClass.CLIENT, cost=cost,
                priority=int(self.conf.get("osd_client_op_priority")),
            )
        )
        self._sched_kick.set()

    def _do_dispatch_op(
        self, conn: Connection, msg: MOSDOp, token: int = 0, span=None,
        cost: int | None = None, op_class: str | None = None,
    ) -> None:
        """dequeue_op (OSD.cc:9491) → PG::do_op."""
        pg = self._get_pg(msg.pgid)
        op_span = span if span is not None else null_span()
        t0 = time.monotonic()
        if cost is None:
            cost = sum(len(op.data) for op in msg.ops) or 4096
        if op_class is None:
            from .pg import op_class_of

            op_class = op_class_of(msg.ops)

        def reply(rep: MOSDOpReply) -> None:
            self.op_tracker.finish(token)
            lat = time.monotonic() - t0
            self.perf.hinc("op_latency", lat)
            self.perf.hinc2("op_size_latency", cost, lat)
            # workload attribution (ISSUE 10): writes account their
            # payload bytes, reads what they returned.  -EAGAIN bounces
            # (misdirected / not-yet-peered) are NOT accounted — the op
            # was never executed and the client's retry will be, so
            # counting both would inflate the pool's ops over what the
            # client actually submitted.  -ETIMEDOUT admission sheds
            # (ISSUE 17) are excluded for the same reason: the op never
            # executed, only its corpse was returned
            from ..common.errs import EAGAIN, ETIMEDOUT

            if rep.result not in (-EAGAIN, -ETIMEDOUT):
                # real payload bytes, NOT `cost` — the QoS cost floors
                # zero-payload ops (delete/create/truncate) at 4096,
                # which would add phantom write bytes to the pool and
                # client views
                nbytes = (
                    sum(len(op.data) for op in msg.ops)
                    if op_class == "write"
                    else sum(len(d) for d in (rep.outdata or []))
                )
                self.io_accountant.account(
                    msg.pgid.pool, msg.reqid.client, op_class, nbytes, lat
                )
            # tail-based always-keep (ISSUE 10 sampling): an op that
            # crossed the complaint age or errored keeps its FULL trace
            # even when head sampling dropped it — the traces worth
            # reading are exactly the ones sampling must not lose
            if lat >= self.op_tracker.complaint_time or rep.result < 0:
                self.tracer.mark_keep(op_span)
            op_span.event("reply sent")
            op_span.finish()

            async def _send():
                try:
                    await conn.send_message(rep)
                except ConnectionError:
                    pass

            asyncio.get_event_loop().create_task(_send())

        deadline = getattr(msg, "deadline", 0.0)
        if deadline and time.monotonic() > deadline:
            # admission-time deadline shed (ISSUE 17): the client has
            # already given up on this op — queue wait ate its budget —
            # so executing it now would spend OSD time nobody is waiting
            # on.  -ETIMEDOUT back (the objecter maps it to the same
            # TimeoutError a local expiry raises), never executed, and
            # excluded from io-accounting like the -EAGAIN bounce.
            from ..common.errs import ETIMEDOUT

            self.perf.inc("op_deadline_shed")
            op_span.event("deadline expired at admission: shed")
            reply(
                MOSDOpReply(
                    reqid=msg.reqid,
                    result=-ETIMEDOUT,
                    outdata=[],
                    version=0,
                    epoch=self.osdmap.epoch,
                )
            )
            return
        if pg is None:
            from ..common.errs import EAGAIN

            reply(
                MOSDOpReply(
                    reqid=msg.reqid,
                    result=-EAGAIN,
                    outdata=[],
                    version=0,
                    epoch=self.osdmap.epoch,
                )
            )
            return
        for op in msg.ops:
            if op.data:
                self.perf.inc("op_in_bytes", len(op.data))
        op_span.event("reached_pg")
        try:
            pg.do_op(msg, reply, conn)
        except Exception:
            # a faulting op handler must not leak its tracker entry (the
            # reply closure, the only finish() site, will never run)
            self.op_tracker.finish(token)
            op_span.event("op handler raised")
            op_span.finish()
            raise

    async def _op_worker(self) -> None:
        """The op worker (the reference's ShardedThreadPool shards,
        OSD.h:1584, collapsed onto the event loop)."""
        while self._running:
            item = self.sched.dequeue()
            if item is None:
                self._sched_kick.clear()
                await self._sched_kick.wait()
                continue
            try:
                item.run()
            except Exception as e:  # an op must not kill the worker
                dout("osd", 0, f"osd.{self.whoami}: op raised {e!r}")
            await asyncio.sleep(0)

    # -- ordered cluster sends -------------------------------------------------

    def internal_op(
        self,
        pool_id: int,
        oid: str,
        ops: list[OSDOp],
        cb,
        snap_id: int = 0,
        timeout: float = 5.0,
        multi: bool = False,
    ) -> None:
        """One op with this OSD acting as a RADOS client toward the
        object's primary — the objecter leg of COPY_FROM and of the cache
        tier's promote/flush (PrimaryLogPG::do_copy_from / agent_work →
        Objecter).  cb(err, data); with multi=True, cb(err, outdata_list)
        receives every sub-op's outdata (the copy-get data+attrs legs).
        -EAGAIN on timeout or unplaceable target so the calling op
        retries."""
        from ..common.errs import EAGAIN

        empty: object = [] if multi else b""
        _pool, ps = self.osdmap.object_to_pg(pool_id, oid)
        _u, _up, _a, primary = self.osdmap.pg_to_up_acting_osds(pool_id, ps)
        if primary == PG_NONE:
            cb(-EAGAIN, empty)
            return
        self._internal_tid += 1
        tid = self._internal_tid
        self._internal_reads[tid] = (cb, multi)

        def expire() -> None:
            stale = self._internal_reads.pop(tid, None)
            if stale is not None:
                stale[0](-EAGAIN, empty)

        asyncio.get_event_loop().call_later(timeout, expire)
        self.send_cluster(
            primary,
            MOSDOp(
                reqid=ReqId(client=f"osd.{self.whoami}", tid=tid),
                pgid=PgId(pool_id, ps, -1),
                oid=oid,
                ops=ops,
                epoch=self.osdmap.epoch,
                snap_id=snap_id,
            ),
        )

    def internal_read(
        self, pool_id: int, oid: str, snap_id: int, cb, timeout: float = 5.0
    ) -> None:
        """Whole-object fetch via internal_op (cb(err, data))."""
        self.internal_op(
            pool_id, oid, [OSDOp(op=OSDOp.READ)], cb, snap_id=snap_id,
            timeout=timeout,
        )

    def send_cluster(self, osd: int, msg: Message) -> None:
        """Ordered send to a peer OSD by id (cluster messenger)."""
        info = self.osdmap.osds.get(osd)
        if info is None or not info.addr:
            dout("osd", 5, f"osd.{self.whoami}: no addr for osd.{osd}, dropping")
            return
        self._send_addr(info.addr, msg)

    def _send_addr(self, addr: str, msg: Message) -> None:
        q = self._out_q.get(addr)
        if q is None:
            q = self._out_q[addr] = asyncio.Queue()
            self._out_tasks[addr] = asyncio.create_task(self._drain(addr, q))
        q.put_nowait(msg)

    async def _drain(self, addr: str, q: asyncio.Queue) -> None:
        while True:
            msg = await q.get()
            try:
                await self.msgr.send_to(addr, msg)
            except ConnectionError:
                dout("osd", 5, f"osd.{self.whoami}: send to {addr} failed")
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # A malformed message must not wedge the whole peer queue.
                dout(
                    "osd", 0,
                    f"osd.{self.whoami}: dropping unsendable {type(msg).__name__}"
                    f" to {addr}: {e!r}",
                )

    # -- heartbeats ------------------------------------------------------------

    def _hb_peers(self) -> list[int]:
        return [
            o
            for o, info in self.osdmap.osds.items()
            if o != self.whoami and info.up
        ]

    async def _heartbeat_loop(self) -> None:
        interval = self.conf.get("osd_heartbeat_interval")
        while self._running:
            await asyncio.sleep(interval)
            if not self.up:
                # Mon may have missed our boot (election in progress) or the
                # subscription connection reset: renew both (OSD::tick).
                await self._send_boot()
                try:
                    await self.monc.resubscribe()
                except ConnectionError:
                    pass
                continue
            for pg in list(self.pgs.values()):
                pg.tick()
            # cross-PG recovery-storm waves ride the same cadence as the
            # per-PG ticks they coordinate (ISSUE 15); a faulting tick
            # must not kill the heartbeat task — pings, failure reports
            # and mgr beacons all ride this loop
            try:
                self.recovery_storm.tick()
            except Exception as e:
                dout("osd", 0,
                     f"osd.{self.whoami}: recovery-storm tick raised {e!r}")
            self._maybe_periodic_scrub()
            self._send_mgr_report()
            if self.conf.get("heartbeat_inject_failure") > 0:
                continue  # pretend our pings are lost (global.yaml.in:865)
            now = time.monotonic()
            for peer in self._hb_peers():
                self._hb_first_tx.setdefault(peer, now)
                self.send_cluster(
                    peer,
                    MOSDPing(
                        op=MOSDPing.PING,
                        stamp=now,
                        epoch=self.osdmap.epoch,
                        from_osd=self.whoami,
                    ),
                )
            self._heartbeat_check(now)

    def _maybe_periodic_scrub(self) -> None:
        """osd_scrub_interval: kick a shallow scrub on primaried PGs
        whose last periodic scrub is older than the interval (the
        reference's OSD::sched_scrub timer, scaled to the toy tick).
        0 (the default) disables the timer — scrubs then only run on
        operator command, the pre-ISSUE-12 behavior."""
        interval = self.conf.get("osd_scrub_interval")
        if interval <= 0:
            return
        now = time.monotonic()
        for pg in list(self.pgs.values()):
            if not pg.peering.is_primary():
                continue
            # first-seen PGs get a random phase inside the interval so
            # the whole cluster never scrubs (and re-scrubs, since each
            # PG records the same kick time) in one tick — the
            # reference jitters scrub scheduling for the same reason
            last = self._last_periodic_scrub.setdefault(
                pg.pgid, now - random.uniform(0.0, interval)
            )
            if now - last < interval:
                continue
            if pg.scrub(deep=False):
                self._last_periodic_scrub[pg.pgid] = now

    def _heartbeat_check(self, now: float) -> None:
        """heartbeat_check (OSD.cc:5834): report peers past the grace."""
        grace = self.conf.get("osd_heartbeat_grace")
        for peer in self._hb_peers():
            first = self._hb_first_tx.get(peer)
            if first is None:
                continue
            last = self._hb_last_rx.get(peer, first)
            failed_for = now - last
            if failed_for > grace and now - first > grace:
                if peer not in self._reported_failed:
                    self._reported_failed.add(peer)
                    self.perf.inc("heartbeat_failures")
                # re-report at most once per grace period while the peer
                # stays failed (ISSUE 15): reports expire mon-side and a
                # send can die with its connection, so a one-shot report
                # could silently never form a markdown quorum — a dead
                # OSD would stay 'up' forever.  The grace cadence keeps
                # transient event-loop stalls from double-reporting a
                # healthy peer every heartbeat.
                last = self._last_failure_report.get(peer, 0.0)
                if now - last >= grace:
                    self._last_failure_report[peer] = now
                    self._report_failure(peer, failed_for)
            else:
                self._reported_failed.discard(peer)
                self._last_failure_report.pop(peer, None)
        self._laggy_check(now)

    def _laggy_check(self, now: float) -> None:
        """Laggy-OSD detection (ISSUE 17): a peer whose RTT EWMA (ping
        replies + EC sub-read service, _note_peer_rtt) inflates past
        osd_heartbeat_slow_factor x the cluster-median peer EWMA —
        floored at 10 ms absolute so a uniformly-fast mesh never flags
        on microsecond noise — is LAGGY: alive (heartbeats answer) but
        slow, the gray failure the markdown path cannot see.  Reported
        to the mon as a non-fatal MOSDFailure(laggy=1) on the grace
        cadence while it persists; hysteresis (exit at half the enter
        threshold) stops boundary flapping; recovery sends a one-shot
        laggy=2 so the mon retires its OSD_SLOW_PEER evidence."""
        factor = self.conf.get("osd_heartbeat_slow_factor")
        if factor <= 0:
            for peer in list(self._laggy_peers):
                self._laggy_clear(peer)
            return
        samples = sorted(self._peer_rtt_ewma.values())
        if len(samples) < 3:
            return  # too few peers for a meaningful median
        median = samples[len(samples) // 2]
        enter = max(factor * median, LAGGY_RTT_FLOOR)
        grace = self.conf.get("osd_heartbeat_grace")
        for peer, ewma in list(self._peer_rtt_ewma.items()):
            if peer in self._reported_failed:
                # dead beats laggy: the markdown path owns this peer
                if peer in self._laggy_peers:
                    self._laggy_peers.discard(peer)
                    self._laggy_reported.pop(peer, None)
                continue
            if peer not in self._laggy_peers:
                if ewma >= enter:
                    self._laggy_peers.add(peer)
                    self._laggy_reported[peer] = now
                    self._report_failure(peer, ewma, laggy=1)
            elif ewma <= enter / 2.0:
                self._laggy_clear(peer)
            elif now - self._laggy_reported.get(peer, 0.0) >= grace:
                # re-report on the grace cadence: mon-side evidence
                # expires and a send can die with its connection
                self._laggy_reported[peer] = now
                self._report_failure(peer, ewma, laggy=1)

    def _laggy_clear(self, peer: int) -> None:
        self._laggy_peers.discard(peer)
        self._laggy_reported.pop(peer, None)
        self._report_failure(peer, 0.0, laggy=2)

    def laggy_peers(self) -> set[int]:
        """Peers currently flagged laggy — EC read planning (via the PG
        listener) deprioritizes these as sub-read sources."""
        return set(self._laggy_peers)

    def _note_peer_rtt(self, peer: int, rtt: float) -> None:
        """One peer round-trip sample: EWMA for the laggy detector plus
        the aggregate and lazily-declared per-peer RTT histograms."""
        prev = self._peer_rtt_ewma.get(peer)
        self._peer_rtt_ewma[peer] = (
            rtt if prev is None else 0.2 * rtt + 0.8 * prev
        )
        self.perf.hinc("osd_heartbeat_rtt", rtt)
        name = f"osd_heartbeat_rtt_osd_{peer}"
        self.perf.ensure_histogram(name, f"ping/sub-read rtt to osd.{peer} (s)")
        self.perf.hinc(name, rtt)

    def note_subread_rtt(self, peer: int, rtt: float) -> None:
        """EC sub-read service-time sample (PG listener hook): feeds the
        same per-peer EWMA as ping RTT, so a peer that answers pings
        promptly but serves reads slowly still trips laggy detection."""
        if peer == self.whoami:
            return  # self-sends are a function call, not the network
        self._note_peer_rtt(peer, rtt)

    def _report_failure(self, peer: int, failed_for: float, laggy: int = 0) -> None:
        """Report a dead peer to every mon (re-sent on the grace cadence
        by _heartbeat_check while the failure persists; the mon dedupes
        repeats per reporter).  laggy=1/2 reports the non-fatal
        slow-peer state instead (failed_for then carries the RTT EWMA);
        the mon surfaces OSD_SLOW_PEER and never marks the target down."""
        info = self.osdmap.osds.get(peer)
        fail = MOSDFailure(
            target=peer,
            target_addr=info.addr if info else "",
            failed_for=failed_for,
            epoch=self.osdmap.epoch,
            laggy=laggy,
        )
        for name in self.monmap.ranks:
            async def _send(addr=self.monmap.addrs[name]):
                try:
                    await self.monc.msgr.send_to(addr, fail)
                except ConnectionError:
                    dout("osd", 2,
                         f"osd.{self.whoami}: failure report for "
                         f"osd.{peer} lost (mon connection)")

            asyncio.get_event_loop().create_task(_send())

    def _handle_ping(self, conn: Connection, msg: MOSDPing) -> None:
        """handle_osd_ping (OSD.cc:5463)."""
        if msg.op == MOSDPing.PING:
            self.send_cluster(
                msg.from_osd,
                MOSDPing(
                    op=MOSDPing.PING_REPLY,
                    stamp=msg.stamp,
                    epoch=self.osdmap.epoch,
                    from_osd=self.whoami,
                ),
            )
        elif msg.op == MOSDPing.PING_REPLY:
            now = time.monotonic()
            self._hb_last_rx[msg.from_osd] = now
            # ping round-trip (now - our PING's stamp, echoed back):
            # the laggy detector's baseline signal (ISSUE 17)
            self._note_peer_rtt(msg.from_osd, now - msg.stamp)

    # -- misc ------------------------------------------------------------------

    def _on_config_msg(self, msg) -> None:
        """Apply centrally-pushed config (MConfig from the ConfigMonitor) to
        the runtime Config, hitting the same observer path a local `set`
        takes — so e.g. QoS/debug knobs change live (md_config_t::
        apply_changes; ConfigMonitor push in the reference).  Options that
        were mon-managed in a previous push but absent now (`config rm`)
        revert to their defaults; unchanged values are skipped so
        observers fire only on real changes."""
        import json as _json

        changes = _json.loads(msg.changes.decode())
        dropped = set(self._pushed_config) - set(changes)
        for name in dropped:
            try:
                default = self.conf.get_option(name).default
                if self.conf.get(name) != default:
                    self.conf.set(name, default)
                    dout("osd", 10, f"osd.{self.whoami} config revert: {name}")
            except KeyError:
                pass
        self._pushed_config = set(changes)
        for name, value in changes.items():
            try:
                if self.conf.get(name) == self.conf.get_option(name).parse(value):
                    continue
                self.conf.set(name, value)
                dout("osd", 10, f"osd.{self.whoami} config push: {name}={value}")
            except (KeyError, ValueError) as e:
                dout("osd", 5, f"osd.{self.whoami} config push skipped {name}: {e}")

    def cluster_log(
        self,
        prio: str,
        msg: str,
        channel: str = "cluster",
        code: str | None = None,
    ) -> None:
        """Structured cluster-log entry (clog → ClusterLogClient →
        LogMonitor): batched, deduped and rate-limited client-side, then
        committed through the mons' paxos so the whole quorum holds the
        same timeline."""
        dout("osd", 0 if prio == "error" else 5,
             f"osd.{self.whoami} clog: {msg}")
        if self._running:
            self.clogc.log(prio, msg, channel=channel, code=code)

    def clog_error(self, msg: str) -> None:
        """Cluster-log error: recorded locally and shipped to the mons'
        LogMonitor (the EC CRC-mismatch sink, src/osd/ECBackend.cc:1080)."""
        self.clog.append(msg)
        self.cluster_log("error", msg)

    def _clog_transitions(self, status: dict) -> None:
        """Diff the beacon's status blob against the last one and emit
        cluster-log entries for the load-bearing transitions that used
        to live only in dout: device-backend DEGRADED/heal and the HBM
        pressure stages (ISSUE 16)."""
        tb = status.get("tpu_backend") or {}
        degraded = bool(tb.get("degraded"))
        if degraded != self._clog_degraded:
            self._clog_degraded = degraded
            if degraded:
                self.cluster_log(
                    "warn",
                    "TPU backend DEGRADED: "
                    f"{tb.get('reason') or 'unknown'} (host fallback engaged)",
                    code="TPU_BACKEND_DEGRADED",
                )
            else:
                self.cluster_log(
                    "info",
                    "TPU backend healed: device launches resumed",
                    code="TPU_BACKEND_DEGRADED",
                )
        hp = status.get("hbm_pressure") or {}
        stage = int(hp.get("stage") or 0)
        if stage != self._clog_hbm_stage:
            prev = self._clog_hbm_stage
            self._clog_hbm_stage = stage
            if stage > prev:
                self.cluster_log(
                    "warn",
                    f"HBM pressure stage {stage} "
                    f"({hp.get('stage_name', '?')}) engaged: "
                    f"residency ratio {hp.get('ratio', 0.0)}",
                    code="TPU_HBM_PRESSURE",
                )
            else:
                self.cluster_log(
                    "info",
                    f"HBM pressure relieved (stage {prev} -> {stage}): "
                    f"residency ratio {hp.get('ratio', 0.0)}",
                    code="TPU_HBM_PRESSURE",
                )

    def num_pgs(self) -> int:
        return len(self.pgs)

    def all_clean(self) -> bool:
        return all(pg.is_clean for pg in self.pgs.values() if pg.peering.is_primary())


def _osd_status(osd: "OSD") -> dict:
    """The status blob the mgr aggregates (DaemonServer daemon status)."""
    pool_objects: dict[str, int] = {}
    pool_bytes: dict[str, int] = {}
    pool_stored: dict[str, int] = {}
    pool_heads: dict[str, int] = {}
    progress: dict[str, list] = {}
    scrub_errors: dict[str, dict] = {}
    slow_count, slow_oldest = osd.op_tracker.slow_ops()
    for pg in osd.pgs.values():
        events = pg.progress_status()
        if events:
            progress[f"{pg.pool.id}.{pg.ps}"] = events
        # scrub inconsistencies from the PGs this OSD primaries (ISSUE 9
        # satellite): the last scrub's errors ride the status blob so
        # the mgr digest and the mon's OSD_SCRUB_ERRORS / PG_DAMAGED
        # HEALTH_ERR can see them — before this they only hit clog and
        # vanished.  Cleared by a later clean scrub (last_result
        # replaced) or by repair rebuilding every bad shard.
        last = pg.scrubber.last_result
        if (
            pg.peering.is_primary()
            and last is not None
            and last.errors
            and not last.aborted
            # a repair scrub that re-queued every inconsistent object
            # for recovery counts as handled: recovery rebuilds the
            # shards, and the next scrub confirms — holding HEALTH_ERR
            # through that window would punish the operator for
            # running `pg repair` exactly as intended
            and last.repaired < len(last.inconsistent)
        ):
            scrub_errors[f"{pg.pool.id}.{pg.ps}"] = {
                "errors": last.errors,
                "deep": last.deep,
                "repaired": last.repaired,
                "inconsistent": {
                    oid: {str(osd_id): why for osd_id, why in bad.items()}
                    for oid, bad in last.inconsistent.items()
                },
            }
        pid = str(pg.pool.id)
        pool_objects[pid] = pool_objects.get(pid, 0) + pg.local_object_count()
        pool_bytes[pid] = pool_bytes.get(pid, 0) + pg.local_bytes_used()
        if pg.peering.is_primary():
            # logical ("STORED") bytes + head counts, counted once from
            # primaries only
            heads = pg.list_heads()
            pool_stored[pid] = pool_stored.get(pid, 0) + sum(
                pg.logical_object_size(o) for o in heads
            )
            pool_heads[pid] = pool_heads.get(pid, 0) + len(heads)
    hbm_pools, hbm_pressure = _hbm_status()
    return {
        "num_pgs": len(osd.pgs),
        "up": osd.up,
        "osdmap_epoch": osd.osdmap.epoch,
        "clog_errors": len(osd.clog),
        # per-pool local object counts — the pg-stats slice the autoscaler
        # needs to verify a pool is empty before a pg_num change
        # (the reference's richer MPGStats -> mgr flow)
        "pool_objects": pool_objects,
        # raw bytes on this OSD (replicas/shards multi-count, `ceph df`
        # USED) and primary-only logical bytes (`ceph df` STORED)
        "pool_bytes": pool_bytes,
        "pool_stored": pool_stored,
        "pool_heads": pool_heads,
        # in-flight ops older than osd_op_complaint_time (OpTracker) —
        # aggregated by the mgr into the digest that raises SLOW_OPS
        "slow_ops": {"count": slow_count, "oldest_sec": slow_oldest},
        # workload attribution (ISSUE 10): cumulative per-pool /
        # per-client ops, bytes and log2 latency histograms from the op
        # reply + recovery paths — the mgr iostat module merges these
        # across OSDs into windowed rates, top-client views, and the
        # SLO burn-rate evaluation
        "pool_io": osd.io_accountant.dump_pools(),
        "client_io": osd.io_accountant.dump_clients(),
        # trace-sampling verdicts (sampled/kept/dropped + live knobs)
        "trace_sampling": osd.tracer.sampling_stats(),
        # per-PG recovery/backfill/scrub progress events from the
        # primaries this OSD hosts (PG.progress_status) — the mgr's
        # progress module turns them into bars with rate + ETA and the
        # PG_RECOVERY_STALLED health check
        "progress": progress,
        # device-backend verdict (ops/guard.py): the mgr aggregates this
        # into the digest slice the TPU_BACKEND_DEGRADED health check
        # (mon HEALTH_WARN + mgr prometheus healthcheck gauge) reads
        "tpu_backend": _tpu_backend_status(),
        # HBM mempool ledger + pressure verdict (ISSUE 13): per-pool
        # residency for the ceph_tpu_mempool_* scrape families, and the
        # pressure evaluation (which also APPLIES the staged trims) the
        # TPU_HBM_PRESSURE health check reads — the status beacon is
        # the periodic driver of the pressure loop
        "hbm_mempools": hbm_pools,
        "hbm_pressure": hbm_pressure,
        # per-PG scrub inconsistencies from this OSD's primaries —
        # aggregated by the mgr into the digest slice the mon's
        # OSD_SCRUB_ERRORS / PG_DAMAGED HEALTH_ERR checks read
        "scrub_errors": scrub_errors,
        # whole-OSD rebuild progress (ISSUE 15): the storm controller's
        # bar — the mgr progress module aggregates these across daemons
        # into per-victim rebuild bars with rate + ETA
        "recovery_storm": osd.recovery_storm.status(),
        # gray-failure tolerance (ISSUE 17): peers this OSD currently
        # sees as laggy plus its hedge/shed ledger — the evidence trail
        # behind the mon's OSD_SLOW_PEER detail and the chaos harness's
        # hedge-rate assertions
        "slow_peers": {
            "laggy": sorted(osd._laggy_peers),
            "hedge_reads": osd.perf.get("ec_hedge_reads"),
            "hedge_wins": osd.perf.get("ec_hedge_wins"),
            "hedge_denied": osd.perf.get("ec_hedge_denied"),
            "op_deadline_shed": osd.perf.get("op_deadline_shed"),
            "subread_deadline_shed": osd.perf.get("subread_deadline_shed"),
        },
    }


def _hbm_status() -> tuple[dict, dict]:
    """(per-pool ledger snapshot, pressure verdict) for the status blob.
    The pressure call is the EVALUATING one — each beacon re-checks the
    ratio and applies/releases the staged trims, so a runtime target
    change takes effect within one report interval."""
    from ..common.mempool import ledger as hbm_ledger

    led = hbm_ledger()
    return led.snapshot(), led.check_pressure()


def _tpu_backend_status() -> dict:
    from ..ops import dispatch as ec_dispatch
    from ..ops.guard import device_guard

    snap = device_guard().snapshot()
    return {
        "degraded": bool(snap["degraded"]),
        "degraded_for_sec": snap["degraded_for_sec"],
        "reason": snap["reason"],
        "fallback_launches": ec_dispatch.FALLBACK_LAUNCHES.snapshot()["launches"],
    }
