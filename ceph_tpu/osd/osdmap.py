"""OSDMap — mirror of src/osd/OSDMap.{h,cc}.

The epoch-versioned cluster map: OSD states (up/down, in/out via
reweight), pools with their CRUSH rule + EC profile, and the
object→PG→OSDs mapping pipeline
(/root/reference/src/osd/OSDMap.cc:2604 `_pg_to_raw_osds` →
crush_do_rule; :2857 `pg_to_up_acting_osds`).  Erasure-coded pools use an
`indep` rule so down shards appear as PG_NONE holes with stable shard
identity — ECBackend depends on that.

Maps are Encodable and propagate as either full maps or Incrementals
(OSDMap::Incremental), exactly like the mon→OSD flow in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..common.encoding import Decoder, Encodable, Encoder
from ..crush import CRUSH_ITEM_NONE, CrushWrapper, crush_hash32_2, str_hash
from ..crush.crush import WEIGHT_ONE

PG_NONE = CRUSH_ITEM_NONE  # missing shard sentinel (CRUSH_ITEM_NONE)

POOL_TYPE_REPLICATED = 1
POOL_TYPE_ERASURE = 3

FLAG_EC_OVERWRITES = 1 << 0  # pool flag (osd_types.h:1222)
FLAG_FULL_QUOTA = 1 << 1     # pool hit its quota (pg_pool_t FLAG_FULL_QUOTA)


def advance_map(current: "OSDMap", msg) -> "OSDMap":
    """Apply an MOSDMap's full maps / incrementals in epoch order
    (the shared OSD::handle_osd_map / Objecter::handle_osd_map advance
    loop).  Epochs at or below `current.epoch` are skipped; an
    incremental with a gap waits for a full map."""
    out = current
    fulls = {int(e): blob for e, blob in msg.maps.items()}
    incs = {int(e): blob for e, blob in msg.incrementals.items()}
    for epoch in sorted(set(fulls) | set(incs)):
        if epoch <= out.epoch:
            continue
        if epoch in incs and out.epoch == epoch - 1:
            out = Incremental.frombytes(incs[epoch]).apply_to(out)
        elif epoch in fulls:
            out = OSDMap.frombytes(fulls[epoch])
    return out


@dataclass
class OsdInfo:
    """Per-OSD state (OSDMap osd_state/osd_weight/osd_addrs)."""

    up: bool = False
    addr: str = ""  # host:port of the OSD's messenger
    weight: int = WEIGHT_ONE  # reweight 0..0x10000; 0 == out
    last_up_epoch: int = 0
    last_down_epoch: int = 0

    @property
    def in_(self) -> bool:
        return self.weight > 0


@dataclass
class PgPool:
    """pg_pool_t analog (src/osd/osd_types.h)."""

    id: int
    name: str
    type: int = POOL_TYPE_REPLICATED
    size: int = 3  # k+m for EC
    min_size: int = 2
    pg_num: int = 8
    crush_rule: int = 0
    erasure_code_profile: str = ""
    stripe_width: int = 0  # k * stripe_unit for EC (OSDMonitor.cc:7715)
    flags: int = 0
    fast_read: bool = False
    snap_seq: int = 0  # self-managed snap id allocator (pg_pool_t::snap_seq)
    # Cache tiering (pg_pool_t tier_of/read_tier/cache_mode,
    # src/osd/osd_types.h; administered via `osd tier ...`,
    # src/mon/OSDMonitor.cc prepare_command tier block):
    tier_of: int = -1  # base pool this pool is a cache tier FOR
    tiers: list[int] = field(default_factory=list)  # cache pools over this one
    read_tier: int = -1  # overlay: clients redirect ops here (set-overlay)
    cache_mode: str = "none"  # none | writeback | readonly
    target_max_objects: int = 0  # tier agent flush/evict threshold (0 = off)
    # pool quotas (pg_pool_t quota_max_*; `osd pool set-quota`); the mon
    # flips FLAG_FULL_QUOTA from the mgr's PGMap digest when exceeded
    quota_max_bytes: int = 0
    quota_max_objects: int = 0
    # application tag (pg_pool_t application_metadata; `osd pool
    # application enable` — rbd/cephfs/rgw claim their pools)
    application: str = ""

    def is_erasure(self) -> bool:
        return self.type == POOL_TYPE_ERASURE

    def is_cache_tier(self) -> bool:
        return self.tier_of >= 0 and self.cache_mode != "none"

    def raw_pg_to_pps(self, ps: int) -> int:
        """Placement seed: pool id folded into the pg seed
        (OSDMap raw_pg_to_pps)."""
        return crush_hash32_2(ps, self.id)


class OSDMap(Encodable):
    def __init__(self) -> None:
        self.epoch = 0
        self.fsid = ""
        self.osds: dict[int, OsdInfo] = {}
        self.pools: dict[int, PgPool] = {}
        self.pool_name_to_id: dict[str, int] = {}
        self.erasure_code_profiles: dict[str, dict[str, str]] = {}
        self.crush = CrushWrapper()
        # fenced client instance ids (osdmap blocklist; OSDMap.h
        # blocklist): OSDs refuse their ops — the fencing rbd-mirror /
        # cephfs eviction build on
        self.blocklist: set[str] = set()
        self._reweights_cache: dict[int, int] | None = None

    # -- queries -------------------------------------------------------------

    def get_pool(self, name_or_id: str | int) -> PgPool | None:
        if isinstance(name_or_id, int):
            return self.pools.get(name_or_id)
        pid = self.pool_name_to_id.get(name_or_id)
        return None if pid is None else self.pools[pid]

    def is_up(self, osd: int) -> bool:
        info = self.osds.get(osd)
        return bool(info and info.up)

    def object_to_pg(self, pool_id: int, name: str) -> tuple[int, int]:
        """(pool, ps) placement group for an object name
        (object_locator_to_pg: rjenkins str hash mod pg_num)."""
        pool = self.pools[pool_id]
        ps = str_hash(name) % pool.pg_num
        return (pool_id, ps)

    def _reweights(self) -> dict[int, int]:
        if self._reweights_cache is None:
            self._reweights_cache = {
                o: info.weight for o, info in self.osds.items()
            }
        return self._reweights_cache

    def pg_to_raw_osds(self, pool_id: int, ps: int) -> list[int]:
        """CRUSH mapping with reweight rejection (OSDMap.cc:2604)."""
        pool = self.pools[pool_id]
        reweights = self._reweights()
        pps = pool.raw_pg_to_pps(ps)
        raw = self.crush.do_rule(pool.crush_rule, pps, pool.size, reweights)
        if not pool.is_erasure():
            return [o for o in raw if o != PG_NONE]
        # indep rules already emit stable holes; pad to size
        raw = raw + [PG_NONE] * (pool.size - len(raw))
        return raw[: pool.size]

    def pg_to_up_acting_osds(
        self, pool_id: int, ps: int
    ) -> tuple[list[int], int, list[int], int]:
        """(up, up_primary, acting, acting_primary)
        (OSDMap.cc:2857).  Down OSDs are holes in up; acting == up here
        (no pg_temp — recovery backfills through map changes instead)."""
        pool = self.pools[pool_id]
        raw = self.pg_to_raw_osds(pool_id, ps)
        if pool.is_erasure():
            up = [o if o != PG_NONE and self.is_up(o) else PG_NONE for o in raw]
        else:
            up = [o for o in raw if o != PG_NONE and self.is_up(o)]
        primary = next((o for o in up if o != PG_NONE), PG_NONE)
        return up, primary, list(up), primary

    def num_up_osds(self) -> int:
        return sum(1 for i in self.osds.values() if i.up)

    # -- mutations (the mon applies these; OSDs only consume) ----------------

    def add_osd(self, osd: int, addr: str = "", up: bool = True) -> None:
        self.osds[osd] = OsdInfo(up=up, addr=addr)
        self._reweights_cache = None

    def set_osd_state(self, osd: int, up: bool, addr: str | None = None) -> None:
        self._reweights_cache = None
        info = self.osds.setdefault(osd, OsdInfo())
        info.up = up
        if addr is not None:
            info.addr = addr
        if up:
            info.last_up_epoch = self.epoch
        else:
            info.last_down_epoch = self.epoch

    def set_osd_weight(self, osd: int, weight: int) -> None:
        self.osds.setdefault(osd, OsdInfo()).weight = weight
        self._reweights_cache = None

    def create_pool(
        self,
        name: str,
        type: int = POOL_TYPE_REPLICATED,
        size: int = 3,
        min_size: int | None = None,
        pg_num: int = 8,
        crush_rule: int = 0,
        erasure_code_profile: str = "",
        stripe_width: int = 0,
        flags: int = 0,
        fast_read: bool = False,
    ) -> PgPool:
        if name in self.pool_name_to_id:
            raise ValueError(f"pool {name} exists")
        pid = max(self.pools, default=0) + 1
        pool = PgPool(
            id=pid,
            name=name,
            type=type,
            size=size,
            min_size=min_size if min_size is not None else max(size - 1, 1),
            pg_num=pg_num,
            crush_rule=crush_rule,
            erasure_code_profile=erasure_code_profile,
            stripe_width=stripe_width,
            flags=flags,
            fast_read=fast_read,
        )
        self.pools[pid] = pool
        self.pool_name_to_id[name] = pid
        return pool

    # -- encoding ------------------------------------------------------------

    def encode(self, enc: Encoder) -> None:
        # v2 appends the per-pool tiering map AFTER the v1 payload (and
        # v3 the quota map), so older decoders skip the trailers via the
        # frame length (the reference's rolling-upgrade convention,
        # src/include/encoding.h ENCODE_START).
        enc.start(5, 1)
        enc.u32(self.epoch)
        enc.string(self.fsid)
        enc.map_(
            self.osds,
            lambda e, k: e.u32(k),
            lambda e, v: (
                e.boolean(v.up),
                e.string(v.addr),
                e.u32(v.weight),
                e.u32(v.last_up_epoch),
                e.u32(v.last_down_epoch),
            ),
        )
        enc.map_(
            self.pools,
            lambda e, k: e.u32(k),
            lambda e, p: (
                e.string(p.name),
                e.u32(p.type),
                e.u32(p.size),
                e.u32(p.min_size),
                e.u32(p.pg_num),
                e.u32(p.crush_rule),
                e.string(p.erasure_code_profile),
                e.u32(p.stripe_width),
                e.u32(p.flags),
                e.boolean(p.fast_read),
                e.u64(p.snap_seq),
            ),
        )
        enc.map_(
            self.erasure_code_profiles,
            lambda e, k: e.string(k),
            lambda e, prof: e.map_(
                prof, lambda e2, k2: e2.string(k2), lambda e2, v2: e2.string(v2)
            ),
        )
        self.crush.encode(enc)
        # --- v2 trailer: cache tiering ----------------------------------
        tiered = {
            pid: p
            for pid, p in self.pools.items()
            if p.tier_of >= 0 or p.tiers or p.read_tier >= 0
            or p.cache_mode != "none" or p.target_max_objects
        }
        enc.map_(
            tiered,
            lambda e, k: e.u32(k),
            lambda e, p: (
                e.i64(p.tier_of),
                e.list_(p.tiers, lambda e2, t: e2.u32(t)),
                e.i64(p.read_tier),
                e.string(p.cache_mode),
                e.u64(p.target_max_objects),
            ),
        )
        # --- v3 trailer: pool quotas ------------------------------------
        quotas = {
            pid: p
            for pid, p in self.pools.items()
            if p.quota_max_bytes or p.quota_max_objects
        }
        enc.map_(
            quotas,
            lambda e, k: e.u32(k),
            lambda e, p: (
                e.u64(p.quota_max_bytes),
                e.u64(p.quota_max_objects),
            ),
        )
        # --- v4 trailer: client blocklist ---------------------------------
        enc.list_(sorted(self.blocklist), lambda e, c: e.string(c))
        # --- v5 trailer: pool application tags ----------------------------
        apps = {pid: p.application for pid, p in self.pools.items() if p.application}
        enc.map_(apps, lambda e, k: e.u32(k), lambda e, a: e.string(a))
        enc.finish()

    @classmethod
    def decode(cls, dec: Decoder) -> "OSDMap":
        m = cls()
        struct_v = dec.start(5)
        m.epoch = dec.u32()
        m.fsid = dec.string()
        m.osds = dec.map_(
            lambda d: d.u32(),
            lambda d: OsdInfo(
                up=d.boolean(),
                addr=d.string(),
                weight=d.u32(),
                last_up_epoch=d.u32(),
                last_down_epoch=d.u32(),
            ),
        )
        pools = dec.map_(
            lambda d: d.u32(),
            lambda d: dict(
                name=d.string(),
                type=d.u32(),
                size=d.u32(),
                min_size=d.u32(),
                pg_num=d.u32(),
                crush_rule=d.u32(),
                erasure_code_profile=d.string(),
                stripe_width=d.u32(),
                flags=d.u32(),
                fast_read=d.boolean(),
                snap_seq=d.u64(),
            ),
        )
        for pid, kw in pools.items():
            m.pools[pid] = PgPool(id=pid, **kw)
            m.pool_name_to_id[kw["name"]] = pid
        m.erasure_code_profiles = dec.map_(
            lambda d: d.string(),
            lambda d: d.map_(lambda d2: d2.string(), lambda d2: d2.string()),
        )
        m.crush = CrushWrapper.decode(dec)
        if struct_v >= 2:  # noqa: SIM102 — versioned trailers read in order
            tiered = dec.map_(
                lambda d: d.u32(),
                lambda d: dict(
                    tier_of=d.i64(),
                    tiers=d.list_(lambda d2: d2.u32()),
                    read_tier=d.i64(),
                    cache_mode=d.string(),
                    target_max_objects=d.u64(),
                ),
            )
            for pid, kw in tiered.items():
                p = m.pools.get(pid)
                if p is not None:
                    for attr, val in kw.items():
                        setattr(p, attr, val)
        if struct_v >= 3:
            quotas = dec.map_(
                lambda d: d.u32(),
                lambda d: (d.u64(), d.u64()),
            )
            for pid, (qb, qo) in quotas.items():
                p = m.pools.get(pid)
                if p is not None:
                    p.quota_max_bytes, p.quota_max_objects = qb, qo
        if struct_v >= 4:
            m.blocklist = set(dec.list_(lambda d: d.string()))
        if struct_v >= 5:
            apps = dec.map_(lambda d: d.u32(), lambda d: d.string())
            for pid, app in apps.items():
                if pid in m.pools:
                    m.pools[pid].application = app
        dec.finish()
        return m


@dataclass
class Incremental(Encodable):
    """OSDMap::Incremental — the delta the mon publishes per epoch.

    Carries only state changes; structural changes (pools, crush, EC
    profiles) ride a full-map re-encode for simplicity, which the
    reference also supports (full map epochs).
    """

    epoch: int = 0
    new_up: dict[int, str] = field(default_factory=dict)  # osd -> addr
    new_down: list[int] = field(default_factory=list)
    new_weights: dict[int, int] = field(default_factory=dict)
    full_map: bytes = b""  # non-empty => decode and replace wholesale

    def encode(self, enc: Encoder) -> None:
        enc.start(1, 1)
        enc.u32(self.epoch)
        enc.map_(self.new_up, lambda e, k: e.u32(k), lambda e, v: e.string(v))
        enc.list_(self.new_down, lambda e, v: e.u32(v))
        enc.map_(self.new_weights, lambda e, k: e.u32(k), lambda e, v: e.u32(v))
        enc.bytes_(self.full_map)
        enc.finish()

    @classmethod
    def decode(cls, dec: Decoder) -> "Incremental":
        dec.start(1)
        inc = cls(
            epoch=dec.u32(),
            new_up=dec.map_(lambda d: d.u32(), lambda d: d.string()),
            new_down=dec.list_(lambda d: d.u32()),
            new_weights=dec.map_(lambda d: d.u32(), lambda d: d.u32()),
            full_map=dec.bytes_(),
        )
        dec.finish()
        return inc

    def apply_to(self, osdmap: OSDMap) -> OSDMap:
        """OSDMap::apply_incremental; deltas must be the successor epoch
        (the reference asserts inc.epoch == epoch + 1)."""
        if self.full_map:
            new_map = OSDMap.frombytes(self.full_map)
            if new_map.epoch < osdmap.epoch:
                raise ValueError(
                    f"stale full map epoch {new_map.epoch} < current {osdmap.epoch}"
                )
            return new_map
        if self.epoch != osdmap.epoch + 1:
            raise ValueError(
                f"incremental epoch {self.epoch} != map epoch {osdmap.epoch} + 1"
            )
        osdmap.epoch = self.epoch
        for osd, addr in self.new_up.items():
            osdmap.set_osd_state(osd, True, addr)
        for osd in self.new_down:
            osdmap.set_osd_state(osd, False)
        for osd, w in self.new_weights.items():
            osdmap.set_osd_weight(osd, w)
        return osdmap
