"""PGBackend — per-PG storage strategy boundary.

Reference: /root/reference/src/osd/PGBackend.{h,cc}.  `build_pg_backend`
selects Replicated vs EC from the pool type and instantiates the codec via
the plugin registry (PGBackend.cc:570-607, plugin name from
`profile["plugin"]`).  The Listener is the PG's callback surface
(PGBackend::Listener): identity, acting set, version allocation, log
append, missing tracking, and the transport hook.
"""

from __future__ import annotations

import abc
from typing import Callable

from ..codec.base import EINVAL
from ..common.errs import EIO
from ..codec.interface import EcError, ErasureCodeInterface
from ..codec.registry import ErasureCodePluginRegistry
from ..msg.message import Message
from ..msg.messages import (
    MOSDPGPull,
    MOSDPGPush,
    MOSDPGPushReply,
    MOSDRepOp,
    MOSDRepOpReply,
    PgId,
    PushOp,
    ReqId,
)
from ..os.objectstore import ObjectStore, StoreError
from ..os.transaction import Transaction
from ..osd.osdmap import PG_NONE, PgPool
from ..stripe import StripeInfo
from .pg_log import Eversion, LogEntry, LOG_DELETE, LOG_MODIFY


def shard_coll(pgid: PgId, shard: int) -> str:
    """Collection name for a PG shard — coll_t(spg_t(pgid, shard)) analog
    (see ECTransaction.cc:79-95 writing to per-shard collections);
    shard < 0 is the replicated whole-PG collection."""
    base = f"{pgid.pool}.{pgid.ps}"
    return base if shard < 0 else f"{base}s{shard}"


class PGListener(abc.ABC):
    """PGBackend::Listener — what the PG provides its backend."""

    pgid: PgId

    @abc.abstractmethod
    def whoami(self) -> int:
        """This OSD's id."""

    @abc.abstractmethod
    def whoami_shard(self) -> int:
        """This OSD's shard index in the acting set (-1 replicated)."""

    @abc.abstractmethod
    def acting(self) -> list[int]:
        """shard -> osd id (PG_NONE holes for down shards)."""

    @abc.abstractmethod
    def epoch(self) -> int:
        """Current map epoch."""

    @abc.abstractmethod
    def next_version(self) -> Eversion:
        """Allocate the next log version (primary)."""

    @abc.abstractmethod
    def send_shard(self, osd: int, msg: Message) -> None:
        """Transport hook; must loop back when osd == whoami()
        (the primary sends to itself, ECBackend.h:336-338)."""

    def append_log(self, entry: LogEntry) -> None:
        """Shard-side log append."""

    def get_shard_missing(self, oid: str) -> set[int]:
        """Shard indices known to be missing this object."""
        return set()

    def shard_data_source(self, shard: int, oid: str) -> int:
        """The osd that can serve `shard`'s bytes for `oid`, or PG_NONE.

        Default: the acting member, when it is placed and not missing
        the object — the pre-ISSUE-15 sourcing rule.  The PG overrides
        this with stray-shard redirection: when CRUSH slot-fill
        reshuffles an EC acting set, a surviving member's chunks live
        under its OLD shard coll (positional shard identity), and the
        last-clean holder of a slot keeps serving reconstruction reads
        for objects still missing on the new member."""
        from ..osd.osdmap import PG_NONE

        acting = self.acting()
        osd = acting[shard] if shard < len(acting) else PG_NONE
        if osd == PG_NONE or shard in self.get_shard_missing(oid):
            return PG_NONE
        return osd

    def on_local_recover(self, oid: str) -> None:
        pass

    def on_global_recover(self, oid: str) -> None:
        pass

    def clog_error(self, msg: str) -> None:
        pass

    def perf_hist(self, name: str, value: float) -> None:
        """Sample a daemon latency histogram (PGs forward to the OSD's
        PerfCounters; standalone harnesses drop the sample)."""


def side_effect_log_entries(listener: PGListener, pgt) -> list:
    """PG-log entries for a transaction's side-effect objects: the snap
    clone it creates and the trimmed clones it deletes.  Without these a
    replica that missed the write would recover the head but never the
    clone (the reference logs clones from make_writeable the same way)."""
    out = []
    if getattr(pgt, "pre_clone", None):
        out.append(
            LogEntry(
                op=LOG_MODIFY,
                oid=pgt.pre_clone,
                version=listener.next_version(),
                reqid=("", 0),
            )
        )
    for extra in getattr(pgt, "also_delete", ()):
        out.append(
            LogEntry(
                op=LOG_DELETE,
                oid=extra,
                version=listener.next_version(),
                reqid=("", 0),
            )
        )
    return out


class PGBackend(abc.ABC):
    def __init__(self, listener: PGListener, store: ObjectStore):
        self.listener = listener
        self.store = store

    @abc.abstractmethod
    def handle_message(self, msg: Message) -> bool:
        """Dispatch a backend sub-op; True if consumed."""

    @abc.abstractmethod
    def submit_transaction(self, pgt, reqid: ReqId, on_commit: Callable[[], None]) -> int:
        ...

    @abc.abstractmethod
    def objects_read_and_reconstruct(
        self, reads, on_complete: Callable[[dict], None], **kw
    ) -> None:
        ...

    @abc.abstractmethod
    def recover_object(
        self, oid: str, missing_on: set[int], on_complete: Callable[[int], None]
    ) -> None:
        ...

    def flush_encodes(self) -> None:
        """Drain any launched-but-undispatched device encodes (EC encode
        pipeline); a no-op for backends without one."""

    def _apply_pushes(self, coll: str, pushes: list[PushOp]) -> list[str]:
        """Write pushed objects + attrs locally (shared by EC shard pushes
        and replicated whole-object pushes); returns the recovered oids."""
        txn = Transaction()
        oids: list[str] = []
        for push in pushes:
            oids.append(push.oid)
            txn.remove(coll, push.oid)
            txn.touch(coll, push.oid)
            txn.write(coll, push.oid, 0, push.data)
            for name, val in push.attrs.items():
                txn.setattr(coll, push.oid, name, val)
            omap = getattr(push, "omap", None)
            if omap:
                txn.omap_setkeys(coll, push.oid, dict(omap))
        self.store.queue_transaction(txn)
        for oid in oids:
            self.listener.on_local_recover(oid)
        return oids


class ReplicatedBackend(PGBackend):
    """Primary-copy replication (src/osd/ReplicatedBackend.cc): the primary
    applies the transaction locally and fans the same transaction to every
    replica via MOSDRepOp; recovery is whole-object push (with pull when the
    primary itself is missing the object)."""

    def __init__(self, listener: PGListener, store: ObjectStore):
        super().__init__(listener, store)
        self._tid = 0
        self.in_flight: dict[int, tuple[set[int], Callable[[], None]]] = {}
        self.pulling: dict[str, tuple[set[int], Callable[[int], None]]] = {}
        self.pushing: dict[str, tuple[set[int], Callable[[int], None]]] = {}

    def _coll(self) -> str:
        return shard_coll(self.listener.pgid, -1)

    def handle_message(self, msg: Message) -> bool:
        if isinstance(msg, MOSDRepOp):
            self._handle_rep_op(msg)
        elif isinstance(msg, MOSDRepOpReply):
            self._handle_rep_op_reply(msg)
        elif isinstance(msg, MOSDPGPull):
            self._handle_pull(msg)
        elif isinstance(msg, MOSDPGPush):
            self._handle_push(msg)
        elif isinstance(msg, MOSDPGPushReply):
            self._handle_push_reply(msg)
        else:
            return False
        return True

    # -- writes ---------------------------------------------------------------

    def submit_transaction(self, pgt, reqid: ReqId, on_commit: Callable[[], None]) -> int:
        from .ec_transaction import ObjectInfo, OI_ATTR

        self._tid += 1
        tid = self._tid
        coll = self._coll()
        txn = Transaction()
        size = 0
        try:
            size = self.store.stat(coll, pgt.oid)
        except StoreError:
            pass
        version = self.listener.next_version()
        if getattr(pgt, "pre_clone", None) is not None:
            # make_writeable: preserve the pre-write head as the snap clone,
            # atomically with the mutation (PrimaryLogPG::make_writeable).
            txn.clone(coll, pgt.oid, pgt.pre_clone)
        for extra in getattr(pgt, "also_delete", ()):
            txn.remove(coll, extra)  # trimmed snap clones
        if pgt.delete:
            txn.remove(coll, pgt.oid)
        else:
            txn.touch(coll, pgt.oid)
            for off, data in pgt.writes:
                txn.write(coll, pgt.oid, off, data)
                size = max(size, off + len(data))
            if pgt.truncate is not None:
                txn.truncate(coll, pgt.oid, pgt.truncate)
                size = pgt.truncate  # PG pre-resolved the sequential size
            txn.setattr(
                coll, pgt.oid, OI_ATTR,
                ObjectInfo(size=size, version=version.version).encode(),
            )
            for name, val in pgt.attrs.items():
                if val is None:
                    txn.rmattr(coll, pgt.oid, name)
                else:
                    txn.setattr(coll, pgt.oid, name, val)
            if getattr(pgt, "omap_clear", False):
                txn.omap_clear(coll, pgt.oid)
            if getattr(pgt, "omap_rm", None):
                txn.omap_rmkeys(coll, pgt.oid, list(pgt.omap_rm))
            if getattr(pgt, "omap_set", None):
                txn.omap_setkeys(coll, pgt.oid, dict(pgt.omap_set))
        blob = txn.tobytes()
        entry = LogEntry(
            op=LOG_DELETE if pgt.delete else LOG_MODIFY,
            oid=pgt.oid,
            version=version,
            reqid=reqid.key(),
        )
        log_bytes = [entry.tobytes()] + [
            e.tobytes() for e in side_effect_log_entries(self.listener, pgt)
        ]
        targets = {o for o in self.listener.acting() if o != PG_NONE}
        self.in_flight[tid] = (set(targets), on_commit)
        for osd in targets:
            self.listener.send_shard(
                osd,
                MOSDRepOp(
                    pgid=self.listener.pgid,
                    from_osd=self.listener.whoami(),
                    tid=tid,
                    reqid=reqid,
                    txn=blob,
                    log_entries=log_bytes,
                ),
            )
        return tid

    def _handle_rep_op(self, msg: MOSDRepOp) -> None:
        for raw in msg.log_entries:
            self.listener.append_log(LogEntry.frombytes(raw))
        self.store.queue_transaction(Transaction.frombytes(msg.txn))
        self.listener.send_shard(
            msg.from_osd,
            MOSDRepOpReply(
                pgid=msg.pgid,
                from_osd=self.listener.whoami(),
                tid=msg.tid,
            ),
        )

    def _handle_rep_op_reply(self, msg: MOSDRepOpReply) -> None:
        entry = self.in_flight.get(msg.tid)
        if entry is None:
            return
        pending, on_commit = entry
        pending.discard(msg.from_osd)
        if not pending:
            del self.in_flight[msg.tid]
            on_commit()

    # -- reads ----------------------------------------------------------------

    def objects_read_and_reconstruct(
        self, reads, on_complete: Callable[[dict], None], **kw
    ) -> None:
        """Replicated reads are local to the primary."""
        coll = self._coll()
        results: dict[str, tuple[int, list[bytes]]] = {}
        for oid, extents in reads.items():
            try:
                bufs = [self.store.read(coll, oid, off, ln) for off, ln in extents]
                results[oid] = (0, bufs)
            except StoreError as e:
                results[oid] = (e.errno, [])
        on_complete(results)

    # -- recovery -------------------------------------------------------------

    def recover_object(
        self, oid: str, missing_on: set[int], on_complete: Callable[[int], None]
    ) -> None:
        """missing_on holds OSD ids (not shards) for replicated pools."""
        coll = self._coll()
        if self.store.exists(coll, oid):
            self._push_object(oid, missing_on, on_complete)
            return
        # Primary is missing the object: pull from a replica first
        # (ReplicatedBackend::prepare_pull analog).
        sources = (
            {o for o in self.listener.acting() if o != PG_NONE}
            - missing_on
            - {self.listener.whoami()}
        )
        if not sources:
            on_complete(-EIO)
            return
        self.pulling[oid] = (missing_on, on_complete)
        self.listener.send_shard(
            min(sources),
            MOSDPGPull(
                pgid=self.listener.pgid,
                oid=oid,
                epoch=self.listener.epoch(),
                from_osd=self.listener.whoami(),
            ),
        )

    def _push_object(
        self, oid: str, targets: set[int], on_complete: Callable[[int], None]
    ) -> None:
        from .ec_transaction import ObjectInfo, OI_ATTR

        coll = self._coll()
        data = self.store.read(coll, oid, 0, 0)
        attrs = self.store.getattrs(coll, oid)
        omap = self.store.omap_get(coll, oid)
        version = 0
        if OI_ATTR in attrs:
            version = ObjectInfo.decode(attrs[OI_ATTR]).version
        self.pushing[oid] = (set(targets), on_complete)
        for osd in targets:
            self.listener.send_shard(
                osd,
                MOSDPGPush(
                    pgid=self.listener.pgid,
                    pushes=[PushOp(oid=oid, data=data, attrs=attrs,
                                   version=version, omap=omap)],
                    epoch=self.listener.epoch(),
                    from_osd=self.listener.whoami(),
                ),
            )

    def _handle_pull(self, msg: MOSDPGPull) -> None:
        from .ec_transaction import ObjectInfo, OI_ATTR

        coll = self._coll()
        data = self.store.read(coll, msg.oid, 0, 0)
        attrs = self.store.getattrs(coll, msg.oid)
        omap = self.store.omap_get(coll, msg.oid)
        version = 0
        if OI_ATTR in attrs:
            version = ObjectInfo.decode(attrs[OI_ATTR]).version
        self.listener.send_shard(
            msg.from_osd,
            MOSDPGPush(
                pgid=msg.pgid,
                pushes=[PushOp(oid=msg.oid, data=data, attrs=attrs,
                               version=version, omap=omap)],
                epoch=self.listener.epoch(),
                from_osd=self.listener.whoami(),
            ),
        )

    def _handle_push(self, msg: MOSDPGPush) -> None:
        oids = self._apply_pushes(self._coll(), msg.pushes)
        for oid in oids:
            pull = self.pulling.pop(oid, None)
            if pull is not None:
                # pull satisfied; continue with pushes to the real targets
                targets, on_complete = pull
                self._push_object(oid, targets, on_complete)
        self.listener.send_shard(
            msg.from_osd,
            MOSDPGPushReply(
                pgid=msg.pgid,
                oids=oids,
                epoch=self.listener.epoch(),
                from_osd=self.listener.whoami(),
            ),
        )

    def _handle_push_reply(self, msg: MOSDPGPushReply) -> None:
        for oid in msg.oids:
            entry = self.pushing.get(oid)
            if entry is None:
                continue
            pending, on_complete = entry
            pending.discard(msg.from_osd)
            if not pending:
                del self.pushing[oid]
                self.listener.on_global_recover(oid)
                on_complete(0)


def build_pg_backend(
    pool: PgPool,
    profiles: dict[str, dict[str, str]],
    listener: PGListener,
    store: ObjectStore,
) -> PGBackend:
    """PGBackend.cc:570-607: Replicated vs EC selection + codec factory."""
    from ..osd.osdmap import FLAG_EC_OVERWRITES, POOL_TYPE_ERASURE
    from .ec_backend import ECBackend

    if pool.type != POOL_TYPE_ERASURE:
        return ReplicatedBackend(listener, store)
    profile = dict(profiles[pool.erasure_code_profile])
    plugin = profile.get("plugin", "tpu")
    ec = ErasureCodePluginRegistry.instance().factory(plugin, profile)
    k = ec.get_data_chunk_count()
    stripe_width = pool.stripe_width or k * 4096
    chunk_size = ec.get_chunk_size(stripe_width)
    if chunk_size * k != stripe_width:
        # mirror the mon's stripe_unit == chunk_size validation
        # (OSDMonitor.cc:7437-7455)
        raise EcError(
            EINVAL,
            f"stripe_width {stripe_width} not compatible with codec chunk "
            f"size {chunk_size} (k={k})",
        )
    sinfo = StripeInfo(stripe_width, chunk_size)
    return ECBackend(
        listener,
        store,
        ec,
        sinfo,
        allows_overwrites=bool(pool.flags & FLAG_EC_OVERWRITES),
        fast_read=pool.fast_read,
    )
