"""ECBackend — the erasure-coded PG I/O engine.

Reference: /root/reference/src/osd/ECBackend.{h,cc}.  Mirrored machinery:

- Write pipeline: `submit_transaction` -> `start_rmw` builds a WritePlan
  (ECBackend.cc:1882-1906); ops needing partial-stripe reads go through the
  ExtentCache + remote reads (`try_state_to_reads`, :1908-1980); encode fans
  out per-shard ECSubWrite transactions (`try_reads_to_commit`, :1982-2037);
  replies gather in `handle_sub_write_reply` -> commit ack (:1158).
- Reads: `objects_read_and_reconstruct` (:2389) computes the minimum shard
  set via `minimum_to_decode` (:1634-1651), sends ECSubRead to each source
  shard (the primary messages itself, ECBackend.h:336-338), verifies and
  gathers replies (`handle_sub_read_reply`, :1191-1328) with redundant-read
  escalation on error, then decodes.
- Recovery: IDLE -> READING -> WRITING -> COMPLETE state machine
  (ECBackend.h:249-289; `continue_recovery_op` ECBackend.cc:591-746), decode
  of missing shards, push via PushOp.
- `handle_sub_read` reads chunks from the ObjectStore with CLAY subchunk
  fragmented-read support and verifies cumulative crc32c vs hinfo
  (:1023-1156).

TPU-first deltas: encode/decode are batched whole-extent device launches
(ceph_tpu.stripe) instead of per-stripe loops, and the transport is a
listener-provided `send(osd, msg)` hook so the same engine runs under the
asyncio messenger or an in-process test harness.
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import json
import time
from dataclasses import dataclass, field
from typing import Callable, Mapping

import numpy as np

from ..codec.base import EIO
from ..codec.interface import EcError, ErasureCodeInterface
from ..common.errs import ETIMEDOUT
from ..common.fault_injector import faultpoint, faultpoint_delay
from ..common import tracer as tracer_mod
from ..common.tracer import null_span
from ..msg.messages import (
    MOSDECSubOpRead,
    MOSDECSubOpReadReply,
    MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
    MOSDPGPush,
    MOSDPGPushReply,
    PgId,
    PushOp,
    ReqId,
)
from ..ops import flight_recorder as flight_recorder_mod
from ..os.objectstore import ObjectStore, StoreError
from ..os.transaction import Transaction
from ..osd.osdmap import PG_NONE
from ..stripe import HashInfo, StripeInfo
from ..stripe import stripe as stripe_mod
from .extent_cache import ExtentCache
from .pg_backend import PGBackend, PGListener, shard_coll
from .ec_transaction import (
    HINFO_ATTR,
    OI_ATTR,
    ObjectInfo,
    PGTransaction,
    WritePlan,
    _merge_ranges,
    finish_transactions,
    get_write_plan,
    launch_encode,
    launch_encode_delta,
)
from .pg_log import Eversion, LogEntry, LOG_DELETE, LOG_MODIFY


# on-device RMW delta path arm bit (ISSUE 18, `ec_tpu_rmw_delta`):
# process-wide like the device cache it composes with; daemons with a
# live Config re-bind it through their runtime observers (osd.py).
# None = not configured yet — read the option default lazily.
_RMW_DELTA: bool | None = None


def configure_rmw_delta(enabled: bool) -> None:
    """Arm/disarm the on-device RMW delta-encode path (the
    `ec_tpu_rmw_delta` observer hook)."""
    global _RMW_DELTA
    _RMW_DELTA = bool(enabled)


def rmw_delta_enabled() -> bool:
    global _RMW_DELTA
    if _RMW_DELTA is None:
        from ..common.options import OPTIONS

        _RMW_DELTA = bool(OPTIONS["ec_tpu_rmw_delta"].default)
    return _RMW_DELTA


@dataclass
class Op:
    """An in-flight write (ECBackend::Op)."""

    tid: int
    pgt: PGTransaction
    reqid: ReqId
    plan: WritePlan
    version: Eversion
    on_commit: Callable[[], None]
    on_failure: Callable[[int], None] | None = None
    obj_size: int = 0
    read_results: dict[int, bytes] = field(default_factory=dict)  # off -> bytes
    pending_commits: set[int] = field(default_factory=set)  # shard ids
    pin: object | None = None
    encoded: bool = False
    # pre-write device-cache generation (ISSUE 11), captured at submit
    # BEFORE this op projects: the RMW read leg reads exactly the
    # committed pre-write bytes (later same-object writes are tid-ordered
    # behind us), so it may serve them from the device cache at this
    # generation.  None when an earlier in-flight write makes the
    # on-disk bytes ambiguous.
    cache_read_gen: object = None
    # this op's encode took the on-device delta path (ISSUE 18): its
    # launch already committed data + parity into the device cache at
    # the write's generation, so the reap must not re-seed (or
    # invalidate) the cache
    delta: bool = False
    # LAUNCHED device encode awaiting dispatch (EncodeStage); the encode
    # pipeline reaps these FIFO so sub-writes fan out in tid order
    encode_stage: object | None = None
    drain_polls: int = 0
    encode_t0: float = 0.0  # launch time; reap samples ec_encode_latency
    # ec:write span (ECBackend::Op::trace); null span unless a tracer is on
    trace: object = field(default_factory=lambda: null_span())


@dataclass
class ReadRequest:
    """One object's read spec inside a ReadOp."""

    to_read: list[tuple[int, int]]  # logical (off, len) as requested
    stripe_ranges: list[tuple[int, int]]  # stripe-aligned covers
    want_attrs: bool = False


@dataclass
class ReadOp:
    """In-flight reconstruct read (ECBackend::ReadOp)."""

    tid: int
    requests: dict[str, ReadRequest]
    want: set[int]  # shard indices we must reconstruct
    sources: dict[int, int]  # shard -> osd we asked
    subchunks: dict[int, list[tuple[int, int]]]
    on_complete: Callable[[dict], None]
    # shard -> {oid -> list[(off, bytes)]}
    replies: dict[int, dict[str, list[tuple[int, bytes]]]] = field(default_factory=dict)
    attrs: dict[str, dict[str, bytes]] = field(default_factory=dict)
    errors: dict[int, set[str]] = field(default_factory=dict)  # shard -> oids
    tried: set[int] = field(default_factory=set)  # shards already asked
    # recovery consumes the raw gathered shard streams instead of the
    # decoded extents; set by recover_object
    on_complete_raw: Callable[["ReadOp", set[int]], None] | None = None
    trace: object = field(default_factory=lambda: null_span())  # ec:read span
    # per-oid device-cache generation overrides (ISSUE 11): the RMW read
    # leg captures the committed pre-write generation at submit, before
    # its own projection would make `_cache_generation` return None
    cache_generations: dict = field(default_factory=dict)
    # gray-failure tolerance (ISSUE 17): the parent op's absolute
    # monotonic deadline (0.0 = none) rides every sub-read so a doomed
    # read cannot pin shard sources past its budget
    deadline: float = 0.0
    send_ts: dict[int, float] = field(default_factory=dict)  # shard -> sent at
    hedge_shards: set[int] = field(default_factory=set)  # speculative sends
    hedge_timer: object | None = None  # asyncio TimerHandle while armed


# never-reused namespace tokens for the device chunk cache: one per
# ECBackend instance, so entries from a torn-down cluster / failed-over
# primary in the same process can never serve another backend's reads
_CACHE_NS = itertools.count(1)

# hedged-read token-bucket burst (ISSUE 17): the most speculative reads
# the budget can bank; osd_ec_hedge_budget_percent sets the refill rate
HEDGE_BURST = 10.0

RECOVERY_IDLE = "IDLE"
RECOVERY_READING = "READING"
RECOVERY_DECODING = "DECODING"
RECOVERY_WRITING = "WRITING"
RECOVERY_COMPLETE = "COMPLETE"


@dataclass
class RecoveryOp:
    """ECBackend::RecoveryOp (ECBackend.h:249-289), extended with a
    DECODING stage: the device decode is LAUNCHED (or aggregator-windowed)
    when the reads complete, and the pushes fan out when the decode
    pipeline reaps it — so multiple in-flight objects' decodes share one
    aggregated launch during recovery/backfill."""

    oid: str
    missing_on: set[int]  # shard indices to rebuild
    on_complete: Callable[[int], None]  # errno
    state: str = RECOVERY_IDLE
    shard_data: dict[int, bytes] = field(default_factory=dict)
    attrs: dict[str, bytes] = field(default_factory=dict)
    pending_pushes: set[int] = field(default_factory=set)
    # LAUNCHED device decode awaiting reap (stripe.PendingDecode)
    pending_decode: object | None = None
    decode_polls: int = 0
    decode_t0: float = 0.0  # launch time; reap samples ec_decode_latency
    # when the WRITING-stage pushes last fanned out: the stalled-push
    # retry (ISSUE 15) re-sends pending shards past the grace, so a
    # dropped/wedged PushOp cannot park the op in WRITING forever
    push_ts: float = 0.0
    push_retries: int = 0
    trace: object = field(default_factory=lambda: null_span())  # ec:recover


class ECBackend(PGBackend):
    """Per-PG EC engine; one instance per OSD hosting a shard of the PG."""

    def __init__(
        self,
        listener: PGListener,
        store: ObjectStore,
        ec: ErasureCodeInterface,
        sinfo: StripeInfo,
        allows_overwrites: bool = False,
        fast_read: bool = False,
        aggregator=None,
        decode_aggregator=None,
        verify_aggregator=None,
    ):
        super().__init__(listener, store)
        self.ec = ec
        self.sinfo = sinfo
        self.allows_overwrites = allows_overwrites
        self.fast_read = fast_read
        # Cross-write launch aggregation: the default instance is shared
        # process-wide, so concurrent small writes from DIFFERENT PGs on
        # this OSD coalesce into one padded device launch (the bucketed
        # all-reduce analog; window knobs in common/options.py).  The
        # commit barrier (flush_encodes) and the pipe drain flush it.
        from ..codec.matrix_codec import (
            default_decode_aggregator,
            default_encode_aggregator,
            default_verify_aggregator,
        )

        self.encode_aggregator = (
            aggregator if aggregator is not None else default_encode_aggregator()
        )
        # Decode twin: recovery / degraded-read decodes from different
        # PGs coalesce per erasure-pattern signature (the backfill case —
        # one pattern, many objects; ec_tpu_decode_aggregate_* knobs).
        self.decode_aggregator = (
            decode_aggregator
            if decode_aggregator is not None
            else default_decode_aggregator()
        )
        # Verify triplet (ISSUE 9): deep-scrub parity recomputes ride
        # compare-only launches under the background QoS lane
        # (ec_tpu_verify_aggregate_* knobs; osd/scrubber.py submits).
        self.verify_aggregator = (
            verify_aggregator
            if verify_aggregator is not None
            else default_verify_aggregator()
        )
        self.extent_cache = ExtentCache()
        # device-resident chunk cache namespace (ISSUE 11): reads of
        # this PG consult/fill the process-wide HBM cache under a
        # never-reused token, keyed further by (oid, shard, generation)
        self._cache_ns = (next(_CACHE_NS), str(listener.pgid))
        self._tid = 0
        self.in_flight: dict[int, Op] = {}  # write tid -> Op
        self.waiting_reads: list[Op] = []
        self.read_ops: dict[int, ReadOp] = {}
        self.recovery_ops: dict[str, RecoveryOp] = {}
        # Projected object state while writes are in flight (the reference's
        # unstable_hashinfo_registry + projected object contexts): later ops
        # submitted before earlier ones commit must see pending size/hinfo.
        self._projected: dict[str, dict] = {}  # oid -> {size, hinfo, refs}
        # Encode pipeline: ops whose device encode is LAUNCHED but whose
        # sub-writes have not fanned out yet.  Reaped strictly FIFO so
        # log entries reach replicas in version order; bounded by
        # encode_depth (the AIO queue-depth analog).
        self._encode_pipe: list[Op] = []
        self.encode_depth = 8
        # Decode pipeline: RecoveryOps whose device decode is LAUNCHED (or
        # windowed in the decode aggregator) but whose pushes have not
        # fanned out yet.  _continue_recovery reaps FIFO; bounded by
        # decode_depth — the small window of in-flight RecoveryOps whose
        # decodes share an aggregated launch.
        self._decode_pipe: list[RecoveryOp] = []
        self.decode_depth = 8
        # lifetime stalled-push retries (ISSUE 15): the witness chaos
        # reads after wedging pushes with the ec.recover_push seam
        self.push_retries = 0
        # Adaptive hedged reads (ISSUE 17): per-peer EWMA of sub-read
        # round-trips feeds the hedge threshold; the token bucket caps
        # speculative sends at osd_ec_hedge_budget_percent of traffic
        # (each completed sub-read earns pct/100 token, a hedge spends
        # one, burst-bounded so an idle primary cannot bank a storm).
        self._peer_ewma: dict[int, float] = {}  # osd -> EWMA rtt seconds
        self._hedge_tokens = HEDGE_BURST
        # late-loser send ledger: tid -> (retired_at, {shard: (peer,
        # sent_at)}) for sub-reads still outstanding when their ReadOp
        # completed — late replies land their RTT sample here
        self._late_sends: dict[int, tuple[float, dict[int, tuple[int, float]]]] = {}

    # -- helpers -------------------------------------------------------------

    def _span(self, name: str, parent=None):
        """Start a span on the daemon tracer (the ZTracer::Trace threaded
        through every handle_sub_* in the reference, ECBackend.h:64-87);
        harnesses without a tracer get no-op spans.  With no explicit
        parent, the active span (the OSD's osd:op, set by dispatch) is
        adopted so the EC stages join the client's trace instead of
        starting a disconnected root."""
        from ..common.tracer import NULL_TRACER

        if parent is None:
            parent = tracer_mod.current_span()
        if parent is not None:
            return parent.child(name)
        return (getattr(self.listener, "tracer", None) or NULL_TRACER).start_span(name)

    def _perf_hist(self, name: str, value: float) -> None:
        """Sample a daemon latency histogram through the listener (PGs
        forward to the OSD's PerfCounters; harnesses without one drop it)."""
        hook = getattr(self.listener, "perf_hist", None)
        if hook is not None:
            hook(name, value)

    def _perf_inc(self, name: str, n: int = 1) -> None:
        """Bump a daemon counter through the listener (hedge/shed
        accounting; harnesses without the hook drop it)."""
        hook = getattr(self.listener, "perf_inc", None)
        if hook is not None:
            hook(name, n)

    def _conf(self, name: str, default):
        """Runtime-mutable knob through the listener (PGs forward to the
        OSD's Config); harnesses without the hook get the default."""
        hook = getattr(self.listener, "conf_get", None)
        if hook is None:
            return default
        v = hook(name)
        return default if v is None else v

    def _laggy_sources(self) -> set[int]:
        """OSDs the heartbeat subsystem currently flags as laggy (slow
        but alive); sub-read planning deprioritizes them (ISSUE 17)."""
        hook = getattr(self.listener, "laggy_peers", None)
        if hook is None:
            return set()
        return set(hook())

    def _next_tid(self) -> int:
        self._tid += 1
        return self._tid

    @property
    def k(self) -> int:
        return self.ec.get_data_chunk_count()

    @property
    def n(self) -> int:
        return self.ec.get_chunk_count()

    def _shard_colls(self) -> dict[int, str]:
        return {s: shard_coll(self.listener.pgid, s) for s in range(self.n)}

    def _local_coll(self) -> str:
        return shard_coll(self.listener.pgid, self.listener.whoami_shard())

    def get_object_info(self, oid: str) -> ObjectInfo | None:
        try:
            return ObjectInfo.decode(self.store.getattr(self._local_coll(), oid, OI_ATTR))
        except StoreError:
            return None

    def get_hash_info(self, oid: str) -> HashInfo | None:
        """ECBackend::get_hash_info — hinfo from the local shard xattr."""
        try:
            return HashInfo.decode(self.store.getattr(self._local_coll(), oid, HINFO_ATTR))
        except StoreError:
            return None

    def object_size(self, oid: str) -> int:
        oi = self.get_object_info(oid)
        return oi.size if oi else 0

    # -- device-resident chunk cache (ISSUE 11) ------------------------------

    def _chunk_cache(self):
        """The process-wide HBM chunk cache when enabled, else None."""
        from ..ops.device_cache import device_chunk_cache

        cache = device_chunk_cache()
        return cache if cache.enabled else None

    def _cache_obj(self, oid: str):
        return (*self._cache_ns, oid)

    def _cache_generation(self, oid: str):
        """Cache generation for an object's chunks: the committed object
        version.  None while writes are in flight (projected state) —
        mid-RMW bytes must never be cached — or when the primary has no
        local object info to version against.  The RMW read leg is the
        one exception: `submit_transaction` captures this BEFORE its own
        projection and threads it through `ReadOp.cache_generations`, so
        the leg that reads exactly the committed pre-write bytes can
        still consult the cache."""
        if oid in self._projected:
            return None
        oi = self.get_object_info(oid)
        return oi.version if oi is not None else None

    def _available_shards(self, oid: str) -> set[int]:
        """Shards with a live data source for `oid`: the acting member
        when up and not missing it, else a stray holder the listener's
        `shard_data_source` redirection names (ISSUE 15) — a CRUSH
        reshuffle moves a survivor's chunks to the wrong slot, but its
        old coll still serves reconstruction reads."""
        src = getattr(self.listener, "shard_data_source", None)
        acting = self.listener.acting()
        missing = self.listener.get_shard_missing(oid)
        out: set[int] = set()
        for s in range(min(self.n, len(acting))):
            if acting[s] != PG_NONE and s not in missing:
                out.add(s)
            elif src is not None and src(s, oid) != PG_NONE:
                out.add(s)
        return out

    def _shard_source(self, s: int, oids) -> int:
        """The osd a shard-`s` sub-read goes to: the listener's
        stray-aware redirection when available, else the acting member
        (the pre-ISSUE-15 rule).  One ReadOp sends ONE sub-read per
        shard, so a mixed multi-object request whose oids resolve to
        DIFFERENT sources falls back to the acting member — the
        per-object failure then rides the normal redundant-read
        escalation.  (In practice every caller batches one object per
        ReadOp, so the sources agree.)"""
        acting = self.listener.acting()
        osd = acting[s] if s < len(acting) else PG_NONE
        src = getattr(self.listener, "shard_data_source", None)
        if src is None:
            return osd
        chosen = PG_NONE
        for oid in oids:
            alt = src(s, oid)
            if alt == PG_NONE:
                continue
            if chosen == PG_NONE:
                chosen = alt
            elif alt != chosen:
                return osd  # sources disagree: keep the acting member
        return chosen if chosen != PG_NONE else osd

    def _logical_range_to_chunk_extent(self, off: int, length: int) -> tuple[int, int]:
        """Stripe-aligned logical (off, len) -> per-shard chunk (off, len)."""
        assert off % self.sinfo.stripe_width == 0
        assert length % self.sinfo.stripe_width == 0
        return (
            self.sinfo.aligned_logical_offset_to_chunk_offset(off),
            (length // self.sinfo.stripe_width) * self.sinfo.chunk_size,
        )

    # -- message entry point --------------------------------------------------

    def handle_message(self, msg) -> bool:
        if isinstance(msg, MOSDECSubOpWrite):
            self.handle_sub_write(msg)
        elif isinstance(msg, MOSDECSubOpWriteReply):
            self.handle_sub_write_reply(msg)
        elif isinstance(msg, MOSDECSubOpRead):
            self.handle_sub_read(msg)
        elif isinstance(msg, MOSDECSubOpReadReply):
            self.handle_sub_read_reply(msg)
        elif isinstance(msg, MOSDPGPush):
            self.handle_recovery_push(msg)
        elif isinstance(msg, MOSDPGPushReply):
            self.handle_recovery_push_reply(msg)
        else:
            return False
        return True

    # -- write pipeline (§3.1) -----------------------------------------------

    def submit_transaction(
        self,
        pgt: PGTransaction,
        reqid: ReqId,
        on_commit: Callable[[], None],
        on_failure: Callable[[int], None] | None = None,
    ) -> int:
        """Primary-only: start the RMW pipeline (ECBackend.cc:1523,1882).
        on_commit fires when all shards committed; on_failure(errno) fires
        if the RMW read phase fails (the reference asserts here)."""
        tid = self._next_tid()
        proj = self._projected.get(pgt.oid)
        obj_size = proj["size"] if proj else self.object_size(pgt.oid)
        plan = get_write_plan(self.sinfo, pgt, obj_size, self.allows_overwrites)
        version = self.listener.next_version()
        op = Op(
            tid=tid,
            pgt=pgt,
            reqid=reqid,
            plan=plan,
            version=version,
            on_commit=on_commit,
            on_failure=on_failure,
            obj_size=obj_size,
            trace=self._span("ec:write"),
        )
        op.trace.keyval("oid", pgt.oid)
        op.trace.keyval("tid", tid)
        op.trace.event("start ec write")
        # device-cache generation for the RMW read leg (ISSUE 11),
        # captured BEFORE this op projects: with no earlier in-flight
        # write the read leg reads exactly the committed pre-write
        # bytes, so it may serve them from the cache at this generation.
        # Invalidation happens at encode dispatch (the moment the bytes
        # actually change), not here — invalidating now would destroy
        # the very entries the read leg consults.
        op.cache_read_gen = self._cache_generation(pgt.oid)
        if proj is None:
            proj = self._projected[pgt.oid] = {
                "size": obj_size,
                "hinfo": None,
                "hinfo_known": False,
                "refs": 0,
            }
        proj["size"] = plan.new_size
        proj["refs"] += 1
        self.in_flight[tid] = op
        self._start_rmw(op)
        return tid

    def _unref_projected(self, oid: str) -> None:
        proj = self._projected.get(oid)
        if proj is not None:
            proj["refs"] -= 1
            if proj["refs"] <= 0:
                del self._projected[oid]

    def _fail_op_chain(self, op: Op, err: int) -> None:
        """Abort a failed un-encoded op and every LATER un-encoded op on the
        same object: their plans were computed against this op's projected
        state, which was never written.  Projected state resets to disk."""
        oid = op.pgt.oid
        doomed = [op] + [
            o
            for o in list(self.in_flight.values()) + self.waiting_reads
            if o.pgt.oid == oid and o.tid > op.tid and not o.encoded
        ]
        for o in doomed:
            self.in_flight.pop(o.tid, None)
        self.waiting_reads = [o for o in self.waiting_reads if o not in doomed]
        self._projected.pop(oid, None)
        self.listener.clog_error(
            f"{self.listener.pgid}: RMW read for {oid} failed ({err}); "
            f"aborting {len(doomed)} queued write(s)"
        )
        self._kick_waiting_reads()
        for o in doomed:
            o.trace.event(f"aborted: rmw read failed ({err})")
            o.trace.finish()
            if o.on_failure is not None:
                o.on_failure(err)

    def _start_rmw(self, op: Op) -> None:
        # try_state_to_reads: ops on the same object encode strictly in tid
        # order — an earlier un-encoded op may still change the bytes (and
        # hinfo chain) this op depends on.
        if self._blocked_by_earlier(op):
            op.trace.event("waiting on earlier write to same object")
            self.waiting_reads.append(op)
            return
        if not op.plan.to_read:
            self._encode_and_dispatch(op)
            return
        self._issue_rmw_reads(op)

    def _blocked_by_earlier(self, op: Op) -> bool:
        return any(
            other.tid < op.tid and not other.encoded and other.pgt.oid == op.pgt.oid
            for other in self.in_flight.values()
        )

    def _issue_rmw_reads(self, op: Op) -> None:
        need: dict[str, list[tuple[int, int]]] = {}
        for off, ln in op.plan.to_read:
            cached = self.extent_cache.present(op.pgt.oid, off, ln)
            if cached is not None:
                op.read_results[off] = cached
            else:
                need.setdefault(op.pgt.oid, []).append((off, ln))
        if not need:
            op.trace.event("rmw inputs served from extent cache")
            self._encode_and_dispatch(op)
            return
        op.trace.event("issue rmw reads")

        def _on_read(results: dict) -> None:
            if self.in_flight.get(op.tid) is not op:
                # the op was aborted while its reads were in flight (an
                # earlier same-object encode failure doomed it): a stale
                # completion must not resurrect it — encoding it now
                # would persist a write whose client already saw EIO,
                # and the error branch would double-fire on_failure
                return
            err, extents = results[op.pgt.oid]
            if err:
                # The reference asserts here (a decodable PG cannot fail its
                # own RMW read); we fail the op without killing the dispatch
                # loop.  Later ops on the object planned against this op's
                # projected size/bytes, so they abort with it.
                self._fail_op_chain(op, err)
                return
            for (off, _ln), data in zip(need[op.pgt.oid], extents):
                op.read_results[off] = data
            self._encode_and_dispatch(op)

        self.objects_read_and_reconstruct(
            need,
            _on_read,
            parent_span=op.trace,
            cache_generations={op.pgt.oid: op.cache_read_gen},
        )

    def _encode_and_dispatch(self, op: Op) -> None:
        """try_reads_to_commit (ECBackend.cc:1982): LAUNCH the device
        encode, pin the merged bytes, and queue the op on the encode
        pipeline.  The launch returns while the chip works; sub-writes fan
        out when the pipeline reaps the op (FIFO), so the next op's RMW
        reads overlap this op's device encode — the overlap the reference
        gets from queued AIO in front of ec_encode_data."""
        cache = self._chunk_cache()
        op.encode_t0 = time.monotonic()
        stage = None
        # on-device RMW delta (ISSUE 18): when the cache holds EVERY
        # shard of the written regions at the op's pre-write generation,
        # parity updates IN HBM (one launch, zero H2D/D2H on its flight
        # record) and the cache generation bumps in place — no
        # invalidation, no materialize launch.  Preconditions: armed,
        # overwrites pool, an actual RMW (to_read non-empty), an
        # unambiguous pre-write generation, and no truncate (a size
        # change re-shapes regions; not worth delta bookkeeping).
        if (
            cache is not None
            and rmw_delta_enabled()
            and self.allows_overwrites
            and op.plan.to_read
            and op.cache_read_gen is not None
            and op.pgt.truncate is None
        ):
            with tracer_mod.span_scope(op.trace):
                stage = launch_encode_delta(
                    op.pgt,
                    op.plan,
                    self.sinfo,
                    self.ec,
                    op.obj_size,
                    op.read_results,
                    cache,
                    self._cache_obj(op.pgt.oid),
                    op.cache_read_gen,
                    op.version.version,
                )
            if stage is not None:
                op.delta = True
                op.trace.event("delta encode launched (cache hit)")
        if stage is None:
            # overwrite invalidation (ISSUE 11): from here on the
            # object's bytes are changing — this op's RMW read leg
            # (which could still serve the committed pre-write bytes) is
            # complete, so drop the now-stale device-resident chunks
            # (the generation bump would make them miss anyway; this
            # frees HBM eagerly).  Also drops any half-committed
            # new-generation entries from an aborted delta attempt.
            if cache is not None:
                cache.invalidate_object(self._cache_obj(op.pgt.oid))
            # scope the launch under ec:write so codec h2d/kernel_launch
            # sub-spans (codec/tracing.py) and the PendingEncode's reap
            # span attach to this op's trace
            with tracer_mod.span_scope(op.trace):
                stage = launch_encode(
                    op.pgt,
                    op.plan,
                    self.sinfo,
                    self.ec,
                    op.obj_size,
                    op.read_results,
                    aggregator=self.encode_aggregator,
                )
        op.encode_stage = stage
        op.encoded = True
        op.trace.event("encode launched")
        # Pin exactly the bytes that were encoded (host-side, available at
        # launch) so overlapping writes pipeline (ExtentCache
        # reserve_extents_for_rmw): a later same-object op's RMW reads see
        # THESE bytes, not the not-yet-applied shard stores.
        pin = self.extent_cache.prepare_pin()
        for off, buf in op.encode_stage.merged.items():
            self.extent_cache.pin_extent(pin, op.pgt.oid, off, buf)
        op.pin = pin
        self._encode_pipe.append(op)
        # Backpressure: past the queue depth, reap the head now (blocking).
        while len(self._encode_pipe) > self.encode_depth:
            self._dispatch_encoded(self._encode_pipe.pop(0))
        self._schedule_drain()
        # Unblock same-object writers that were waiting on our encode; their
        # RMW inputs come from the pin.
        self._kick_waiting_reads()

    def _schedule_drain(self) -> None:
        """Reap finished encodes from a running event loop; without one
        (synchronous harnesses) the caller drains via flush_encodes()."""
        if not self._encode_pipe:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        loop.call_soon(self._drain_encode_pipe)

    def _drain_encode_pipe(self) -> None:
        """Dispatch every op whose launch finished, strictly FIFO.  A head
        still computing is re-polled a few times, then reaped blocking —
        bounded staleness beats an unbounded poll loop."""
        while self._encode_pipe:
            op = self._encode_pipe[0]
            # A head still sitting in the aggregation window gets the same
            # re-poll grace as a computing one (~100 ms for co-riders to
            # arrive and fill the window) — flushing on first sight would
            # defeat ec_tpu_aggregate_window on the event-loop path, where
            # this drain runs before the next write is even dispatched.
            # After the grace, drain the window: no amount of polling
            # launches a windowed encode.
            if not op.encode_stage.launched() and op.drain_polls >= 50:
                self.encode_aggregator.flush()
            if not op.encode_stage.ready() and op.drain_polls < 50:
                op.drain_polls += 1
                try:
                    asyncio.get_running_loop().call_later(
                        0.002, self._drain_encode_pipe
                    )
                except RuntimeError:
                    pass
                return
            self._dispatch_encoded(self._encode_pipe.pop(0))

    def flush_encodes(self) -> None:
        """Drain the whole encode pipeline (the barrier before commit
        checks in synchronous harnesses; EncodePipeline.flush analog).
        Drains the aggregation window first: a commit barrier must launch
        everything still waiting for co-riders.  A failed aggregated
        launch is sticky on its group — each affected op fails cleanly at
        its own reap below — so the barrier itself never throws.

        Also drains the recovery DECODE pipeline: synchronous harnesses
        (the test clusters' pump loops) use this as their only barrier,
        and a windowed recovery decode must never outlive it."""
        self.encode_aggregator.flush()
        while self._encode_pipe:
            self._dispatch_encoded(self._encode_pipe.pop(0))
        self.flush_decodes()

    def flush_decodes(self) -> None:
        """Drain the recovery decode pipeline: launch every windowed
        decode group and reap every in-flight RecoveryOp decode, fanning
        out its pushes (or failing it cleanly — a failed aggregated
        decode is sticky on its group and surfaces at each op's reap)."""
        self.decode_aggregator.flush()
        while self._decode_pipe:
            self._finish_recovery_decode(self._decode_pipe[0])

    def _csum_submit(self, chunk: bytes, chunk_off: int):
        """EC-transaction fusion (ISSUE 20): a freshly materialized shard
        chunk's per-BLOCK crc32c is submitted into the shared checksum
        offload window right at encode-reap time, so the digests ride the
        same launch cadence as the encodes that produced the bytes; the
        returned ticket lands on the shard Transaction's write as its
        ``csums`` hint (BlueStore skips its stored-form csum pass for raw
        aligned blocks).  Misaligned chunks return None — the store
        computes its own csums as usual."""
        from ..os.bluestore import BLOCK

        if not chunk or chunk_off % BLOCK or len(chunk) % BLOCK:
            return None
        from ..ops.checksum_offload import default_csum_aggregator

        blocks = np.frombuffer(chunk, dtype=np.uint8).reshape(-1, BLOCK)
        return default_csum_aggregator().submit_blocks(blocks)

    def _dispatch_encoded(self, op: Op) -> None:
        """Reap one launched encode and fan out its sub-writes
        (the completion half of try_reads_to_commit)."""
        proj = self._projected.get(op.pgt.oid)
        # hinfo resolves at completion time, in tid order: the projected
        # (pending) chain if an earlier op already produced one, else the
        # on-disk xattr.  None is ambiguous in proj["hinfo"], hence the
        # separate known flag.
        if proj is not None and proj["hinfo_known"]:
            hinfo = proj["hinfo"]
        else:
            hinfo = self.get_hash_info(op.pgt.oid)
        # cache seeding (ISSUE 18): a materialize-path write on an
        # overwrites pool seeds every region's k+m shard chunks into the
        # device cache at its generation — the residency the NEXT RMW's
        # delta path hits.  A delta-path op skips it (its launch already
        # committed data + parity in place, with no host round-trip).
        cache = self._chunk_cache()
        seed = (
            cache is not None
            and rmw_delta_enabled()
            and self.allows_overwrites
            and not op.delta
            and not op.pgt.delete
        )
        # the reap may run from a bare event-loop callback (_drain_encode_pipe):
        # re-enter the op's span scope so materialization sub-spans attach
        with tracer_mod.span_scope(op.trace):
            try:
                txns, new_hinfo, merged = finish_transactions(
                    op.encode_stage,
                    op.pgt,
                    op.plan,
                    self.sinfo,
                    self.ec,
                    self._shard_colls(),
                    op.obj_size,
                    hinfo,
                    op.version.version,
                    chunk_cache=cache if seed else None,
                    cache_obj=(
                        self._cache_obj(op.pgt.oid) if seed else None
                    ),
                    cache_generation=(
                        op.version.version if seed else None
                    ),
                    csum_submit=(
                        self._csum_submit
                        if getattr(self.store, "_csum_offload", False)
                        else None
                    ),
                )
            except EcError as e:
                # a failed (aggregated) encode launch surfaces here, at
                # the op that owns the ticket: fail the op cleanly —
                # release its pin, reset projected state, abort dependent
                # writes — instead of leaking it from a drain callback
                self._fail_encoded_op(op, e)
                return
        op.encode_stage = None
        op.trace.event("encoded")
        if op.encode_t0:
            # launch -> reap: what the OSD's ec_encode_latency histogram
            # attributes to the encode stage
            self._perf_hist("ec_encode_latency", time.monotonic() - op.encode_t0)
        if proj is not None:
            proj["hinfo"] = new_hinfo
            proj["hinfo_known"] = True

        entry = LogEntry(
            op=LOG_DELETE if op.pgt.delete else LOG_MODIFY,
            oid=op.pgt.oid,
            version=op.version,
            reqid=op.reqid.key(),
        )
        acting = self.listener.acting()
        from .pg_backend import side_effect_log_entries

        log_bytes = [entry.tobytes()] + [
            e.tobytes()
            for e in side_effect_log_entries(self.listener, op.pgt)
        ]
        # Register EVERY pending shard before dispatching ANY sub-write:
        # the self-send applies synchronously, and its reply must not see a
        # half-filled pending set (it would commit after the local apply
        # alone, racing the remote shards).
        sends: list[tuple[int, MOSDECSubOpWrite]] = []
        for s in range(self.n):
            osd = acting[s] if s < len(acting) else PG_NONE
            if osd == PG_NONE:
                continue
            op.pending_commits.add(s)
            sends.append(
                (
                    osd,
                    MOSDECSubOpWrite(
                        pgid=self.listener.pgid.with_shard(s),
                        from_osd=self.listener.whoami(),
                        tid=op.tid,
                        reqid=op.reqid,
                        txn=txns[s].tobytes(),
                        at_version=op.version.version,
                        log_entries=log_bytes,
                    ),
                )
            )
        op.trace.event(f"sub-writes dispatched to {len(sends)} shards")
        for osd, msg in sends:
            self.listener.send_shard(osd, msg)
        # Unblock readers that were waiting on our pin.
        self._kick_waiting_reads()

    def _fail_encoded_op(self, op: Op, err: EcError) -> None:
        """Fail an op whose LAUNCHED encode could not be materialized.

        Unlike the RMW-read failure path (where later same-object ops are
        necessarily still un-encoded), by reap time later ops may have
        ALREADY encoded — against projected state embedding this op's
        bytes (their merges read our pin).  Letting one of those commit
        would persist a write the client was told failed, so the abort
        dooms every later same-object op that has not yet dispatched its
        sub-writes, encoded or not.  Negative errno, matching the
        read-failure convention."""
        oid = op.pgt.oid
        errno = -abs(err.errno or EIO)
        # a delta-path op already committed data + parity into the device
        # cache at its (now never-to-commit) generation: drop them —
        # stale generations would miss anyway, but the bytes are dead
        if op.delta:
            cache = self._chunk_cache()
            if cache is not None:
                cache.invalidate_object(self._cache_obj(oid))
        doomed = [op] + [
            o
            for o in list(self.in_flight.values()) + self.waiting_reads
            if o.pgt.oid == oid and o.tid > op.tid and not o.pending_commits
        ]
        for o in doomed:
            self.in_flight.pop(o.tid, None)
        self.waiting_reads = [o for o in self.waiting_reads if o not in doomed]
        self._encode_pipe = [o for o in self._encode_pipe if o not in doomed]
        # Projected state: earlier same-object ops may be DISPATCHED but
        # uncommitted — dropping the projection entirely would let the
        # next write plan against the stale on-disk size while their
        # commits are still landing.  Roll the projection back to the
        # newest survivor's planned state (its reap already set the hinfo
        # chain); only a survivor-free object resets to disk.
        proj = self._projected.get(oid)
        if proj is not None:
            proj["refs"] -= len(doomed)
            survivors = [
                o for o in self.in_flight.values() if o.pgt.oid == oid
            ]
            if proj["refs"] <= 0 or not survivors:
                self._projected.pop(oid, None)
            else:
                proj["size"] = max(survivors, key=lambda o: o.tid).plan.new_size
        self.listener.clog_error(
            f"{self.listener.pgid}: encode launch for {oid} failed ({errno}); "
            f"aborting {len(doomed)} queued write(s)"
        )
        for o in doomed:
            if o.pin is not None:
                self.extent_cache.release_pin(o.pin)
                o.pin = None
            o.encode_stage = None
            o.trace.event(f"aborted: encode launch failed ({errno})")
            o.trace.finish()
            if o.on_failure is not None:
                o.on_failure(errno)
        self._kick_waiting_reads()

    def _kick_waiting_reads(self) -> None:
        ready = [op for op in self.waiting_reads if not self._blocked_by_earlier(op)]
        self.waiting_reads = [op for op in self.waiting_reads if op not in ready]
        for op in ready:
            if op.plan.to_read:
                self._issue_rmw_reads(op)
            else:
                self._encode_and_dispatch(op)

    def handle_sub_write(self, msg: MOSDECSubOpWrite) -> None:
        """Shard-side apply (ECBackend.cc:945): transaction + log append."""
        txn = Transaction.frombytes(msg.txn)
        for raw in msg.log_entries:
            self.listener.append_log(LogEntry.frombytes(raw))
        self.store.queue_transaction(txn)
        reply = MOSDECSubOpWriteReply(
            pgid=msg.pgid,
            from_osd=self.listener.whoami(),
            tid=msg.tid,
            committed=True,
        )
        self.listener.send_shard(msg.from_osd, reply)

    def handle_sub_write_reply(self, msg: MOSDECSubOpWriteReply) -> None:
        op = self.in_flight.get(msg.tid)
        if op is None:
            return
        op.pending_commits.discard(msg.pgid.shard)
        op.trace.event(f"commit from shard {msg.pgid.shard}")
        if not op.pending_commits:
            del self.in_flight[op.tid]
            if op.pin is not None:
                self.extent_cache.release_pin(op.pin)
            self._unref_projected(op.pgt.oid)
            self._kick_waiting_reads()
            op.trace.event("all shards committed")
            op.trace.finish()
            op.on_commit()

    # -- read path (§3.1 reads / §3.2 gather) --------------------------------

    def objects_read_and_reconstruct(
        self,
        reads: Mapping[str, list[tuple[int, int]]],
        on_complete: Callable[[dict], None],
        fast_read: bool | None = None,
        want_attrs: bool = False,
        on_complete_raw: Callable[[ReadOp, set[int]], None] | None = None,
        want_shards: set[int] | None = None,
        parent_span=None,
        cache_generations: Mapping | None = None,
        deadline: float = 0.0,
    ) -> None:
        """Client/RMW/recovery reads with reconstruction
        (ECBackend.cc:2389).  on_complete receives
        {oid: (errno, [bytes per requested extent])}; recovery passes
        on_complete_raw to consume the gathered shard streams directly.
        `deadline` (ISSUE 17) is the parent op's absolute monotonic
        budget: sub-reads inherit it so shards shed work for a read the
        client has already given up on."""
        fast = self.fast_read if fast_read is None else fast_read
        tid = self._next_tid()
        requests: dict[str, ReadRequest] = {}
        for oid, extents in reads.items():
            ranges = [
                self.sinfo.offset_len_to_stripe_bounds(off, ln) for off, ln in extents
            ]
            requests[oid] = ReadRequest(
                to_read=list(extents),
                stripe_ranges=_merge_ranges(ranges),
                want_attrs=want_attrs,
            )
        # minimum shard set over all objects (get_min_avail_to_read_shards)
        avail = set.intersection(*(self._available_shards(o) for o in reads))
        chunk_index = getattr(self.ec, "chunk_index", lambda i: i)
        want = (
            want_shards
            if want_shards is not None
            else {chunk_index(i) for i in range(self.k)}
        )
        trace = self._span("ec:read", parent=parent_span)
        trace.keyval("oids", lambda: ",".join(sorted(reads)))
        trace.keyval("tid", tid)
        try:
            minimum = self.ec.minimum_to_decode(want, avail)
        except EcError:
            trace.event("not decodable from available shards")
            trace.finish()
            on_complete({oid: (-EIO, []) for oid in reads})
            return
        sub_count = self.ec.get_sub_chunk_count()
        preempt: set[int] = set()
        laggy = self._laggy_sources()
        if laggy and not fast:
            # Laggy-peer deprioritization (ISSUE 17): plan the read
            # entirely off non-laggy sources when the stripe allows it;
            # when a laggy source is unavoidable, hedge PREEMPTIVELY —
            # one extra shard up front so the slow peer never sits alone
            # on the critical path.
            oid_list = list(reads)
            srcs = {s: self._shard_source(s, oid_list) for s in avail}
            clean = {s for s in avail if srcs[s] not in laggy}
            if self._decodable(want, clean):
                minimum = self.ec.minimum_to_decode(want, clean)
                trace.event("laggy sources deprioritized")
            else:
                extra = [s for s in avail - set(minimum) if srcs[s] not in laggy]
                if extra and self._hedge_spend():
                    preempt = {
                        min(extra, key=lambda s: self._peer_ewma.get(srcs[s], 0.0))
                    }
                    self._perf_inc("ec_hedge_reads")
                    trace.event(
                        lambda: f"preemptive hedge to shard {sorted(preempt)}"
                        " (laggy source unavoidable)"
                    )
        sources = set(minimum) | preempt
        if fast:
            sources = set(avail)  # redundant reads, first k win (ECBackend.h:371)
        rop = ReadOp(
            tid=tid,
            requests=requests,
            want=want,
            sources={},
            subchunks={s: list(minimum.get(s, [(0, sub_count)])) for s in sources},
            on_complete=on_complete,
            on_complete_raw=on_complete_raw,
            trace=trace,
            cache_generations=dict(cache_generations or {}),
            deadline=deadline,
            hedge_shards=set(preempt),
        )
        self.read_ops[tid] = rop
        self._send_reads(rop, sources)

    def _send_reads(self, rop: ReadOp, shards: set[int]) -> None:
        sub_count = self.ec.get_sub_chunk_count()
        # Register every source before sending: the self-send replies
        # synchronously and must see the complete source set, or the
        # completion check runs against a partial plan.
        sends: list[tuple[int, MOSDECSubOpRead]] = []
        oids = list(rop.requests)
        now = time.monotonic()
        for s in shards:
            osd = self._shard_source(s, oids)
            rop.sources[s] = osd
            rop.tried.add(s)
            rop.send_ts[s] = now
            to_read: dict[str, list[list[int]]] = {}
            for oid, req in rop.requests.items():
                exts = []
                for off, ln in req.stripe_ranges:
                    c_off, c_len = self._logical_range_to_chunk_extent(off, ln)
                    exts.append([c_off, c_len])
                to_read[oid] = exts
            runs = rop.subchunks.get(s, [(0, sub_count)])
            sends.append(
                (
                    osd,
                    MOSDECSubOpRead(
                        pgid=self.listener.pgid.with_shard(s),
                        from_osd=self.listener.whoami(),
                        tid=rop.tid,
                        to_read=to_read,
                        subchunks={
                            oid: [[o, c] for o, c in runs] for oid in rop.requests
                        },
                        attrs_to_read=(
                            list(rop.requests)
                            if any(r.want_attrs for r in rop.requests.values())
                            else []
                        ),
                    ),
                )
            )
        rop.trace.event(lambda: f"sub-reads to shards {sorted(shards)}")
        for osd, msg in sends:
            msg.deadline = rop.deadline  # sub-reads inherit the op budget
            self.listener.send_shard(osd, msg)
        # a self-send above may have completed the op synchronously; the
        # arm helper no-ops (and _retire_rop already cancelled) if so
        self._arm_hedge_timer(rop)

    # -- adaptive hedged reads (ISSUE 17) ------------------------------------

    def _hedge_spend(self) -> bool:
        """Take one token from the hedge budget; False (counted as
        ec_hedge_denied) means plain waiting — the bucket refills as
        sub-reads complete.  osd_ec_hedge_budget_percent <= 0 uncaps."""
        pct = float(self._conf("osd_ec_hedge_budget_percent", 5.0))
        if pct <= 0:
            return True
        if self._hedge_tokens >= 1.0:
            self._hedge_tokens -= 1.0
            return True
        self._perf_inc("ec_hedge_denied")
        return False

    def _hedge_earn(self) -> None:
        """Each completed sub-read banks pct/100 token, burst-bounded."""
        pct = float(self._conf("osd_ec_hedge_budget_percent", 5.0))
        if pct > 0:
            self._hedge_tokens = min(HEDGE_BURST, self._hedge_tokens + pct / 100.0)

    def _hedge_threshold(self, peer: int) -> float:
        """Seconds an outstanding sub-read to `peer` may age before it
        counts as slow: quantile x the peer's EWMA round-trip, floored
        at osd_ec_hedge_min_ms so cold/fast peers don't hedge on noise."""
        q = float(self._conf("osd_ec_hedge_quantile", 3.0))
        floor = float(self._conf("osd_ec_hedge_min_ms", 10.0)) / 1000.0
        return max(q * self._peer_ewma.get(peer, 0.0), floor)

    def _arm_hedge_timer(self, rop: ReadOp) -> None:
        """(Re)schedule the hedge check for the earliest moment an
        outstanding sub-read crosses its slowness threshold.  Inert when
        hedging is disabled, the op is done, or no event loop runs (the
        synchronous test harnesses)."""
        if float(self._conf("osd_ec_hedge_quantile", 3.0)) <= 0:
            return
        if self.read_ops.get(rop.tid) is not rop:
            return  # already retired (synchronous self-send completion)
        outstanding = set(rop.sources) - set(rop.replies) - set(rop.errors)
        if not outstanding:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        now = time.monotonic()
        expiry = min(
            rop.send_ts.get(s, now) + self._hedge_threshold(rop.sources[s])
            for s in outstanding
        )
        if rop.hedge_timer is not None:
            rop.hedge_timer.cancel()
        rop.hedge_timer = loop.call_later(
            max(expiry - now, 0.0), self._hedge_fire, rop.tid
        )

    def _hedge_fire(self, tid: int) -> None:
        """Hedge-timer body: if an outstanding sub-read is past its
        threshold, issue ONE speculative read to the best untried shard
        source (budget permitting).  First k replies win through the
        normal gather; the loser's late reply hits a retired tid and is
        dropped, so a double-count is structurally impossible."""
        rop = self.read_ops.get(tid)
        if rop is None:
            return
        rop.hedge_timer = None
        if float(self._conf("osd_ec_hedge_quantile", 3.0)) <= 0:
            return
        now = time.monotonic()
        outstanding = set(rop.sources) - set(rop.replies) - set(rop.errors)
        overdue = {
            s
            for s in outstanding
            if now - rop.send_ts.get(s, now) >= self._hedge_threshold(rop.sources[s])
        }
        if not overdue:
            self._arm_hedge_timer(rop)  # a reply raced the timer; re-aim
            return
        if rop.deadline and now > rop.deadline:
            return  # doomed read: never spend hedge budget on it
        remaining = (
            set.intersection(*(self._available_shards(o) for o in rop.requests))
            - rop.tried
        )
        if not remaining:
            return  # every source asked; error escalation owns the rest
        if not self._hedge_spend():
            return  # budget exhausted: plain waiting
        oids = list(rop.requests)
        laggy = self._laggy_sources()

        def rank(s: int):
            peer = self._shard_source(s, oids)
            return (peer in laggy, self._peer_ewma.get(peer, 0.0), s)

        s = min(remaining, key=rank)
        rop.subchunks[s] = [(0, self.ec.get_sub_chunk_count())]
        rop.hedge_shards.add(s)
        self._perf_inc("ec_hedge_reads")
        rop.trace.event(
            lambda: f"hedged read to shard {s} (slow shards {sorted(overdue)})"
        )
        self._send_reads(rop, {s})

    def _retire_rop(self, rop: ReadOp) -> None:
        """Drop a ReadOp from the in-flight table and disarm its hedge
        timer; late replies now hit an unknown tid and are reaped.

        Late-loser RTT ledger (ISSUE 17): a hedged-past slow shard's
        reply arrives AFTER the op completes — and that reply carries
        the one signal a gray peer ever emits, its service time.  If the
        late losers were reaped blind, hedging would mask exactly the
        slowness the laggy detector needs to see.  Remember where the
        still-outstanding sub-reads went so `_note_late_reply` can land
        the sample (and the budget earn) before dropping the data."""
        self.read_ops.pop(rop.tid, None)
        t = rop.hedge_timer
        if t is not None:
            rop.hedge_timer = None
            t.cancel()
        outstanding = set(rop.sources) - set(rop.replies) - set(rop.errors)
        sends = {
            s: (rop.sources[s], rop.send_ts[s])
            for s in outstanding
            if rop.sources.get(s, PG_NONE) != PG_NONE and s in rop.send_ts
        }
        if sends:
            self._late_sends[rop.tid] = (time.monotonic(), sends)
            self._prune_late_sends()

    # answers for retired tids stay attributable this long; anything
    # later is a dead peer's ghost, not a service-time signal
    LATE_SEND_TTL = 120.0

    def _prune_late_sends(self) -> None:
        cutoff = time.monotonic() - self.LATE_SEND_TTL
        for tid in [
            t for t, (at, _s) in self._late_sends.items() if at < cutoff
        ]:
            del self._late_sends[tid]

    def _sample_peer_rtt(self, peer: int, rtt: float) -> None:
        """One sub-read service-time sample: feeds the per-peer hedge
        threshold EWMA and (through the listener) the OSD-level laggy
        detector."""
        prev = self._peer_ewma.get(peer)
        self._peer_ewma[peer] = rtt if prev is None else 0.2 * rtt + 0.8 * prev
        hook = getattr(self.listener, "note_peer_rtt", None)
        if hook is not None:
            hook(peer, rtt)

    def _note_late_reply(self, msg: MOSDECSubOpReadReply) -> None:
        """A reply for a retired ReadOp: sample the peer's service time
        from the late-send ledger (the slow peer a hedge raced past is
        the laggy detector's prime witness), earn back the hedge budget
        for the completed sub-read, then reap the payload unread — the
        op already completed, so counting its data twice is impossible."""
        entry = self._late_sends.get(msg.tid)
        if entry is None:
            return
        _retired_at, sends = entry
        rec = sends.pop(msg.pgid.shard, None)
        if not sends:
            del self._late_sends[msg.tid]
        if rec is None:
            return
        peer, sent = rec
        self._sample_peer_rtt(peer, time.monotonic() - sent)
        self._hedge_earn()

    def handle_sub_read(self, msg: MOSDECSubOpRead) -> None:
        """Shard-side read (ECBackend.cc:1023-1156): extents (with CLAY
        subchunk runs) + cumulative crc verification on whole-shard reads."""
        coll = shard_coll(self.listener.pgid, msg.pgid.shard)
        buffers: dict[str, list[list[bytes]]] = {}
        attrs: dict[str, dict[str, bytes]] = {}
        errors: dict[str, int] = {}
        deadline = getattr(msg, "deadline", 0.0)
        if deadline and time.monotonic() > deadline:
            # Sub-read deadline shed (ISSUE 17): the parent op's budget
            # is spent, so the client already gave up — answer every
            # object -ETIMEDOUT without touching the store, releasing
            # this shard source immediately instead of pinning it.
            self._perf_inc("subread_deadline_shed")
            self.listener.send_shard(
                msg.from_osd,
                MOSDECSubOpReadReply(
                    pgid=msg.pgid,
                    from_osd=self.listener.whoami(),
                    tid=msg.tid,
                    buffers={},
                    attrs={},
                    errors={oid: -ETIMEDOUT for oid in msg.to_read},
                ),
            )
            return
        # gray-failure injection (ec.sub_read delay_ms mode): answer
        # correctly but late — the reply is deferred below, off-loop.
        # Scoped by daemon identity so a harness can gray ONE shard
        # source while its peers stay fast.
        inject_delay = faultpoint_delay(
            "ec.sub_read", who=f"osd.{self.listener.whoami()}"
        )
        sub_count = self.ec.get_sub_chunk_count()
        for oid, extents in msg.to_read.items():
            runs = [tuple(r) for r in msg.subchunks.get(oid, [[0, sub_count]])]
            out: list[list[bytes]] = []
            try:
                # shard-side EIO injection (ec.sub_read): answers this
                # object with an error, driving the primary's redundant-
                # read escalation + reconstruct path
                try:
                    faultpoint("ec.sub_read")
                except Exception as e:
                    raise EcError(EIO, f"injected sub-read fault: {e}")
                shard_size = self.store.stat(coll, oid)
                for off, ln in extents:
                    ln = min(ln, max(shard_size - off, 0))
                    if runs == [(0, sub_count)]:
                        data = self.store.read(coll, oid, off, ln)
                        if off == 0 and ln == shard_size:
                            self._verify_hinfo(coll, oid, msg.pgid.shard, data)
                    else:
                        # CLAY fragmented read (ECBackend.cc:1047-1068): the
                        # subchunk runs select planes within EACH stripe-chunk
                        # of the extent.
                        cs = self.sinfo.chunk_size
                        sub_sz = cs // sub_count
                        parts = []
                        for block in range(off, off + ln, cs):
                            parts.extend(
                                self.store.read(
                                    coll, oid, block + o * sub_sz, c * sub_sz
                                )
                                for o, c in runs
                            )
                        data = b"".join(parts)
                    out.append([_u64b(off), data])
                buffers[oid] = out
                if oid in msg.attrs_to_read:
                    attrs[oid] = self.store.getattrs(coll, oid)
            except (StoreError, EcError) as e:
                errors[oid] = getattr(e, "errno", -EIO)
        reply = MOSDECSubOpReadReply(
            pgid=msg.pgid,
            from_osd=self.listener.whoami(),
            tid=msg.tid,
            buffers=buffers,
            attrs=attrs,
            errors=errors,
        )
        if inject_delay > 0:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                loop = None  # sync harness: delay inert, answer now
            if loop is not None:
                # the gray shard: correct bytes, late — deferred on the
                # event loop so the injected latency never blocks it
                loop.call_later(
                    inject_delay, self.listener.send_shard, msg.from_osd, reply
                )
                return
        self.listener.send_shard(msg.from_osd, reply)

    def _verify_hinfo(self, coll: str, oid: str, shard: int, data: bytes) -> None:
        try:
            hinfo = HashInfo.decode(self.store.getattr(coll, oid, HINFO_ATTR))
        except StoreError:
            return  # overwrite pool / no hinfo: crc lives off-path
        if hinfo.get_total_chunk_size() == len(data) and not hinfo.verify_chunk(shard, data):
            self.listener.clog_error(
                f"{self.listener.pgid}: shard {shard} crc mismatch on {oid}"
            )
            raise EcError(EIO, f"chunk crc mismatch on {oid} shard {shard}")

    def handle_sub_read_reply(self, msg: MOSDECSubOpReadReply) -> None:
        """Gather + decodability check + redundant-read escalation
        (ECBackend.cc:1191-1328)."""
        rop = self.read_ops.get(msg.tid)
        if rop is None:
            # late loser (completed/hedged-past op): its service time
            # still feeds the laggy detector, then the payload is reaped
            self._note_late_reply(msg)
            return
        shard = msg.pgid.shard
        # per-peer service-time EWMA (ISSUE 17): every sub-read round
        # trip feeds the hedge threshold AND the OSD's laggy detector
        sent = rop.send_ts.get(shard)
        peer = rop.sources.get(shard, PG_NONE)
        if sent is not None and peer != PG_NONE:
            self._sample_peer_rtt(peer, time.monotonic() - sent)
        self._hedge_earn()
        rop.trace.event(
            lambda: f"reply from shard {shard}"
            + (f" with errors {sorted(msg.errors)}" if msg.errors else "")
        )
        if msg.errors:
            rop.errors.setdefault(shard, set()).update(msg.errors)
        if msg.buffers:
            rop.replies[shard] = {
                oid: [(int.from_bytes(off, "little"), data) for off, data in exts]
                for oid, exts in msg.buffers.items()
            }
        for oid, att in msg.attrs.items():
            rop.attrs.setdefault(oid, {}).update(att)
        self._check_read_op(rop)

    def _check_read_op(self, rop: ReadOp) -> None:
        good = {
            s
            for s in rop.replies
            if not rop.errors.get(s)
        }
        sub_count = self.ec.get_sub_chunk_count()
        fragmented = any(
            [tuple(r) for r in runs] != [(0, sub_count)]
            for runs in rop.subchunks.values()
        )
        if fragmented:
            # The fragment plan (e.g. CLAY repair planes) is fixed at issue
            # time: ALL planned helpers must answer; a failed helper voids
            # the plan and we fall back to full-chunk reads.
            planned = set(rop.subchunks)
            if planned <= good:
                self._retire_rop(rop)
                self._complete_read_op(rop, good)
                return
            if planned - set(rop.replies) - set(rop.errors):
                return  # still outstanding
            avail = (
                set.intersection(*(self._available_shards(o) for o in rop.requests))
                - set(rop.errors)
            )
            rop.trace.event("fragment plan voided; full-chunk fallback")
            rop.replies.clear()
            rop.subchunks = {s: [(0, sub_count)] for s in avail}
            self._send_reads(rop, avail)
            return
        needed = set(self.ec.minimum_to_decode(rop.want, good)) if self._decodable(rop.want, good) else None
        if needed is not None and needed <= good:
            self._retire_rop(rop)
            self._complete_read_op(rop, good)
            return
        # not yet decodable: have all asked shards answered?
        outstanding = set(rop.sources) - set(rop.replies) - set(rop.errors)
        if outstanding:
            return
        # escalate: ask shards not yet tried (send_all_remaining_reads)
        remaining = (
            set.intersection(*(self._available_shards(o) for o in rop.requests))
            - rop.tried
        )
        if remaining:
            rop.trace.event(
                f"redundant-read escalation to shards {sorted(remaining)}"
            )
            for s in remaining:
                rop.subchunks[s] = [(0, sub_count)]
            self._send_reads(rop, remaining)
            return
        self._retire_rop(rop)
        rop.trace.event("read failed: no decodable shard set")
        rop.trace.finish()
        rop.on_complete({oid: (-EIO, []) for oid in rop.requests})

    def _decodable(self, want: set[int], have: set[int]) -> bool:
        try:
            self.ec.minimum_to_decode(want, have)
            return True
        except EcError:
            return False

    def _complete_read_op(self, rop: ReadOp, good: set[int]) -> None:
        if rop.hedge_shards & good:
            # a speculative read answered in time to join the decode set:
            # the hedge paid for itself (win-rate vs ec_hedge_reads)
            self._perf_inc("ec_hedge_wins")
        if rop.on_complete_raw is not None:
            rop.trace.event("raw shard streams handed to recovery")
            rop.trace.finish()
            rop.on_complete_raw(rop, good)
            return
        results: dict[str, tuple[int, list[bytes]]] = {}

        def reconstruct_all() -> None:
            # Two-phase: SUBMIT every object's decode as a ticket first,
            # then materialize.  With the decode window open (window > 1)
            # same-pattern objects in this ReadOp land in one aggregation
            # group and the first materialization reaps it as one padded
            # launch; at the default window (<= 1, immediate mode) each
            # submission dispatches on its own, exactly like the direct
            # path always did.
            launched: dict[str, list] = {}
            for oid, req in rop.requests.items():
                try:
                    launched[oid] = self._launch_reconstruct(rop, oid, req, good)
                except EcError as e:
                    results[oid] = (e.errno, [])
            for oid, pends in launched.items():
                try:
                    results[oid] = (0, self._finish_reconstruct(pends))
                except EcError as e:
                    results[oid] = (e.errno, [])

        # hedge flag on the flight records (ISSUE 17): decode launches
        # fed by a winning speculative sub-read carry "hedged", so the
        # Perfetto timeline shows WHICH launches a straggler would have
        # stalled.  No-op scope when no hedge shard made the good set.
        hint = (
            flight_recorder_mod.hedged_hint()
            if rop.hedge_shards & good
            else contextlib.nullcontext()
        )
        if not rop.want <= good:
            t0 = time.monotonic()
            # decode path: spans make the degraded read visible end to end
            with rop.trace.child("ec:reconstruct") as sp:
                sp.keyval("have", ",".join(map(str, sorted(good))))
                sp.keyval("want", ",".join(map(str, sorted(rop.want))))
                with tracer_mod.span_scope(sp), hint:
                    reconstruct_all()
            self._perf_hist("ec_decode_latency", time.monotonic() - t0)
        else:
            with tracer_mod.span_scope(rop.trace), hint:
                reconstruct_all()
        rop.trace.event("read complete")
        rop.trace.finish()
        rop.on_complete(results)

    def _reconstruct_object(
        self, rop: ReadOp, oid: str, req: ReadRequest, good: set[int]
    ) -> list[bytes]:
        """Decode one object's extents from gathered shard buffers."""
        return self._finish_reconstruct(
            self._launch_reconstruct(rop, oid, req, good)
        )

    def _launch_reconstruct(
        self, rop: ReadOp, oid: str, req: ReadRequest, good: set[int]
    ) -> list[tuple[int, int, int, "stripe_mod.PendingDecode"]]:
        """SUBMIT one object's extent decodes (tickets via the shared
        DecodeAggregator) without materializing — phase one of the
        reconstruct, so concurrent objects coalesce into one launch.

        Device-cache consult (ISSUE 11): the decode launcher checks the
        HBM chunk cache for the missing chunks FIRST — a repeated
        degraded read (or the read leg of a degraded RMW cycle, which
        flows through the same path) of an unchanged object serves from
        the device with one D2H copy, skipping the survivor H2D and the
        kernel entirely; a miss caches its reconstruction for next time.
        """
        cache = self._chunk_cache()
        if cache is None:
            gen = None
        elif oid in rop.cache_generations:
            # RMW read leg: the submit-time pre-write generation (our own
            # projection would make _cache_generation return None)
            gen = rop.cache_generations[oid]
        else:
            gen = self._cache_generation(oid)
        out = []
        for off, ln in req.to_read:
            s_off, s_len = self.sinfo.offset_len_to_stripe_bounds(off, ln)
            c_off, c_len = self._logical_range_to_chunk_extent(s_off, s_len)
            shards: dict[int, np.ndarray] = {}
            for s in good:
                per_oid = rop.replies.get(s, {}).get(oid)
                if per_oid is None:
                    continue
                buf = self._extract(per_oid, c_off, c_len)
                if buf is not None:
                    shards[s] = np.frombuffer(buf, dtype=np.uint8)
            if not self._decodable(set(range(self.k)), set(shards)):
                # drain this object's already-submitted extents: an
                # abandoned ticket would otherwise ride its group to the
                # next flush as device work nobody materializes
                for *_rest, pend in out:
                    try:
                        pend.result()
                    except EcError:
                        pass
                raise EcError(EIO, f"cannot reconstruct {oid}")
            pend = stripe_mod.decode_concat_launch(
                self.sinfo, self.ec, shards, aggregator=self.decode_aggregator,
                chunk_cache=cache,
                cache_key=(self._cache_obj(oid), gen),
                cache_off=c_off,
            )
            out.append((off, ln, s_off, pend))
        return out

    def _finish_reconstruct(self, launched) -> list[bytes]:
        """Materialize phase-one tickets into the requested extents."""
        out: list[bytes] = []
        for off, ln, s_off, pend in launched:
            logical = pend.result()
            lo = off - s_off
            out.append(logical[lo : lo + ln].tobytes())
        return out

    @staticmethod
    def _extract(extents: list[tuple[int, bytes]], off: int, length: int) -> bytes | None:
        for e_off, data in extents:
            if e_off <= off and off + length <= e_off + len(data):
                return bytes(data[off - e_off : off - e_off + length])
            if e_off == off:  # short read at EOF
                return bytes(data)
        return None

    # -- recovery (§3.2) -----------------------------------------------------

    def recovery_inflight(self) -> dict[str, int]:
        """Recovery-pipeline depth for the PG's progress event (ISSUE 8):
        how many objects are mid-recovery and how many of those are
        parked on the decode pipeline awaiting an (aggregated) launch
        reap — the mgr progress module shows these as in-flight work so
        a stall inside the DECODING stage is distinguishable from an
        idle PG."""
        return {
            "recovering": len(self.recovery_ops),
            "decoding": len(self._decode_pipe),
        }

    def recover_object(
        self, oid: str, missing_on: set[int], on_complete: Callable[[int], None]
    ) -> None:
        """Primary-only: rebuild `missing_on` shards (run_recovery_op)."""
        rec = RecoveryOp(
            oid=oid,
            missing_on=set(missing_on),
            on_complete=on_complete,
            trace=self._span("ec:recover"),
        )
        rec.trace.keyval("oid", oid)
        rec.trace.keyval("missing_on", ",".join(map(str, sorted(missing_on))))
        self.recovery_ops[oid] = rec
        self._continue_recovery(rec)

    def _continue_recovery(self, rec: RecoveryOp) -> None:
        """continue_recovery_op (ECBackend.cc:591-746), plus the DECODING
        stage: reaping a launched (possibly aggregated) device decode and
        fanning out the pushes.  The decode pipeline keeps a small window
        of RecoveryOps in this state so concurrent objects' decodes share
        one padded launch."""
        if rec.state == RECOVERY_DECODING:
            self._finish_recovery_decode(rec)
            return
        if rec.state == RECOVERY_IDLE:
            rec.state = RECOVERY_READING
            avail = self._available_shards(rec.oid)
            want = set(rec.missing_on)

            rec.trace.event("gather surviving shards")

            def _on_fail(results: dict) -> None:
                err, _ = results[rec.oid]
                del self.recovery_ops[rec.oid]
                rec.trace.event(f"recovery read failed ({err})")
                rec.trace.finish()
                rec.on_complete(err or -EIO)

            self.objects_read_and_reconstruct(
                {rec.oid: [(0, self._recovery_extent(rec.oid, avail))]},
                _on_fail,
                want_attrs=True,
                on_complete_raw=lambda rop, good: self._handle_recovery_read_complete(
                    rec, rop
                ),
                want_shards=want,
                fast_read=False,
                parent_span=rec.trace,
            )

    def _recovery_extent(self, oid: str, avail: set[int]) -> int:
        """Logical length covering the whole object (stripe-aligned)."""
        oi = self.get_object_info(oid)
        if oi is not None:
            return self.sinfo.logical_to_next_stripe_offset(oi.size)
        # primary itself missing: size discovered from survivor attrs later.
        # A survivor shard hosted locally (co-located collections) gives the
        # exact extent...
        for s in sorted(avail):
            coll = shard_coll(self.listener.pgid, s)
            try:
                return self.sinfo.aligned_chunk_offset_to_logical_offset(
                    self.store.stat(coll, oid)
                )
            except StoreError:
                continue
        # ...otherwise over-ask: shard-side reads clamp to the actual
        # shard size (handle_sub_read), so a generous stripe-aligned cover
        # recovers the WHOLE object instead of silently truncating it to
        # one stripe (multi-stripe objects whose primary lost its shard).
        return self.sinfo.logical_to_next_stripe_offset(1 << 30)

    def _handle_recovery_read_complete(self, rec: RecoveryOp, rop: ReadOp) -> None:
        """LAUNCH the decode of the missing shards (ECBackend.cc:435-501).

        The bulk matrix path submits the decode to the shared
        DecodeAggregator as a ticket and parks the RecoveryOp on the
        decode pipeline (state DECODING) instead of blocking — concurrent
        objects with the same erasure pattern share one padded launch;
        pushes fan out at the reap (_finish_recovery_decode).  The CLAY
        fragmented path is one batched (stripes, ...) launch already and
        completes inline."""
        sub_count = self.ec.get_sub_chunk_count()
        have: dict[int, np.ndarray] = {}
        fragmented = False
        for s, per_oid in rop.replies.items():
            exts = per_oid.get(rec.oid)
            if not exts or rop.errors.get(s):
                continue
            if len(exts) == 1:
                # common whole-shard single-extent reply: wrap the payload
                # zero-copy (np.stack in the decode gather pays the one
                # unavoidable copy)
                have[s] = np.frombuffer(exts[0][1], dtype=np.uint8)
            else:
                buf = b"".join(data for _off, data in exts)
                have[s] = np.frombuffer(buf, dtype=np.uint8)
            runs = [tuple(r) for r in rop.subchunks.get(s, [(0, sub_count)])]
            if runs != [(0, sub_count)]:
                fragmented = True
        rec.attrs = rop.attrs.get(rec.oid, {})
        want = set(rec.missing_on)
        t0 = time.monotonic()
        try:
            if fragmented:
                rebuilt = self._decode_fragmented(rec, have, want)
            else:
                cache = self._chunk_cache()
                gen = (
                    self._cache_generation(rec.oid)
                    if cache is not None else None
                )
                with tracer_mod.span_scope(rec.trace):
                    rec.pending_decode = stripe_mod.decode_shards_launch(
                        self.sinfo, self.ec, have, want,
                        aggregator=self.decode_aggregator,
                        chunk_cache=cache,
                        cache_key=(self._cache_obj(rec.oid), gen),
                    )
                rec.decode_t0 = t0
                rec.state = RECOVERY_DECODING
                rec.trace.event("decode launched")
                self._decode_pipe.append(rec)
                # Backpressure: past the window, reap the head (blocking).
                while len(self._decode_pipe) > self.decode_depth:
                    self._finish_recovery_decode(self._decode_pipe[0])
                self._schedule_decode_drain()
                return
            self._perf_hist("ec_decode_latency", time.monotonic() - t0)
        except (EcError, KeyError) as e:
            del self.recovery_ops[rec.oid]
            rec.trace.event(f"decode failed ({e})")
            rec.trace.finish()
            rec.on_complete(getattr(e, "errno", -EIO))
            return
        rec.shard_data = rebuilt
        self._push_recovered(rec)

    def _decode_fragmented(
        self, rec: RecoveryOp, have: dict[int, np.ndarray], want: set[int]
    ) -> dict[int, bytes]:
        """CLAY repair: helpers supplied, per stripe-chunk, the
        concatenated repair-plane fragments; rebuild with the true chunk
        size.  One batched (stripes, helpers, frag) launch when the codec
        vectorizes fragment repair; the per-stripe loop stays as the
        fallback for codecs (or plans) that don't."""
        cs = self.sinfo.chunk_size
        stripes = self._full_shard_len(rec) // cs
        batch = getattr(self.ec, "decode_fragments_batch", None)
        if (
            batch is not None
            and stripes > 0
            and all(arr.size % stripes == 0 for arr in have.values())
        ):
            frags = {
                s: arr.reshape(stripes, arr.size // stripes)
                for s, arr in have.items()
            }
            try:
                with tracer_mod.span_scope(rec.trace):
                    decoded = batch(want, frags, cs)
                return {
                    s: np.ascontiguousarray(decoded[s]).tobytes() for s in want
                }
            except EcError:
                pass  # not a batchable repair plan: per-stripe fallback
        pieces: dict[int, list[bytes]] = {s: [] for s in want}
        for s_idx in range(stripes):
            frag_chunks = {}
            for s, arr in have.items():
                frag = arr.size // stripes
                frag_chunks[s] = arr[s_idx * frag : (s_idx + 1) * frag]
            decoded = self.ec.decode(want, frag_chunks, chunk_size=cs)
            for s in want:
                pieces[s].append(np.asarray(decoded[s]).tobytes())
        # join once: += bytes concatenation is O(n^2) in stripe count
        return {s: b"".join(pieces[s]) for s in want}

    def _finish_recovery_decode(self, rec: RecoveryOp) -> None:
        """Reap one launched recovery decode and fan out its pushes (the
        completion half of the DECODING stage).  A failed (aggregated)
        launch surfaces here, at the op that owns the ticket."""
        if rec in self._decode_pipe:
            self._decode_pipe.remove(rec)
        want = set(rec.missing_on)
        try:
            with tracer_mod.span_scope(rec.trace):
                decoded = rec.pending_decode.result()
            rebuilt = {s: np.asarray(decoded[s]).tobytes() for s in want}
        except (EcError, KeyError) as e:
            del self.recovery_ops[rec.oid]
            rec.pending_decode = None
            rec.trace.event(f"decode failed ({e})")
            rec.trace.finish()
            rec.on_complete(getattr(e, "errno", -EIO))
            return
        rec.pending_decode = None
        if rec.decode_t0:
            self._perf_hist("ec_decode_latency", time.monotonic() - rec.decode_t0)
        rec.shard_data = rebuilt
        self._push_recovered(rec)

    def _schedule_decode_drain(self) -> None:
        """Reap finished recovery decodes from a running event loop;
        without one (synchronous harnesses) the barrier drains via
        flush_decodes()."""
        if not self._decode_pipe:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        loop.call_soon(self._drain_decode_pipe)

    def _drain_decode_pipe(self) -> None:
        """Push out every RecoveryOp whose decode finished, strictly FIFO.
        A head still windowed/computing gets the same re-poll grace as the
        encode pipe (~100 ms for same-pattern co-riders to arrive), then
        the window is drained — no amount of polling launches a windowed
        decode."""
        while self._decode_pipe:
            rec = self._decode_pipe[0]
            pend = rec.pending_decode
            if not pend.launched() and rec.decode_polls >= 50:
                self.decode_aggregator.flush()
            if not pend.ready() and rec.decode_polls < 50:
                rec.decode_polls += 1
                try:
                    asyncio.get_running_loop().call_later(
                        0.002, self._drain_decode_pipe
                    )
                except RuntimeError:
                    pass
                return
            self._finish_recovery_decode(rec)

    def _push_recovered(self, rec: RecoveryOp) -> None:
        """Fan out PushOps for the rebuilt shards (the WRITING stage)."""
        want = set(rec.missing_on)
        rebuilt = rec.shard_data
        rec.state = RECOVERY_WRITING
        rec.trace.event(f"decoded; pushing to shards {sorted(want)}")
        # progress accounting (ISSUE 8): the reconstructed bytes are the
        # honest "bytes done" figure — the PG folds them into the
        # progress event the mgr's progress module renders
        note = getattr(self.listener, "note_recovery_bytes", None)
        if note is not None:
            note(rec.oid, sum(len(v) for v in rebuilt.values()))
        acting = self.listener.acting()
        version = 0
        if OI_ATTR in rec.attrs:
            version = ObjectInfo.decode(rec.attrs[OI_ATTR]).version
        # Register all pending pushes before sending any: a push to our own
        # shard replies synchronously and must not observe a partial set.
        sends: list[tuple[int, MOSDPGPush]] = []
        for s in sorted(want):
            osd = acting[s] if s < len(acting) else PG_NONE
            if osd == PG_NONE:
                continue
            rec.pending_pushes.add(s)
            push = PushOp(
                oid=rec.oid,
                data=rebuilt[s],
                attrs=dict(rec.attrs),
                version=version,
            )
            sends.append(
                (
                    osd,
                    MOSDPGPush(
                        pgid=self.listener.pgid.with_shard(s),
                        pushes=[push],
                        epoch=self.listener.epoch(),
                        from_osd=self.listener.whoami(),
                    ),
                )
            )
        if not sends:
            self._finish_recovery(rec)
            return
        rec.push_ts = time.monotonic()
        for osd, msg in sends:
            self.listener.send_shard(osd, msg)

    def retry_stalled_pushes(self, grace: float) -> int:
        """Re-send pending PushOps older than `grace` seconds (ISSUE 15
        recovery-path hardening; tick-driven from the PG).  A push the
        target dropped — a dying daemon, the `ec.recover_push` chaos
        seam — would otherwise park its RecoveryOp in WRITING forever.
        Re-applying a push the target DID land is idempotent (same
        rebuilt bytes, same attrs), and a late first reply just empties
        pending_pushes before the duplicate's reply is ignored.
        Returns the number of ops retried."""
        if grace <= 0:
            return 0
        now = time.monotonic()
        retried = 0
        acting = self.listener.acting()
        for rec in list(self.recovery_ops.values()):
            if (
                rec.state != RECOVERY_WRITING
                or not rec.pending_pushes
                or not rec.push_ts
                or now - rec.push_ts < grace
            ):
                continue
            version = 0
            if OI_ATTR in rec.attrs:
                version = ObjectInfo.decode(rec.attrs[OI_ATTR]).version
            rec.push_ts = now
            rec.push_retries += 1
            self.push_retries += 1
            retried += 1
            rec.trace.event(
                lambda rec=rec: "retrying stalled pushes to shards "
                f"{sorted(rec.pending_pushes)}"
            )
            for s in sorted(rec.pending_pushes):
                osd = acting[s] if s < len(acting) else PG_NONE
                if osd == PG_NONE:
                    continue
                self.listener.send_shard(
                    osd,
                    MOSDPGPush(
                        pgid=self.listener.pgid.with_shard(s),
                        pushes=[PushOp(
                            oid=rec.oid,
                            data=rec.shard_data[s],
                            attrs=dict(rec.attrs),
                            version=version,
                        )],
                        epoch=self.listener.epoch(),
                        from_osd=self.listener.whoami(),
                    ),
                )
        return retried

    def _full_shard_len(self, rec: RecoveryOp) -> int:
        """True (unfragmented) shard length for CLAY repair decode."""
        oi_blob = rec.attrs.get(OI_ATTR)
        if oi_blob is not None:
            size = ObjectInfo.decode(oi_blob).size
            return self.sinfo.logical_to_next_chunk_offset(size)
        raise EcError(EIO, f"no object info for {rec.oid}")

    def handle_recovery_push(self, msg: MOSDPGPush) -> None:
        """Target shard writes the pushed chunk (§3.2 WRITING)."""
        # recovery-push wedge seam (ec.recover_push): the push is
        # dropped on the floor — no apply, no reply — exactly as a
        # target dying mid-delivery would drop it.  The primary's
        # stalled-push retry (retry_stalled_pushes) re-sends past the
        # osd_recovery_push_retry_sec grace, so chaos can wedge pushes
        # mid-storm and watch recovery self-heal.
        from ..common.fault_injector import InjectedFailure, faultpoint
        from ..common.log import dout

        try:
            faultpoint("ec.recover_push")
        except InjectedFailure as e:
            dout("ec", 1, f"{self.listener.pgid}: dropping injected-fault "
                          f"recovery push for {msg.pgid} ({e})")
            return
        coll = shard_coll(self.listener.pgid, msg.pgid.shard)
        oids = self._apply_pushes(coll, msg.pushes)
        reply = MOSDPGPushReply(
            pgid=msg.pgid,
            oids=oids,
            epoch=self.listener.epoch(),
            from_osd=self.listener.whoami(),
        )
        self.listener.send_shard(msg.from_osd, reply)

    def handle_recovery_push_reply(self, msg: MOSDPGPushReply) -> None:
        for oid in msg.oids:
            rec = self.recovery_ops.get(oid)
            if rec is None:
                continue
            rec.pending_pushes.discard(msg.pgid.shard)
            if not rec.pending_pushes:
                self._finish_recovery(rec)

    def _finish_recovery(self, rec: RecoveryOp) -> None:
        rec.state = RECOVERY_COMPLETE
        del self.recovery_ops[rec.oid]
        rec.trace.event("all pushes acked; recovered")
        rec.trace.finish()
        self.listener.on_global_recover(rec.oid)
        rec.on_complete(0)

    # -- scrub support --------------------------------------------------------

    def scan_shard(self, shard: int) -> dict[str, dict]:
        """Deep-scrub scan: per-object size + crc32c of the local chunk
        (be_deep_scrub analog, ECBackend.cc:2518)."""
        from ..utils.crc32c import crc32c

        coll = shard_coll(self.listener.pgid, shard)
        out: dict[str, dict] = {}
        try:
            oids = self.store.list_objects(coll)
        except StoreError:
            return out
        for oid in oids:
            data = self.store.read(coll, oid, 0, 0)
            hinfo = None
            try:
                hinfo = HashInfo.decode(self.store.getattr(coll, oid, HINFO_ATTR))
            except StoreError:
                pass
            digest = crc32c(data, HashInfo.SEED)
            entry = {"size": len(data), "digest": digest}
            if hinfo is not None:
                entry["hinfo_digest"] = hinfo.get_chunk_hash(shard)
                entry["hinfo_size"] = hinfo.get_total_chunk_size()
            out[oid] = entry
        return out


def _u64b(v: int) -> bytes:
    return int(v).to_bytes(8, "little")
