"""OSD layer — mirror of /root/reference/src/osd.

The data-path daemon and its erasure-coded backend (SURVEY.md §2.2):
OSDMap (cluster topology + pools + EC profiles), the EC stripe/transaction
machinery, the RMW write pipeline, recovery, scrub, heartbeats, and the
op scheduler.
"""

from .osdmap import Incremental, OSDMap, PgPool, PG_NONE

__all__ = ["Incremental", "OSDMap", "PgPool", "PG_NONE"]
