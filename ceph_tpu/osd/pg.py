"""PG — the placement-group execution context.

Mirrors the slice of src/osd/PG.{h,cc} + PrimaryLogPG.cc that executes
client ops and drives recovery:

- `do_op` is PrimaryLogPG::do_op → execute_ctx → do_osd_ops
  (/root/reference/src/osd/PrimaryLogPG.cc:1978,4134,5960): the op-code
  switch over an MOSDOp's OSDOp vector, reads completing asynchronously
  through the backend's reconstructing read path, writes becoming one
  PGTransaction submitted to the PGBackend (issue_repop,
  PrimaryLogPG.cc:11387).
- Degraded-object gating is PrimaryLogPG::wait_for_degraded_object: ops
  touching an object that is missing anywhere queue until recovery
  completes, and that object's recovery is prioritized.
- The recovery driver is the OSD's recovery work-queue scaled down:
  up to `osd_recovery_max_active` objects in flight, each via
  PGBackend::recover_object (§3.2 of SURVEY.md).
- The PG implements PGListener — the boundary the backends (EC and
  replicated) call back through, src/osd/PGBackend.h Listener.
"""

from __future__ import annotations

import asyncio
import bisect
import time
from collections import OrderedDict
from typing import TYPE_CHECKING, Callable

from ..common.errs import (
    EAGAIN,
    EBUSY,
    ECANCELED,
    EDQUOT,
    EINVAL,
    ENODATA,
    ENOENT,
    EOPNOTSUPP,
    EPERM,
)
from ..common.log import dout
from ..msg.messages import (
    MBackfillReserve,
    MOSDOp,
    MOSDOpReply,
    MOSDPGLog,
    MOSDPGNotify,
    MOSDPGQuery,
    OSDOp,
    PgId,
    ReqId,
)
from ..os.transaction import Transaction
from .ec_transaction import PGTransaction
from .osdmap import FLAG_FULL_QUOTA, PG_NONE, POOL_TYPE_ERASURE, PgPool
from .peering import PeeringState
from .pg_backend import PGListener, build_pg_backend, shard_coll
from .pg_log import Eversion, LogEntry, Missing, PGLog, PgInfo
from .snaps import SS_ATTR, WHITEOUT_ATTR, SnapSet, clone_oid
from ..cls.objclass import WR as CLS_WR, ClsError, HCtx as ClsHCtx, get_method as cls_get_method

WRITE_OPS = {
    OSDOp.WRITE,
    OSDOp.WRITEFULL,
    OSDOp.DELETE,
    OSDOp.TRUNCATE,
    OSDOp.APPEND,
    OSDOp.SETXATTR,
    OSDOp.RMXATTR,
    OSDOp.ROLLBACK,
    OSDOp.COPY_FROM,
    OSDOp.OMAPSETVALS,
    OSDOp.OMAPRMKEYS,
    OSDOp.OMAPCLEAR,
    OSDOp.ZERO,
    OSDOp.WRITESAME,
}

# Cache-tier dirty marker (object_info_t FLAG_DIRTY analog): set by client
# writes on a writeback cache PG, cleared by flush; rides the write
# transaction so replicas agree.
DIRTY_ATTR = "cache_dirty"


# Wire blobs for GETXATTRS dumps and omap ops (the copy-get attrs map,
# /root/reference/src/osd/PrimaryLogPG.cc do_copy_get).
from ..common.encoding import (  # noqa: E402 (module-level re-export)
    decode_kv_map as decode_attrs,
    encode_kv_map as encode_attrs,
)


def cmpxattr_ok(cur: bytes | None, want: bytes, mode: int) -> bool:
    """CEPH_OSD_CMPXATTR_OP_* byte-string comparison; a missing xattr
    compares as empty (the reference's cmpxattr on absent attrs)."""
    cur = cur if cur is not None else b""
    if mode == 1:
        return cur == want
    if mode == 2:
        return cur != want
    if mode == 3:
        return cur > want
    if mode == 4:
        return cur >= want
    if mode == 5:
        return cur < want
    if mode == 6:
        return cur <= want
    return False


def op_is_write(op: OSDOp) -> bool:
    """Write-class test honoring CALL's per-method RD/WR flags
    (PrimaryLogPG classifies CALL by the resolved method's flags)."""
    if op.op == OSDOp.CALL:
        try:
            cls_name, method = op.name.split(".", 1)
            flags, _fn = cls_get_method(cls_name, method)
        except Exception:
            # unresolvable: route through the read path, which reports
            # the precise error (-EOPNOTSUPP)
            return False
        return bool(flags & CLS_WR)
    return op.op in WRITE_OPS


def op_class_of(ops) -> str:
    """Attribution class for a whole MOSDOp (ISSUE 10): write if ANY
    sub-op writes, else read — the single source for the QoS/accounting
    classification."""
    return "write" if any(op_is_write(op) for op in ops) else "read"


class PG(PGListener):
    """One placement group hosted by an OSD (possibly one shard of it)."""

    def __init__(self, osd, pool: PgPool, ps: int, profiles: dict):
        self.osd = osd
        self.pool = pool
        self.ps = ps
        self.pgid = PgId(pool.id, ps, -1)
        self.pg_log = PGLog()
        self.info = PgInfo()
        self._acting: list[int] = []
        self._epoch = 0
        self._version = 0
        self.peering = PeeringState(
            self.pgid,
            osd.whoami,
            self.pg_log,
            self.info,
            send=self._send_peering,
            on_active=self._on_active,
            list_local_objects=self._list_local,
            drop_local_object=self._drop_local_object,
        )
        self.backend = build_pg_backend(pool, profiles, self, osd.store)
        from .scrubber import PgScrubber

        self.scrubber = PgScrubber(self)
        self.recovering: set[str] = set()
        self.waiting_for_degraded: dict[str, list[Callable[[], None]]] = {}
        # stray shard sources (ISSUE 15): EC shard identity is
        # POSITIONAL (acting index -> shard coll), and CRUSH slot-fill
        # after an out can reshuffle survivors' slots.  `_shard_holders`
        # remembers, per slot, who held its data at the last CLEAN tick
        # — the stray whose old coll still has valid chunks while the
        # new member rebuilds; `_moved_members` records, per interval,
        # members whose slot changed (their local chunks sit under the
        # wrong coll, so activation marks their objects missing).
        self._shard_holders: dict[int, int] = {}
        self._moved_members: dict[int, int] = {}  # osd -> old shard
        # backfill driver state (PeeringState Backfilling/WaitRemote states)
        self._bf_granted: set[int] = set()  # targets that granted a slot
        self._bf_inflight: set[str] = set()  # oids being pushed this chunk
        self._bf_failed: set[str] = set()  # pushes that errored this chunk
        self._bf_chunk_targets: dict[int, list[str]] = {}
        self._bf_local_reserved = False
        self._bf_gen = 0  # bumped on interval change; stales out callbacks
        self._colls_made: set[str] = set()
        # Completed write results by reqid (PrimaryLogPG's dup-op check
        # against the pg log's reqid index): a client resend after a lost
        # reply must get the original result, not a second execution.
        self._reqid_results: dict[tuple[str, int], MOSDOpReply] = {}
        self._inflight_reqids: dict[tuple[str, int], list] = {}
        # watch/notify (PrimaryLogPG watchers / Notify in Watch.cc):
        # oid -> (entity, cookie) -> connection; cookies are only unique
        # per watcher entity, exactly like the reference's watch key
        # (pair<uint64_t, entity_name_t>, PrimaryLogPG.h).
        # Simplification vs the reference: watches are primary-memory only
        # (the reference persists them in object_info and clients re-watch
        # after ENOTCONN) — a primary failover drops them, so watchers
        # must re-register after cluster topology changes.
        self.watchers: dict[str, dict[tuple[str, int], object]] = {}
        self._notify_id = 0
        # notify_id -> {"pending": set[(entity, cookie)], "acks", "finish"}
        self._notifies: dict[int, dict] = {}
        # cache tiering (PrimaryLogPG promote_object / TierAgent):
        self._promoting: dict[str, list] = {}  # oid -> queued (msg,reply,conn)
        self._tier_pass: set[tuple[str, int]] = set()  # reqids past the gate
        self._tier_lru: "OrderedDict[str, None]" = OrderedDict()
        self._tier_tid = 0
        self._tier_agent_busy = False
        # oids mid-flush: writes are blocked (queued) until the write-back
        # and dirty-clear land, else a racing write could be marked clean
        # and lost on evict (the reference's wait_for_blocked_object).
        self._flushing: dict[str, list] = {}
        # recovery-progress accounting (ISSUE 8): the high-water total of
        # missing objects this recovery episode and the done counters —
        # progress_status() folds them into the OSD status blob the mgr
        # progress module aggregates.  Reset when the episode completes.
        self._recovery_total = 0
        self._recovery_done = 0
        self._recovery_done_bytes = 0
        # completion-report repeats remaining: the final done==total
        # event is re-emitted on a few status reports, because the mgr
        # samples a last-write-wins status blob and a one-shot report
        # can be overwritten before the module's next tick sees it
        self._recovery_final_reports = 0

    # -- interval / peering ----------------------------------------------------

    def on_new_interval(self, epoch: int, acting: list[int]) -> None:
        """OSDMap advance (PG::handle_advance_map).  Re-peering only
        happens when the *interval* changed — i.e. the acting set moved
        (PastIntervals::is_new_interval); unrelated epoch bumps (another
        pool created, another OSD booting) must not bounce an active PG
        back through GetInfo."""
        interval_changed = acting != self._acting or self._epoch == 0
        self._epoch = epoch
        if not interval_changed:
            return
        # positional shard moves (ISSUE 15): a surviving member placed
        # at a DIFFERENT slot holds its chunks under the old shard coll
        # — wrong bytes for the new slot.  Remember the moves; every
        # activation of this interval marks those members' objects
        # missing (rebuild at the new slot), while _shard_holders keeps
        # redirecting reconstruction reads at the old slot-holder's
        # still-valid stray chunks.
        self._moved_members = {}
        if self.pool.type == POOL_TYPE_ERASURE and self._acting:
            for s, osd in enumerate(acting):
                if osd == PG_NONE or osd not in self._acting:
                    continue
                old = self._acting.index(osd)
                if old != s:
                    self._moved_members[osd] = old
        self._acting = list(acting)
        self._ensure_local_coll()
        self.scrubber.reset()  # an interval change aborts in-flight scrubs
        self._reset_backfill()  # reservations do not survive an interval
        # in-flight recoveries die with the interval (the reference's
        # on_change cancels them): a push sent to a member that went
        # down mid-interval would otherwise pin its oid in `recovering`
        # forever — re-peering recomputes the missing sets and the next
        # tick re-admits whatever still needs rebuilding (ISSUE 15)
        self.recovering.clear()
        # recovery-progress episode dies with the interval: a demoted
        # primary's progress_status goes silent BEFORE its reset branch
        # can run, and stale done counts would otherwise pre-fill the
        # bar when this OSD becomes primary again
        self._recovery_total = 0
        self._recovery_done = 0
        self._recovery_done_bytes = 0
        self._recovery_final_reports = 0
        self.peering.start_peering_interval(epoch, acting)

    def tick(self) -> None:
        """Periodic liveness: retry stuck peering, keep recovery moving,
        abort scrubs whose shard died."""
        self.peering.tick()
        self.scrubber.tick(time.monotonic())
        if (
            self.pool.type == POOL_TYPE_ERASURE
            and self.peering.is_active()
            and self.is_clean
        ):
            # last-clean shard-holder snapshot (ISSUE 15): while the PG
            # is clean every slot's data is exactly where acting says;
            # this map is what stray-shard redirection falls back to
            # after the next reshuffle
            self._shard_holders = {
                s: o for s, o in enumerate(self._acting) if o != PG_NONE
            }
        if self.peering.is_active():
            self._kick_recovery()
            self._kick_backfill()
            # stalled-push retry (ISSUE 15): a recovery push the target
            # dropped must not park its op in WRITING forever
            retry = getattr(self.backend, "retry_stalled_pushes", None)
            if retry is not None and self.peering.is_primary():
                retry(float(self.osd.conf.get("osd_recovery_push_retry_sec")))

    def _ensure_local_coll(self) -> None:
        coll = shard_coll(self.pgid, self.whoami_shard())
        if coll in self._colls_made:
            return
        if not self.osd.store.collection_exists(coll):
            self.osd.store.queue_transaction(Transaction().create_collection(coll))
        self._colls_made.add(coll)

    def _send_peering(self, osd: int, msg) -> None:
        self.osd.send_cluster(osd, msg)

    def _list_local(self) -> list[str]:
        coll = shard_coll(self.pgid, self.whoami_shard())
        try:
            return self.osd.store.list_objects(coll)
        except Exception:
            return []

    def list_heads(self) -> list[str]:
        """Client-visible head objects (snap clones carry the reserved
        "@" separator and are internal)."""
        return [o for o in self._list_local() if "@" not in o]

    def logical_object_size(self, oid: str) -> int:
        return self._object_size(oid)

    def local_object_count(self) -> int:
        """O(1)/one-readdir count for stat reporting (no enumeration)."""
        coll = shard_coll(self.pgid, self.whoami_shard())
        try:
            return self.osd.store.count_objects(coll)
        except Exception:
            return 0

    def local_bytes_used(self) -> int:
        """Raw bytes this OSD stores for the PG (every local object incl.
        snap clones and EC shard chunks) — the pg_stats slice `ceph df`'s
        USED column aggregates."""
        coll = shard_coll(self.pgid, self.whoami_shard())
        total = 0
        try:
            for oid in self.osd.store.list_objects(coll):
                try:
                    total += self.osd.store.stat(coll, oid)
                except Exception:
                    pass
        except Exception:
            return 0
        return total

    def _drop_local_object(self, oid: str) -> None:
        """Divergent-rewind hook: a stale-but-present local copy must be
        dropped so recovery PULLS the authoritative version instead of
        treating the local bytes as good (recover_object's exists() check
        would otherwise push the divergent copy back out as 'repair')."""
        coll = shard_coll(self.pgid, self.whoami_shard())
        try:
            if self.osd.store.exists(coll, oid):
                self.osd.store.queue_transaction(Transaction().remove(coll, oid))
        except Exception:
            pass

    def _on_active(self) -> None:
        self._version = max(self._version, self.pg_log.head.version)
        self._rebuild_dup_window()
        self._apply_shard_moves()
        # kick the storm controller AT the flood (ISSUE 15): activation
        # is the moment a whole-OSD failure's missing sets appear, and
        # waiting for the next heartbeat tick would let the per-PG
        # trickle race the first wave
        storm = getattr(self.osd, "recovery_storm", None)
        if storm is not None:
            storm.tick()
        self._kick_recovery()

    def _apply_shard_moves(self) -> None:
        """Primary activation hook (ISSUE 15): members whose shard slot
        moved this interval have every pre-interval object's chunk under
        the WRONG coll — mark those objects missing (for self and for
        peers) so recovery rebuilds them at the new slot.  The census is
        the primary's own shard coll (its OLD one if it moved itself):
        a full member's coll lists every object in the PG."""
        if not self._moved_members or self.pool.type != POOL_TYPE_ERASURE:
            return
        census_shard = self._moved_members.get(
            self.osd.whoami, self.whoami_shard()
        )
        if census_shard < 0:
            return
        coll = shard_coll(self.pgid, census_shard)
        try:
            oids = self.osd.store.list_objects(coll)
        except Exception as e:
            dout("osd", 2, f"pg {self.pgid}: shard-move census of {coll} "
                           f"unavailable ({e!r})")
            oids = []
        if not oids:
            # a primary with an empty coll (fresh member pulled into the
            # set) still knows the object population from the merged
            # authoritative log — walk it in order so deletes cancel
            live: set[str] = set()
            for e in self.pg_log.entries:
                if e.is_delete():
                    live.discard(e.oid)
                else:
                    live.add(e.oid)
            oids = sorted(live)
        if not oids:
            return
        v = self.pg_log.head
        for osd, old_shard in self._moved_members.items():
            dout(
                "osd", 1,
                f"pg {self.pgid}: osd.{osd} moved shard {old_shard} -> "
                f"{self._acting.index(osd)}; marking {len(oids)} objects "
                "for rebuild at the new slot",
            )
            if osd == self.osd.whoami:
                for oid in oids:
                    self.peering.missing.add(oid, v)
            else:
                m = self.peering.peer_missing.setdefault(osd, Missing())
                for oid in oids:
                    m.add(oid, v)

    def shard_data_source(self, shard: int, oid: str) -> int:
        """Stray-shard read sourcing (ISSUE 15; overrides the PGListener
        default): the acting member serves when placed and not missing
        the object; otherwise the slot's last-clean HOLDER — whose old
        coll still has valid chunks, because writes to missing objects
        are degraded-blocked until recovery lands — serves the
        reconstruction read."""
        if self.pool.type != POOL_TYPE_ERASURE:
            return super().shard_data_source(shard, oid)
        acting_osd = (
            self._acting[shard] if shard < len(self._acting) else PG_NONE
        )
        if acting_osd != PG_NONE and shard not in self.get_shard_missing(oid):
            return acting_osd
        holder = self._shard_holders.get(shard, PG_NONE)
        if (
            holder != PG_NONE
            and holder != acting_osd
            and self.osd.osdmap.is_up(holder)
        ):
            return holder
        return PG_NONE

    def _rebuild_dup_window(self) -> None:
        """Replay reqid dup detection from the PG log on activation.

        The in-memory dup maps die with the old primary; the Objecter's
        resend loop reuses the same tid, so without replay a non-idempotent
        op (APPEND, offset WRITE) that already committed would re-execute on
        the new primary.  The reference rebuilds dups from the pg log
        (PGLog::dups / PrimaryLogPG already-complete checks); here every
        logged write's reqid is reinstated as a completed-op reply."""
        self._reqid_results.clear()
        self._inflight_reqids.clear()
        for e in self.pg_log.entries[-1000:]:  # same bound as the live window
            if e.reqid == ("", 0):
                continue
            self._reqid_results[e.reqid] = MOSDOpReply(
                reqid=ReqId(*e.reqid),
                result=0,
                outdata=[],
                version=e.version.version,
                epoch=self._epoch,
            )

    def handle_peering_message(self, msg) -> bool:
        # peering wedge seam (peering.msg): the message is dropped
        # before the state machine sees it — a lost query/notify/log
        # mid-storm.  Self-heal is tick-driven: PeeringState.tick
        # restarts a primary stuck in GetInfo/GetLog, which re-queries.
        from ..common.fault_injector import InjectedFailure, faultpoint

        try:
            faultpoint("peering.msg")
        except InjectedFailure as e:
            dout("osd", 1, f"pg {self.pgid}: dropping injected-fault "
                           f"peering message {type(msg).__name__} ({e})")
            return True
        if isinstance(msg, MOSDPGQuery):
            self._ensure_local_coll()
            self.peering.handle_query(msg)
        elif isinstance(msg, MOSDPGNotify):
            self.peering.handle_notify(msg)
        elif isinstance(msg, MOSDPGLog):
            was_active = self.peering.is_active()
            self.peering.handle_log(msg)
            if not was_active and self.peering.is_active():
                self._version = max(self._version, self.pg_log.head.version)
        else:
            return False
        return True

    # -- PGListener ------------------------------------------------------------

    def whoami(self) -> int:
        return self.osd.whoami

    @property
    def tracer(self):
        """The daemon tracer the EC backend threads spans through
        (ECBackend.h:64-87 ZTracer::Trace parameters)."""
        t = getattr(self.osd, "tracer", None)
        if t is None:
            from ..common.tracer import NULL_TRACER

            t = NULL_TRACER
        return t

    def perf_hist(self, name: str, value: float) -> None:
        """EC stage latency -> the OSD's PerfHistogram counters
        (ec_encode_latency / ec_decode_latency)."""
        perf = getattr(self.osd, "perf", None)
        if perf is None:
            return
        try:
            perf.hinc(name, value)
        except (KeyError, AttributeError):
            pass  # harness OSD without the histogram declared

    def perf_inc(self, name: str, n: int = 1) -> None:
        """EC hedge/shed accounting -> the OSD's counters (ISSUE 17)."""
        perf = getattr(self.osd, "perf", None)
        if perf is None:
            return
        try:
            perf.inc(name, n)
        except (KeyError, AttributeError):
            pass  # harness OSD without the counter declared

    def conf_get(self, name: str):
        """Runtime-mutable knob lookup for the EC backend (hedge
        quantile/floor/budget ride the OSD's live Config)."""
        conf = getattr(self.osd, "conf", None)
        return conf.get(name) if conf is not None else None

    def note_peer_rtt(self, peer: int, rtt: float) -> None:
        """Sub-read service-time sample -> the OSD's laggy detector."""
        hook = getattr(self.osd, "note_subread_rtt", None)
        if hook is not None:
            hook(peer, rtt)

    def laggy_peers(self) -> set[int]:
        """OSDs the heartbeat subsystem flags as slow-but-alive; the EC
        backend deprioritizes them as sub-read sources."""
        hook = getattr(self.osd, "laggy_peers", None)
        return set(hook()) if hook is not None else set()

    def whoami_shard(self) -> int:
        if self.pool.type != POOL_TYPE_ERASURE:
            return -1
        if self.osd.whoami in self._acting:
            return self._acting.index(self.osd.whoami)
        return -1

    def acting(self) -> list[int]:
        return self._acting

    def epoch(self) -> int:
        return self._epoch

    def next_version(self) -> Eversion:
        self._version += 1
        return Eversion(self._epoch, self._version)

    def send_shard(self, osd: int, msg) -> None:
        if osd == self.osd.whoami:
            # the primary "sends to itself" (ECBackend.h:336-338)
            self.backend.handle_message(msg)
        else:
            self.osd.send_cluster(osd, msg)

    def append_log(self, entry: LogEntry) -> None:
        if entry.version > self.pg_log.head:
            self.pg_log.append(entry)
        self.info.last_update = self.pg_log.head
        self._version = max(self._version, entry.version.version)
        # A sub-write for an object voids any stale missing record: the
        # write pipeline only runs on recovered objects.
        self.peering.missing.rm(entry.oid)
        # Bounded log (PGLog::trim, osd_min/max_pg_log_entries): every
        # shard trims identically since all apply the same entries.  A
        # down OSD whose head falls behind the trimmed tail can no longer
        # log-recover — that is what makes it a backfill target.
        max_entries = self.osd.conf.get("osd_max_pg_log_entries")
        if len(self.pg_log.entries) > max_entries:
            keep = self.osd.conf.get("osd_min_pg_log_entries")
            self.pg_log.trim(self.pg_log.entries[-keep - 1].version)

    def get_shard_missing(self, oid: str) -> set[int]:
        # Backfill targets behind the cursor count as missing for READ
        # availability (their shard is stale or absent), even though they
        # do not block writes as degraded.
        osds = self.peering.osds_missing(oid) | self.peering.backfill_pending_osds(
            oid
        )
        if self.pool.type != POOL_TYPE_ERASURE:
            return osds
        return {
            self._acting.index(o)
            for o in osds
            if o in self._acting
        }

    def on_local_recover(self, oid: str) -> None:
        self.peering.mark_recovered(oid, self.osd.whoami)

    def on_global_recover(self, oid: str) -> None:
        # progress accounting gates on the recovery driver's in-flight
        # set: backfill pushes reuse backend.recover_object (and thus
        # land here) without ever entering `recovering`, and the
        # backend + _recover_one's completion BOTH call this hook for a
        # real recovery — counting on the membership test keeps done at
        # exactly one per recovered object and zero for backfill
        if oid in self.recovering:
            self._recovery_done += 1
        for osd in list(self.peering.peer_missing):
            self.peering.mark_recovered(oid, osd)
        self.peering.mark_recovered(oid, self.osd.whoami)
        self.recovering.discard(oid)
        for cb in self.waiting_for_degraded.pop(oid, []):
            cb()
        # completion-driven waves (ISSUE 15): while a storm is engaged,
        # each landed recovery frees in-flight budget — admit the next
        # wave NOW instead of waiting out the heartbeat tick
        storm = getattr(self.osd, "recovery_storm", None)
        if storm is not None and storm.engaged:
            storm.tick()
        self._kick_recovery()

    def clog_error(self, msg: str) -> None:
        self.osd.clog_error(msg)

    # -- client op execution ---------------------------------------------------

    def do_op(
        self, msg: MOSDOp, reply: Callable[[MOSDOpReply], None], conn=None
    ) -> None:
        """PrimaryLogPG::do_op.  `reply` delivers the MOSDOpReply; `conn`
        is the client session (needed to push watch notifies)."""
        if not self.peering.is_primary() or not self.peering.is_active():
            # Misdirected or not-yet-peered: tell the client to refresh its
            # map and resend (the reference drops + relies on the map sub;
            # an explicit EAGAIN keeps the same retry loop without a race).
            reply(
                MOSDOpReply(
                    reqid=msg.reqid,
                    result=-EAGAIN,
                    outdata=[],
                    version=0,
                    epoch=self._epoch,
                )
            )
            return
        oid = msg.oid
        if self.peering.object_missing_anywhere(oid):
            # wait_for_degraded_object: queue + prioritize its recovery
            self.waiting_for_degraded.setdefault(oid, []).append(
                lambda: self.do_op(msg, reply, conn)
            )
            self._recover_one(oid)
            return
        if "@" in oid and msg.reqid.client and not msg.reqid.client.startswith(
            "osd."
        ):
            # "@" separates snap clones in the flat store namespace
            # (snaps.clone_oid); a client object named like a clone could
            # be shadowed or destroyed by the snap machinery.  The
            # reference carries snap ids in hobject_t instead of the name;
            # here the character is reserved.
            reply(self._errored(msg, -EINVAL))
            return
        # Classify once: op_is_write resolves CALL methods (possibly an
        # import on first use), so the result is shared by the tier gate
        # and the dispatch decision below.
        writing = any(op_is_write(op) for op in msg.ops)
        if (
            writing
            and (self.pool.flags & FLAG_FULL_QUOTA)
            and msg.reqid.client
            and not msg.reqid.client.startswith("osd.")
        ):
            # pool over quota: client mutations bounce with -EDQUOT
            # (librados surfaces exactly this on quota-full pools);
            # OSD-internal traffic (flush/promote) still flows
            reply(self._errored(msg, -EDQUOT))
            return
        # Cache-tier gate (PrimaryLogPG::maybe_handle_cache): promote on
        # miss, forward deletes to the base, reject writes on readonly.
        # OSD-internal traffic ("osd." clients: promote writes, flush acks)
        # bypasses it.
        if (
            self.pool.is_cache_tier()
            and msg.reqid.client
            and not msg.reqid.client.startswith("osd.")
            and not self._tier_gate(msg, reply, conn, writing)
        ):
            return
        first = msg.ops[0].op if msg.ops else 0
        if first == OSDOp.WATCH:
            self._do_watch(conn, msg, reply)
            return
        if first == OSDOp.NOTIFY:
            self._do_notify(msg, reply)
            return
        if writing:
            if self.scrubber.write_blocked(oid):
                # write_blocked_by_scrub: hold until the chunk completes
                self.scrubber.waiting_writes.append(
                    lambda: self.do_op(msg, reply, conn)
                )
                return
            key = msg.reqid.key()
            done = self._reqid_results.get(key)
            if done is not None:
                reply(done)  # duplicate of a completed write
                return
            waiters = self._inflight_reqids.get(key)
            if waiters is not None:
                waiters.append(reply)  # duplicate of an in-flight write
                return
            self._inflight_reqids[key] = []
            self._do_write(msg, reply)
        else:
            self._do_read(msg, reply)

    def _do_write(self, msg: MOSDOp, reply) -> None:
        pgt = PGTransaction(oid=msg.oid)
        outdata: list[bytes] = [b""] * len(msg.ops)
        size = self._object_size(msg.oid)
        exists = self._object_exists(msg.oid)
        hctx = None  # object-class context, shared across this op's CALLs
        for i, op in enumerate(msg.ops):
            if op.op == OSDOp.WRITE:
                pgt.write(op.off, op.data)
                size = max(size, op.off + len(op.data))
                pgt.attrs.setdefault(WHITEOUT_ATTR, None)  # resurrect
            elif op.op == OSDOp.WRITEFULL:
                pgt.write(0, op.data)
                pgt.truncate = len(op.data)
                size = len(op.data)
                pgt.attrs.setdefault(WHITEOUT_ATTR, None)
            elif op.op == OSDOp.APPEND:
                pgt.write(size, op.data)
                size += len(op.data)
                pgt.attrs.setdefault(WHITEOUT_ATTR, None)
            elif op.op == OSDOp.ZERO:
                # CEPH_OSD_OP_ZERO: the extent reads back as zeros; does
                # not extend the object (the reference zeroes within
                # bounds and ignores wholly-past-end extents)
                ln = min(int(op.len), max(size - int(op.off), 0))
                if ln > 0:
                    pgt.write(int(op.off), b"\x00" * ln)
            elif op.op == OSDOp.WRITESAME:
                # CEPH_OSD_OP_WRITESAME: tile data across [off, off+len)
                if (
                    not op.data
                    or int(op.len) % len(op.data)
                    or int(op.len) <= 0
                ):
                    self._inflight_reqids.pop(msg.reqid.key(), None)
                    reply(self._errored(msg, -EINVAL))
                    return
                tiled = bytes(op.data) * (int(op.len) // len(op.data))
                pgt.write(int(op.off), tiled)
                size = max(size, int(op.off) + len(tiled))
                pgt.attrs.setdefault(WHITEOUT_ATTR, None)
            elif op.op == OSDOp.TRUNCATE:
                pgt.truncate = op.off
                size = op.off
            elif op.op == OSDOp.DELETE:
                if msg.snap_id:
                    # snap trim, not a head delete (PrimaryLogPG::trim_object)
                    if not exists:
                        # nothing to trim; a txn would materialize a
                        # phantom head via touch+setattr
                        self._finish_write(
                            msg,
                            reply,
                            MOSDOpReply(
                                reqid=msg.reqid,
                                result=0,
                                outdata=[b""] * len(msg.ops),
                                version=self._version,
                                epoch=self._epoch,
                            ),
                            remember=True,
                        )
                        return
                    self._apply_snap_trim(msg, pgt)
                elif self._get_snapset(msg.oid).clones or (
                    exists and msg.snaps
                ):
                    # Snapshots reference (or are about to clone) this head:
                    # deletion becomes a WHITEOUT — zero bytes + marker,
                    # SnapSet preserved so clones stay reachable
                    # (object_info_t FLAG_WHITEOUT; PrimaryLogPG _delete_oid)
                    pgt.truncate = 0
                    pgt.attrs[WHITEOUT_ATTR] = b"1"
                    size = 0
                else:
                    pgt.delete = True
                    size = 0
            elif op.op == OSDOp.SETXATTR:
                pgt.attrs[f"_{op.name}"] = op.data
                pgt.attrs.setdefault(WHITEOUT_ATTR, None)
            elif op.op == OSDOp.RMXATTR:
                pgt.attrs[f"_{op.name}"] = None  # staged removal
            elif op.op == OSDOp.CMPXATTR:
                # guard op: a failed compare aborts the WHOLE transaction
                # (nothing staged lands) with -ECANCELED, the atomic
                # check-and-mutate librbd/rgw build on
                key = f"_{op.name}"
                cur = (
                    pgt.attrs[key]
                    if key in pgt.attrs
                    else self._getxattr(msg.oid, key)
                )
                if not cmpxattr_ok(cur, op.data, int(op.off)):
                    self._inflight_reqids.pop(msg.reqid.key(), None)
                    reply(self._errored(msg, -ECANCELED))
                    return
            elif op.op in (
                OSDOp.OMAPSETVALS, OSDOp.OMAPRMKEYS, OSDOp.OMAPCLEAR
            ):
                # omap rides replicated pools only (the reference's
                # pool_requires_alignment / MODE check answers the same)
                if self.pool.type == POOL_TYPE_ERASURE:
                    self._inflight_reqids.pop(msg.reqid.key(), None)
                    reply(self._errored(msg, -EOPNOTSUPP))
                    return
                if op.op == OSDOp.OMAPSETVALS:
                    pgt.omap_set.update(decode_attrs(op.data))
                elif op.op == OSDOp.OMAPRMKEYS:
                    from ..common.encoding import decode_str_list

                    for k in decode_str_list(op.data):
                        # keep op order: a later rm wins over an earlier
                        # set in this compound op (backends apply rm
                        # before set)
                        pgt.omap_set.pop(k, None)
                        pgt.omap_rm.append(k)
                else:
                    pgt.omap_clear = True
                    pgt.omap_set.clear()
                    pgt.omap_rm.clear()
                pgt.attrs.setdefault(WHITEOUT_ATTR, None)
            elif op.op == OSDOp.ROLLBACK:
                self._start_rollback(msg, reply, int(op.off))
                return
            elif op.op == OSDOp.COPY_FROM:
                self._start_copy_from(msg, reply, op)
                return
            elif op.op == OSDOp.CALL:
                # WR-class object-class method: runs against the pre-op
                # state overlaid with everything staged EARLIER in this
                # op (pgt.attrs), and its mutations fold into the SAME
                # PGTransaction immediately — so a later plain op
                # overrides a class write and vice versa, honoring the
                # client's op ordering (PrimaryLogPG do_osd_ops CALL).
                if hctx is None:
                    hctx = self._make_hctx(
                        msg.oid, msg, writable=True, pgt=pgt
                    )
                try:
                    cls_name, method = op.name.split(".", 1)
                    _flags, fn = cls_get_method(cls_name, method)
                    # enforce CLS_METHOD_WR per method, not per message:
                    # an RD method riding a compound write op must still
                    # be denied mutations
                    hctx.writable = bool(_flags & CLS_WR)
                    outdata[i] = fn(hctx, op.data) or b""
                except ClsError as e:
                    # a failing method aborts the WHOLE transaction
                    # (nothing staged so far may land)
                    self._inflight_reqids.pop(msg.reqid.key(), None)
                    reply(self._errored(msg, e.errno))
                    return
                except Exception as e:
                    # a buggy/malformed-input method must not leak the
                    # exception past the reply (the client would hang on
                    # its registered reqid); the reference maps method
                    # faults to an errno the same way
                    dout("osd", 1, f"cls {op.name} raised {e!r}")
                    self._inflight_reqids.pop(msg.reqid.key(), None)
                    reply(self._errored(msg, -EINVAL))
                    return
                # fold this method's staged mutations NOW (in op order)
                staged = hctx.dirty()
                for k, v in hctx.attrs.items():
                    pgt.attrs[f"_{k}"] = v
                hctx.attrs.clear()
                if hctx.omap_cleared:
                    pgt.omap_clear = True
                    pgt.omap_set.clear()
                    pgt.omap_rm.clear()
                    hctx.omap_cleared = False
                for k, v in hctx.omap.items():
                    if v is None:
                        pgt.omap_set.pop(k, None)
                        pgt.omap_rm.append(k)
                    else:
                        pgt.omap_set[k] = v
                hctx.omap.clear()
                if hctx.data is not None:
                    pgt.write(0, hctx.data)
                    pgt.truncate = len(hctx.data)
                    size = len(hctx.data)
                    hctx.folded_data = hctx.data  # later methods' read()
                    hctx.data = None
                if staged:
                    pgt.attrs.setdefault(WHITEOUT_ATTR, None)
            else:
                self._inflight_reqids.pop(msg.reqid.key(), None)
                reply(self._errored(msg, -EINVAL))
                return
        # `size` tracked the ops SEQUENTIALLY (write-then-truncate caps,
        # truncate-then-write extends); make it authoritative for the
        # backends, which cannot recover op order from the PGTransaction.
        if pgt.truncate is not None:
            pgt.truncate = size
        # make_writeable (PrimaryLogPG): first mutation after a new snap
        # clones the current head — atomically with this transaction.
        if msg.snaps and not msg.snap_id:
            ss = self._get_snapset(msg.oid)
            if exists:
                new_snaps = ss.needs_clone(msg.snap_seq, list(msg.snaps))
                if new_snaps:
                    cid = ss.add_clone(new_snaps, self._object_size(msg.oid))
                    pgt.pre_clone = clone_oid(msg.oid, cid)
                    pgt.attrs[SS_ATTR] = ss.encode()
            elif not pgt.delete:
                # Created after those snaps existed: they must not cover
                # it, and reads at them must answer ENOENT.
                newest = max(msg.snaps)
                if newest > ss.seq:
                    ss.seq = newest
                    ss.born = newest
                    pgt.attrs[SS_ATTR] = ss.encode()
        # Cache-tier dirty marking (object_info_t FLAG_DIRTY): client
        # mutations on a writeback cache are flush candidates; internal
        # writes (promotes, flush bookkeeping) stay clean.
        if (
            self.pool.cache_mode == "writeback"
            and self.pool.tier_of >= 0
            and not pgt.delete
            and msg.reqid.client
            and not msg.reqid.client.startswith("osd.")
        ):
            pgt.attrs[DIRTY_ATTR] = b"1"

        def finish(rep: MOSDOpReply, remember: bool) -> None:
            self._finish_write(msg, reply, rep, remember)

        def on_commit() -> None:
            finish(
                MOSDOpReply(
                    reqid=msg.reqid,
                    result=0,
                    outdata=outdata,
                    version=self._version,
                    epoch=self._epoch,
                ),
                remember=True,
            )

        def on_failure(err: int) -> None:
            finish(self._errored(msg, -abs(err)), remember=False)

        kwargs = {}
        if self.pool.type == POOL_TYPE_ERASURE:
            kwargs["on_failure"] = on_failure
        try:
            self.backend.submit_transaction(pgt, msg.reqid, on_commit, **kwargs)
        except Exception as e:  # EcError on an invalid write plan
            err = getattr(e, "errno", EINVAL)
            finish(self._errored(msg, -abs(err)), remember=False)

    def _do_read(self, msg: MOSDOp, reply) -> None:
        outdata: list[bytes] = [b""] * len(msg.ops)
        read_extents: list[tuple[int, tuple[int, int]]] = []  # (op idx, extent)
        # Snapshot reads resolve to the covering clone (find_object_context):
        # the head serves when no clone is newer than the requested snap.
        target = msg.oid
        if msg.snap_id:
            ss = self._get_snapset(msg.oid)
            if msg.snap_id <= ss.born:
                reply(self._errored(msg, -ENOENT))  # created after the snap
                return
            cid = ss.resolve(msg.snap_id)
            if cid is not None:
                target = clone_oid(msg.oid, cid)
        size = self._object_size(target)
        exists = self._object_exists(target)
        if exists and self._getxattr(target, WHITEOUT_ATTR):
            exists, size = False, 0  # deleted head kept only for its clones
        result = 0
        for i, op in enumerate(msg.ops):
            if op.op == OSDOp.READ:
                if not exists:
                    result = -ENOENT
                    break
                ln = op.len or max(size - op.off, 0)
                ln = min(ln, max(size - op.off, 0))
                if ln > 0:
                    read_extents.append((i, (op.off, ln)))
            elif op.op == OSDOp.LIST_SNAPS:
                outdata[i] = self._get_snapset(msg.oid).encode()
            elif op.op == OSDOp.STAT:
                if not exists:
                    result = -ENOENT
                    break
                outdata[i] = size.to_bytes(8, "little")
            elif op.op == OSDOp.GETXATTR:
                val = self._getxattr(target, f"_{op.name}")
                if val is None:
                    result = -ENODATA
                    break
                outdata[i] = val
            elif op.op == OSDOp.CMPXATTR:
                cur = self._getxattr(target, f"_{op.name}")
                if not cmpxattr_ok(cur, op.data, int(op.off)):
                    result = -ECANCELED
                    break
            elif op.op == OSDOp.LIST_WATCHERS:
                # PrimaryLogPG do_osd_ops CEPH_OSD_OP_LIST_WATCHERS:
                # (entity, cookie) pairs currently registered on the head
                import json as _json

                outdata[i] = _json.dumps(
                    [
                        {"watcher": e, "cookie": c}
                        for e, c in sorted(self.watchers.get(msg.oid, {}))
                    ]
                ).encode()
            elif op.op == OSDOp.GETXATTRS:
                # Bulk client-xattr dump — the attrs leg of copy-get
                # (PrimaryLogPG::do_copy_get), consumed by COPY_FROM and
                # cache-tier promotion so metadata survives the trip.
                outdata[i] = encode_attrs(self._client_attrs(target))
            elif op.op in (OSDOp.OMAPGETKEYS, OSDOp.OMAPGETVALS):
                if self.pool.type == POOL_TYPE_ERASURE:
                    result = -EOPNOTSUPP
                    break
                coll = shard_coll(self.pgid, -1)
                try:
                    omap = self.osd.store.omap_get(coll, target)
                except Exception:
                    omap = {}
                if op.op == OSDOp.OMAPGETVALS:
                    outdata[i] = encode_attrs(omap)
                else:
                    from ..common.encoding import encode_str_list

                    outdata[i] = encode_str_list(sorted(omap))
            elif op.op == OSDOp.CALL:
                # RD-class object-class method (PrimaryLogPG do_osd_ops
                # CALL case; WR methods classify as writes in do_op)
                hctx = self._make_hctx(target, msg, writable=False)
                try:
                    cls_name, method = op.name.split(".", 1)
                    _flags, fn = cls_get_method(cls_name, method)
                    outdata[i] = fn(hctx, op.data) or b""
                except ClsError as e:
                    result = e.errno
                    break
                except Exception as e:
                    # a buggy/malformed-input method must not leak past
                    # the reply (the client would hang on its reqid)
                    dout("osd", 1, f"cls {op.name} raised {e!r}")
                    result = -EINVAL
                    break
            elif op.op == OSDOp.PGLS:
                # PrimaryLogPG::do_pgnls — enumerate this PG's heads
                # (snap clones are internal, filtered like the reference
                # filters non-head snapids from nls listings)
                import json as _json

                outdata[i] = _json.dumps(
                    sorted(
                        o
                        for o in self._list_local()
                        if "@" not in o
                        and not self._getxattr(o, WHITEOUT_ATTR)
                    )
                ).encode()
            else:
                result = -EINVAL
                break
        if result != 0 or not read_extents:
            reply(
                MOSDOpReply(
                    reqid=msg.reqid,
                    result=result,
                    outdata=outdata,
                    version=self._version,
                    epoch=self._epoch,
                )
            )
            return

        def on_read(results: dict) -> None:
            err, bufs = results[target]
            if err:
                reply(self._errored(msg, err))
                return
            for (i, _ext), buf in zip(read_extents, bufs):
                outdata[i] = buf
            reply(
                MOSDOpReply(
                    reqid=msg.reqid,
                    result=0,
                    outdata=outdata,
                    version=self._version,
                    epoch=self._epoch,
                )
            )

        self.backend.objects_read_and_reconstruct(
            {target: [ext for _i, ext in read_extents]},
            on_read,
            # end-to-end budget (ISSUE 17): sub-reads inherit the op's
            # remaining deadline so shards shed a doomed read's work
            deadline=getattr(msg, "deadline", 0.0),
        )

    def _finish_write(
        self, msg: MOSDOp, reply, rep: MOSDOpReply, remember: bool
    ) -> None:
        """Complete a write-class op: record in the dup window and release
        queued duplicate repliers."""
        key = msg.reqid.key()
        if remember:
            self._reqid_results[key] = rep
            if len(self._reqid_results) > 1000:  # bounded dup window
                self._reqid_results.pop(next(iter(self._reqid_results)))
        # Cache-tier residency bookkeeping: every completed mutation is the
        # authoritative place to learn an object now exists (first writes
        # arrive via the promotion pass-through, which skips the gate's
        # touch) or is gone (deletes).
        if self.pool.is_cache_tier() and rep.result == 0:
            if self._object_exists(msg.oid):
                self._tier_touch(msg.oid)
                self._tier_maybe_agent()
            else:
                self._tier_lru.pop(msg.oid, None)
        reply(rep)
        for dup_reply in self._inflight_reqids.pop(key, []):
            dup_reply(rep)

    # -- snapshots (PrimaryLogPG snap machinery) -------------------------------

    def _get_snapset(self, oid: str) -> SnapSet:
        return SnapSet.decode(self._getxattr(oid, SS_ATTR))

    def _apply_snap_trim(self, msg: MOSDOp, pgt: PGTransaction) -> None:
        """DELETE with a snap id = trim that snap from the object
        (PrimaryLogPG::trim_object): drop it from its clone's coverage and
        delete the clone once nothing references it."""
        ss = self._get_snapset(msg.oid)
        gone = ss.drop_snap(msg.snap_id)
        pgt.attrs[SS_ATTR] = ss.encode()
        if gone is not None:
            pgt.also_delete.append(clone_oid(msg.oid, gone))
        if not ss.clones and self._getxattr(msg.oid, WHITEOUT_ATTR):
            # last clone gone and the head was only a whiteout: reclaim it
            # (the snap-trimmer's whiteout garbage collection)
            pgt.delete = True
            pgt.attrs.clear()

    def _start_rollback(self, msg: MOSDOp, reply, snap_id: int) -> None:
        """ROLLBACK: make the head identical to the object's state at
        `snap_id` (PrimaryLogPG::_rollback_to).  Resolved clone content is
        read back and applied through the normal write pipeline, so EC
        hinfo/extent-cache stay coherent and replicas converge via the
        same repop path as any write."""
        oid = msg.oid
        ss = self._get_snapset(oid)
        if snap_id <= ss.born:
            # The object did not exist at that snap: rollback = delete
            # (the reference's _rollback_to ENOENT → _delete_oid path).
            msg.ops[:] = [OSDOp(op=OSDOp.DELETE)]
            self._do_write(msg, reply)
            return
        cid = ss.resolve(snap_id)
        if cid is None:
            # no clone newer than the snap: the head IS that state
            self._finish_write(
                msg,
                reply,
                MOSDOpReply(
                    reqid=msg.reqid,
                    result=0,
                    outdata=[b""] * len(msg.ops),
                    version=self._version,
                    epoch=self._epoch,
                ),
                remember=True,
            )
            return
        src = clone_oid(oid, cid)
        src_size = self._object_size(src)

        def proceed(data: bytes) -> None:
            msg.ops[:] = [OSDOp(op=OSDOp.WRITEFULL, data=data)]
            self._do_write(msg, reply)

        if src_size == 0:
            proceed(b"")
            return

        def on_read(results: dict) -> None:
            err, bufs = results[src]
            if err:
                self._finish_write(
                    msg, reply, self._errored(msg, err), remember=False
                )
                return
            proceed(bufs[0] if bufs else b"")

        self.backend.objects_read_and_reconstruct(
            {src: [(0, src_size)]}, on_read
        )

    def _start_copy_from(self, msg: MOSDOp, reply, op: OSDOp) -> None:
        """COPY_FROM: fetch the source object's bytes (this OSD acting as a
        client toward the source's primary — the objecter leg of
        PrimaryLogPG::do_copy_from) and apply them through the write
        pipeline as a full write."""
        src, src_snap = op.name, int(op.off)

        def on_fetched(err: int, outs: list[bytes]) -> None:
            if err:
                self._finish_write(
                    msg, reply, self._errored(msg, -abs(err)), remember=False
                )
                return
            data = outs[0] if outs else b""
            attrs = decode_attrs(outs[1]) if len(outs) > 1 else {}
            # copy-get carries the attr map too (PrimaryLogPG do_copy_get):
            # the copy REPLACES the destination — its old client xattrs
            # go, the source's come
            stale = set(self._client_attrs(msg.oid)) - set(attrs)
            msg.ops[:] = (
                [OSDOp(op=OSDOp.WRITEFULL, data=data)]
                + [OSDOp(op=OSDOp.RMXATTR, name=k) for k in sorted(stale)]
                + [
                    OSDOp(op=OSDOp.SETXATTR, name=k, data=v)
                    for k, v in sorted(attrs.items())
                ]
            )
            self._do_write(msg, reply)

        self.osd.internal_op(
            self.pool.id,
            src,
            [OSDOp(op=OSDOp.READ), OSDOp(op=OSDOp.GETXATTRS)],
            on_fetched,
            snap_id=src_snap,
            multi=True,
        )

    # -- object classes (src/objclass; PrimaryLogPG CALL) ----------------------

    def _make_hctx(self, oid: str, msg: MOSDOp, writable: bool, pgt=None):
        """cls_method_context_t for `oid`: pre-op state reads + staged
        overlay.  With `pgt`, attr reads consult the transaction first so
        a method observes plain SETXATTRs (and earlier folded CALLs) from
        the same compound op, in order.  Sync DATA reads are unavailable
        on EC pools (the reference's objects_read_sync answers
        -EOPNOTSUPP there too) and reflect pre-op bytes plus whole-object
        class writes — byte-range plain writes earlier in the same
        compound op are not visible to a later method's read().  Xattr
        state — what lock/version/refcount/numops key on — is fully
        ordered on every pool type."""
        from ..common.errs import EOPNOTSUPP

        exists = self._object_exists(oid) and not self._getxattr(
            oid, WHITEOUT_ATTR
        )

        def read_fn() -> bytes:
            if self.pool.type == POOL_TYPE_ERASURE:
                raise ClsError(
                    EOPNOTSUPP, "sync object read on an EC pool"
                )
            coll = shard_coll(self.pgid, -1)
            return bytes(
                self.osd.store.read(coll, oid, 0, self._object_size(oid))
            )

        def getattr_fn(name: str):
            if pgt is not None and f"_{name}" in pgt.attrs:
                return pgt.attrs[f"_{name}"]  # None == removed
            return self._getxattr(oid, f"_{name}")

        def omap_fn() -> dict:
            # on-store omap overlaid with what THIS op already staged
            # (clear -> rm -> set, the backends' apply order)
            coll = shard_coll(self.pgid, -1)
            try:
                base = dict(self.osd.store.omap_get(coll, oid))
            except Exception:
                base = {}
            if pgt is not None:
                if pgt.omap_clear:
                    base = {}
                for k in pgt.omap_rm:
                    base.pop(k, None)
                base.update(pgt.omap_set)
            return base

        return ClsHCtx(
            exists=exists,
            read_fn=read_fn,
            getattr_fn=getattr_fn,
            entity=msg.reqid.client,
            writable=writable,
            omap_fn=None if self.pool.type == POOL_TYPE_ERASURE else omap_fn,
        )

    # -- cache tiering (PrimaryLogPG maybe_handle_cache / TierAgentState) ------

    def _tier_gate(self, msg: MOSDOp, reply, conn, writing: bool) -> bool:
        """Returns True to continue normal dispatch, False when the op was
        consumed (promotion in flight, forwarded, or rejected).
        `writing` is do_op's once-computed write classification.

        Scope mirrors the reference's writeback/readonly modes with one
        documented simplification: cache pools don't combine with pool
        snapshots.  Promotion and flush carry client xattrs (cls state)
        alongside bytes, as the reference's copy-get does.
        """
        first = msg.ops[0].op if msg.ops else 0
        if msg.oid in self._flushing and (
            writing or first in (OSDOp.CACHE_FLUSH, OSDOp.CACHE_EVICT)
        ):
            # Mid-flush: a write racing the write-back could get its dirty
            # mark cleared and then be evicted — queue until the flush
            # completes (PrimaryLogPG wait_for_blocked_object).
            self._flushing[msg.oid].append((msg, reply, conn))
            return False
        if first == OSDOp.CACHE_FLUSH:
            self._do_cache_flush(msg, reply)
            return False
        if first == OSDOp.CACHE_EVICT:
            self._do_cache_evict(msg, reply)
            return False
        if first in (OSDOp.PGLS, OSDOp.NOTIFY):
            return True
        key = msg.reqid.key()
        if key in self._tier_pass:
            return True
        if writing and self.pool.cache_mode == "readonly":
            reply(self._errored(msg, -EPERM))
            return False
        pure_delete = (
            writing
            and all(op.op == OSDOp.DELETE for op in msg.ops)
            and not msg.snap_id
        )
        if pure_delete and self.pool.cache_mode == "writeback":
            # Forward the delete to the base pool FIRST: a cache-only
            # delete would resurrect from the base on the next miss.
            def on_base(err: int, _data: bytes) -> None:
                if err and err != -ENOENT:
                    reply(self._errored(msg, err))
                    return
                self._tier_lru.pop(msg.oid, None)
                self._tier_pass.add(key)
                try:
                    self.do_op(msg, reply, conn)
                finally:
                    self._tier_pass.discard(key)

            self.osd.internal_op(
                self.pool.tier_of, msg.oid, [OSDOp(op=OSDOp.DELETE)], on_base
            )
            return False
        # COPY_FROM reads its SOURCE locally via an internal fetch that
        # bypasses this gate, so a cold (base-resident) source must be
        # promoted before the copy can run.
        for op in msg.ops:
            if op.op == OSDOp.COPY_FROM and not self._object_exists(op.name):
                self._tier_promote(op.name, (msg, reply, conn))
                return False
        if self._object_exists(msg.oid):
            self._tier_touch(msg.oid)
            if writing:
                self._tier_maybe_agent()
            return True
        # Miss: promote from the base pool, queue the op behind the fetch
        # (PrimaryLogPG::promote_object + wait_for_blocked_object).
        self._tier_promote(msg.oid, (msg, reply, conn))
        if writing:
            self._tier_maybe_agent()
        return False

    def _tier_promote(self, oid: str, entry) -> None:
        """Queue an op behind promotion of `oid`; start the base fetch if
        this is the first waiter."""
        waiters = self._promoting.get(oid)
        if waiters is not None:
            waiters.append(entry)
            return
        self._promoting[oid] = [entry]

        def on_fetched(err: int, outs: list[bytes]) -> None:
            data = outs[0] if outs else b""
            attrs = decode_attrs(outs[1]) if len(outs) > 1 else {}
            self._tier_promoted(oid, err, data, attrs)

        # copy-get: data + the client-xattr map in one fetch, so cls
        # state (locks, versions, refcounts) survives promotion
        self.osd.internal_op(
            self.pool.tier_of,
            oid,
            [OSDOp(op=OSDOp.READ), OSDOp(op=OSDOp.GETXATTRS)],
            on_fetched,
            multi=True,
        )

    def _tier_drain(self, oid: str) -> None:
        """Re-dispatch ops queued behind a promotion; each gets a one-shot
        gate pass so a base-absent object can't loop through promotion."""
        for m, r, c in self._promoting.pop(oid, []):
            k = m.reqid.key()
            self._tier_pass.add(k)
            try:
                self.do_op(m, r, c)
            finally:
                self._tier_pass.discard(k)

    def _tier_promoted(
        self, oid: str, err: int, data: bytes, attrs: dict[str, bytes] | None = None
    ) -> None:
        if err == -ENOENT:
            # Base has nothing: reads answer ENOENT, writes create fresh.
            self._tier_drain(oid)
            return
        if err:
            for m, r, _c in self._promoting.pop(oid, []):
                r(self._errored(m, -EAGAIN if err == -EAGAIN else err))
            return
        # Write the promoted copy through the replicated pipeline as an
        # internal (clean, non-dirty) object — bytes AND client xattrs,
        # so flush→evict→promote round-trips cls state — then release
        # the waiters.
        self._tier_tid += 1
        pm = MOSDOp(
            reqid=ReqId(client=f"osd.{self.osd.whoami}.promote", tid=self._tier_tid),
            pgid=PgId(self.pool.id, self.pgid.ps, -1),
            oid=oid,
            ops=[OSDOp(op=OSDOp.WRITEFULL, data=data)]
            + [
                OSDOp(op=OSDOp.SETXATTR, name=k, data=v)
                for k, v in sorted((attrs or {}).items())
            ],
            epoch=self._epoch,
        )

        def on_written(rep: MOSDOpReply) -> None:
            if rep.result:
                for m, r, _c in self._promoting.pop(oid, []):
                    r(self._errored(m, rep.result))
                return
            self._tier_touch(oid)
            self._tier_drain(oid)

        self.do_op(pm, on_written)

    def _tier_touch(self, oid: str) -> None:
        self._tier_lru[oid] = None
        self._tier_lru.move_to_end(oid)

    def _is_dirty(self, oid: str) -> bool:
        return bool(self._getxattr(oid, DIRTY_ATTR))

    def _tier_flush(self, oid: str, done) -> None:
        """Write a dirty object's bytes back to the base pool, then clear
        the dirty marker through the replicated pipeline.  done(err).
        Writes on `oid` are blocked (queued in _flushing) for the duration,
        so the clear cannot race a fresh mutation."""
        if not self._object_exists(oid):
            done(-ENOENT)
            return
        if oid in self._flushing:
            done(-EBUSY)  # a flush is already running; writes are queued
            return
        if not self._is_dirty(oid):
            # Clean normally means base-backed — but an object written into
            # the pool BEFORE `osd tier add` is clean with no base copy
            # (and would be unevictable, see _tier_evict).  Verify, and
            # write it back if the base lacks it.
            def on_stat(err: int, _data: bytes) -> None:
                if err == -ENOENT:
                    self._tier_writeback(oid, done)
                else:
                    done(0 if not err else err)

            self.osd.internal_op(
                self.pool.tier_of, oid, [OSDOp(op=OSDOp.STAT)], on_stat
            )
            return
        self._tier_writeback(oid, done)

    def _tier_writeback(self, oid: str, done) -> None:
        """The write-back leg of a flush: copy bytes AND client xattrs
        (cls locks/versions/refcounts included — the reference's copy-get
        carries the attr map) to the base pool, then clear the dirty
        marker.  Writers on `oid` queue in _flushing."""
        self._flushing[oid] = []
        coll = shard_coll(self.pgid, -1)
        data = self.osd.store.read(coll, oid, 0, self._object_size(oid))
        attrs = self._client_attrs(oid)

        def finish(err: int) -> None:
            waiters = self._flushing.pop(oid, [])
            done(err)
            for m, r, c in waiters:
                self.do_op(m, r, c)

        def on_ack(err: int, _data: bytes) -> None:
            if err:
                finish(err)
                return
            pgt = PGTransaction(oid=oid)
            pgt.attrs[DIRTY_ATTR] = None  # rm
            self._tier_tid += 1
            self.backend.submit_transaction(
                pgt,
                ReqId(client=f"osd.{self.osd.whoami}.flush", tid=self._tier_tid),
                lambda: finish(0),
            )

        self.osd.internal_op(
            self.pool.tier_of,
            oid,
            [OSDOp(op=OSDOp.WRITEFULL, data=bytes(data))]
            + [
                OSDOp(op=OSDOp.SETXATTR, name=k, data=v)
                for k, v in sorted(attrs.items())
            ],
            on_ack,
        )

    def _tier_evict(self, oid: str, done) -> None:
        """Drop a CLEAN object from the cache (local delete only — the base
        copy is authoritative; the next miss re-promotes).  done(err).

        Before deleting, the base copy's existence is verified: an object
        that predates the tier relationship (written into the pool before
        `osd tier add`) carries no dirty mark yet exists nowhere else —
        deleting it would be permanent loss.  Such objects answer -EBUSY
        (flush them first), which also covers the reference's reason for
        refusing non-empty tier pools without --force-nonempty."""
        if not self._object_exists(oid):
            done(-ENOENT)
            return
        if self._is_dirty(oid):
            done(-EBUSY)
            return
        if oid in self._flushing:
            done(-EBUSY)  # a flush (or another evict) holds the object
            return
        # Block writes on the oid for the whole evict (the reference's
        # object-context write lock): a write acked while the base STAT
        # is in flight must not be deleted out from under the client.
        self._flushing[oid] = []

        def finish(err: int) -> None:
            waiters = self._flushing.pop(oid, [])
            done(err)
            for m, r, c in waiters:
                self.do_op(m, r, c)

        def on_base_stat(err: int, _data: bytes) -> None:
            if err:
                # base copy unverifiable (absent or unreachable): refuse
                finish(-EBUSY)
                return
            if self._is_dirty(oid):  # re-dirtied while we checked
                finish(-EBUSY)
                return
            pgt = PGTransaction(oid=oid, delete=True)
            self._tier_tid += 1
            self._tier_lru.pop(oid, None)
            self.backend.submit_transaction(
                pgt,
                ReqId(client=f"osd.{self.osd.whoami}.evict", tid=self._tier_tid),
                lambda: finish(0),
            )

        self.osd.internal_op(
            self.pool.tier_of, oid, [OSDOp(op=OSDOp.STAT)], on_base_stat
        )

    def _tier_op_done(self, msg: MOSDOp, reply):
        """done(err) closure answering a CACHE_FLUSH/CACHE_EVICT client op."""

        def done(err: int) -> None:
            if err:
                reply(self._errored(msg, err))
            else:
                reply(
                    MOSDOpReply(
                        reqid=msg.reqid,
                        result=0,
                        outdata=[b""] * len(msg.ops),
                        version=self._version,
                        epoch=self._epoch,
                    )
                )

        return done

    def _do_cache_flush(self, msg: MOSDOp, reply) -> None:
        self._tier_flush(msg.oid, self._tier_op_done(msg, reply))

    def _do_cache_evict(self, msg: MOSDOp, reply) -> None:
        self._tier_evict(msg.oid, self._tier_op_done(msg, reply))

    def _tier_share(self) -> int:
        """This PG's slice of the pool-wide object target (ceil split;
        the reference agent works from per-PG dirty/full ratios)."""
        return -(-self.pool.target_max_objects // max(1, self.pool.pg_num))

    def _tier_maybe_agent(self) -> None:
        """Cheap trigger: only schedule the agent's full store scan when
        the in-memory LRU (an approximate local head count — rebuilt
        lazily after a primary restart) crosses the PG's share.  Runs for
        readonly caches too: promotions accumulate there and must still
        honor target_max_objects (evict-only; nothing is ever dirty)."""
        if (
            self.pool.target_max_objects
            and self.pool.cache_mode in ("writeback", "readonly")
            and len(self._tier_lru) > self._tier_share()
        ):
            asyncio.get_event_loop().call_soon(self._tier_agent)

    def _tier_agent(self) -> None:
        """Flush-and-evict down to target_max_objects, coldest first
        (TierAgentState evict_mode; utilization-driven in the reference,
        object-count-driven here).  One store scan computes the whole
        victim batch; victims are processed sequentially, then the scan
        repeats only if still over target."""
        target = self.pool.target_max_objects
        if (
            not target
            or self.pool.cache_mode == "none"
            or self._tier_agent_busy
            or not self.peering.is_primary()
        ):
            return
        share = self._tier_share()
        heads = [o for o in self._list_local() if "@" not in o]
        excess = len(heads) - share
        if excess <= 0:
            return
        # coldest = LRU order, with never-touched objects (e.g. after a
        # primary restart, the in-memory LRU is empty) treated as coldest
        in_lru = {o: i for i, o in enumerate(self._tier_lru)}
        victims = sorted(heads, key=lambda o: in_lru.get(o, -1))[:excess]
        self._tier_agent_busy = True
        loop = asyncio.get_event_loop()

        def next_victim(err: int) -> None:
            if err:
                # e.g. base pool unplaceable (-EAGAIN): stop this batch and
                # back off instead of spinning against a stuck victim
                self._tier_agent_busy = False
                loop.call_later(0.5, self._tier_agent)
                return
            if not victims:
                self._tier_agent_busy = False
                loop.call_soon(self._tier_agent)  # rescan; exits when under
                return
            victim = victims.pop(0)

            def flushed(e: int) -> None:
                if e:
                    next_victim(e)
                else:
                    self._tier_evict(victim, next_victim)

            self._tier_flush(victim, flushed)

        next_victim(0)

    # -- watch / notify (PrimaryLogPG watchers, Watch.cc) ----------------------

    def _do_watch(self, conn, msg: MOSDOp, reply) -> None:
        op = msg.ops[0]
        cookie = int(op.off)
        if not self._object_exists(msg.oid):
            reply(self._errored(msg, -ENOENT))
            return
        table = self.watchers.setdefault(msg.oid, {})
        wkey = (msg.reqid.client, cookie)
        if op.len:
            table[wkey] = conn
        else:
            table.pop(wkey, None)
            if not table:
                self.watchers.pop(msg.oid, None)
        reply(
            MOSDOpReply(
                reqid=msg.reqid,
                result=0,
                outdata=[b""],
                version=self._version,
                epoch=self._epoch,
            )
        )

    def _do_notify(self, msg: MOSDOp, reply) -> None:
        import json as _json

        from ..msg.messages import MWatchNotify

        op = msg.ops[0]
        timeout_s = (int(op.off) or 3000) / 1000.0
        watchers = dict(self.watchers.get(msg.oid, {}))
        self._notify_id += 1
        nid = self._notify_id
        state = {
            "pending": set(watchers),
            "acks": {},
            "conns": dict(watchers),
            "done": False,
        }

        def finish() -> None:
            if state["done"]:
                return
            state["done"] = True
            self._notifies.pop(nid, None)
            out = _json.dumps(
                {
                    "acks": {
                        f"{ent}/{ck}": p.hex()
                        for (ent, ck), p in state["acks"].items()
                    },
                    "timeouts": sorted(
                        f"{ent}/{ck}" for ent, ck in state["pending"]
                    ),
                }
            ).encode()
            reply(
                MOSDOpReply(
                    reqid=msg.reqid,
                    result=0,
                    outdata=[out],
                    version=self._version,
                    epoch=self._epoch,
                )
            )

        state["finish"] = finish
        if not watchers:
            finish()
            return
        self._notifies[nid] = state
        for (entity, cookie), conn in watchers.items():
            push = MWatchNotify(
                oid=msg.oid,
                pgid=self.pgid,
                notify_id=nid,
                cookie=cookie,
                payload=op.data,
                is_ack=0,
                watcher=entity,
            )

            async def _send(conn=conn, push=push, wkey=(entity, cookie)) -> None:
                try:
                    await conn.send_message(push)
                except ConnectionError:
                    state["pending"].discard(wkey)
                    if not state["pending"]:
                        finish()

            asyncio.get_event_loop().create_task(_send())
        asyncio.get_event_loop().call_later(timeout_s, finish)

    def handle_watch_ack(self, msg) -> None:
        state = self._notifies.get(msg.notify_id)
        wkey = (msg.watcher, msg.cookie)
        if state is None or wkey not in state["pending"]:
            return
        state["pending"].discard(wkey)
        state["acks"][wkey] = msg.payload
        if not state["pending"]:
            state["finish"]()

    def on_client_reset(self, conn) -> None:
        """A client session died: its watches evaporate (watch timeout via
        connection teardown) and pending notifies stop waiting for it."""
        for oid in list(self.watchers):
            table = self.watchers[oid]
            for wkey in [k for k, wc in table.items() if wc is conn]:
                del table[wkey]
            if not table:
                del self.watchers[oid]
        for state in list(self._notifies.values()):
            stale = {
                k for k, wc in state["conns"].items() if wc is conn
            } & state["pending"]
            if stale:
                state["pending"] -= stale
                if not state["pending"]:
                    state["finish"]()

    def _errored(self, msg: MOSDOp, err: int) -> MOSDOpReply:
        return MOSDOpReply(
            reqid=msg.reqid,
            result=err,
            outdata=[],
            version=0,
            epoch=self._epoch,
        )

    # -- object metadata helpers ----------------------------------------------

    def _object_size(self, oid: str) -> int:
        if self.pool.type == POOL_TYPE_ERASURE:
            return self.backend.object_size(oid)
        coll = shard_coll(self.pgid, -1)
        try:
            return self.osd.store.stat(coll, oid)
        except Exception:
            return 0

    def _object_exists(self, oid: str) -> bool:
        if self.pool.type == POOL_TYPE_ERASURE:
            return self.backend.get_object_info(oid) is not None
        coll = shard_coll(self.pgid, -1)
        return self.osd.store.exists(coll, oid)

    def _getxattr(self, oid: str, name: str) -> bytes | None:
        coll = shard_coll(self.pgid, self.whoami_shard())
        try:
            return self.osd.store.getattr(coll, oid, name)
        except Exception:
            return None

    def _client_attrs(self, oid: str) -> dict[str, bytes]:
        """All client-visible xattrs (the `_`-prefixed store attrs: plain
        SETXATTRs plus object-class state — cls_lock holders, cls_version,
        refcounts), keyed by their client names."""
        coll = shard_coll(self.pgid, self.whoami_shard())
        try:
            raw = self.osd.store.getattrs(coll, oid)
        except Exception:
            return {}
        return {k[1:]: v for k, v in raw.items() if k.startswith("_")}

    # -- recovery driver -------------------------------------------------------

    def _kick_recovery(self) -> None:
        """Start recoveries up to osd_recovery_max_active
        (the OSD recovery wq, scaled to this PG).  While the OSD's
        recovery-storm controller is ENGAGED, admission belongs to its
        cross-PG waves (ISSUE 15) — the per-PG trickle yields so wave
        pacing (and its SLO shedding) actually governs; degraded-op
        prioritization still admits directly via _recover_one."""
        if not self.peering.is_primary() or not self.peering.is_active():
            return
        storm = getattr(self.osd, "recovery_storm", None)
        if storm is not None and storm.engaged:
            return
        max_active = self.osd.conf.get("osd_recovery_max_active")
        for oid in self.peering.all_missing_oids():
            if len(self.recovering) >= max_active:
                break
            self._recover_one(oid)

    def _recover_one(self, oid: str) -> None:
        if oid in self.recovering or not self.peering.is_active():
            return
        osds = self.peering.osds_missing(oid)
        if not osds:
            return
        self.recovering.add(oid)
        if self.pool.type == POOL_TYPE_ERASURE:
            missing_on = {
                self._acting.index(o) for o in osds if o in self._acting
            }
        else:
            missing_on = osds

        def on_complete(err: int) -> None:
            if err:
                self.recovering.discard(oid)
                self.clog_error(f"pg {self.pgid} recovery of {oid} failed: {err}")
                return
            self.on_global_recover(oid)

        self.backend.recover_object(oid, missing_on, on_complete)

    def progress_active(self) -> bool:
        """READ-ONLY: does this PG currently have progress-worthy
        activity on this primary?  The pure predicate monitoring polls
        (tools/chaos.py) use instead of progress_status(), whose
        episode bookkeeping belongs to the OSD's own status reports."""
        p = self.peering
        return (
            p.is_primary()
            and p.is_active()
            and bool(
                p.all_missing_oids()
                or self.recovering
                or p.backfill_targets
                or self.scrubber.active
            )
        )

    def note_recovery_bytes(self, oid: str, nbytes: int) -> None:
        """Backend hook (ECBackend._push_recovered): reconstructed bytes
        fold into this PG's recovery-progress event.  Gated like the
        done counter — backfill pushes ride the same backend path but
        are not recovery."""
        if oid in self.recovering:
            self._recovery_done_bytes += int(nbytes)
            # workload attribution (ISSUE 10): recovery traffic counts
            # against its pool under the `recovery` op class, so the
            # iostat view separates tenant load from the cluster's own
            accountant = getattr(self.osd, "io_accountant", None)
            if accountant is not None:
                accountant.account(
                    self.pool.id, "recovery", "recovery", nbytes
                )

    def progress_status(self) -> list[dict]:
        """Progress events for the OSD status blob (ISSUE 8): one entry
        per active recovery / backfill / scrub on this PRIMARY, each
        with objects/bytes done vs total.  The mgr's progress module
        (mgr/progress.py) aggregates these into per-PG bars with rate +
        ETA and raises PG_RECOVERY_STALLED when one stops advancing.

        Episode bookkeeping note: this renderer maintains the recovery
        high-water total (a monotone max) and zeroes the counters once
        an episode drains — both IDEMPOTENT, so extra callers beyond
        the status heartbeat are safe; they just cannot observe a
        final done==total event (absence is the completion signal)."""
        p = self.peering
        if not p.is_primary() or not p.is_active():
            return []
        events: list[dict] = []
        outstanding = p.all_missing_oids()
        if outstanding or self.recovering:
            self._recovery_final_reports = 0  # episode (re)opened
            # high-water total: newly discovered missing objects grow
            # the denominator, they never shrink `done`
            self._recovery_total = max(
                self._recovery_total, self._recovery_done + len(outstanding)
            )
            ev = {
                "kind": "recovery",
                "objects_done": self._recovery_done,
                "objects_total": self._recovery_total,
                "bytes_done": self._recovery_done_bytes,
                "bytes_total": 0,  # unknown until rebuilt (best-effort)
            }
            inflight = getattr(self.backend, "recovery_inflight", None)
            if inflight is not None:
                ev["inflight"] = inflight()
            events.append(ev)
        elif (
            self._recovery_total
            or self._recovery_done
            or self._recovery_done_bytes
        ):
            # episode complete: emit a final done==total report so the
            # mgr can classify the event as completed (without it the
            # event simply vanishes at done<total and counts as
            # expired/lost).  Repeated on a few reports — the mgr
            # samples a last-write-wins status blob, so a one-shot
            # report can be overwritten before a module tick sees it —
            # then the counters reset so the next episode starts at
            # zero.  The done counters are checked too: an episode that
            # starts AND finishes entirely between two status reports
            # never set _recovery_total here, and its leftover done
            # count would pre-fill the next episode's bar.
            if self._recovery_done:
                if not self._recovery_final_reports:
                    self._recovery_final_reports = 3
                events.append({
                    "kind": "recovery",
                    "objects_done": self._recovery_done,
                    # everything still outstanding drained some other
                    # way (overwrites); the recovered count IS the
                    # episode's completed total
                    "objects_total": self._recovery_done,
                    "bytes_done": self._recovery_done_bytes,
                    "bytes_total": 0,
                })
                self._recovery_final_reports -= 1
            if not self._recovery_final_reports:
                self._recovery_total = 0
                self._recovery_done = 0
                self._recovery_done_bytes = 0
        if p.backfill_targets:
            heads = sorted(self._list_local())
            total = len(heads) * len(p.backfill_targets)
            done = 0
            for osd in p.backfill_targets:
                cursor = p.last_backfill.get(osd, "")
                done += bisect.bisect_right(heads, cursor)
            events.append({
                "kind": "backfill",
                "objects_done": done,
                "objects_total": max(total, done),
                "bytes_done": 0,
                "bytes_total": 0,
            })
        scrub = self.scrubber.progress()
        if scrub is not None:
            events.append(scrub)
        return events

    def blocked_ops_summary(self) -> dict:
        """What's queued and why (OpTracker's dump_blocked_ops view):
        degraded-wait, promotion-wait, and flush-wait queues by object."""
        out = {}
        if self.waiting_for_degraded:
            out["waiting_for_degraded"] = {
                oid: len(cbs) for oid, cbs in self.waiting_for_degraded.items()
            }
        if self._promoting:
            out["waiting_for_promote"] = {
                oid: len(w) for oid, w in self._promoting.items()
            }
        if self._flushing:
            out["waiting_for_flush"] = {
                oid: len(w) for oid, w in self._flushing.items()
            }
        return out

    # -- lost/unfound (PrimaryLogPG mark_all_unfound_lost; MissingLoc) ---------

    def list_unfound(self) -> list[str]:
        """Missing objects with NO live source (MissingLoc's unfound set):
        replicated — no up acting member holds a copy; EC — fewer than k
        up shards hold theirs.  Recovery of these can never complete and
        ops touching them wait forever until the operator intervenes
        (qa/tasks/ec_lost_unfound.py is the reference's coverage)."""
        p = self.peering
        if not p.is_primary() or not p.is_active():
            # conflating "wrong daemon" with "nothing unfound" would
            # mislead the operator running this against a replica
            raise ValueError(
                f"pg {self.pgid}: not the active primary here"
            )
        up_acting = [
            o
            for o in self._acting
            if o != PG_NONE and self.osd.osdmap.is_up(o)
        ]
        need = self.backend.k if self.pool.type == POOL_TYPE_ERASURE else 1
        out = []
        for oid in p.all_missing_oids():
            # a backfill target whose cursor hasn't passed `oid` holds at
            # best a STALE copy — it is not a source (same union
            # get_shard_missing applies on the read path)
            missing_on = p.osds_missing(oid) | p.backfill_pending_osds(oid)
            holders = [o for o in up_acting if o not in missing_on]
            if len(holders) < need:
                out.append(oid)
        return out

    def mark_unfound_lost(self, mode: str = "delete") -> list[str]:
        """`ceph pg <pgid> mark_unfound_lost delete` — give up on unfound
        objects: drop them from every missing set, delete surviving
        remnant shards through the normal transaction fan-out, and
        release ops queued behind their recovery (they re-run and answer
        ENOENT).  Only the reference's `delete` mode is offered: `revert`
        requires prior-version data this framework's log doesn't retain.
        """
        if mode != "delete":
            raise ValueError(
                "only mode='delete' is supported (revert needs rollback data)"
            )
        lost = self.list_unfound()
        for oid in lost:
            self.peering.missing.rm(oid)
            for m in self.peering.peer_missing.values():
                m.rm(oid)
            self.recovering.discard(oid)
            self._tier_tid += 1
            pgt = PGTransaction(oid=oid, delete=True)
            try:
                self.backend.submit_transaction(
                    pgt,
                    ReqId(
                        client=f"osd.{self.osd.whoami}.lost",
                        tid=self._tier_tid,
                    ),
                    lambda: None,
                )
            except Exception as e:
                # remnant cleanup is best-effort: the object is already
                # struck from the missing sets either way
                dout("osd", 5, f"pg {self.pgid}: lost-delete of {oid}: {e!r}")
            self.clog_error(
                f"pg {self.pgid} marking unfound object {oid} lost (deleted)"
            )
            for cb in self.waiting_for_degraded.pop(oid, []):
                cb()
        return lost

    # -- backfill driver -------------------------------------------------------
    #
    # PeeringState's WaitLocalBackfillReserved → WaitRemoteBackfillReserved
    # → Backfilling chain (PeeringState.cc), tick-driven: the primary takes
    # a local slot, reserves a remote slot on every target, then walks its
    # sorted object namespace in osd_backfill_scan_max chunks, pushing each
    # object and advancing the per-target last_backfill cursor.

    def _backfill_key(self) -> tuple:
        return ("bf", self.pool.id, self.ps)

    def _kick_backfill(self) -> None:
        p = self.peering
        if (
            not p.is_primary()
            or not p.is_active()
            or not p.backfill_targets
            or self._bf_inflight
        ):
            return
        if not self._bf_local_reserved:
            # backfill rides the base priority so a storm's recovery
            # reservation (osd_recovery_op_priority, strictly higher)
            # can preempt it mid-chunk; the preempt callback surrenders
            # every slot and the tick loop re-grants deterministically
            # once the storm releases
            if not self.osd.local_reserver.try_reserve(
                self._backfill_key(),
                priority=0,
                on_preempt=self._on_backfill_preempted,
            ):
                return  # all local slots busy; retry next tick
            self._bf_local_reserved = True
        missing_grants = p.backfill_targets - self._bf_granted
        if missing_grants:
            # Reservation messages carry the INTERVAL epoch (peering.epoch,
            # set only when the acting set changes) so unrelated map bumps
            # cannot invalidate an in-flight grant.
            for osd in sorted(missing_grants):
                self.osd.send_cluster(
                    osd,
                    MBackfillReserve(
                        pgid=self.pgid,
                        op=MBackfillReserve.REQUEST,
                        epoch=self.peering.epoch,
                        from_osd=self.osd.whoami,
                    ),
                )
            return  # chunk starts when the grants arrive
        self._backfill_chunk()

    def on_backfill_reserve(self, msg: MBackfillReserve) -> None:
        """GRANT/REJECT from a target (primary side)."""
        stale = (
            msg.epoch != self.peering.epoch
            or msg.from_osd not in self.peering.backfill_targets
        )
        if stale:
            if msg.op == MBackfillReserve.GRANT:
                # The grantor holds a remote slot for a session we no
                # longer run: hand it back or it leaks forever.
                self.osd.send_cluster(
                    msg.from_osd,
                    MBackfillReserve(
                        pgid=self.pgid,
                        op=MBackfillReserve.RELEASE,
                        epoch=msg.epoch,
                        from_osd=self.osd.whoami,
                    ),
                )
            return
        if msg.op == MBackfillReserve.GRANT:
            self._bf_granted.add(msg.from_osd)
            if self.peering.backfill_targets <= self._bf_granted:
                self._backfill_chunk()
        elif msg.op == MBackfillReserve.REJECT:
            # Target full (RemoteReservationRejectedTooFull): give up every
            # reservation we hold so other PGs on this OSD can run, and
            # retry the whole handshake on a later tick.
            self._surrender_reservations()

    def _backfill_chunk(self) -> None:
        import bisect

        p = self.peering
        if not p.backfill_targets or self._bf_inflight:
            return
        if not self._bf_local_reserved:
            # preempted (or never reserved): the walk stops at the next
            # chunk boundary; the tick loop re-reserves and resumes from
            # the cursors once a slot frees
            return
        scan_max = self.osd.conf.get("osd_backfill_scan_max")
        objects = self._list_local()  # store returns them sorted
        self._bf_chunk_targets = {}
        self._bf_failed = set()
        chunk: dict[str, set[int]] = {}
        for osd in sorted(p.backfill_targets):
            lo = bisect.bisect_right(objects, p.last_backfill[osd])
            pending = objects[lo : lo + scan_max]
            self._bf_chunk_targets[osd] = pending
            for oid in pending:
                chunk.setdefault(oid, set()).add(osd)
        if not chunk:
            self._backfill_complete(list(p.backfill_targets))
            return
        self._bf_inflight = set(chunk)
        self.osd.perf.inc("backfill_pushes", len(chunk))
        gen = self._bf_gen
        for oid, osds in chunk.items():
            if self.pool.type == POOL_TYPE_ERASURE:
                missing_on = {
                    self._acting.index(o) for o in osds if o in self._acting
                }
            else:
                missing_on = osds

            def on_done(err: int, oid=oid) -> None:
                if gen != self._bf_gen:
                    return  # interval changed mid-push; session is dead
                self._bf_inflight.discard(oid)
                if err:
                    self._bf_failed.add(oid)
                    self.clog_error(
                        f"pg {self.pgid} backfill push of {oid} failed: {err}"
                    )
                if not self._bf_inflight:
                    self._backfill_chunk_done()

            self.backend.recover_object(oid, missing_on, on_done)

    def _backfill_chunk_done(self) -> None:
        p = self.peering
        scan_max = self.osd.conf.get("osd_backfill_scan_max")
        # A failed push caps cursor advance below the failed object, so it
        # is re-scanned (and re-pushed) by a later chunk — the cursor must
        # never skip an untransferred object.
        barrier = min(self._bf_failed) if self._bf_failed else None
        had_failures = bool(self._bf_failed)
        finished: list[int] = []
        for osd, pending in self._bf_chunk_targets.items():
            if osd not in p.backfill_targets:
                continue
            done = (
                pending
                if barrier is None
                else [o for o in pending if o < barrier]
            )
            if done:
                p.last_backfill[osd] = max(p.last_backfill[osd], done[-1])
            if not had_failures and len(pending) < scan_max:
                finished.append(osd)  # scan exhausted: target is complete
        self._bf_chunk_targets = {}
        self._bf_failed = set()
        if finished:
            self._backfill_complete(finished)
        if p.backfill_targets:
            if had_failures:
                return  # retry from the barrier on the next tick, not hot
            self._backfill_chunk()  # keep walking; chunk size throttles

    def _backfill_complete(self, targets: list[int]) -> None:
        p = self.peering
        for osd in targets:
            dout("osd", 5, f"pg {self.pgid} backfill to osd.{osd} complete")
            p.backfill_targets.discard(osd)
            p.last_backfill.pop(osd, None)
            self._bf_granted.discard(osd)
            self.osd.send_cluster(
                osd,
                MBackfillReserve(
                    pgid=self.pgid,
                    op=MBackfillReserve.RELEASE,
                    epoch=self.peering.epoch,
                    from_osd=self.osd.whoami,
                ),
            )
        if not p.backfill_targets:
            self._release_local_backfill()

    def _release_local_backfill(self) -> None:
        if self._bf_local_reserved:
            self.osd.local_reserver.release(self._backfill_key())
            self._bf_local_reserved = False

    def _on_backfill_preempted(self) -> None:
        """A higher-priority reservation (recovery-storm rebuild) took
        our local slot: surrender the remote grants too — holding them
        while unable to push would starve the targets' other primaries
        — and let the tick loop re-run the whole handshake once a slot
        frees.  The local slot is already gone (the reserver popped it
        before firing this callback), so only the flag resets here;
        `_surrender_reservations`'s release of the un-held key is the
        exactly-once no-op the reserver guarantees."""
        self._bf_local_reserved = False
        self._surrender_reservations()

    def _surrender_reservations(self) -> None:
        """Give back every slot (local + granted remotes) without touching
        cursors — used on REJECT so one full target cannot starve other
        PGs; the next tick restarts the handshake from scratch."""
        for osd in self._bf_granted:
            self.osd.send_cluster(
                osd,
                MBackfillReserve(
                    pgid=self.pgid,
                    op=MBackfillReserve.RELEASE,
                    epoch=self.peering.epoch,
                    from_osd=self.osd.whoami,
                ),
            )
        self._bf_granted = set()
        self._release_local_backfill()

    def _reset_backfill(self) -> None:
        """Interval change: reservations and cursors die with the interval
        (PeeringState::clear_backfill_state)."""
        self._bf_gen += 1  # stale out in-flight push callbacks
        self._surrender_reservations()
        self._bf_inflight = set()
        self._bf_failed = set()
        self._bf_chunk_targets = {}

    # -- scrub -----------------------------------------------------------------

    def scrub(self, deep: bool = False, repair: bool = False, on_done=None) -> bool:
        """Primary-only scrub kick (PgScrubber)."""
        if not self.peering.is_primary() or not self.peering.is_active():
            return False
        return self.scrubber.start(deep=deep, repair=repair, on_done=on_done)

    def handle_scrub_message(self, msg) -> bool:
        from ..msg.messages import MOSDRepScrub, MOSDRepScrubMap

        if isinstance(msg, MOSDRepScrub):
            self.scrubber.handle_rep_scrub(msg)
        elif isinstance(msg, MOSDRepScrubMap):
            self.scrubber.handle_scrub_map(msg)
        else:
            return False
        return True

    def send_scrub(self, osd: int, msg) -> None:
        # Loopback via the event loop, not direct call: a synchronous
        # self-delivery chain would recurse one stack frame per chunk
        # (chunk -> map -> compare -> next chunk) and overflow on big PGs.
        if osd == self.osd.whoami:
            asyncio.get_event_loop().call_soon(self.scrubber.handle_rep_scrub, msg)
        else:
            self.osd.send_cluster(osd, msg)

    def send_scrub_reply(self, osd: int, msg) -> None:
        if osd == self.osd.whoami:
            asyncio.get_event_loop().call_soon(self.scrubber.handle_scrub_map, msg)
        else:
            self.osd.send_cluster(osd, msg)

    def mark_shard_missing(self, oid: str, osd: int) -> None:
        """Repair path: treat a corrupt shard as missing so recovery
        rebuilds it (the reference's repair → recovery handoff)."""
        v = self.pg_log.head
        if osd == self.osd.whoami:
            self.peering.missing.add(oid, v)
            if self.pool.type != POOL_TYPE_ERASURE:
                # Replicated recovery pulls from a replica only when the
                # primary's copy is ABSENT (recover_object's exists()
                # check) — a corrupt-but-present copy would be pushed back
                # out as "repair".  Drop it so the pull path engages.
                coll = shard_coll(self.pgid, -1)
                self.osd.store.queue_transaction(Transaction().remove(coll, oid))
        else:
            self.peering.peer_missing.setdefault(osd, Missing()).add(oid, v)

    def request_recovery(self, oid: str) -> None:
        self._recover_one(oid)

    @property
    def is_clean(self) -> bool:
        return (
            self.peering.is_active()
            and not self.peering.missing.items
            and all(not m.items for m in self.peering.peer_missing.values())
            and not self.peering.backfill_targets
        )
