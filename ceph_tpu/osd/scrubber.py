"""Scrub — mirror of src/osd/scrubber/ (PgScrubber + scrub_backend).

Reference structure (SURVEY.md §2.2 "Scrub"):

- The primary drives a chunky scrub FSM (src/osd/scrubber/
  scrub_machine.cc): objects are scrubbed in bounded chunks, each chunk
  gathering a **scrub map** (oid → size/digest/attr digests) from every
  acting shard (MOSDRepScrub → MOSDRepScrubMap), then comparing them in
  the scrub backend (src/osd/scrubber/scrub_backend.cc
  select_auth_object / compare_smaps).
- Shallow scrub compares sizes/metadata; **deep scrub** reads the data
  and compares content digests.  For EC pools each shard's chunk digest
  is checked against the `hinfo` cumulative crc32c it persisted at write
  time (ECBackend::be_deep_scrub, /root/reference/src/osd/ECBackend.cc:
  2518) — corrupt shards are detected locally, without needing k-way
  agreement.
- Inconsistencies raise cluster-log errors and feed `repair`: the bad
  shard is marked missing and the standard recovery path (§3.2) rebuilds
  it.

The scrub map is JSON here (the reference uses encoded ScrubMap structs);
the comparison semantics follow the reference.
"""

from __future__ import annotations

import base64
import json
import time
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ..common.log import dout
from ..msg.messages import MOSDRepScrub, MOSDRepScrubMap, PgId
from ..os.objectstore import StoreError
from .ec_transaction import HINFO_ATTR, OI_ATTR, ObjectInfo
from .osdmap import PG_NONE, POOL_TYPE_ERASURE
from .pg_backend import shard_coll
from ..stripe import HashInfo


@dataclass
class ScrubResult:
    """Summary the reference reports via `pg <pgid> query` / clog."""

    deep: bool = False
    objects_scrubbed: int = 0
    errors: int = 0
    # oid -> {shard/osd: reason}
    inconsistent: dict[str, dict[int, str]] = field(default_factory=dict)
    repaired: int = 0
    aborted: bool = False
    # oids whose parity equation is broken but whose corrupt shard could
    # NOT be localized (every shard passed its digest-vs-hinfo check):
    # repair must not trust any shard — re-encoding parity from a
    # possibly-corrupt data shard would make the damage permanent and
    # silent, so these stay inconsistent (HEALTH_ERR) for the operator
    unrepairable: set[str] = field(default_factory=set)

    @property
    def clean(self) -> bool:
        return self.errors == 0 and not self.aborted


CHUNK_MAX = 25  # objects per scrub chunk (osd_scrub_chunk_max)


class PgScrubber:
    """Primary-side scrub driver for one PG (PgScrubber analog)."""

    def __init__(self, pg):
        self.pg = pg
        self._tid = 0
        self.active = False
        # in-flight chunk state
        self._maps: dict[int, dict] = {}  # osd -> scrub map (parsed)
        self._pending: set[int] = set()
        self._result: ScrubResult | None = None
        self._cursor = ""
        self._deep = False
        self._repair = False
        self._on_done: Callable[[ScrubResult], None] | None = None
        self.last_result: ScrubResult | None = None
        self._chunk_range: tuple[str, str] = ("", "")
        self._chunk_started: float = 0.0
        # client writes queued while their object's chunk is being
        # scrubbed (write_blocked_by_scrub)
        self.waiting_writes: list[Callable[[], None]] = []
        self.gather_timeout = 10.0  # seconds before an unanswered chunk aborts
        # progress accounting (ISSUE 8): object total snapshotted at
        # start() so the mgr progress module can render done/total
        self._total_objects = 0

    # -- lifecycle guards ------------------------------------------------------

    def reset(self) -> None:
        """Interval change / abort (PgScrubber::on_new_interval): drop the
        in-flight scrub so the PG can scrub again later."""
        if not self.active:
            return
        self.active = False
        self._pending.clear()
        self._maps.clear()
        res = self._result or ScrubResult()
        res.aborted = True
        self._flush_waiting_writes()
        if self._on_done is not None:
            on_done, self._on_done = self._on_done, None
            on_done(res)

    def tick(self, now: float) -> None:
        """Abort a scrub that stopped making progress — a shard that never
        answered, or an in-flight chunk wedged by an error (a crashed
        replica or a raised compare must not disable scrubbing forever)."""
        if self.active and now - self._chunk_started > self.gather_timeout:
            dout(
                "osd", 1,
                f"pg {self.pg.pgid} scrub: no map from {sorted(self._pending)} "
                f"after {self.gather_timeout}s; aborting",
            )
            self.reset()

    def write_blocked(self, oid: str) -> bool:
        """write_blocked_by_scrub: writes to an object inside the chunk
        being gathered wait until the chunk completes, so shard maps are
        built against a stable view."""
        if not self.active:
            return False
        start, end = self._chunk_range
        return oid >= start and (not end or oid < end)

    def _flush_waiting_writes(self) -> None:
        waiting, self.waiting_writes = self.waiting_writes, []
        for cb in waiting:
            cb()

    # -- shard-side map building ----------------------------------------------

    def build_scrub_map(
        self, shard: int, deep: bool, start: str, end: str
    ) -> dict[str, dict]:
        """What one shard reports for its objects in [start, end)
        (build_scrub_map_chunk).  For EC shards the deep digest is the
        local chunk crc checked against hinfo (be_deep_scrub)."""
        from ..utils.crc32c import crc32c

        store = self.pg.osd.store
        coll = shard_coll(self.pg.pgid, shard)
        out: dict[str, dict] = {}
        try:
            oids = sorted(store.list_objects(coll))
        except StoreError:
            return out
        for oid in oids:
            if oid < start or (end and oid >= end):
                continue
            entry: dict = {"size": store.stat(coll, oid)}
            attrs = store.getattrs(coll, oid)
            if OI_ATTR in attrs:
                oi = ObjectInfo.decode(attrs[OI_ATTR])
                entry["oi_size"] = oi.size
                entry["version"] = oi.version
            if deep:
                data = store.read(coll, oid, 0, 0)
                entry["digest"] = crc32c(data, HashInfo.SEED)
                if HINFO_ATTR in attrs:
                    hinfo = HashInfo.decode(attrs[HINFO_ATTR])
                    entry["hinfo_digest"] = hinfo.get_chunk_hash(shard)
                    entry["hinfo_size"] = hinfo.get_total_chunk_size()
                    # EC deep scrub ships the shard chunk bytes to the
                    # primary (ISSUE 9): the device verify path
                    # recomputes parity across all k+m shards in one
                    # aggregated compare-only launch, which the
                    # digest-vs-hinfo check alone cannot do (a shard
                    # whose hinfo was rewritten consistently with its
                    # corrupt bytes passes the digest check but breaks
                    # the parity equation).  Only for codecs that CAN
                    # consume them — shipping ~1.33x the object size
                    # per shard to a primary whose plugin has no verify
                    # path would be pure network overhead.
                    if self._ec_codec()[0] is not None:
                        entry["data"] = base64.b64encode(data).decode()
                else:
                    # replicated deep scrub covers omap too (be_deep_scrub
                    # omap_digest): crc over the canonical KV encoding
                    from ..common.encoding import encode_kv_map

                    try:
                        omap = store.omap_get(coll, oid)
                    except StoreError:
                        omap = {}
                    if omap:
                        entry["omap_digest"] = crc32c(
                            encode_kv_map(omap), HashInfo.SEED
                        )
            out[oid] = entry
        return out

    def handle_rep_scrub(self, msg: MOSDRepScrub) -> None:
        """Replica side: build + return our map."""
        smap = self.build_scrub_map(
            self.pg.whoami_shard(), msg.deep, msg.chunk_start, msg.chunk_end
        )
        self.pg.send_scrub_reply(
            msg.from_osd,
            MOSDRepScrubMap(
                pgid=msg.pgid,
                epoch=self.pg.epoch(),
                from_osd=self.pg.whoami(),
                scrub_tid=msg.scrub_tid,
                scrub_map=json.dumps(smap).encode(),
            ),
        )

    # -- primary FSM -----------------------------------------------------------

    def start(
        self,
        deep: bool = False,
        repair: bool = False,
        on_done: Callable[[ScrubResult], None] | None = None,
    ) -> bool:
        """Kick a scrub (PgScrubber::initiate_regular_scrub).  Returns
        False if one is already running."""
        if self.active:
            return False
        self.active = True
        self._deep = deep
        self._repair = repair
        self._on_done = on_done
        self._result = ScrubResult(deep=deep)
        self._cursor = ""
        self._total_objects = len(self._list_local())
        self._next_chunk()
        return True

    def progress(self) -> dict | None:
        """Scrub progress event for the OSD status blob (ISSUE 8): the
        mgr progress module aggregates these into per-PG bars.  None
        when no scrub is running."""
        if not self.active or self._result is None:
            return None
        return {
            "kind": "deep-scrub" if self._deep else "scrub",
            "objects_done": self._result.objects_scrubbed,
            "objects_total": max(
                self._total_objects, self._result.objects_scrubbed
            ),
            "bytes_done": 0,
            "bytes_total": 0,
        }

    def _next_chunk(self) -> None:
        """Select the next object range and gather maps (NewChunk state)."""
        self._tid += 1
        self._maps = {}
        self._chunk_started = time.monotonic()
        acting = self.pg.acting()
        self._pending = set()
        start = self._cursor
        # Chunk bound: Nth object past the cursor on OUR shard (all shards
        # hold the same object names for a PG, EC included).
        local = sorted(
            o
            for o in self._list_local()
            if o >= start
        )
        end = local[CHUNK_MAX] if len(local) > CHUNK_MAX else ""
        self._chunk_range = (start, end)
        for shard, osd in enumerate(acting):
            if osd == PG_NONE:
                continue
            self._pending.add(osd)
        for shard, osd in enumerate(acting):
            if osd == PG_NONE:
                continue
            msg = MOSDRepScrub(
                pgid=self.pg.pgid.with_shard(shard),
                epoch=self.pg.epoch(),
                from_osd=self.pg.whoami(),
                deep=self._deep,
                scrub_tid=self._tid,
                chunk_start=start,
                chunk_end=end,
            )
            self.pg.send_scrub(osd, msg)

    def _list_local(self) -> list[str]:
        store = self.pg.osd.store
        coll = shard_coll(self.pg.pgid, self.pg.whoami_shard())
        try:
            return store.list_objects(coll)
        except StoreError:
            return []

    def handle_scrub_map(self, msg: MOSDRepScrubMap) -> None:
        if not self.active or msg.scrub_tid != self._tid:
            return
        self._maps[msg.from_osd] = json.loads(msg.scrub_map.decode())
        self._pending.discard(msg.from_osd)
        if not self._pending:
            self._compare_chunk()

    def _compare_chunk(self) -> None:
        """scrub_backend compare_smaps over the gathered maps."""
        res = self._result
        acting = self.pg.acting()
        is_ec = self.pg.pool.type == POOL_TYPE_ERASURE
        all_oids = sorted({o for m in self._maps.values() for o in m})
        # Deep EC chunks verify parity on the device (ISSUE 9): SUBMIT
        # the whole chunk's codewords as one verify ticket first, run
        # the host metadata/digest compares while the launch is in
        # flight, then reap the bitmaps below.  While the backend is
        # DEGRADED the aggregator re-runs the identical compare on the
        # host oracle, so the bitmap is byte-identical either way.
        verify = None
        if self._deep and is_ec and all_oids:
            verify = self._submit_ec_verify(all_oids, acting)
        host_bad: dict[str, dict[int, str]] = {}
        for oid in all_oids:
            res.objects_scrubbed += 1
            if is_ec:
                host_bad[oid] = self._compare_ec_object(oid, acting)
            else:
                host_bad[oid] = self._compare_replicated_object(oid, acting)
        if verify is not None:
            self._reap_ec_verify(verify, host_bad, acting)
        for oid, bad in host_bad.items():
            if bad:
                res.errors += len(bad)
                res.inconsistent[oid] = bad
                self.pg.clog_error(
                    f"pg {self.pg.pgid} scrub: {oid} inconsistent on "
                    + ", ".join(f"osd.{o} ({why})" for o, why in bad.items())
                )
        start, end = self._chunk_range
        # Advance (or finish) BEFORE releasing blocked writes: a write
        # flushed while the old chunk range is still current would re-block
        # against it and strand forever on the final chunk.
        if end:
            self._cursor = end
            self._next_chunk()
        else:
            self._finish()
        self._flush_waiting_writes()

    # -- device-offloaded EC parity verify (ISSUE 9) ---------------------------

    def _ec_codec(self):
        """The PG backend's matrix codec + stripe info, or (None, None)
        when the pool's codec has no device verify path (non-matrix
        plugins): the host digest compare then stands alone, as before."""
        backend = getattr(self.pg, "backend", None)
        ec = getattr(backend, "ec", None)
        sinfo = getattr(backend, "sinfo", None)
        if ec is None or sinfo is None or not hasattr(ec, "verify_array"):
            return None, None
        return ec, sinfo

    def _submit_ec_verify(self, oids: list[str], acting: list[int]):
        """Stack every verifiable object's shard chunks into one
        (stripes, k+m, L) codeword batch and SUBMIT it to the shared
        VerifyAggregator — one ticket per scrub chunk, so the whole
        chunk's parity recompute rides one compare-only launch (padded
        and coalesced with other PGs' scrubs by the aggregator).
        Returns (ticket, spans, ec) or None; spans maps oid -> (start,
        stripes) into the batch.

        An object is verifiable when every acting shard answered with
        chunk bytes of one common length; anything else (missing shard,
        truncated shard, no hinfo) is already the host compare's
        business.  Ragged final chunks zero-pad to the chunk size on
        data AND parity rows — the code is linear, encode(0) == 0, so
        padding preserves the parity equation exactly."""
        ec, sinfo = self._ec_codec()
        if ec is None:
            return None
        k, m = ec.k, ec.m
        n = k + m
        if len(acting) < n or any(osd == PG_NONE for osd in acting[:n]):
            return None
        L = sinfo.chunk_size
        raw_of = ec.chunk_index
        batches: list[np.ndarray] = []
        spans: dict[str, tuple[int, int]] = {}
        start = 0
        for oid in oids:
            rows: list[bytes] = []
            for i in range(n):
                entry = self._maps.get(acting[raw_of(i)], {}).get(oid)
                blob = entry.get("data") if entry else None
                if blob is None:
                    rows = []
                    break
                rows.append(base64.b64decode(blob))
            if not rows or len({len(r) for r in rows}) != 1 or not len(rows[0]):
                continue
            shard_len = len(rows[0])
            stripes = -(-shard_len // L)
            padded = np.zeros((n, stripes * L), dtype=np.uint8)
            for i, r in enumerate(rows):
                padded[i, :shard_len] = np.frombuffer(r, dtype=np.uint8)
            # (n, stripes*L) -> (stripes, n, L): each stripe's rows stay
            # in encode order, matching verify_array's contract
            batches.append(
                padded.reshape(n, stripes, L).transpose(1, 0, 2)
            )
            spans[oid] = (start, stripes)
            start += stripes
        if not batches:
            return None
        agg = getattr(self.pg.backend, "verify_aggregator", None)
        if agg is None:
            from ..codec.matrix_codec import default_verify_aggregator

            agg = default_verify_aggregator()
        try:
            ticket = agg.submit(ec, np.ascontiguousarray(np.concatenate(batches)))
        except Exception as e:
            dout("osd", 1,
                 f"pg {self.pg.pgid} scrub: verify submit failed ({e!r}); "
                 "host compare stands alone")
            return None
        return ticket, spans, ec

    def _reap_ec_verify(
        self,
        verify,
        host_bad: dict[str, dict[int, str]],
        acting: list[int],
    ) -> None:
        """Reap the chunk's mismatch bitmaps and merge attributions into
        the host compare's verdict.  A nonzero per-object bitmap whose
        shards all passed the digest check is the case the offload
        exists for: the parity equation is broken even though every
        shard is self-consistent — attribute the mismatched parity
        row(s).  A reap failure (device error whose host recompute also
        failed) degrades to the digest-only verdict, never to a scrub
        abort."""
        ticket, spans, ec = verify
        try:
            bitmap = np.asarray(ticket)
        except Exception as e:
            dout("osd", 1,
                 f"pg {self.pg.pgid} scrub: verify reap failed ({e!r}); "
                 "host compare stands alone")
            return
        raw_of = ec.chunk_index
        for oid, (start, stripes) in spans.items():
            bits = int(np.bitwise_or.reduce(bitmap[start : start + stripes]))
            if not bits or host_bad.get(oid):
                # clean, or the digest compare already attributed the
                # corrupt shard (don't double-report one object)
                continue
            # the equation is broken but every shard passed its own
            # digest check: the bitmap proves damage, not WHICH shard.
            # Report it on the mismatched parity row(s) for visibility,
            # but flag the object unrepairable — auto-repair re-encodes
            # parity from the data shards, and if the corrupt shard is a
            # data shard that would cement the corruption and clear the
            # health check over permanently damaged user data.
            self._result.unrepairable.add(oid)
            bad = host_bad.setdefault(oid, {})
            for j in range(ec.m):
                if bits >> j & 1:
                    bad[acting[raw_of(ec.k + j)]] = (
                        f"ec parity recompute mismatch (row {j}; corrupt "
                        "shard not localized — not auto-repairable)"
                    )

    def _compare_ec_object(self, oid: str, acting: list[int]) -> dict[int, str]:
        """EC comparison: every acting shard must hold the object, sized
        per hinfo (a truncated shard is as lost as an absent one), with
        consistent object-info metadata; deep adds the chunk-digest check
        against the hinfo crc persisted at write time (be_deep_scrub)."""
        bad: dict[int, str] = {}
        # Shallow metadata authority: the modal (oi_size, version) pair.
        # Ties break deterministically — highest version first, then the
        # copy held by the lowest shard — so two runs over the same maps
        # always blame the same side (the old max(set(...)) pick
        # depended on set iteration order, i.e. on hash seeding).
        metas_by_shard = [
            (shard, (e["oi_size"], e.get("version")))
            for shard, e in (
                (shard, self._maps.get(osd, {}).get(oid))
                for shard, osd in enumerate(acting)
                if osd != PG_NONE
            )
            if e is not None and "oi_size" in e
        ]
        counts: dict[tuple, int] = {}
        for _shard, meta in metas_by_shard:
            counts[meta] = counts.get(meta, 0) + 1
        auth_meta = None
        best_key: tuple | None = None
        for _shard, meta in sorted(metas_by_shard):
            version = meta[1] if meta[1] is not None else -1
            key = (counts[meta], version)
            if best_key is None or key > best_key:  # strict: ties keep
                best_key = key                      # the lowest shard
                auth_meta = meta
        for shard, osd in enumerate(acting):
            if osd == PG_NONE:
                continue
            entry = self._maps.get(osd, {}).get(oid)
            if entry is None:
                if not self._object_expected_missing(oid, osd):
                    bad[osd] = "missing"
                continue
            if "hinfo_size" in entry and entry.get("size") != entry["hinfo_size"]:
                bad[osd] = "shard size mismatch vs hinfo"
                continue
            if (
                auth_meta is not None
                and "oi_size" in entry
                and (entry["oi_size"], entry.get("version")) != auth_meta
            ):
                bad[osd] = "object info mismatch vs authoritative copy"
                continue
            if self._deep and "hinfo_digest" in entry:
                if entry.get("digest") != entry["hinfo_digest"]:
                    bad[osd] = "data digest mismatch vs hinfo"
        return bad

    def _compare_replicated_object(
        self, oid: str, acting: list[int]
    ) -> dict[int, str]:
        """Replicated comparison: majority digest wins (select_auth_object
        picks a trusted authoritative copy; majority is our stand-in).
        With size=2 an exact tie is undecidable — the reference breaks it
        with the object-info data_digest recorded at write time, which
        our ObjectInfo does not carry; the deterministic fallback here
        (lowest-osd copy) can pick the corrupt side.  Run size>=3 pools
        if scrub-repair must be trustworthy, as the reference also
        recommends."""
        bad: dict[int, str] = {}
        entries = {
            osd: self._maps.get(osd, {}).get(oid)
            for osd in acting
            if osd != PG_NONE
        }
        digests = [
            (e.get("digest"), e.get("size"), e.get("omap_digest"))
            for osd, e in sorted(entries.items())
            if e is not None
        ]
        if not digests:
            return bad
        auth = max(dict.fromkeys(digests), key=digests.count)
        for osd, e in entries.items():
            if e is None:
                if not self._object_expected_missing(oid, osd):
                    bad[osd] = "missing"
            elif (e.get("digest"), e.get("size"), e.get("omap_digest")) != auth:
                if e.get("omap_digest") != auth[2]:
                    bad[osd] = "omap digest mismatch vs authoritative copy"
                else:
                    bad[osd] = "digest/size mismatch vs authoritative copy"
        return bad

    def _object_expected_missing(self, oid: str, osd: int) -> bool:
        """An object mid-recovery is not a scrub error."""
        return osd in self.pg.peering.osds_missing(oid)

    def _finish(self) -> None:
        res = self._result
        self.active = False
        self.last_result = res
        if self._repair and res.inconsistent:
            for oid, bad in res.inconsistent.items():
                if oid in res.unrepairable:
                    # the corrupt shard was never localized: rebuilding
                    # the flagged parity shards would re-encode from a
                    # possibly-corrupt data shard and hide the damage
                    self.pg.clog_error(
                        f"pg {self.pg.pgid} repair: {oid} parity "
                        "mismatch with no localized shard; refusing "
                        "auto-repair (restore the object from a replica "
                        "or backup)"
                    )
                    continue
                for osd in bad:
                    self.pg.mark_shard_missing(oid, osd)
                res.repaired += 1
                self.pg.request_recovery(oid)
        if res.repaired:
            # the repair side of the scrub timeline (ISSUE 16): the
            # error entries above raised it, this closes it.  Guarded —
            # unit tests scrub against a bare fake PG/OSD.
            clog = getattr(
                getattr(self.pg, "osd", None), "cluster_log", None
            )
            if clog is not None:
                clog(
                    "info",
                    f"pg {self.pg.pgid} repair: {res.repaired} object(s) "
                    "re-queued for recovery (shards rebuilt)",
                    code="OSD_SCRUB_ERRORS",
                )
        dout(
            "osd",
            5,
            f"pg {self.pg.pgid} {'deep-' if res.deep else ''}scrub: "
            f"{res.objects_scrubbed} objects, {res.errors} errors",
        )
        if self._on_done is not None:
            self._on_done(res)
