"""Bounded reservation slots — src/common/AsyncReserver.h scaled down.

The reference queues prioritized reservation requests and grants them
asynchronously; OSDs hold a `local_reserver` (their own backfill slots)
and a `remote_reserver` (slots they grant to other primaries), both
bounded by `osd_max_backfills`.  Here grants are immediate-or-denied and
denied callers retry from their periodic tick — same bound, no queue
(the tick loop is this framework's requeue mechanism, see
PeeringState.tick).
"""

from __future__ import annotations

from typing import Callable, Hashable


class Reserver:
    def __init__(self, slots: Callable[[], int]):
        self._slots = slots
        self._held: set[Hashable] = set()

    def try_reserve(self, key: Hashable) -> bool:
        """Grant a slot (idempotent per key); False when full."""
        if key in self._held:
            return True
        if len(self._held) >= max(1, int(self._slots())):
            return False
        self._held.add(key)
        return True

    def release(self, key: Hashable) -> None:
        self._held.discard(key)

    def held(self) -> int:
        return len(self._held)
