"""Bounded reservation slots — src/common/AsyncReserver.h scaled down.

The reference queues prioritized reservation requests, grants them
asynchronously, and PREEMPTS lower-priority holders when a
higher-priority request arrives (the recovery-beats-backfill rule that
keeps a whole-OSD rebuild from queueing behind a leisurely backfill).
OSDs hold a `local_reserver` (their own backfill/recovery slots) and a
`remote_reserver` (slots they grant to other primaries), both bounded by
`osd_max_backfills`.

Here grants are immediate-or-denied and denied callers retry from their
periodic tick (the tick loop is this framework's requeue mechanism, see
PeeringState.tick) — same bound, no queue — but the preemption half is
real: a `try_reserve` at a strictly higher priority than the
lowest-priority current holder evicts that holder, firing its
`on_preempt` callback exactly once so it can surrender cleanly and
retry later.  Ties never preempt (a re-granted backfill must not be
bounced by an equal-priority sibling), so grant order is deterministic
under the tick-retry discipline.
"""

from __future__ import annotations

from typing import Callable, Hashable


class Reserver:
    def __init__(self, slots: Callable[[], int]):
        self._slots = slots
        # key -> (priority, on_preempt or None)
        self._held: dict[Hashable, tuple[int, Callable[[], None] | None]] = {}
        self.preemptions = 0  # lifetime preempt count (introspection)

    def try_reserve(
        self,
        key: Hashable,
        priority: int = 0,
        on_preempt: Callable[[], None] | None = None,
    ) -> bool:
        """Grant a slot (idempotent per key; a re-reserve refreshes the
        priority/callback); False when full of >= priority holders.
        When full, the LOWEST-priority holder is preempted iff its
        priority is strictly below the request's — its `on_preempt`
        fires after its slot is gone, so the callback observes the
        post-preemption state and a re-reserve from inside it queues
        behind the winner instead of recursing into it."""
        if key in self._held:
            self._held[key] = (int(priority), on_preempt)
            return True
        if len(self._held) >= max(1, int(self._slots())):
            victim = min(
                self._held, key=lambda k: self._held[k][0], default=None
            )
            if victim is None or self._held[victim][0] >= int(priority):
                return False
            _vprio, vcb = self._held.pop(victim)
            self.preemptions += 1
            self._held[key] = (int(priority), on_preempt)
            if vcb is not None:
                vcb()
            return True
        self._held[key] = (int(priority), on_preempt)
        return True

    def release(self, key: Hashable) -> bool:
        """Release a held slot; True iff the key was actually held.
        Releasing a preempted (or never-granted) key is a no-op — the
        exactly-once contract interval-change cleanup relies on."""
        return self._held.pop(key, None) is not None

    def held(self) -> int:
        return len(self._held)

    def holders(self) -> dict[Hashable, int]:
        """{key: priority} snapshot (introspection/tests)."""
        return {k: prio for k, (prio, _cb) in self._held.items()}
