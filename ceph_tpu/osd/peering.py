"""Peering state machine — mirror of src/osd/PeeringState.{h,cc}.

The reference drives peering with a boost::statechart machine
(/root/reference/src/osd/PeeringState.h:460 lists the event set); the
states that matter for correctness are the primary's
GetInfo → GetLog → GetMissing → Activating → Active chain and the
replica's Stray → ReplicaActive.  This module keeps those states and the
same information flow, as plain explicit-state code:

- **GetInfo**: the primary queries every acting shard for its `pg_info_t`
  (MOSDPGQuery(INFO) → MOSDPGNotify), the reference's
  PeeringState::proc_replica_info.
- **GetLog**: if some shard's `last_update` beats ours, fetch its log
  delta (MOSDPGQuery(LOG) → MOSDPGLog) and merge it, computing our own
  missing set from the entries we had never applied
  (PGLog::merge_log / proc_master_log).
- **GetMissing** is folded into activation: the primary holds the
  authoritative log, so each lagging peer's missing set is computed
  locally from the log delta past that peer's `last_update`
  (PGLog::proc_replica_log), and the delta is pushed to the peer in
  MOSDPGLog so it reaches the same conclusion (activate_map path).
- Shards whose logs fell behind the tail cannot log-recover and become
  **backfill targets** (PeeringState's backfill machinery): instead of
  enumerating every object into a missing set up front, the primary
  walks its object namespace in sorted chunks with a `last_backfill`
  cursor per target (osd_types.h BackfillInterval), pushing each chunk
  and advancing the cursor — writes keep flowing while backfill runs,
  since repops reach the target regardless and the eventual full-object
  push includes any bytes written meanwhile.  The PG drives the scan
  (PG._kick_backfill) under local+remote reservations.
- **Active**: `missing` + `peer_missing` feed the recovery machinery
  (PGBackend::recover_object, §3.2) and degraded-object write blocking.

Epochs guard everything: a new osdmap interval restarts peering
(PeeringState::start_peering_interval), and stale messages from an older
epoch are dropped on receipt.
"""

from __future__ import annotations

import enum
from typing import TYPE_CHECKING, Callable

from ..common.log import dout
from ..msg.messages import MOSDPGLog, MOSDPGNotify, MOSDPGQuery, PgId
from .osdmap import PG_NONE
from .pg_log import Eversion, LogEntry, Missing, PGLog, PgInfo


class PeerState(enum.Enum):
    """The state names the reference's statechart uses
    (PeeringState.h Initial/Reset/Started/GetInfo/GetLog/Active/...)."""

    RESET = "Reset"
    GETINFO = "GetInfo"
    GETLOG = "GetLog"
    ACTIVE = "Active"
    REPLICA_ACTIVE = "ReplicaActive"
    STRAY = "Stray"


class PeeringState:
    """Per-PG peering driver.  Owned by the PG; sends through callbacks so
    it stays transport-agnostic (unit tests pump a queue)."""

    def __init__(
        self,
        pgid: PgId,
        whoami: int,
        log: PGLog,
        info: PgInfo,
        send: Callable[[int, object], None],
        on_active: Callable[[], None],
        list_local_objects: Callable[[], list[str]],
        drop_local_object: Callable[[str], None] | None = None,
    ):
        self.pgid = pgid
        self.whoami = whoami
        self.log = log
        self.info = info
        self.send = send
        self.on_active = on_active
        self.list_local_objects = list_local_objects
        self.drop_local_object = drop_local_object

        self.state = PeerState.RESET
        self.epoch = 0
        self.acting: list[int] = []
        self.primary: int = PG_NONE
        self.peer_info: dict[int, PgInfo] = {}
        self.missing = Missing()  # our own missing objects
        self.peer_missing: dict[int, Missing] = {}  # primary-only
        self.backfill_targets: set[int] = set()
        # lifetime count of backfills STARTED (pg stats' backfill state
        # counter): survives completion, so tests/operators can tell a
        # finished backfill from one that never happened
        self.backfill_started_total = 0
        # per-target sorted-namespace cursor: objects <= cursor are
        # backfilled ("" = none yet; advanced by PG._kick_backfill)
        self.last_backfill: dict[int, str] = {}

    # -- interval handling ----------------------------------------------------

    def start_peering_interval(self, epoch: int, acting: list[int]) -> None:
        """New map interval (PeeringState::start_peering_interval):
        drop in-flight peering state and restart from GetInfo/Stray."""
        self.epoch = epoch
        self.acting = list(acting)
        self.primary = next((o for o in acting if o != PG_NONE), PG_NONE)
        self.peer_info = {}
        self.peer_missing = {}
        self.backfill_targets = set()
        self.last_backfill = {}
        if self.primary != self.whoami:
            self.state = PeerState.STRAY
            return
        self.state = PeerState.GETINFO
        peers = self._up_peers()
        if not peers:
            self._activate()
            return
        for osd in peers:
            self.send(
                osd,
                MOSDPGQuery(
                    pgid=self.pgid,
                    op=MOSDPGQuery.INFO,
                    epoch=self.epoch,
                    from_osd=self.whoami,
                    since_epoch=0,
                    since_ver=0,
                ),
            )

    def _up_peers(self) -> list[int]:
        return [o for o in self.acting if o not in (self.whoami, PG_NONE)]

    def tick(self) -> None:
        """Liveness re-kick (the reference gets this from statechart
        timeouts + map-advance requeues): a primary stuck in GetInfo or
        GetLog re-sends its one-shot queries — a dropped message (peer's
        map behind, connection reset) must not wedge the PG forever."""
        if self.state in (PeerState.GETINFO, PeerState.GETLOG):
            self.start_peering_interval(self.epoch, self.acting)

    def is_primary(self) -> bool:
        return self.primary == self.whoami

    def is_active(self) -> bool:
        return self.state in (PeerState.ACTIVE, PeerState.REPLICA_ACTIVE)

    # -- message handling ------------------------------------------------------

    def handle_query(self, msg: MOSDPGQuery) -> None:
        """A primary asks for our info or log (replica side)."""
        if msg.epoch < self.epoch:
            return  # stale interval
        if msg.op == MOSDPGQuery.INFO:
            self.send(
                msg.from_osd,
                MOSDPGNotify(
                    pgid=self.pgid,
                    info=self.info.tobytes(),
                    epoch=msg.epoch,
                    from_osd=self.whoami,
                ),
            )
        elif msg.op == MOSDPGQuery.LOG:
            since = self._common_point(Eversion(msg.since_epoch, msg.since_ver))
            if self.log.can_catch_up(since):
                entries = self.log.entries_after(since)
            else:
                entries = list(self.log.entries)  # best effort full log
                since = self.log.tail
            blob = _pack_entries(entries)
            self.send(
                msg.from_osd,
                MOSDPGLog(
                    pgid=self.pgid,
                    info=self.info.tobytes(),
                    log=blob,
                    epoch=msg.epoch,
                    from_osd=self.whoami,
                    since_epoch=since.epoch,
                    since_ver=since.version,
                ),
            )

    def _common_point(self, v: Eversion) -> Eversion:
        """Newest point of agreement with a peer claiming head `v`.

        If `v` is not an entry of our log (and is inside our log window),
        the peer's head is DIVERGENT — it logged writes the surviving
        acting set never saw (e.g. an old primary that crashed before
        replicating).  The delta must then start from our newest entry
        below `v`, so the peer can detect and rewind everything past it
        (PeeringState::proc_replica_log / PGLog::rewind_divergent_log)."""
        if (
            not v
            or v <= self.log.tail
            or any(e.version == v for e in self.log.entries)
        ):
            return v
        older = [e.version for e in self.log.entries if e.version < v]
        return max(older) if older else self.log.tail

    def handle_notify(self, msg: MOSDPGNotify) -> None:
        """proc_replica_info: gather infos during GetInfo."""
        if msg.epoch != self.epoch or self.state != PeerState.GETINFO:
            return
        self.peer_info[msg.from_osd] = PgInfo.frombytes(msg.info)
        if set(self.peer_info) >= set(self._up_peers()):
            self._choose_auth_log()

    def _choose_auth_log(self) -> None:
        """find_best_info (PeeringState.cc): highest last_update wins;
        ties break toward ourselves to avoid a needless log fetch."""
        best_osd, best = self.whoami, self.info
        for osd, info in self.peer_info.items():
            if info.last_update > best.last_update:
                best_osd, best = osd, info
        if best_osd == self.whoami:
            self._activate()
            return
        self.state = PeerState.GETLOG
        self.auth_osd = best_osd
        self.send(
            best_osd,
            MOSDPGQuery(
                pgid=self.pgid,
                op=MOSDPGQuery.LOG,
                epoch=self.epoch,
                from_osd=self.whoami,
                since_epoch=self.log.head.epoch,
                since_ver=self.log.head.version,
            ),
        )

    def handle_log(self, msg: MOSDPGLog) -> None:
        """Either the auth shard's reply to our GetLog (primary) or the
        primary's activation delta (replica)."""
        if msg.epoch != self.epoch:
            return
        entries = _unpack_entries(msg.log)
        since = Eversion(msg.since_epoch, msg.since_ver)
        if self.state == PeerState.GETLOG and msg.from_osd == getattr(
            self, "auth_osd", None
        ):
            auth_info = PgInfo.frombytes(msg.info)
            self._merge_log(entries, auth_last=auth_info.last_update, since=since)
            self.info.last_update = auth_info.last_update
            self._activate()
        elif self.state in (PeerState.STRAY, PeerState.REPLICA_ACTIVE):
            auth_info = PgInfo.frombytes(msg.info)
            self._merge_log(entries, auth_last=auth_info.last_update, since=since)
            self.info.last_update = self.log.head
            self.info.last_epoch_started = msg.epoch
            self.state = PeerState.REPLICA_ACTIVE
            dout("osd", 10, f"pg {self.pgid} replica active @ {self.log.head}")

    def _merge_log(
        self,
        entries: list[LogEntry],
        auth_last: Eversion | None = None,
        since: Eversion | None = None,
    ) -> None:
        """PGLog::merge_log: adopt the authoritative delta.

        `since` is the point the sender computed the delta from (its newest
        entry at/below our claimed head).  Local entries past `since` that
        are absent from the delta are DIVERGENT — writes the rest of the
        acting set never saw, including the canonical failover case where a
        dead primary's unreplicated write sits at an *older* epoch than the
        new auth head.  The reference rewinds them to prior_version
        (PGLog::_merge_divergent_entries); here the entry is dropped from
        the log, the divergent on-disk copy is dropped (so recovery PULLS
        the authoritative version instead of pushing the stale copy back
        out), and the object is marked missing at prior_version."""
        if auth_last is not None:
            start = since if since is not None else auth_last
            delta_versions = {
                (e.version.epoch, e.version.version) for e in entries
            }
            divergent = [
                e
                for e in self.log.entries
                if e.version > start
                and (e.version.epoch, e.version.version) not in delta_versions
            ]
            if divergent:
                keep = {id(e) for e in divergent}
                self.log.entries = [
                    e for e in self.log.entries if id(e) not in keep
                ]
                rewound: set[str] = set()
                for e in divergent:
                    if e.oid in rewound:
                        continue
                    rewound.add(e.oid)
                    dout(
                        "osd",
                        5,
                        f"pg {self.pgid} rewinding divergent {e.oid} "
                        f"{e.version} -> {e.prior_version}",
                    )
                    if self.drop_local_object is not None:
                        self.drop_local_object(e.oid)
                    if e.prior_version:
                        self.missing.add(e.oid, e.prior_version)
                    else:
                        # created by the divergent write: it simply should
                        # not exist; nothing to recover
                        self.missing.rm(e.oid)
        for entry in entries:
            if entry.version > self.log.head:
                self.log.append(entry)
                self.missing.add_next_event(entry)

    # -- activation ------------------------------------------------------------

    def _activate(self) -> None:
        """PeeringState::activate: compute peer missing sets, ship log
        deltas, open for business."""
        self.state = PeerState.ACTIVE
        self.info.last_epoch_started = self.epoch
        head = self.log.head
        for osd in self._up_peers():
            pinfo = self.peer_info.get(osd, PgInfo())
            # A peer whose claimed head is not in our (authoritative) log
            # holds divergent entries: rewind its effective head to the
            # newest agreed point so the delta spans the divergent region
            # and the peer can detect + rewind it (proc_replica_log).
            peer_head = self._common_point(pinfo.last_update)
            if pinfo.last_update >= head and peer_head == pinfo.last_update:
                self.peer_missing[osd] = Missing()
                continue
            if self.log.can_catch_up(peer_head):
                # proc_replica_log: delta past the peer's head = its missing
                self.peer_missing[osd] = self.log.missing_from(peer_head)
                delta = self.log.entries_after(peer_head)
                delta_since = peer_head
            else:
                # Log trimmed past the peer: chunked backfill, not an
                # up-front mark-all-missing.  peer_missing stays empty so
                # client writes are not blocked as degraded; the PG's
                # backfill driver copies the namespace behind a cursor.
                self.backfill_targets.add(osd)
                self.backfill_started_total += 1
                self.last_backfill[osd] = ""
                self.peer_missing[osd] = Missing()
                delta = list(self.log.entries)
                delta_since = self.log.tail
            blob = _pack_entries(delta)
            self.send(
                osd,
                MOSDPGLog(
                    pgid=self.pgid,
                    info=self.info.tobytes(),
                    log=blob,
                    epoch=self.epoch,
                    from_osd=self.whoami,
                    since_epoch=delta_since.epoch,
                    since_ver=delta_since.version,
                ),
            )
        dout(
            "osd",
            10,
            f"pg {self.pgid} active @ e{self.epoch}: "
            f"{len(self.missing)} missing here, "
            f"{sum(len(m) for m in self.peer_missing.values())} on peers",
        )
        self.on_active()

    # -- recovery bookkeeping --------------------------------------------------

    def object_missing_anywhere(self, oid: str) -> bool:
        return oid in self.missing or any(
            oid in m for m in self.peer_missing.values()
        )

    def osds_missing(self, oid: str) -> set[int]:
        """OSDs (not shards) that lack oid."""
        out = {o for o, m in self.peer_missing.items() if oid in m}
        if oid in self.missing:
            out.add(self.whoami)
        return out

    def backfill_pending_osds(self, oid: str) -> set[int]:
        """Backfill targets whose cursor has not passed `oid`: their copy
        (if any) is STALE and must never serve reads — the availability
        gate mark-all-missing used to provide, without the write blocking
        (is_backfill_target + last_backfill comparison in the reference's
        missing_loc)."""
        return {
            o
            for o in self.backfill_targets
            if oid > self.last_backfill.get(o, "")
        }

    def mark_recovered(self, oid: str, osd: int) -> None:
        if osd == self.whoami:
            self.missing.rm(oid)
        elif osd in self.peer_missing:
            self.peer_missing[osd].rm(oid)

    def all_missing_oids(self) -> list[str]:
        oids: set[str] = set(self.missing.items)
        for m in self.peer_missing.values():
            oids.update(m.items)
        return sorted(oids)


def _pack_entries(entries: list[LogEntry]) -> bytes:
    return b"".join(
        len(e := entry.tobytes()).to_bytes(4, "little") + e for entry in entries
    )


def _unpack_entries(blob: bytes) -> list[LogEntry]:
    entries: list[LogEntry] = []
    off = 0
    while off < len(blob):
        ln = int.from_bytes(blob[off : off + 4], "little")
        off += 4
        entries.append(LogEntry.frombytes(blob[off : off + ln]))
        off += ln
    return entries
