"""ECTransaction — logical object mutation -> k+m per-shard transactions.

Reference: /root/reference/src/osd/ECTransaction.{h,cc}.  `WritePlan`
(ECTransaction.h:26-33) captures which stripe-aligned extents must be read
(partial-stripe overwrites) and which will be written; `generate_transactions`
(ECTransaction.cc:109) turns the logical write into one ObjectStore
transaction per shard, writing each shard's chunk at
`logical_to_prev_chunk_offset(offset)` with SEQUENTIAL_WRITE|APPEND_ONLY
alloc hints (ECTransaction.cc:37-95), and appending to the per-shard
cumulative HashInfo.

TPU-first delta: the reference encodes stripe-by-stripe inside
`ECUtil::encode` (ECUtil.cc:123-162); here the whole write extent is encoded
in ONE batched device launch via ceph_tpu.stripe.encode, so a 1 MiB append
is a single (stripes, k, chunk) kernel call instead of 256 4 KiB loops.

Write rules mirror the reference's pool semantics:
- Without EC overwrites, writes must be stripe-width-aligned appends (or a
  full rewrite from 0) — RADOS enforces `required_alignment = stripe_width`
  for EC pools — and HashInfo digests chain on each append.
- With FLAG_EC_OVERWRITES, arbitrary extents go through read-modify-write:
  partial stripes are read (plan.to_read), merged, re-encoded; cumulative
  hinfo can no longer be maintained and is dropped (the reference likewise
  bypasses hinfo on overwrite pools).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

import numpy as np

from ..codec.base import EINVAL
from ..codec.interface import EcError, ErasureCodeInterface
from ..os.transaction import Transaction
from ..stripe import HashInfo, StripeInfo
from ..stripe import stripe as stripe_mod

# Attr names on every shard object (reference: OI_ATTR "_", hinfo_key).
OI_ATTR = "_"
HINFO_ATTR = "hinfo_key"


@dataclass
class ObjectInfo:
    """object_info_t subset: logical size + version stamp."""

    size: int = 0
    version: int = 0

    def encode(self) -> bytes:
        return json.dumps({"size": self.size, "version": self.version}).encode()

    @classmethod
    def decode(cls, blob: bytes) -> "ObjectInfo":
        obj = json.loads(blob.decode())
        return cls(size=int(obj["size"]), version=int(obj["version"]))


@dataclass
class PGTransaction:
    """Logical mutation of one object (PGTransaction analog, the unit
    PrimaryLogPG hands to the backend)."""

    oid: str
    writes: list[tuple[int, bytes]] = field(default_factory=list)
    truncate: int | None = None
    delete: bool = False
    attrs: dict[str, bytes | None] = field(default_factory=dict)  # None = rm
    # Snapshot clone-on-write (PrimaryLogPG::make_writeable): before the
    # mutation applies, the current head is cloned to this oid — per shard
    # for EC, whole-object for replicated — atomically with the write.
    pre_clone: str | None = None
    # Extra whole-object deletions riding this txn (snap-trimmed clones).
    also_delete: list[str] = field(default_factory=list)
    # omap mutations (replicated pools only; the PG rejects omap ops on
    # EC pools with -EOPNOTSUPP as the reference does)
    omap_set: dict[str, bytes] = field(default_factory=dict)
    omap_rm: list[str] = field(default_factory=list)
    omap_clear: bool = False

    def write(self, off: int, data: bytes) -> "PGTransaction":
        self.writes.append((off, bytes(data)))
        return self


@dataclass
class WritePlan:
    """ECTransaction.h:26-33."""

    to_read: list[tuple[int, int]] = field(default_factory=list)  # stripe-aligned
    will_write: list[tuple[int, int]] = field(default_factory=list)
    new_size: int = 0
    invalidates_hinfo: bool = False


def _merge_ranges(ranges: list[tuple[int, int]]) -> list[tuple[int, int]]:
    out: list[tuple[int, int]] = []
    for off, ln in sorted(ranges):
        if out and off <= out[-1][0] + out[-1][1]:
            prev_off, prev_ln = out[-1]
            out[-1] = (prev_off, max(prev_ln, off + ln - prev_off))
        else:
            out.append((off, ln))
    return out


def get_write_plan(
    sinfo: StripeInfo,
    pgt: PGTransaction,
    obj_size: int,
    allows_overwrites: bool,
) -> WritePlan:
    """Stripe-aligned read/write sets for the mutation
    (ECTransaction get_write_plan, incl. unaligned truncate handling)."""
    plan = WritePlan(new_size=obj_size)
    sw = sinfo.stripe_width
    if pgt.delete:
        plan.new_size = 0
        return plan
    padded_size = sinfo.logical_to_next_stripe_offset(obj_size)
    write_ranges: list[tuple[int, int]] = []
    read_ranges: list[tuple[int, int]] = []
    for off, data in pgt.writes:
        end = off + len(data)
        plan.new_size = max(plan.new_size, end)
        start_aligned = sinfo.logical_to_prev_stripe_offset(off)
        end_aligned = sinfo.logical_to_next_stripe_offset(end)
        if not allows_overwrites:
            if off % sw != 0 or (off != padded_size and off != 0):
                raise EcError(
                    EINVAL,
                    f"EC pool without overwrites requires stripe-aligned "
                    f"append at {padded_size}, got offset {off}",
                )
            if off == 0 and obj_size > 0 and end_aligned < padded_size:
                # A shrinking WRITEFULL is still a full replacement when the
                # accompanying truncate discards the old tail.
                if not (pgt.truncate is not None and pgt.truncate <= end):
                    raise EcError(EINVAL, "full rewrite must cover the object")
        else:
            plan.invalidates_hinfo = True
            # Partial head/tail stripes that already exist must be read.
            for stripe_off in (start_aligned, end_aligned - sw):
                covered = off <= stripe_off and end >= stripe_off + sw
                exists = stripe_off < padded_size
                if exists and not covered:
                    read_ranges.append((stripe_off, sw))
        write_ranges.append((start_aligned, end_aligned - start_aligned))
    if pgt.truncate is not None:
        # The PG pre-resolves truncate to the sequential final size
        # (write-then-truncate caps; WRITEFULL replaces exactly).
        t = pgt.truncate
        plan.new_size = t
        if t < obj_size and t % sw != 0:
            # Unaligned truncate: the surviving partial stripe is re-encoded
            # with a zeroed tail (ECTransaction's truncate handling).
            stripe_off = sinfo.logical_to_prev_stripe_offset(t)
            read_ranges.append((stripe_off, sw))
            write_ranges.append((stripe_off, sw))
            plan.invalidates_hinfo = True
        elif t < obj_size:
            plan.invalidates_hinfo = True
    plan.to_read = _merge_ranges(read_ranges)
    plan.will_write = _merge_ranges(write_ranges)
    return plan


@dataclass
class EncodeStage:
    """A write's LAUNCHED encode: merged logical bytes (host-side, ready at
    launch — what the extent cache pins) plus one PendingEncode per
    contiguous region whose device work may still be in flight.  The
    launch/finish split is the AIO hand-off of the reference's RMW
    pipeline (ECBackend.h:536-555): the next op's reads overlap this op's
    device encode."""

    merged: dict[int, bytearray]
    pending: dict[int, "stripe_mod.PendingEncode"]

    def ready(self) -> bool:
        return all(p.ready() for p in self.pending.values())

    def launched(self) -> bool:
        """False while any region's encode still sits in an aggregation
        window (a flush, not time, will make it ready)."""
        return all(p.launched() for p in self.pending.values())


def merge_writes(
    pgt: PGTransaction,
    plan: WritePlan,
    obj_size: int,
    read_data: dict[int, bytes],
) -> dict[int, bytearray]:
    """The RMW merge: per contiguous will_write region, the committed
    pre-write bytes (read_data) overlaid with the mutation's writes,
    zero-filled past an in-region truncate.  Shared by the materialize
    path (launch_encode) and the on-device delta path
    (launch_encode_delta) so both encode exactly the same logical
    bytes."""
    merged: dict[int, bytearray] = {}
    if pgt.delete:
        return merged
    for off, ln in plan.will_write:
        buf = bytearray(ln)
        # old bytes (RMW) first
        for r_off, r_data in read_data.items():
            r_end = r_off + len(r_data)
            lo, hi = max(off, r_off), min(off + ln, r_end)
            if lo < hi:
                buf[lo - off : hi - off] = r_data[lo - r_off : hi - r_off]
        merged[off] = buf
    for w_off, w_data in pgt.writes:
        for off, buf in merged.items():
            lo, hi = max(w_off, off), min(w_off + len(w_data), off + len(buf))
            if lo < hi:
                buf[lo - off : hi - off] = w_data[lo - w_off : hi - w_off]
    if pgt.truncate is not None and pgt.truncate < obj_size:
        t = pgt.truncate
        for off, buf in merged.items():
            if off <= t < off + len(buf):
                buf[t - off :] = b"\x00" * (off + len(buf) - t)
    return merged


def launch_encode(
    pgt: PGTransaction,
    plan: WritePlan,
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    obj_size: int,
    read_data: dict[int, bytes],
    aggregator=None,
) -> EncodeStage:
    """Merge RMW inputs with the new bytes and LAUNCH the device encodes
    (one batched launch per contiguous region) without materializing
    parity — phase one of generate_transactions.  An `aggregator` routes
    the launches through the cross-write aggregation window (ECBackend
    passes its shared EncodeAggregator; the sync composition below does
    not)."""
    merged = merge_writes(pgt, plan, obj_size, read_data)
    if pgt.delete:
        return EncodeStage(merged=merged, pending={})
    pending = {
        off: stripe_mod.encode_launch(
            sinfo, ec, bytes(merged[off]), aggregator=aggregator
        )
        for off in sorted(merged)
    }
    return EncodeStage(merged=merged, pending=pending)


def launch_encode_delta(
    pgt: PGTransaction,
    plan: WritePlan,
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    obj_size: int,
    read_data: dict[int, bytes],
    cache,
    cache_obj,
    old_gen,
    new_gen,
) -> EncodeStage | None:
    """Phase one via the fully on-device RMW delta path (ISSUE 18), or
    None when it does not apply to EVERY region — mixed materialize/
    delta stages are not worth the bookkeeping, and the all-or-nothing
    verdict keeps the fallback trivially correct (the caller invalidates
    the object and re-launches through `launch_encode`, dropping any
    half-committed new-generation cache entries)."""
    merged = merge_writes(pgt, plan, obj_size, read_data)
    if pgt.delete or not merged:
        return None
    pending: dict[int, "stripe_mod.PendingEncode"] = {}
    for off in sorted(merged):
        pend = stripe_mod.encode_delta_launch(
            sinfo, ec, bytes(merged[off]), cache, cache_obj,
            old_gen, new_gen,
            sinfo.aligned_logical_offset_to_chunk_offset(off),
        )
        if pend is None:
            return None
        pending[off] = pend
    return EncodeStage(merged=merged, pending=pending)


def finish_transactions(
    stage: EncodeStage,
    pgt: PGTransaction,
    plan: WritePlan,
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    shard_colls: dict[int, str],
    obj_size: int,
    hinfo: HashInfo | None,
    version: int,
    chunk_cache=None,
    cache_obj=None,
    cache_generation=None,
    csum_submit=None,
) -> tuple[dict[int, Transaction], HashInfo | None, dict[int, bytes]]:
    """Phase two: materialize the launched encodes (blocking only until
    THIS op's launches finish) and build the per-shard Transactions +
    hinfo chain.  Must run in submit (tid) order per object — the hinfo
    chain consumes the materialized parity bytes.

    With ``chunk_cache``/``cache_obj``/``cache_generation`` set (the
    ECBackend passes them when the RMW delta path is armed and this op
    took the MATERIALIZE path), every region's k+m shard chunks seed the
    device cache at the write's generation — the residency the NEXT
    cache-hit RMW deltas against (a delta-path op skips this: its launch
    already committed data and parity in place).

    With ``csum_submit`` set (the store advertises csum offload), each
    freshly materialized shard chunk's per-block checksums are submitted
    into the SAME offload launch window the encode was reaped in —
    ``csum_submit(chunk, chunk_off)`` returns a ticket (or None) that
    rides the shard Transaction as the write's ``csums`` hint, so
    BlueStore skips its own stored-form csum pass for raw aligned
    blocks (EC-transaction fusion)."""
    n = ec.get_chunk_count()
    txns = {s: Transaction() for s in range(n)}

    if pgt.pre_clone is not None:
        # Clone each shard's pre-write state (data + attrs incl. hinfo)
        # in the same transaction as the write — the EC shape of
        # make_writeable's clone (per-shard objects clone per-shard).
        for s, txn in txns.items():
            txn.clone(shard_colls[s], pgt.oid, pgt.pre_clone)
    for extra in pgt.also_delete:
        for s, txn in txns.items():
            txn.remove(shard_colls[s], extra)

    if pgt.delete:
        for s, txn in txns.items():
            txn.remove(shard_colls[s], pgt.oid)
        return txns, None, {}

    merged = stage.merged
    old_padded = sinfo.logical_to_next_stripe_offset(obj_size)

    # Emit per-shard chunk writes at the mapped chunk offset
    # (ECTransaction.cc:74-93), reaping each region's launch.
    region_appends: dict[int, dict[int, bytes]] = {}
    for off in sorted(merged):
        shards = stage.pending[off].result()
        chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(off)
        region_appends[off] = {}
        for s in range(n):
            chunk = np.ascontiguousarray(shards[s]).tobytes()
            csums = (
                csum_submit(chunk, chunk_off)
                if csum_submit is not None
                else None
            )
            txns[s].write(
                shard_colls[s], pgt.oid, chunk_off, chunk, csums=csums
            )
            region_appends[off][s] = chunk
            if chunk_cache is not None:
                chunk_cache.put(
                    cache_obj, s, cache_generation, chunk, off=chunk_off
                )

    # Cumulative hinfo: appends chain onto the existing digests; a full
    # rewrite from 0 restarts the chain (stale digests would flag every
    # subsequent read as corrupt); anything else drops hinfo.
    new_hinfo = None if plan.invalidates_hinfo else hinfo
    if not plan.invalidates_hinfo and merged:
        offs = sorted(merged)
        if obj_size == 0 or offs[0] >= old_padded:
            new_hinfo = hinfo if hinfo is not None else HashInfo(n)
        elif offs[0] == 0 and len(merged[0]) >= old_padded:
            new_hinfo = HashInfo(n)  # full rewrite: fresh chain
        else:
            new_hinfo = None
        if new_hinfo is not None:
            for off in offs:
                new_hinfo.append(new_hinfo.get_total_chunk_size(), region_appends[off])

    # Shard-object truncate for shrinking truncates (chunk-aligned tail).
    if pgt.truncate is not None and pgt.truncate < obj_size:
        shard_size = sinfo.logical_to_next_chunk_offset(pgt.truncate)
        for s, txn in txns.items():
            txn.truncate(shard_colls[s], pgt.oid, shard_size)

    oi = ObjectInfo(size=plan.new_size, version=version)
    for s, txn in txns.items():
        txn.setattr(shard_colls[s], pgt.oid, OI_ATTR, oi.encode())
        if new_hinfo is not None:
            txn.setattr(shard_colls[s], pgt.oid, HINFO_ATTR, new_hinfo.encode())
        elif hinfo is not None:
            txn.rmattr(shard_colls[s], pgt.oid, HINFO_ATTR)
        for name, val in pgt.attrs.items():
            if val is None:
                txn.rmattr(shard_colls[s], pgt.oid, name)
            else:
                txn.setattr(shard_colls[s], pgt.oid, name, val)
    return txns, new_hinfo, {off: bytes(buf) for off, buf in merged.items()}


def generate_transactions(
    pgt: PGTransaction,
    plan: WritePlan,
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    shard_colls: dict[int, str],
    obj_size: int,
    read_data: dict[int, bytes],
    hinfo: HashInfo | None,
    version: int,
) -> tuple[dict[int, Transaction], HashInfo | None, dict[int, bytes]]:
    """Build one Transaction per shard (ECTransaction::generate_transactions,
    ECTransaction.cc:109) — the synchronous launch+finish composition.
    `read_data` maps stripe-aligned offsets from plan.to_read to their
    current logical bytes (RMW input).

    Returns (shard -> Transaction, updated hinfo or None when dropped,
    merged logical bytes per will_write range — what the extent cache pins
    so overlapping writes see exactly what was encoded)."""
    stage = launch_encode(pgt, plan, sinfo, ec, obj_size, read_data)
    return finish_transactions(
        stage, pgt, plan, sinfo, ec, shard_colls, obj_size, hinfo, version
    )
