"""Object snapshot metadata — SnapSet and clone naming.

Reference: src/osd/osd_types.h `SnapSet` (per-head snapshot state:
`seq`, ordered `clones`, per-clone covered snaps + size) and
PrimaryLogPG::make_writeable (the clone-on-first-write-after-snap step).
Self-managed-snap model: snap ids are allocated from the pool's
`snap_seq` counter by the OSDMonitor; clients send a SnapContext with
every write.

Clone objects live beside the head in the same PG collection as
`<oid>@<cloneid>` — the `rbd_data.<id>.<objno>@<snap>` shape librbd's
data objects take, but server-side and crash-consistent (the clone rides
the same backend transaction as the triggering write).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

SS_ATTR = "ss"  # SnapSet attr on the head object (SS_ATTR "snapset")
# Deleted-but-snapshotted heads stay as zero-byte whiteouts so the
# SnapSet (and its clones) remain reachable (object_info_t FLAG_WHITEOUT)
WHITEOUT_ATTR = "whiteout"


def clone_oid(oid: str, snap_id: int) -> str:
    return f"{oid}@{snap_id}"


@dataclass
class SnapSet:
    """Per-object snapshot state (osd_types.h SnapSet)."""

    seq: int = 0  # newest snap this head has cloned for
    # oldest-first: {"id": cloneid, "snaps": [covered ids], "size": bytes}
    clones: list[dict] = field(default_factory=list)
    # newest snap that already existed when the object was created: reads
    # at snaps <= born answer ENOENT (the object was not there yet)
    born: int = 0

    def encode(self) -> bytes:
        return json.dumps(
            {"seq": self.seq, "clones": self.clones, "born": self.born}
        ).encode()

    @classmethod
    def decode(cls, blob: bytes | None) -> "SnapSet":
        if not blob:
            return cls()
        obj = json.loads(blob.decode())
        return cls(
            seq=int(obj["seq"]),
            clones=list(obj["clones"]),
            born=int(obj.get("born", 0)),
        )

    def needs_clone(self, snapc_seq: int, snaps: list[int]) -> list[int]:
        """Snap ids newer than our seq: non-empty means the head must be
        cloned before this write (make_writeable's `snapc.seq > obj seq`
        test — a stale SnapContext whose seq is not past ours never
        clones, even if its snaps list is malformed)."""
        if snapc_seq <= self.seq:
            return []
        return sorted(s for s in snaps if s > self.seq)

    def add_clone(self, covered: list[int], size: int) -> int:
        """Record a clone covering `covered` (ascending); returns its id
        (the newest covered snap, Ceph's cloneid convention)."""
        cid = covered[-1]
        self.clones.append({"id": cid, "snaps": covered, "size": size})
        self.seq = cid
        return cid

    def resolve(self, snap_id: int) -> int | None:
        """Which clone serves a read at `snap_id`?  The oldest clone with
        id >= snap_id (its content is the head as of that snap); None =
        the head itself (object unchanged since the snap).  Mirrors
        PrimaryLogPG::find_object_context's clone walk."""
        for c in self.clones:
            if c["id"] >= snap_id:
                return c["id"]
        return None

    def drop_snap(self, snap_id: int) -> int | None:
        """Snap trim: remove `snap_id` from coverage; returns the clone id
        to DELETE when it no longer covers any snap, else None
        (PrimaryLogPG::trim_object)."""
        for i, c in enumerate(self.clones):
            if snap_id in c["snaps"]:
                c["snaps"] = [s for s in c["snaps"] if s != snap_id]
                if not c["snaps"]:
                    del self.clones[i]
                    return c["id"]
                return None
        return None
