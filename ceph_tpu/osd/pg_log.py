"""PG log, info, and missing set — mirror of src/osd/PGLog / osd_types.

Reference: /root/reference/src/osd/PGLog.{h,cc} and osd_types.h
(`pg_log_entry_t`, `pg_info_t`, `pg_missing_t`).  The log is the
authoritative per-PG mutation history: every write appends an entry at a
monotonically increasing `eversion_t` (epoch, version); peering compares
shard logs to find the authoritative history, and divergent shards compute
their missing set by walking the delta (PGLog::proc_replica_log /
pg_missing_t::add_next_event analog in `Missing.add_next_event`).
Shards whose logs fell too far behind recover by backfill instead.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..common.encoding import Decoder, Encodable, Encoder


@dataclass(frozen=True, order=True)
class Eversion:
    """eversion_t: (epoch, version), totally ordered."""

    epoch: int = 0
    version: int = 0

    def __bool__(self) -> bool:
        return self.epoch != 0 or self.version != 0

    def encode(self, enc: Encoder) -> None:
        enc.u32(self.epoch)
        enc.u64(self.version)

    @classmethod
    def decode(cls, dec: Decoder) -> "Eversion":
        return cls(dec.u32(), dec.u64())


# Log entry op kinds (pg_log_entry_t::MODIFY/DELETE/...).
LOG_MODIFY = 1
LOG_DELETE = 2
LOG_ERROR = 4


@dataclass
class LogEntry(Encodable):
    """pg_log_entry_t: one mutation in the PG's history."""

    op: int = LOG_MODIFY
    oid: str = ""
    version: Eversion = field(default_factory=Eversion)
    prior_version: Eversion = field(default_factory=Eversion)
    reqid: tuple[str, int] = ("", 0)

    def is_delete(self) -> bool:
        return self.op == LOG_DELETE

    def encode(self, enc: Encoder) -> None:
        enc.start(1, 1)
        enc.u8(self.op)
        enc.string(self.oid)
        self.version.encode(enc)
        self.prior_version.encode(enc)
        enc.string(self.reqid[0])
        enc.u64(self.reqid[1])
        enc.finish()

    @classmethod
    def decode(cls, dec: Decoder) -> "LogEntry":
        dec.start(1)
        e = cls(
            op=dec.u8(),
            oid=dec.string(),
            version=Eversion.decode(dec),
            prior_version=Eversion.decode(dec),
        )
        e.reqid = (dec.string(), dec.u64())
        dec.finish()
        return e


@dataclass
class PgInfo(Encodable):
    """pg_info_t: summary a shard reports during peering."""

    last_update: Eversion = field(default_factory=Eversion)
    last_complete: Eversion = field(default_factory=Eversion)
    log_tail: Eversion = field(default_factory=Eversion)
    last_epoch_started: int = 0

    def encode(self, enc: Encoder) -> None:
        enc.start(1, 1)
        self.last_update.encode(enc)
        self.last_complete.encode(enc)
        self.log_tail.encode(enc)
        enc.u32(self.last_epoch_started)
        enc.finish()

    @classmethod
    def decode(cls, dec: Decoder) -> "PgInfo":
        dec.start(1)
        info = cls(
            last_update=Eversion.decode(dec),
            last_complete=Eversion.decode(dec),
            log_tail=Eversion.decode(dec),
            last_epoch_started=dec.u32(),
        )
        dec.finish()
        return info


class Missing:
    """pg_missing_t: oid -> (need, have) versions."""

    def __init__(self) -> None:
        self.items: dict[str, tuple[Eversion, Eversion]] = {}

    def __len__(self) -> int:
        return len(self.items)

    def __contains__(self, oid: str) -> bool:
        return oid in self.items

    def add(self, oid: str, need: Eversion, have: Eversion = Eversion()) -> None:
        self.items[oid] = (need, have)

    def rm(self, oid: str) -> None:
        self.items.pop(oid, None)

    def add_next_event(self, entry: LogEntry) -> None:
        """Walking a log delta we don't have: each entry makes its object
        missing at that version (pg_missing_t::add_next_event)."""
        if entry.is_delete():
            self.items.pop(entry.oid, None)
        else:
            have = self.items.get(entry.oid, (None, entry.prior_version))[1]
            self.items[entry.oid] = (entry.version, have)


class PGLog:
    """In-memory ordered log with trim (PGLog.h IndexedLog analog)."""

    def __init__(self) -> None:
        self.entries: list[LogEntry] = []
        self.tail = Eversion()

    @property
    def head(self) -> Eversion:
        return self.entries[-1].version if self.entries else self.tail

    def append(self, entry: LogEntry) -> None:
        assert entry.version > self.head, (entry.version, self.head)
        self.entries.append(entry)

    def trim(self, to: Eversion) -> None:
        """Drop entries <= to (PGLog::trim); tail advances."""
        keep = [e for e in self.entries if e.version > to]
        if len(keep) != len(self.entries):
            self.tail = max(self.tail, to)
            self.entries = keep

    def entries_after(self, v: Eversion) -> list[LogEntry]:
        """The delta a lagging shard needs; valid only if v >= tail."""
        assert v >= self.tail, (v, self.tail)
        return [e for e in self.entries if e.version > v]

    def can_catch_up(self, v: Eversion) -> bool:
        """Whether a shard at version v can log-recover (else backfill)."""
        return v >= self.tail

    def missing_from(self, v: Eversion) -> Missing:
        """Missing set for a shard whose last_update is v."""
        missing = Missing()
        for e in self.entries_after(v):
            missing.add_next_event(e)
        return missing

    def encode_entries(self) -> list[bytes]:
        return [e.tobytes() for e in self.entries]
