"""Recovery-storm controller — wave-batched whole-OSD rebuild (ISSUE 15).

ROADMAP item 2's headline: 23.4 GB/s/chip x 8 chips of decode bandwidth
exists, but whole-OSD rebuild used to trickle per-PG through
`osd_recovery_max_active` with no cross-PG coordination and no feedback
from the SLO pipeline.  This controller makes rebuild a deliberately
scheduled pipeline:

- **Wave batching**: when the outstanding missing-object count across
  this OSD's primaried PGs crosses `osd_recovery_storm_min_objects`
  (the whole-OSD-failure signature — an osdmap out-event is noted as
  the storm's *victim* for the progress bar), the controller ENGAGES:
  it widens the shared DecodeAggregator window to the wave size and
  admits recoveries round-robin across PGs in waves of up to
  `osd_recovery_storm_wave_objects`, so many PGs' reconstruction
  decodes co-ride few padded mesh-wide launches on the recovery QoS
  lane (the ECBackend decode pipeline + sharded dispatch built in PRs
  4/7/11 do the heavy lifting; this is the missing conductor).  Wave
  depth is bounded by `osd_recovery_storm_max_inflight` across ALL
  PGs — the cross-PG analog of the per-PG knob it supersedes.
- **SLO-aware admission**: each tick the controller evaluates a LOCAL
  client burn rate from the OSD's own io-accounting latency histograms
  (the per-OSD input of the mgr iostat/SLO layer): the delta of
  client read/write ops slower than `osd_recovery_storm_slo_target_ms`
  over the error budget (1 - `osd_recovery_storm_slo_objective`).
  Burn above `osd_recovery_storm_burn_threshold` SHEDS (wave halves
  toward the floor); at/below it RAMPS (wave doubles toward the
  ceiling).  An idle cluster rebuilds at full blast; one burning its
  latency budget backs recovery off before SLO_LATENCY_BREACH fires.
- **Priority**: an engaged storm holds a `local_reserver` slot at
  `osd_recovery_op_priority`, PREEMPTING a granted backfill reservation
  (osd/reserver.py) — rebuild-for-durability outranks rebalancing.
- **Observability**: per-wave flight records (kind ``recovery_wave``,
  rendered as their own Perfetto row by tools/trace_export.py),
  ``recovery_storm.*`` counters/gauges on the MMgrReport (the
  ``ceph_tpu_recovery_storm_*`` scrape families), and a
  ``recovery_storm`` status-blob slice the mgr progress module
  aggregates into a whole-OSD rebuild bar with rate + ETA.

Every knob is runtime-mutable: all reads happen per tick, and the wave
ceiling additionally has a config observer clamping the live adaptive
wave the moment it shrinks.
"""

from __future__ import annotations

import time
import weakref

from ..common.log import dout

# rate smoothing: EMA weight of the newest objects/sec sample (the
# progress module's constant, reused so the two ETAs behave alike)
_RATE_ALPHA = 0.3
# minimum client ops in a burn window before the rate is trusted —
# two slow ops on an idle pool are noise, not an SLO breach
_BURN_MIN_OPS = 4
# minimum seconds between burn evaluations: ticks are completion-driven
# (PG.on_global_recover kicks per recovered object), and swapping the
# io-accounting baseline on every kick would shrink the window below
# the min-ops floor — burn would read 0.0 mid-breach and a shed would
# ramp right back.  Wave adjustments clock to these evaluations.
_BURN_EVAL_SEC = 0.25

# storms currently ENGAGED across the process: the decode aggregator is
# process-wide (embedded multi-OSD harnesses share it), so the widened
# window is restored from config only when the LAST storm disengages —
# one OSD finishing must not narrow a sibling's mid-episode window.
# Weak references: a torn-down controller (harness OSD that never ran
# to disengage) must not pin the refcount forever.
_ENGAGED: "weakref.WeakSet" = weakref.WeakSet()


def _under_target(lat_dump: dict | None, target_sec: float) -> tuple[int, int]:
    """(total samples, samples at/under target) from a cumulative
    PerfHistogram.dump() payload ({"histogram": {"buckets": [[le, cum],
    ...], "count": N}}); tolerates missing/empty dumps."""
    h = (lat_dump or {}).get("histogram") or {}
    total = int(h.get("count") or 0)
    under = 0
    for le, cum in h.get("buckets") or []:
        if le == "+Inf":
            continue
        if float(le) <= target_sec:
            under = int(cum)
        else:
            break
    return total, under


class RecoveryStormController:
    """Per-OSD cross-PG recovery orchestrator (one per OSD daemon)."""

    # completed-storm status re-emits on this many reports: the mgr
    # samples a last-write-wins status blob, so a one-shot final bar
    # could vanish before a module tick sees it (the PG progress
    # renderer's trick, applied to the whole-OSD bar)
    FINAL_REPORTS = 3

    def __init__(self, osd):
        self.osd = osd
        self.engaged = False
        # osd id -> monotonic stamp it was seen leaving up+in: the
        # storm's "victims" label for the whole-OSD rebuild bar
        self.victims: dict[int, float] = {}
        # monotone counters (the ceph_tpu_recovery_storm_* families)
        self.waves = 0
        self.objects_admitted = 0
        self.sheds = 0
        self.ramps = 0
        self.storms_started = 0
        self.storms_completed = 0
        self.preempted_backfills = 0
        # live levels (gauges)
        self._wave = int(osd.conf.get("osd_recovery_storm_wave_objects"))
        self._burn = 0.0
        self._inflight = 0
        # episode progress
        self._total = 0
        self._done = 0
        self._rate = 0.0
        self._engaged_at = 0.0
        self._last_tick = 0.0
        self._last_done = 0
        self._prev_io: dict | None = None
        self._last_burn_eval = 0.0
        self._final_reports = 0
        self._last_status: dict = {}
        # a runtime ceiling change clamps the live adaptive wave NOW —
        # the observer half of the config wiring (the per-tick re-reads
        # are the other half)
        osd.conf.add_observer(
            ["osd_recovery_storm_wave_objects"],
            lambda _n, v: self._clamp_wave(int(v)),
        )

    def _clamp_wave(self, ceiling: int) -> None:
        self._wave = max(1, min(self._wave, max(1, ceiling)))

    # -- osdmap transitions ----------------------------------------------------

    def note_osdmap(self, old, new) -> None:
        """Called on every map advance: an OSD leaving up+in is a storm
        victim candidate (named on the rebuild bar); one returning to
        up+in is struck — its data no longer needs a whole-OSD rebuild."""
        now = time.monotonic()
        for oid, info in old.osds.items():
            ninfo = new.osds.get(oid)
            if ninfo is None:
                continue
            if (info.up and info.in_) and not (ninfo.up and ninfo.in_):
                self.victims[oid] = now
        for oid in list(self.victims):
            ninfo = new.osds.get(oid)
            if ninfo is not None and ninfo.up and ninfo.in_:
                del self.victims[oid]

    # -- the tick loop ---------------------------------------------------------

    def tick(self) -> None:
        """One admission pass (heartbeat-driven, like PG.tick)."""
        conf = self.osd.conf
        ready: list[tuple[object, list[str]]] = []
        inflight = 0
        outstanding = 0
        for key in sorted(self.osd.pgs):
            pg = self.osd.pgs[key]
            if not (pg.peering.is_primary() and pg.peering.is_active()):
                continue
            inflight += len(pg.recovering)
            oids = [
                o for o in pg.peering.all_missing_oids()
                if o not in pg.recovering
            ]
            outstanding += len(oids)
            if oids:
                ready.append((pg, oids))
        self._inflight = inflight
        if not self.engaged:
            if (
                outstanding + inflight
                >= int(conf.get("osd_recovery_storm_min_objects"))
            ):
                self._engage(outstanding + inflight)
            else:
                return
        # episode progress: high-water total, done derived from what is
        # no longer outstanding (newly discovered work grows the
        # denominator, never regresses done — the PG bar's discipline)
        self._total = max(self._total, self._done + outstanding + inflight)
        self._done = max(self._done, self._total - outstanding - inflight)
        self._update_rate()
        self._adapt_wave()
        max_inflight = int(conf.get("osd_recovery_storm_max_inflight"))
        if ready and inflight < max_inflight:
            budget = min(self._wave, max_inflight - inflight)
            admitted = self._admit_wave(ready, budget)
            if admitted:
                self.waves += 1
                self.objects_admitted += admitted
                self._inflight += admitted
        if outstanding == 0 and inflight == 0:
            self._disengage()

    def _update_rate(self) -> None:
        now = time.monotonic()
        dt = now - self._last_tick
        if dt >= 0.01:
            # sample from the done-delta over the tick; first tick of an
            # episode only seeds the clock
            delta = self._done - getattr(self, "_last_done", 0)
            if delta > 0:
                sample = delta / dt
                self._rate = (
                    sample if self._rate == 0.0
                    else _RATE_ALPHA * sample + (1 - _RATE_ALPHA) * self._rate
                )
            self._last_tick = now
            self._last_done = self._done

    def _clog(self, prio: str, msg: str) -> None:
        """Storm timeline entries (ISSUE 16): engage/shed/wave/complete
        land in the cluster log so the storm is reconstructable from
        `log last` alone.  Guarded — unit tests drive the controller
        with a bare fake OSD."""
        clog = getattr(self.osd, "cluster_log", None)
        if clog is not None:
            clog(prio, msg, code="RECOVERY_STORM")

    # -- engagement ------------------------------------------------------------

    def _engage(self, total: int) -> None:
        self.engaged = True
        self.storms_started += 1
        now = time.monotonic()
        self._engaged_at = now
        self._last_tick = now
        self._last_done = 0
        self._total = total
        self._done = 0
        self._rate = 0.0
        self._burn = 0.0
        self._prev_io = None
        self._final_reports = 0
        self._wave = max(
            1, int(self.osd.conf.get("osd_recovery_storm_wave_objects"))
        )
        self._last_burn_eval = 0.0
        # widen the (shared) decode window so one wave's decodes co-ride
        # few padded launches; restored from config when the LAST
        # engaged storm in the process disengages (the aggregator is
        # shared — widening is monotone across concurrent storms, and
        # the _ENGAGED refcount keeps one OSD's finish from narrowing
        # a sibling's mid-episode window)
        _ENGAGED.add(self)
        self.osd.decode_aggregator.configure(
            window=max(
                int(self.osd.conf.get("ec_tpu_decode_aggregate_window")),
                self._wave,
            )
        )
        # rebuild-for-durability outranks rebalancing: take a local slot
        # at recovery priority, preempting a granted backfill (its
        # on_preempt surrenders cleanly; the tick loop re-grants after
        # the storm releases)
        before = self.osd.local_reserver.preemptions
        self.osd.local_reserver.try_reserve(
            ("storm", self.osd.whoami),
            priority=int(self.osd.conf.get("osd_recovery_op_priority")),
        )
        self.preempted_backfills += (
            self.osd.local_reserver.preemptions - before
        )
        dout(
            "osd", 1,
            f"osd.{self.osd.whoami}: recovery storm ENGAGED "
            f"({total} objects outstanding, victims "
            f"{sorted(self.victims) or '[]'})",
        )
        self._clog(
            "info",
            f"recovery storm ENGAGED: {total} objects outstanding, "
            f"victims {sorted(self.victims) or '[]'}",
        )

    def _disengage(self) -> None:
        self.engaged = False
        self.storms_completed += 1
        self._done = self._total  # the bar completes at exactly 100%
        self._final_reports = self.FINAL_REPORTS
        self._last_status = self._render(final=True)
        _ENGAGED.discard(self)
        if not _ENGAGED:
            self.osd.decode_aggregator.configure(
                window=int(
                    self.osd.conf.get("ec_tpu_decode_aggregate_window")
                )
            )
        self.osd.local_reserver.release(("storm", self.osd.whoami))
        self.victims.clear()
        dout(
            "osd", 1,
            f"osd.{self.osd.whoami}: recovery storm complete "
            f"({self._total} objects, {self.waves} waves lifetime)",
        )
        self._clog(
            "info",
            f"recovery storm complete: {self._total} objects rebuilt, "
            f"{self.waves} waves lifetime",
        )

    # -- wave admission --------------------------------------------------------

    def _admit_wave(
        self, ready: list[tuple[object, list[str]]], budget: int
    ) -> int:
        """Admit up to `budget` recoveries round-robin across PGs (one
        object per PG per turn, so a 40-object PG cannot starve a
        4-object one) and commit the wave's flight record."""
        t0 = time.monotonic()
        queues = [(pg, list(oids)) for pg, oids in ready]
        admitted = 0
        pgs_touched: set = set()
        while queues and admitted < budget:
            next_queues = []
            for pg, oids in queues:
                if admitted >= budget:
                    break
                oid = oids.pop(0)
                already = oid in pg.recovering
                pg._recover_one(oid)
                if not already and oid in pg.recovering:
                    admitted += 1
                    pgs_touched.add(id(pg))
                if oids:
                    next_queues.append((pg, oids))
            queues = next_queues
        if admitted:
            self._record_wave(t0, admitted, len(pgs_touched))
            # per-wave timeline breadcrumb at debug severity: the
            # "waves" step of the storm sequence, cheap enough that the
            # client-side rate limiter is the only bound it needs
            self._clog(
                "debug",
                f"recovery storm wave: {admitted} objects across "
                f"{len(pgs_touched)} pgs (wave size {self._wave})",
            )
        return admitted

    def _record_wave(self, t0: float, objects: int, pgs: int) -> None:
        """One flight record per wave: the Perfetto storm row and the
        launches-vs-objects witness chaos asserts against."""
        from ..ops.flight_recorder import flight_recorder, new_record

        rec = new_record(
            "recovery_wave",
            group=self._group_name(),
            tickets=pgs,
            stripes=objects,
            batch=objects,
            submit_ts=t0,
            sched_class="recovery",
        )
        rec["dispatch_ts"] = t0
        flight_recorder().commit(rec)

    def _group_name(self) -> str:
        victims = "+".join(f"osd.{o}" for o in sorted(self.victims))
        return f"storm:{victims or f'osd.{self.osd.whoami}:local'}"

    # -- SLO-aware admission ---------------------------------------------------

    def _adapt_wave(self) -> None:
        # clock shed/ramp decisions to the burn-evaluation cadence: a
        # completion-driven tick between evaluations must neither swap
        # the io baseline (shrinking the burn window to nothing) nor
        # step the wave on a stale verdict
        now = time.monotonic()
        if now - self._last_burn_eval < _BURN_EVAL_SEC:
            return
        self._last_burn_eval = now
        conf = self.osd.conf
        self._burn = self._client_burn()
        ceiling = max(1, int(conf.get("osd_recovery_storm_wave_objects")))
        floor = max(
            1, int(conf.get("osd_recovery_storm_min_wave_objects"))
        )
        floor = min(floor, ceiling)
        threshold = float(conf.get("osd_recovery_storm_burn_threshold"))
        if self._burn > threshold:
            new = max(floor, self._wave // 2)
            if new < self._wave:
                self.sheds += 1
                self._clog(
                    "info",
                    f"recovery storm SHED: wave {self._wave} -> {new} "
                    f"(client burn {self._burn:.2f} > {threshold})",
                )
        else:
            new = min(ceiling, max(self._wave * 2, floor))
            if new > self._wave:
                self.ramps += 1
        self._wave = max(floor, min(new, ceiling))

    def _client_burn(self) -> float:
        """Worst per-pool local burn rate over the last tick window:
        (client read/write ops slower than the target) / error budget,
        from the io-accounting histogram deltas.  0.0 while disabled,
        on the first tick (no baseline), or under the min-ops floor."""
        conf = self.osd.conf
        target_ms = float(conf.get("osd_recovery_storm_slo_target_ms"))
        accountant = getattr(self.osd, "io_accountant", None)
        if accountant is None:
            return 0.0
        cur = accountant.dump_pools()
        prev, self._prev_io = self._prev_io, cur
        if target_ms <= 0 or prev is None:
            return 0.0
        objective = float(conf.get("osd_recovery_storm_slo_objective"))
        budget = max(1e-6, 1.0 - objective)
        target_sec = target_ms / 1e3
        worst = 0.0
        for pid, classes in cur.items():
            for cls in ("read", "write"):
                total1, under1 = _under_target(
                    (classes.get(cls) or {}).get("lat"), target_sec
                )
                total0, under0 = _under_target(
                    ((prev.get(pid) or {}).get(cls) or {}).get("lat"),
                    target_sec,
                )
                d_total = total1 - total0
                if d_total < _BURN_MIN_OPS:
                    continue
                d_bad = d_total - (under1 - under0)
                worst = max(worst, (d_bad / d_total) / budget)
        return worst

    # -- surfaces --------------------------------------------------------------

    def _render(self, final: bool = False) -> dict:
        now = time.monotonic()
        remaining = max(0, self._total - self._done)
        eta = (
            None
            if final or self._rate <= 0.0
            else round(remaining / self._rate, 1)
        )
        return {
            "engaged": bool(self.engaged),
            "victims": sorted(f"osd.{o}" for o in self.victims),
            "objects_done": self._done,
            "objects_total": self._total,
            "wave_objects": self._wave,
            "inflight": self._inflight,
            "waves": self.waves,
            "burn_rate": round(self._burn, 3),
            "rate_objects_per_sec": 0.0 if final else round(self._rate, 3),
            "eta_seconds": eta,
            "elapsed_seconds": round(now - self._engaged_at, 1),
        }

    def status(self) -> dict:
        """The `recovery_storm` OSD status-blob slice ({} when idle):
        the mgr progress module aggregates these across daemons into a
        whole-OSD rebuild bar with rate + ETA."""
        if self.engaged:
            self._last_status = self._render()
            return dict(self._last_status)
        if self._final_reports > 0:
            self._final_reports -= 1
            return dict(self._last_status)
        return {}

    def perf_dump(self) -> dict:
        """Flat scalars for the MMgrReport `recovery_storm.*` namespace
        (the scrape renders one ceph_tpu_recovery_storm_* family per
        key; wave_objects/inflight/engaged/burn_rate are gauges, the
        rest monotone counters — mgr/prometheus._perf_type)."""
        return {
            "waves": self.waves,
            "objects_admitted": self.objects_admitted,
            "sheds": self.sheds,
            "ramps": self.ramps,
            "storms_started": self.storms_started,
            "storms_completed": self.storms_completed,
            "preempted_backfills": self.preempted_backfills,
            "wave_objects": self._wave,
            "inflight": self._inflight,
            "engaged": int(self.engaged),
            "burn_rate": round(self._burn, 3),
        }
