"""ExtentCache — pins in-flight stripe extents for the EC RMW pipeline.

Reference: /root/reference/src/osd/ExtentCache.{h,cc} (invariants documented
at ExtentCache.h:30-90): while a partial-stripe overwrite is in flight, its
read-modify-write extents stay pinned so a subsequent overlapping write reads
the *pending* bytes from cache instead of re-reading stale shards — writes to
the same stripe pipeline instead of stalling.

Extents are per-object byte ranges of the *logical* (stripe-aligned) address
space.  Each write op holds a pin over the segments it inserted; pinned
segments overlay in insertion order (newest write wins), and releasing the
pin drops its segments.
"""

from __future__ import annotations


class _Segment:
    __slots__ = ("oid", "off", "data")

    def __init__(self, oid: str, off: int, data: bytes):
        self.oid = oid
        self.off = off
        self.data = bytes(data)


class Pin:
    """write_pin analog: the handle one in-flight write op holds."""

    def __init__(self) -> None:
        self.segments: list[_Segment] = []


class ExtentCache:
    def __init__(self) -> None:
        # oid -> segments in insertion (pipeline) order; later segments
        # overlay earlier ones where they overlap.
        self._data: dict[str, list[_Segment]] = {}

    def prepare_pin(self) -> Pin:
        return Pin()

    def present(self, oid: str, off: int, length: int) -> bytes | None:
        """Bytes for [off, off+length) if fully covered by pinned pending
        writes (overlaid newest-last), else None."""
        segs = self._data.get(oid)
        if not segs:
            return None
        out = bytearray(length)
        intervals: list[tuple[int, int]] = []
        end = off + length
        for seg in segs:  # insertion order: later writes overwrite earlier
            lo = max(off, seg.off)
            hi = min(end, seg.off + len(seg.data))
            if lo < hi:
                out[lo - off : hi - off] = seg.data[lo - seg.off : hi - seg.off]
                intervals.append((lo, hi))
        intervals.sort()
        cur = off
        for lo, hi in intervals:
            if lo > cur:
                return None  # gap
            cur = max(cur, hi)
        return bytes(out) if cur >= end else None

    def pin_extent(self, pin: Pin, oid: str, off: int, data: bytes) -> None:
        """Insert [off, off+len) pending bytes under this op's pin
        (ExtentCache::reserve_extents_for_rmw)."""
        seg = _Segment(oid, off, data)
        self._data.setdefault(oid, []).append(seg)
        pin.segments.append(seg)

    def release_pin(self, pin: Pin) -> None:
        """Write committed: this op's segments leave the cache
        (ExtentCache::release_write_pin)."""
        for seg in pin.segments:
            segs = self._data.get(seg.oid)
            if segs is None:
                continue
            try:
                segs.remove(seg)
            except ValueError:
                pass
            if not segs:
                del self._data[seg.oid]
        pin.segments.clear()

    def empty(self) -> bool:
        return not self._data
