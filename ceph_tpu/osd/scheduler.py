"""Op QoS schedulers — mirror of src/osd/scheduler/.

Reference: /root/reference/src/osd/scheduler/mClockScheduler.h:72 (dmClock
tag-based scheduler over the external dmclock submodule; see also
src/dmclock/src/dmclock_server.h) and OpScheduler.h's WPQ alternative
(`osd_op_queue` option selects one, as here).

The dmClock algorithm (Gulati et al., OSDI'10) assigns each scheduling
class a (reservation, weight, limit) triple in IOPS:

- every queued item gets three tags: R (reservation), P (proportional),
  L (limit), each advancing from the class's previous tag by 1/rate;
- dequeue first serves any class whose R tag is in the past (reservations
  are guaranteed), then falls back to the smallest P tag among classes
  whose L tag is in the past (weights share the spare capacity, limits
  cap it).

Items carry an abstract `cost` (bytes) that scales the tag increments the
way the reference's mClock cost model scales by item size
(mClockScheduler.cc calc_scaled_cost).
"""

from __future__ import annotations

import enum
import heapq
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable


class SchedClass(enum.Enum):
    """Scheduling classes (op_scheduler_class in OpSchedulerItem.h)."""

    CLIENT = "client"
    RECOVERY = "background_recovery"
    SCRUB = "background_scrub"
    BEST_EFFORT = "background_best_effort"


@dataclass
class ClientProfile:
    """dmClock (reservation, weight, limit); 0 = unset/unlimited."""

    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0


@dataclass
class _Tags:
    r: float = 0.0
    p: float = 0.0
    l: float = 0.0


@dataclass
class WorkItem:
    """One schedulable unit (OpSchedulerItem): an opaque runnable plus
    its class, cost in bytes, and priority for the WPQ fallback."""

    run: Callable[[], None]
    klass: SchedClass = SchedClass.CLIENT
    cost: int = 4096
    priority: int = 63


class OpScheduler:
    """Abstract scheduler (OpScheduler.h)."""

    def enqueue(self, item: WorkItem) -> None:
        raise NotImplementedError

    def dequeue(self) -> WorkItem | None:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def empty(self) -> bool:
        return len(self) == 0


class MClockScheduler(OpScheduler):
    """dmClock-lite over per-class FIFO queues (mClockScheduler.h:72).

    Rates are expressed in items/sec for a nominal 4 KiB item; an item of
    cost C consumes C/4096 nominal items, matching the reference's scaled
    cost model.  The clock is injectable for deterministic tests.
    """

    NOMINAL_COST = 4096.0

    def __init__(
        self,
        profiles: dict[SchedClass, ClientProfile] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.profiles = profiles or {
            SchedClass.CLIENT: ClientProfile(reservation=1.0, weight=2.0),
            SchedClass.RECOVERY: ClientProfile(weight=1.0, limit=3.0),
            SchedClass.SCRUB: ClientProfile(weight=1.0, limit=3.0),
            SchedClass.BEST_EFFORT: ClientProfile(weight=1.0),
        }
        self.clock = clock
        self._queues: dict[SchedClass, deque[tuple[_Tags, WorkItem]]] = {
            k: deque() for k in SchedClass
        }
        self._last: dict[SchedClass, _Tags] = {k: _Tags() for k in SchedClass}
        self._size = 0

    def _profile(self, klass: SchedClass) -> ClientProfile:
        return self.profiles.get(klass, ClientProfile())

    def update_profile(self, klass: SchedClass, profile: ClientProfile) -> None:
        """Runtime reconfiguration (the reference's config-observer path,
        mClockScheduler.h:72 md_config_obs_t).  The class's tag chain
        restarts: a reservation of 0 stores r = inf as the last tag, and
        without a reset a later nonzero reservation would compute
        max(now, inf + 1/res) forever — the knob would be permanently
        inert (the reference rebuilds the dmclock client info on config
        change for the same reason)."""
        self.profiles[klass] = profile
        self._last[klass] = _Tags()

    def enqueue(self, item: WorkItem) -> None:
        now = self.clock()
        prof = self._profile(item.klass)
        last = self._last[item.klass]
        scale = item.cost / self.NOMINAL_COST
        tags = _Tags()
        # Tag formulas from dmclock_server.h: next tag = max(now, prev+1/rate)
        tags.r = (
            max(now, last.r + scale / prof.reservation)
            if prof.reservation > 0
            else float("inf")
        )
        tags.p = max(now, last.p + scale / prof.weight) if prof.weight > 0 else now
        tags.l = max(now, last.l + scale / prof.limit) if prof.limit > 0 else now
        self._last[item.klass] = tags
        self._queues[item.klass].append((tags, item))
        self._size += 1

    def dequeue(self) -> WorkItem | None:
        if self._size == 0:
            return None
        now = self.clock()
        # Phase 1: honor reservations whose R tag has matured.
        best_r: SchedClass | None = None
        for klass, q in self._queues.items():
            if q and q[0][0].r <= now:
                if best_r is None or q[0][0].r < self._queues[best_r][0][0].r:
                    best_r = klass
        if best_r is not None:
            return self._pop(best_r)
        # Phase 2: weight-based among classes under their limit.
        best_p: SchedClass | None = None
        for klass, q in self._queues.items():
            if q and q[0][0].l <= now:
                if best_p is None or q[0][0].p < self._queues[best_p][0][0].p:
                    best_p = klass
        if best_p is not None:
            return self._pop(best_p)
        # Everything is limited: serve the nearest limit tag anyway rather
        # than idle (work-conserving, as the reference's immediate mode).
        nearest = min(
            (k for k in self._queues if self._queues[k]),
            key=lambda k: self._queues[k][0][0].l,
        )
        return self._pop(nearest)

    def _pop(self, klass: SchedClass) -> WorkItem:
        _tags, item = self._queues[klass].popleft()
        self._size -= 1
        return item

    def __len__(self) -> int:
        return self._size


class WPQScheduler(OpScheduler):
    """Weighted priority queue fallback (OpScheduler.h WPQ): strict
    priority with FIFO within a priority."""

    def __init__(self):
        self._heap: list[tuple[int, int, WorkItem]] = []
        self._seq = 0

    def enqueue(self, item: WorkItem) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (-item.priority, self._seq, item))

    def dequeue(self) -> WorkItem | None:
        if not self._heap:
            return None
        return heapq.heappop(self._heap)[2]

    def __len__(self) -> int:
        return len(self._heap)


def make_scheduler(kind: str, **kw) -> OpScheduler:
    """`osd_op_queue` selection (OpScheduler.cc make_scheduler)."""
    if kind == "wpq":
        return WPQScheduler()
    return MClockScheduler(**kw)
