"""Secure/compressed on-wire session — mirror of src/msg/async/
crypto_onwire.{h,cc} + compression_onwire.{h,cc}.

After the cephx auth phase both ends hold a session key (derived from the
handshake exactly like the reference's connection_secret) and the
negotiated feature set.  Every subsequent frame is carried inside an
on-wire record:

    magic "CW" | u8 flags | u8 pad | u32 body_len | body

- COMPRESSED: the frame bytes are zlib-deflated first
  (compression_onwire's tx_handler; zlib plays the reference's
  snappy/zstd role).
- SECURE: body = 12-byte nonce || AES-128-GCM ciphertext+tag over the
  (possibly compressed) frame bytes.  The nonce is a 4-byte random salt
  plus a strictly increasing 8-byte counter per direction
  (AES128GCM_OnWireTxHandler's nonce handling); receivers reject
  non-monotonic counters, so a replayed record fails even inside the
  same session.

Tampering anywhere (ciphertext, flags, truncation) surfaces as a
decrypt/parse error and the connection faults — the reference's
ceph_msg_data integrity contract under msgr2 secure mode.
"""

from __future__ import annotations

import hmac
import hashlib
import os
import struct
import zlib

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # plaintext/compress paths work without the package
    AESGCM = None

MAGIC = b"CW"
FLAG_SECURE = 1
FLAG_COMPRESSED = 2

_HEAD = struct.Struct("<2sBBI")  # magic, flags, pad, body_len
NONCE_LEN = 12
KEY_LEN = 16  # AES-128, the reference's connection-secret size


class OnWireError(Exception):
    pass


def derive_session_key(secret: bytes, *parts: bytes) -> bytes:
    """Session key from the auth exchange (cephx's connection_secret
    derivation: both sides know `secret` and the handshake transcript)."""
    return hmac.new(secret, b"\x00session\x00" + b"\x00".join(parts),
                    hashlib.sha256).digest()[:KEY_LEN]


MAX_FRAME = 64 << 20  # decompressed frame ceiling (bomb guard)


class OnWireSession:
    """Per-connection record codec (one per direction pair).

    Each direction runs under its OWN AES key, derived from the
    connection secret and the direction label — a reflected record (the
    sender's own ciphertext played back at it) fails authentication
    instead of decrypting as peer traffic, and the two directions can
    never collide on a nonce (the reference separates directions via its
    nonce/secret split in AES128GCM_OnWireTxHandler)."""

    def __init__(
        self, key: bytes | None, secure: bool, compress: bool,
        initiator: bool = True,
    ):
        if secure and not key:
            raise OnWireError("secure mode requires a session key")
        self.secure = secure
        self.compress = compress
        if secure:
            if AESGCM is None:
                raise OnWireError(
                    "secure mode requires the 'cryptography' package"
                )
            c2s = derive_session_key(key, b"dir:c2s")
            s2c = derive_session_key(key, b"dir:s2c")
            tx, rx = (c2s, s2c) if initiator else (s2c, c2s)
            self._tx_aead = AESGCM(tx)
            self._rx_aead = AESGCM(rx)
        else:
            self._tx_aead = self._rx_aead = None
        self._tx_salt = os.urandom(4)
        self._tx_counter = 0
        self._rx_counter = -1  # strictly increasing; replays rejected

    @property
    def active(self) -> bool:
        return self.secure or self.compress

    def wrap(self, frame_bytes: bytes) -> bytes:
        body = frame_bytes
        flags = 0
        if self.compress:
            body = zlib.compress(body, level=1)
            flags |= FLAG_COMPRESSED
        if self.secure:
            self._tx_counter += 1
            nonce = self._tx_salt + struct.pack("<Q", self._tx_counter)
            body = nonce + self._tx_aead.encrypt(nonce, body, None)
            flags |= FLAG_SECURE
        return _HEAD.pack(MAGIC, flags, 0, len(body)) + body

    def unwrap(self, body: bytes) -> bytes:
        if self.secure:
            if len(body) < NONCE_LEN + 16:
                raise OnWireError("short secure record")
            nonce, ct = body[:NONCE_LEN], body[NONCE_LEN:]
            (counter,) = struct.unpack("<Q", nonce[4:])
            if counter <= self._rx_counter:
                raise OnWireError("replayed or reordered secure record")
            try:
                body = self._rx_aead.decrypt(nonce, ct, None)
            except Exception as e:  # InvalidTag
                raise OnWireError(f"decrypt failed: {e}") from e
            self._rx_counter = counter
        if self.compress:
            try:
                # bounded inflate: a deflate bomb must not OOM the daemon
                d = zlib.decompressobj()
                body = d.decompress(body, MAX_FRAME)
                if d.unconsumed_tail:
                    raise OnWireError("decompressed frame exceeds MAX_FRAME")
            except zlib.error as e:
                raise OnWireError(f"decompress failed: {e}") from e
        return body


async def read_record(reader) -> bytes:
    """Read one on-wire record body from a StreamReader."""
    head = await reader.readexactly(_HEAD.size)
    magic, _flags, _pad, body_len = _HEAD.unpack(head)
    if magic != MAGIC:
        raise OnWireError(f"bad onwire magic {magic!r}")
    if body_len > 1 << 30:
        raise OnWireError(f"implausible record length {body_len}")
    return await reader.readexactly(body_len)
