"""The typed message catalog — mirror of src/messages/.

Reference: /root/reference/src/messages/ (170 versioned classes).  This
catalog implements the subset the framework's daemons exchange, with the
EC sub-op messages mirroring ECMsgTypes
(/root/reference/src/osd/ECMsgTypes.h): ECSubWrite carries a serialized
per-shard transaction (:23-89); ECSubRead carries per-object
(off,len,flags) plus per-shard subchunk vectors (:105-116);
ECSubReadReply returns buffers/attrs/errors (:118-129).
"""

from __future__ import annotations

from ..common.encoding import Decoder, Encodable, Encoder
from .message import Message, message_type, PRIO_HIGH


class Struct(Message):
    """A nested wire struct using the same FIELDS machinery as Message
    (WRITE_CLASS_ENCODER on plain types); never sent standalone."""


class PgId(Struct):
    """spg_t analog: pool + placement seed + shard (-1 = whole PG /
    replicated)."""

    FIELDS = [("pool", "u64"), ("ps", "u32"), ("shard", "i64")]

    def __init__(self, pool=0, ps=0, shard=-1):
        super().__init__(pool=pool, ps=ps, shard=shard)

    def key(self) -> tuple[int, int]:
        return (self.pool, self.ps)

    def with_shard(self, shard: int) -> "PgId":
        return PgId(self.pool, self.ps, shard)

    def __repr__(self):
        return f"{self.pool}.{self.ps}s{self.shard}"

    def __eq__(self, other):
        return (
            isinstance(other, PgId)
            and (self.pool, self.ps, self.shard)
            == (other.pool, other.ps, other.shard)
        )

    def __hash__(self):
        return hash((self.pool, self.ps, self.shard))


class OSDOp(Struct):
    """One client sub-operation (osd_types.h OSDOp / do_osd_ops codes)."""

    # op codes (CEPH_OSD_OP_* analog)
    READ = 1
    WRITE = 2
    WRITEFULL = 3
    DELETE = 4
    STAT = 5
    TRUNCATE = 6
    APPEND = 7
    GETXATTR = 8
    SETXATTR = 9
    PGLS = 10  # list objects in the PG (rados ls; PrimaryLogPG do_pgnls)
    ROLLBACK = 11     # roll head back to a snap's clone (off = snap id)
    LIST_SNAPS = 12   # dump the object's SnapSet
    WATCH = 13        # register/unregister a watch (off = cookie, len = 1/0)
    NOTIFY = 14       # notify watchers (data = payload, off = timeout ms)
    COPY_FROM = 15    # copy another object's content (name = src oid)
    CACHE_FLUSH = 16  # write a dirty cache-tier object back to the base pool
    CACHE_EVICT = 17  # drop a clean object from the cache tier
    CALL = 18         # object-class method (name = "cls.method", data = input)
    GETXATTRS = 19    # bulk-dump all client xattrs (copy-get attr leg)
    RMXATTR = 20      # remove one client xattr (CEPH_OSD_OP_RMXATTR)
    # omap (CEPH_OSD_OP_OMAP*): str->bytes KV attached to the object,
    # replicated pools only (the reference rejects omap on EC pools too)
    OMAPGETKEYS = 21  # -> encoded str list
    OMAPGETVALS = 22  # -> encoded kv map (whole omap)
    OMAPSETVALS = 23  # data = encoded kv map to merge
    OMAPRMKEYS = 24   # data = encoded str list
    OMAPCLEAR = 25
    CMPXATTR = 26     # guard: xattr vs data per `off` mode; -ECANCELED on miss
    LIST_WATCHERS = 27  # dump the object's watch table (rados listwatchers)
    ZERO = 28         # zero an extent (CEPH_OSD_OP_ZERO)
    WRITESAME = 29    # tile `data` across [off, off+len) (CEPH_OSD_OP_WRITESAME)

    FIELDS = [
        ("op", "u8"),
        ("off", "u64"),
        ("len", "u64"),
        ("data", "bytes"),
        ("name", "str"),  # xattr name for *XATTR ops
    ]

    def __init__(self, op=0, off=0, len=0, data=b"", name=""):
        super().__init__(op=op, off=off, len=len, data=data, name=name)


class ReqId(Struct):
    """osd_reqid_t: originating entity + client-unique tid."""

    FIELDS = [("client", "str"), ("tid", "u64")]

    def __init__(self, client="", tid=0):
        super().__init__(client=client, tid=tid)

    def key(self) -> tuple[str, int]:
        return (self.client, self.tid)


class PushOp(Struct):
    """Recovery push payload (osd_types.h PushOp, carried by MOSDPGPush)."""

    FIELDS = [
        ("oid", "str"),
        ("data", "bytes"),
        ("attrs", ("map", "str", "bytes")),
        ("version", "u64"),
        ("omap", ("map", "str", "bytes")),
    ]

    def __init__(self, oid="", data=b"", attrs=None, version=0, omap=None):
        super().__init__(
            oid=oid, data=data, attrs=attrs or {}, version=version,
            omap=omap or {},
        )


# --- liveness ----------------------------------------------------------------


@message_type(1)
class MPing(Message):
    FIELDS = [("stamp", "f64")]


@message_type(10)
class MOSDPing(Message):
    """OSD<->OSD heartbeat (src/messages/MOSDPing.h; handled at
    OSD.cc:5463 handle_osd_ping)."""

    PING = 1
    PING_REPLY = 2

    FIELDS = [("op", "u8"), ("stamp", "f64"), ("epoch", "u32"), ("from_osd", "u32")]
    priority = PRIO_HIGH


# --- client I/O --------------------------------------------------------------


@message_type(4)
class MOSDOp(Message):
    """Client op to the primary (src/messages/MOSDOp.h).

    Snapshot plumbing rides the op like the reference's: writes carry the
    client's SnapContext (`snap_seq` + descending `snaps`, the
    self-managed-snap model) so the PG can clone-on-first-write; reads
    carry `snap_id` (0 = head, CEPH_NOSNAP analog inverted for
    compactness) to address a snapshot's clone."""

    FIELDS = [
        ("reqid", ReqId),
        ("pgid", PgId),
        ("oid", "str"),
        ("ops", ("list", OSDOp)),
        ("epoch", "u32"),
        ("snap_seq", "u64"),
        ("snaps", ("list", "u64")),
        ("snap_id", "u64"),
    ]

    def __init__(
        self,
        reqid=None,
        pgid=None,
        oid="",
        ops=None,
        epoch=0,
        snap_seq=0,
        snaps=None,
        snap_id=0,
    ):
        super().__init__(
            reqid=reqid,
            pgid=pgid,
            oid=oid,
            ops=ops or [],
            epoch=epoch,
            snap_seq=snap_seq,
            snaps=snaps or [],
            snap_id=snap_id,
        )


@message_type(5)
class MOSDOpReply(Message):
    """src/messages/MOSDOpReply.h."""

    FIELDS = [
        ("reqid", ReqId),
        ("result", "i64"),
        ("outdata", ("list", "bytes")),  # per-op output
        ("version", "u64"),
        ("epoch", "u32"),
    ]


# --- EC sub-ops (ECMsgTypes.h) ----------------------------------------------


@message_type(6)
class MOSDECSubOpWrite(Message):
    """Primary -> shard write (MOSDECSubOpWrite.h; ECSubWrite at
    ECMsgTypes.h:23-89).  `txn` is the encoded per-shard ObjectStore
    transaction; log_entries roll the PG log forward on the shard."""

    FIELDS = [
        ("pgid", PgId),
        ("from_osd", "u32"),
        ("tid", "u64"),
        ("reqid", ReqId),
        ("txn", "bytes"),
        ("at_version", "u64"),
        ("log_entries", ("list", "bytes")),
    ]
    priority = PRIO_HIGH


@message_type(7)
class MOSDECSubOpWriteReply(Message):
    FIELDS = [
        ("pgid", PgId),
        ("from_osd", "u32"),
        ("tid", "u64"),
        ("committed", "bool"),
    ]
    priority = PRIO_HIGH


@message_type(8)
class MOSDECSubOpRead(Message):
    """Primary -> shard read (ECSubRead, ECMsgTypes.h:105-116):
    per-object extent lists plus CLAY subchunk (offset,count) runs."""

    FIELDS = [
        ("pgid", PgId),
        ("from_osd", "u32"),
        ("tid", "u64"),
        # oid -> list of (off, len) extents
        ("to_read", ("map", "str", ("list", ("list", "u64")))),
        # oid -> subchunk (offset, count) runs within each chunk
        ("subchunks", ("map", "str", ("list", ("list", "u64")))),
        ("attrs_to_read", ("list", "str")),
    ]
    priority = PRIO_HIGH


@message_type(9)
class MOSDECSubOpReadReply(Message):
    """ECSubReadReply (ECMsgTypes.h:118-129): buffers + attrs + errors."""

    FIELDS = [
        ("pgid", PgId),
        ("from_osd", "u32"),
        ("tid", "u64"),
        # oid -> list of (off, data) returned extents
        ("buffers", ("map", "str", ("list", ("list", "bytes")))),
        ("attrs", ("map", "str", ("map", "str", "bytes"))),
        ("errors", ("map", "str", "i64")),
    ]
    priority = PRIO_HIGH


# --- cluster membership ------------------------------------------------------


@message_type(11)
class MOSDBoot(Message):
    """OSD -> mon boot announcement (src/messages/MOSDBoot.h)."""

    FIELDS = [("osd", "u32"), ("addr", "str"), ("epoch", "u32")]


@message_type(12)
class MOSDFailure(Message):
    """OSD -> mon failure report (src/messages/MOSDFailure.h; quorum
    checked at OSDMonitor.cc:2791 prepare_failure)."""

    FIELDS = [
        ("target", "u32"),
        ("target_addr", "str"),
        ("failed_for", "f64"),
        ("epoch", "u32"),
        # ISSUE 17: 0 = dead (unresponsive, the classic report); 1 =
        # laggy (heartbeats answered but slow — the gray-failure state:
        # mon surfaces OSD_SLOW_PEER, never marks down); 2 = laggy
        # cleared (the reporter's peer recovered)
        ("laggy", "u8"),
    ]

    def __init__(self, target=0, target_addr="", failed_for=0.0,
                 epoch=0, laggy=0, **kw):
        super().__init__(
            target=target, target_addr=target_addr,
            failed_for=failed_for, epoch=epoch, laggy=laggy, **kw,
        )


@message_type(13)
class MOSDMap(Message):
    """Map publication (src/messages/MOSDMap.h): full maps and/or
    incrementals keyed by epoch."""

    FIELDS = [
        ("fsid", "str"),
        ("maps", ("map", "u32", "bytes")),
        ("incrementals", ("map", "u32", "bytes")),
    ]


# --- mon ---------------------------------------------------------------------


@message_type(14)
class MMonCommand(Message):
    """CLI/admin command (src/messages/MMonCommand.h); cmd is the JSON
    command blob like the reference's cmdmap."""

    FIELDS = [("tid", "u64"), ("cmd", "str")]


@message_type(15)
class MMonCommandAck(Message):
    FIELDS = [("tid", "u64"), ("retval", "i64"), ("rs", "str"), ("outbl", "bytes")]


@message_type(16)
class MMonSubscribe(Message):
    """Subscriptions (src/messages/MMonSubscribe.h): what -> start epoch;
    the mon pushes updates (osdmap) as they commit."""

    FIELDS = [("what", ("map", "str", "u32"))]


@message_type(17)
class MMonPaxos(Message):
    """Paxos protocol (src/messages/MMonPaxos.h)."""

    OP_COLLECT = 1
    OP_LAST = 2
    OP_BEGIN = 3
    OP_ACCEPT = 4
    OP_COMMIT = 5
    OP_LEASE = 6

    FIELDS = [
        ("op", "u8"),
        ("pn", "u64"),
        ("last_committed", "u64"),
        ("values", ("map", "u64", "bytes")),
        # pn under which an accepted-but-uncommitted value (at slot
        # last_committed+1 in `values`) was accepted; 0 = none
        ("uncommitted_pn", "u64"),
    ]
    priority = PRIO_HIGH

    def __init__(self, uncommitted_pn: int = 0, **kwargs):
        super().__init__(uncommitted_pn=uncommitted_pn, **kwargs)


@message_type(18)
class MMonElection(Message):
    """Mon elections (src/messages/MMonElection.h / ElectionLogic)."""

    OP_PROPOSE = 1
    OP_ACK = 2
    OP_VICTORY = 3

    # `quorum` rides OP_VICTORY so every member (peons included) learns
    # the full quorum set, as the reference's victory message does.
    FIELDS = [("op", "u8"), ("epoch", "u64"), ("rank", "u32"),
              ("quorum", ("list", "u32"))]
    priority = PRIO_HIGH

    def __init__(self, op=0, epoch=0, rank=0, quorum=None):
        super().__init__(op=op, epoch=epoch, rank=rank, quorum=quorum or [])


# --- peering / recovery ------------------------------------------------------


@message_type(19)
class MOSDPGQuery(Message):
    """Primary asks a shard for its pg_info or log tail
    (src/messages/MOSDPGQuery.h; pg_query_t INFO/LOG types in
    osd_types.h)."""

    INFO = 1
    LOG = 2

    FIELDS = [
        ("pgid", PgId),
        ("op", "u8"),
        ("epoch", "u32"),
        ("from_osd", "u32"),
        # LOG queries: send entries after (since_epoch, since_ver)
        ("since_epoch", "u32"),
        ("since_ver", "u64"),
    ]


@message_type(20)
class MOSDPGNotify(Message):
    """Shard replies with pg_info (src/messages/MOSDPGNotify.h)."""

    FIELDS = [("pgid", PgId), ("info", "bytes"), ("epoch", "u32"), ("from_osd", "u32")]


@message_type(21)
class MOSDPGLog(Message):
    FIELDS = [
        ("pgid", PgId),
        ("info", "bytes"),
        ("log", "bytes"),
        ("epoch", "u32"),
        ("from_osd", "u32"),
        # the version the delta starts after — lets the receiver detect
        # local entries in (since, head] absent from the delta as divergent
        ("since_epoch", "u32"),
        ("since_ver", "u64"),
    ]


@message_type(22)
class MOSDPGPush(Message):
    """Recovery pushes (src/messages/MOSDPGPush.h → §3.2 WRITING)."""

    FIELDS = [
        ("pgid", PgId),
        ("pushes", ("list", PushOp)),
        ("epoch", "u32"),
        ("from_osd", "u32"),
    ]


@message_type(23)
class MOSDPGPushReply(Message):
    FIELDS = [
        ("pgid", PgId),
        ("oids", ("list", "str")),
        ("epoch", "u32"),
        ("from_osd", "u32"),
    ]


@message_type(24)
class MOSDRepOp(Message):
    """Primary -> replica transaction for replicated pools
    (src/messages/MOSDRepOp.h; fanned out by
    ReplicatedBackend::submit_transaction)."""

    FIELDS = [
        ("pgid", PgId),
        ("from_osd", "u32"),
        ("tid", "u64"),
        ("reqid", ReqId),
        ("txn", "bytes"),
        ("log_entries", ("list", "bytes")),
    ]
    priority = PRIO_HIGH


@message_type(25)
class MOSDRepOpReply(Message):
    FIELDS = [("pgid", PgId), ("from_osd", "u32"), ("tid", "u64")]
    priority = PRIO_HIGH


@message_type(26)
class MOSDPGPull(Message):
    """Primary asks a replica to push an object it is itself missing
    (src/messages/MOSDPGPull.h)."""

    FIELDS = [("pgid", PgId), ("oid", "str"), ("epoch", "u32"), ("from_osd", "u32")]


# --- scrub -------------------------------------------------------------------


@message_type(27)
class MOSDRepScrub(Message):
    """Primary asks a shard for its scrub map over an object chunk
    (src/messages/MOSDRepScrub.h; chunky scrub in
    src/osd/scrubber/pg_scrubber.cc)."""

    FIELDS = [
        ("pgid", PgId),
        ("epoch", "u32"),
        ("from_osd", "u32"),
        ("deep", "bool"),
        ("scrub_tid", "u64"),
        # chunk boundaries: scrub objects with start <= name < end
        # ("" end = unbounded)
        ("chunk_start", "str"),
        ("chunk_end", "str"),
    ]


@message_type(28)
class MOSDRepScrubMap(Message):
    """Shard's scrub map reply (src/messages/MOSDRepScrubMap.h);
    `scrub_map` is a JSON blob of oid -> {size, digest, ...}."""

    FIELDS = [
        ("pgid", PgId),
        ("epoch", "u32"),
        ("from_osd", "u32"),
        ("scrub_tid", "u64"),
        ("scrub_map", "bytes"),
    ]


# --- mgr ---------------------------------------------------------------------


@message_type(29)
class MMgrBeacon(Message):
    """Mgr -> mon availability beacon (src/messages/MMgrBeacon.h);
    drives MgrMonitor's active/standby election."""

    FIELDS = [("name", "str"), ("addr", "str")]


@message_type(30)
class MMgrMap(Message):
    """Mon -> subscribers: who the active mgr is
    (src/messages/MMgrMap.h / MgrMap)."""

    FIELDS = [
        ("epoch", "u32"),
        ("active_name", "str"),
        ("active_addr", "str"),
        ("standbys", ("list", "str")),
    ]


@message_type(31)
class MMgrReport(Message):
    """Daemon -> mgr perf/status report (src/messages/MMgrReport.h;
    consumed by DaemonServer).  perf/status are JSON blobs."""

    FIELDS = [("daemon", "str"), ("perf", "bytes"), ("status", "bytes")]


# --- config / log / auth services --------------------------------------------


@message_type(32)
class MConfig(Message):
    """Mon -> subscriber: centrally-managed config relevant to that entity
    (src/messages/MConfig.h; built by ConfigMonitor::check_sub from the
    global < type-section < entity layering).  `changes` is a JSON object
    {option: raw value}."""

    FIELDS = [("version", "u32"), ("changes", "bytes")]


@message_type(33)
class MLog(Message):
    """Cluster-log entries (src/messages/MLog.h), both directions: daemons
    send new entries to the mons (LogClient -> LogMonitor), the mons push
    committed entries to "log" subscribers.  `entries` is a JSON list of
    {"prio", "who", "stamp", "msg"}; `version` is the committed log version
    (0 on the daemon->mon leg)."""

    FIELDS = [("version", "u64"), ("entries", "bytes")]


@message_type(34)
class MBackfillReserve(Message):
    """Backfill reservation protocol (src/messages/MBackfillReserve.h):
    the primary reserves a remote slot on each backfill target before
    scanning (AsyncReserver handshake), releasing it on completion or
    interval change."""

    REQUEST, GRANT, REJECT, RELEASE = 0, 1, 2, 3

    FIELDS = [
        ("pgid", PgId),
        ("op", "u8"),
        ("epoch", "u32"),
        ("from_osd", "u32"),
    ]


@message_type(35)
class MWatchNotify(Message):
    """Watch/notify push + ack (src/messages/MWatchNotify.h): the primary
    pushes a notify to every watcher's session; watchers ack with the same
    type (`is_ack`=1) carrying their optional reply payload."""

    FIELDS = [
        ("oid", "str"),
        ("pgid", PgId),
        ("notify_id", "u64"),
        ("cookie", "u64"),
        ("payload", "bytes"),
        ("is_ack", "u8"),
        ("watcher", "str"),  # acking entity name
    ]


# --- MDS / CephFS ------------------------------------------------------------


@message_type(36)
class MClientRequest(Message):
    """Client -> MDS metadata op (src/messages/MClientRequest.h).  `op` is
    the request name (mkdir, create, lookup, readdir, unlink, rmdir,
    rename, setattr, open, release); `args` is a JSON blob — the dynamic
    shape of the reference's filepath+args union.  `client` (v2) is the
    sender's per-instance identity: with a STABLE tid across retries it
    forms the (client, tid) reqid the MDS's completed-request table
    dedups on, so a retried non-idempotent op (mkdir/create/unlink/
    rename) replays its recorded reply instead of re-executing ('' = a
    v1 sender; no dedup)."""

    VERSION = 2
    COMPAT = 1
    FIELDS = [
        ("tid", "u64"), ("op", "str"), ("args", "bytes"), ("client", "str")
    ]

    @classmethod
    def decode(cls, dec):
        # struct_v-gated tail (encoding.h WRITE_CLASS_ENCODER shape): a
        # v1 frame simply lacks `client` and decodes as a no-dedup
        # sender, instead of overrunning the versioned frame
        struct_v = dec.start(cls.VERSION)
        msg = cls.__new__(cls)
        msg.src = ""
        msg.seq = 0
        msg.tid = dec.u64()
        msg.op = dec.string()
        msg.args = dec.bytes_()
        msg.client = dec.string() if struct_v >= 2 else ""
        dec.finish()
        return msg


@message_type(37)
class MClientReply(Message):
    """MDS -> client reply (src/messages/MClientReply.h): result errno +
    JSON payload (inode records, dentry lists, cap grants)."""

    FIELDS = [("tid", "u64"), ("result", "i64"), ("payload", "bytes")]


@message_type(38)
class MClientCaps(Message):
    """Capability traffic both ways (src/messages/MClientCaps.h): the MDS
    REVOKEs caps it granted; clients ACK revokes and RELEASE caps they
    drop.  `caps` is the wanted/held mask ("r", "w", "rw")."""

    REVOKE, ACK, RELEASE = 0, 1, 2

    FIELDS = [("op", "u8"), ("ino", "u64"), ("caps", "str"), ("tid", "u64")]


@message_type(39)
class MMDSBeacon(Message):
    """MDS -> mon availability beacon (src/messages/MMDSBeacon.h): drives
    MDSMonitor's rank assignment and failover.  `state` is the daemon's
    self-reported state (boot / standby / active).  `client` is the
    daemon's RADOS client instance id (objecter reqid name, '' when the
    daemon runs embedded without one): what the MDSMonitor blocklists
    through the OSDMonitor when it fails this daemon over — the
    reference's MDSMonitor::fail_mds_gid blocklisting the gid's addrs."""

    FIELDS = [
        ("name", "str"), ("addr", "str"), ("state", "str"), ("client", "str")
    ]


@message_type(41)
class MMonMgrReport(Message):
    """Active mgr -> mons: the PGMap digest (src/messages/
    MMonMgrReport.h).  `digest` is a JSON pool-stats summary the mon
    serves through `ceph df` / health; volatile (re-sent each beacon
    interval), not paxos state — the freshest report wins."""

    FIELDS = [("digest", "bytes")]


@message_type(40)
class MMDSMap(Message):
    """Mon -> subscribers: the FSMap (src/messages/MMDSMap.h + FSMap):
    per-filesystem rank-0 holders plus the shared standby pool, as a
    JSON envelope {"filesystems": {name: {meta_pool, data_pool,
    active_name, active_addr}}, "standbys": {daemon: addr}}.  Clients
    resolve their filesystem's active MDS from this; standby daemons
    learn here which filesystem they were promoted to."""

    FIELDS = [("epoch", "u32"), ("fsmap", "bytes")]

    def filesystems(self) -> dict:
        import json as _json

        return _json.loads(self.fsmap.decode() or "{}").get("filesystems", {})
