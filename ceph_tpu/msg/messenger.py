"""AsyncMessenger — mirror of src/msg/async/AsyncMessenger.{h,cc}.

Reference behaviors mirrored (SURVEY.md §2.5):
- `Messenger::create` + bind/listen/accept with a banner + identity
  exchange (ProtocolV2 hello phase).
- Dispatcher chain with a fast-dispatch path (`ms_fast_dispatch`
  bypasses the queue, src/osd/OSD.cc:7244) and `ms_handle_reset`
  connection-fault callbacks.
- Per-peer-type Policy (lossy vs lossless: lossless connections
  transparently reconnect and re-send queued messages).
- Dispatch throttling (`ms_dispatch_throttle_bytes`) and probabilistic
  fault injection (`ms_inject_socket_failures`,
  global.yaml.in:1240-1271).

Implementation is asyncio on TCP — the event-loop structure of the
reference's epoll workers, minus the manual buffer management that Python
streams already provide.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass

from ..common import tracer as tracer_mod
from ..common.fault_injector import (
    InjectedFailure,
    faultpoint,
    faultpoint_delay,
)
from ..common.log import dout
from ..common.throttle import AsyncThrottle
from .crypto import (
    FLAG_COMPRESSED,
    FLAG_SECURE,
    OnWireError,
    OnWireSession,
    read_record,
)
from .frames import (
    Frame,
    TAG_HELLO,
    TAG_KEEPALIVE,
    TAG_MESSAGE,
    frame_from_bytes,
    read_frame,
    FrameError,
)
from .message import Message, decode_message, encode_message


@dataclass
class Policy:
    """Per-peer-type connection policy (src/msg/Policy.h)."""

    lossy: bool = True  # drop state on error (client->osd)
    server: bool = False  # accept-only side
    resend_on_reconnect: bool = False  # lossless peers re-queue unacked sends

    @classmethod
    def lossy_client(cls) -> "Policy":
        return cls(lossy=True)

    @classmethod
    def lossless_peer(cls) -> "Policy":
        return cls(lossy=False, resend_on_reconnect=True)

    @classmethod
    def stateless_server(cls) -> "Policy":
        return cls(lossy=True, server=True)


class Dispatcher:
    """Receiver interface (src/msg/Dispatcher.h)."""

    def ms_can_fast_dispatch(self, msg: Message) -> bool:
        return False

    def ms_fast_dispatch(self, conn: "Connection", msg: Message) -> None:
        raise NotImplementedError

    def ms_dispatch(self, conn: "Connection", msg: Message) -> bool:
        """Return True if handled."""
        return False

    def ms_handle_reset(self, conn: "Connection") -> None:
        pass

    def ms_handle_accept(self, conn: "Connection") -> None:
        pass


# Lossless resend bounds: ~4 s of backoff across 12 attempts covers any
# transient fault, and the wall-clock window caps the worst case — a
# zombie peer whose TCP accepts succeed but whose handshakes burn their
# full timeouts per attempt — so a permanently dead peer surfaces
# ConnectionError instead of pinning the connection's send lock.
_RESEND_TRIES = 12
_RESEND_WINDOW = 15.0  # seconds


class Connection:
    """One peer session (AsyncConnection).  Owns the socket streams, a
    send queue, and (for lossless policies) the unacked resend queue."""

    def __init__(self, msgr: "Messenger", peer_addr: str, policy: Policy):
        self.msgr = msgr
        self.peer_addr = peer_addr
        self.peer_name = ""  # filled by hello exchange
        self.policy = policy
        self.auth_entity = ""  # authenticated peer (cephx server side)
        # negotiated secure/compressed on-wire codec (crypto_onwire);
        # None = legacy raw frames
        self._onwire: OnWireSession | None = None
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        from ..common.lockdep import make_async_lock

        self._send_lock = make_async_lock(f"conn_send:{msgr.name}")
        self._out_seq = 0
        self._closed = False
        self._read_task: asyncio.Task | None = None
        # set when a lossless resend window gave the peer up: sends
        # before this instant fail fast instead of each serially burning
        # a fresh full retry window under the send lock
        self._dead_until = 0.0

    @property
    def connected(self) -> bool:
        return self._writer is not None and not self._closed

    # -- lifecycle -----------------------------------------------------------

    async def _attach(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._reader = reader
        self._writer = writer
        # the reader is BOUND at attach time: a fault can null
        # self._reader before the task's first step runs, and the task
        # must then exit, not read from None
        self._read_task = asyncio.create_task(self._read_loop(reader))

    async def _connect(self) -> None:
        reader, writer = await self.msgr.stack.connect(self.peer_addr)
        # hello: announce who we are + desired on-wire features
        # (ProtocolV2 hello/ident phase; features negotiate like
        # ProtocolV2's connection modes)
        hello = Frame(
            TAG_HELLO,
            [
                self.msgr.name.encode(),
                self.msgr.addr.encode(),
                bytes([self.msgr._feature_bits()]),
            ],
        )
        writer.write(hello.pack(self.msgr.crc_data))
        await writer.drain()
        try:
            # bounded like the accept side: a peer that accepted the
            # connection but died before replying must not wedge this
            # connection (send_message holds the send lock meanwhile)
            frame = await asyncio.wait_for(read_frame(reader), 10.0)
            if frame.tag != TAG_HELLO:
                raise FrameError(f"expected hello, got tag {frame.tag}")
            self.peer_name = frame.segments[0].decode()
            chosen = (
                frame.segments[2][0]
                if len(frame.segments) > 2 and frame.segments[2]
                else 0
            ) & self.msgr._feature_bits()
            if self.msgr.secure and not chosen & FLAG_SECURE:
                # we REQUIRE encryption (ms_mode=secure); a peer that
                # cannot do it must not get a cleartext session
                raise FrameError("peer does not support required secure mode")
            session_key = b""
            if self.msgr.auth is not None:
                # cephx handshake rides auth frames before the session
                # opens (ProtocolV2 auth phase).  Bounded: an auth-less
                # peer silently ignores auth frames, and an unbounded wait
                # here would wedge the connection's send lock forever.
                _ticket, session_key = await asyncio.wait_for(
                    self.msgr.auth.client_auth(
                        *_frame_io(reader, writer, self.msgr.crc_data),
                        peer=self.peer_addr,
                    ),
                    timeout=5.0,
                )
            # always (re)assign: a lossless reconnect may renegotiate a
            # DIFFERENT feature set than the previous session
            self._onwire = (
                OnWireSession(
                    session_key,
                    secure=bool(chosen & FLAG_SECURE),
                    compress=bool(chosen & FLAG_COMPRESSED),
                    initiator=True,
                )
                if chosen
                else None
            )
        except Exception as e:
            # close the half-open socket and keep send_message's contract:
            # connection failures surface as ConnectionError
            writer.close()
            raise ConnectionError(
                f"handshake with {self.peer_addr} failed: {e}"
            ) from e
        await self._attach(reader, writer)

    async def close(self) -> None:
        self._closed = True
        if self._read_task is not None:
            self._read_task.cancel()
            self._read_task = None
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        self._reader = None

    def _fault(self) -> None:
        """Connection error (AsyncConnection::fault): lossy connections
        reset; lossless ones reconnect lazily on next send."""
        writer, self._writer = self._writer, None
        self._reader = None
        if writer is not None:
            writer.close()
        if self.policy.lossy:
            self._closed = True
            self.msgr._drop_connection(self)
        self.msgr._notify_reset(self)

    # -- send ----------------------------------------------------------------

    async def send_message(self, msg: Message) -> None:
        """Queue-and-send (AsyncConnection::send_message).  Raises on
        lossy connections that are closed; lossless ones transparently
        reconnect and RESEND the faulted message (Policy.resend_on_
        reconnect — the reference requeues unacked messages on the new
        session), bounded by _RESEND_TRIES so a PERMANENTLY dead peer
        surfaces ConnectionError to the caller's own recovery (objecter
        resend, OSD peering) instead of wedging the send lock forever.

        Duplication: the injection checks (`msgr.send` faultpoint + the
        legacy ms_inject_socket_failures knob) run BEFORE any bytes hit
        the wire, so INJECTED faults can never duplicate a delivered
        frame.  A real socket error after a full write but before drain
        returns can resend a frame the peer already processed —
        at-least-once, like any ack-less retransmit (the reference
        closes the gap with session seq replay, which needs the ack
        machinery this model doesn't carry)."""
        async with self._send_lock:
            if self._closed:
                raise ConnectionError(f"connection to {self.peer_addr} closed")
            if asyncio.get_event_loop().time() < self._dead_until:
                raise ConnectionError(
                    f"peer {self.peer_addr} recently unreachable"
                )
            self._out_seq += 1
            msg.src = self.msgr.name
            msg.seq = self._out_seq
            env, payload = encode_message(msg)
            frame = Frame(TAG_MESSAGE, [env, payload])
            attempt = 0
            give_up_at = asyncio.get_event_loop().time() + _RESEND_WINDOW
            while True:
                if self._closed:  # closed underneath a resend backoff
                    raise ConnectionError(
                        f"connection to {self.peer_addr} closed"
                    )
                if self._writer is None and self.policy.server:
                    # accept-side connections cannot re-dial the peer:
                    # not retryable, surface immediately
                    raise ConnectionError(f"not connected to {self.peer_addr}")
                try:
                    if self._writer is None:
                        # Lazy connect (first send), and lazy REconnect for
                        # lossless policies; faulted lossy connections were
                        # marked closed in _fault() and never reach here.
                        await self._connect()
                    faultpoint("msgr.send")
                    self.msgr._maybe_inject_fault()
                    delay = faultpoint_delay("msgr.send", who=self.msgr.name)
                    if delay > 0:
                        # latency injection (ISSUE 17): a slow NIC, not a
                        # dead one — the frame still goes out, late.  The
                        # sleep holds only THIS connection's send lock
                        await asyncio.sleep(delay)
                    raw = frame.pack(self.msgr.crc_data)
                    if self._onwire is not None:
                        raw = self._onwire.wrap(raw)
                    self._writer.write(raw)
                    await self._writer.drain()
                    return
                except (ConnectionError, OSError, InjectedFailure):
                    self._fault()
                    if self.policy.lossy or not self.policy.resend_on_reconnect:
                        raise ConnectionError(
                            f"send to {self.peer_addr} failed"
                        )
                    if self._closed:
                        raise ConnectionError(
                            f"connection to {self.peer_addr} closed"
                        )
                    attempt += 1
                    # bounded by attempts AND wall clock: a zombie peer
                    # whose accepts succeed but handshakes stall would
                    # otherwise stretch 12 attempts into minutes of
                    # handshake timeouts while holding the send lock
                    if (
                        attempt > _RESEND_TRIES
                        or asyncio.get_event_loop().time() > give_up_at
                    ):
                        # peer looks permanently gone: give the message
                        # back to the caller's recovery loop, and fail
                        # queued senders fast for another window instead
                        # of each serially re-burning a full one
                        self._dead_until = (
                            asyncio.get_event_loop().time() + _RESEND_WINDOW
                        )
                        raise ConnectionError(
                            f"send to {self.peer_addr} failed after "
                            f"{attempt} resend attempts"
                        )
                    # lossless: back off briefly and resend the SAME frame
                    # (same seq) over a fresh session
                    self.msgr.resends += 1
                    await asyncio.sleep(min(0.5, 0.01 * (1 << min(attempt, 6))))

    # -- receive -------------------------------------------------------------

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        # bound to the reader it was attached with: a lossless reconnect
        # attaches a NEW loop, and this (stale) one must neither read the
        # fresh stream nor fault the fresh session when its dead socket
        # finally errors out
        try:
            while not self._closed and self._reader is reader:
                if self._onwire is not None:
                    body = await read_record(reader)
                    frame = frame_from_bytes(self._onwire.unwrap(body))
                else:
                    frame = await read_frame(reader)
                faultpoint("msgr.recv")
                self.msgr._maybe_inject_fault()
                if frame.tag == TAG_KEEPALIVE:
                    continue
                if frame.tag != TAG_MESSAGE:
                    continue
                msg = decode_message(frame.segments[0], frame.segments[1])
                await self.msgr._deliver(self, msg)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
            FrameError,
            OnWireError,
            InjectedFailure,
            asyncio.CancelledError,
        ):
            if not self._closed and self._reader is reader:
                self._fault()


def _frame_io(reader, writer, crc_data: bool):
    """(send_frame, recv_frame) pair for the auth handshake — raw tagged
    frames on the not-yet-attached stream."""

    async def send_frame(tag: int, segments: list[bytes]) -> None:
        writer.write(Frame(tag, segments).pack(crc_data))
        await writer.drain()

    async def recv_frame() -> tuple[int, list[bytes]]:
        frame = await read_frame(reader)
        return frame.tag, frame.segments

    return send_frame, recv_frame


class Messenger:
    """The endpoint: bind/listen + outgoing connection cache
    (AsyncMessenger).  One per daemon role, as in ceph_osd.cc:548-561
    (the reference creates 7; here cluster+client traffic share one)."""

    def __init__(
        self,
        name: str,
        addr: str = "",
        crc_data: bool = True,
        inject_socket_failures: int = 0,
        inject_internal_delays: float = 0.0,
        dispatch_throttle_bytes: int = 0,
        auth=None,  # CephxAuth (src/auth/cephx); None = auth_none
        secure: bool = False,  # AES-GCM sessions (ms_mode=secure)
        compress: bool = False,  # on-wire frame compression
        stack: str = "posix",  # ms_type: posix | inproc (msg/stack.py)
    ):
        from .stack import make_stack

        self.name = name  # entity name, e.g. "osd.0"
        self.addr = addr  # host:port once bound (or for identification)
        self.stack = make_stack(stack)
        self.crc_data = crc_data
        if secure and auth is None:
            raise ValueError(
                "ms_secure requires cephx auth (the session key comes from "
                "the handshake, crypto_onwire.cc)"
            )
        self.secure = secure
        self.compress = compress
        self.inject_socket_failures = inject_socket_failures
        # ms_inject_internal_delays (global.yaml.in:1271): seconds of
        # injected sleep before local delivery, runtime-mutable
        self.inject_internal_delays = float(inject_internal_delays)
        self.resends = 0  # lossless transparent resends (fault recovery)
        self._rng = random.Random(hash(name) & 0xFFFF)
        self.dispatchers: list[Dispatcher] = []
        self._conns: dict[str, Connection] = {}  # peer_addr -> conn
        self._server: asyncio.AbstractServer | None = None
        self._throttle = (
            AsyncThrottle("msgr.dispatch", dispatch_throttle_bytes)
            if dispatch_throttle_bytes
            else None
        )
        self.default_policy = Policy.lossy_client()
        self._accepted: list[Connection] = []
        self.auth = auth
        # daemon-attached Tracer (common/tracer.py): when set and enabled,
        # delivery of a trace-carrying message records a messenger span
        # parent-linked to the sender's (the blkin "async messenger" hop)
        self.tracer = None

    # -- setup ---------------------------------------------------------------

    def add_dispatcher_head(self, d: Dispatcher) -> None:
        self.dispatchers.insert(0, d)

    def add_dispatcher_tail(self, d: Dispatcher) -> None:
        self.dispatchers.append(d)

    async def bind(self, addr: str) -> None:
        self._server, self.addr = await self.stack.listen(addr, self._accept)

    async def shutdown(self) -> None:
        # Close live connections before the listener: Python 3.12's
        # Server.wait_closed() blocks until every handler's transport is
        # finished, so open accepted connections would deadlock it.
        for conn in list(self._conns.values()) + self._accepted:
            await conn.close()
        self._conns.clear()
        self._accepted.clear()
        if self._server is not None:
            self._server.close()
            try:
                # belt-and-braces bound: accept handlers are themselves
                # time-bounded now, but a shutdown must never hang on a
                # straggler — abandoning it is benign once close() has
                # stopped new accepts
                await asyncio.wait_for(self._server.wait_closed(), 15.0)
            except asyncio.TimeoutError:
                dout("msgr", 1, f"{self.name}: listener straggler at shutdown")
            self._server = None
        # Let cancelled read-loop tasks and closed transports unwind.
        await asyncio.sleep(0)

    # -- connections ---------------------------------------------------------

    def get_connection(self, peer_addr: str, policy: Policy | None = None) -> Connection:
        """Get-or-create an outgoing connection (connect lazily on first
        send) — AsyncMessenger::get_connection."""
        conn = self._conns.get(peer_addr)
        if conn is None or conn._closed:
            conn = Connection(self, peer_addr, policy or self.default_policy)
            self._conns[peer_addr] = conn
        return conn

    async def send_to(self, peer_addr: str, msg: Message) -> None:
        await self.get_connection(peer_addr).send_message(msg)

    def _drop_connection(self, conn: Connection) -> None:
        existing = self._conns.get(conn.peer_addr)
        if existing is conn:
            del self._conns[conn.peer_addr]

    def _feature_bits(self) -> int:
        return (FLAG_SECURE if self.secure else 0) | (
            FLAG_COMPRESSED if self.compress else 0
        )

    async def _accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            # Bounded hello: a peer that connects and never speaks (e.g. a
            # daemon dying mid-teardown) must not pin this handler open —
            # Python 3.12's Server.wait_closed() waits on every handler,
            # so an unbounded await here deadlocks messenger shutdown.
            try:
                frame = await asyncio.wait_for(read_frame(reader), 10.0)
            except (asyncio.TimeoutError, asyncio.IncompleteReadError):
                writer.close()
                return
            if frame.tag != TAG_HELLO:
                writer.close()
                return
            conn = Connection(self, frame.segments[1].decode(), Policy.stateless_server())
            conn.peer_name = frame.segments[0].decode()
            peer_feat = (
                frame.segments[2][0]
                if len(frame.segments) > 2 and frame.segments[2]
                else 0
            )
            chosen = peer_feat & self._feature_bits()
            if self.secure and not chosen & FLAG_SECURE:
                # encryption is required on this endpoint; no cleartext
                # fallback for a non-secure peer
                writer.close()
                return
            reply = Frame(
                TAG_HELLO,
                [self.name.encode(), self.addr.encode(), bytes([chosen])],
            )
            writer.write(reply.pack(self.crc_data))
            await writer.drain()
            session_key = b""
            if self.auth is not None:
                try:
                    # Bounded like the client side: a stalled peer must not
                    # pin this accept task (and its socket) forever.
                    conn.auth_entity, session_key = await asyncio.wait_for(
                        self.auth.server_auth(
                            *_frame_io(reader, writer, self.crc_data)
                        ),
                        timeout=5.0,
                    )
                except Exception as e:  # AuthError, timeout, noise
                    # a rejected accept must be visible: silent drops
                    # look like a network blackhole to the operator
                    dout("ms", 1,
                         f"{self.name}: accept auth failed: {e!r}")
                    writer.close()
                    return
            if chosen:
                conn._onwire = OnWireSession(
                    session_key,
                    secure=bool(chosen & FLAG_SECURE),
                    compress=bool(chosen & FLAG_COMPRESSED),
                    initiator=False,
                )
            await conn._attach(reader, writer)
            self._accepted.append(conn)
            for d in self.dispatchers:
                d.ms_handle_accept(conn)
        except (
            FrameError,
            OSError,
            asyncio.IncompleteReadError,
            # malformed hellos (missing segments, non-UTF-8 names) must
            # close the socket, not kill the accept task
            IndexError,
            UnicodeDecodeError,
            ValueError,
        ):
            writer.close()

    # -- delivery ------------------------------------------------------------

    async def _deliver(self, conn: Connection, msg: Message) -> None:
        if self.inject_internal_delays > 0:
            await asyncio.sleep(self.inject_internal_delays)
        size = 64  # envelope floor; payload length dominates below
        if self._throttle is not None:
            await self._throttle.get(size)
        span = None
        if self.tracer is not None and self.tracer.enabled:
            ctx = tracer_mod.extract(msg)
            if ctx is not None:
                span = self.tracer.start_span(
                    f"msgr:{type(msg).__name__}", remote=ctx
                )
                span.keyval("src", msg.src)
        try:
            with tracer_mod.span_scope(span):
                for d in self.dispatchers:
                    if d.ms_can_fast_dispatch(msg):
                        d.ms_fast_dispatch(conn, msg)
                        return
                for d in self.dispatchers:
                    handled = d.ms_dispatch(conn, msg)
                    if asyncio.iscoroutine(handled):
                        handled = await handled
                    if handled:
                        return
            dout("ms", 0, f"{self.name}: unhandled message {msg!r} from {msg.src}")
        finally:
            if span is not None:
                span.finish()
            if self._throttle is not None:
                await self._throttle.put(size)

    def _notify_reset(self, conn: Connection) -> None:
        for d in self.dispatchers:
            d.ms_handle_reset(conn)

    def _maybe_inject_fault(self) -> None:
        if self.inject_socket_failures > 0:
            if self._rng.randrange(self.inject_socket_failures) == 0:
                raise ConnectionError("injected socket failure")
