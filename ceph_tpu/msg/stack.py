"""Pluggable network stacks — mirror of the reference's NetworkStack
family (src/msg/async/Stack.h; PosixStack.h, rdma/, dpdk/ selected by
`ms_type`).

The messenger talks to a `NetworkStack` for exactly two things: dial a
peer and listen for peers.  Two stacks ship:

- `posix` — asyncio TCP, the default (PosixStack analog).
- `inproc` — zero-copy in-process pipes between messengers sharing an
  interpreter.  This is the kernel-bypass member of the family: where
  the reference's dpdk/rdma stacks skip the kernel between HOSTS, this
  one skips the kernel for the many-daemons-one-process topology the
  framework actually runs (vstart dev clusters, the standalone test
  tier, and OSD-colocated TPU hosts), moving frames by reference
  through asyncio StreamReader buffers instead of loopback TCP.

Stacks preserve asyncio's (reader, writer) stream contract, so the
protocol layer (frames, auth, secure/compressed on-wire sessions) is
byte-identical over every stack — the same invariant the reference
keeps by running Protocol V2 unchanged over posix/rdma/dpdk.
"""

from __future__ import annotations

import asyncio
import itertools


class NetworkStack:
    """connect/listen boundary (Stack.h NetworkStack)."""

    async def connect(self, addr: str):
        """-> (StreamReader, StreamWriter-like) for a dialed peer."""
        raise NotImplementedError

    async def listen(self, addr: str, client_cb) -> tuple[object, str]:
        """Start accepting; `client_cb(reader, writer)` per peer.
        -> (server-like with close()/wait_closed(), bound address)."""
        raise NotImplementedError


def _split(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class PosixStack(NetworkStack):
    """asyncio TCP (PosixStack.h)."""

    async def connect(self, addr: str):
        return await asyncio.open_connection(*_split(addr))

    async def listen(self, addr: str, client_cb):
        host, port = _split(addr)
        server = await asyncio.start_server(client_cb, host, port)
        actual = server.sockets[0].getsockname()[1]
        return server, f"{host}:{actual}"


class _PipeReader(asyncio.StreamReader):
    """StreamReader that publishes its own buffered-byte count and signals
    consumption, so the writing side gets real backpressure without
    poking at StreamReader privates."""

    def __init__(self):
        super().__init__()
        self.pending = 0  # bytes fed minus bytes consumed
        self.drained = asyncio.Event()

    def feed_data(self, data) -> None:
        self.pending += len(data)
        super().feed_data(data)

    def _note_consumed(self, data) -> None:
        self.pending -= len(data)
        self.drained.set()

    async def read(self, n: int = -1):
        data = await super().read(n)
        self._note_consumed(data)
        return data

    async def readexactly(self, n: int):
        data = await super().readexactly(n)
        self._note_consumed(data)
        return data

    # NOTE: no readline override — StreamReader.readline delegates to
    # self.readuntil, so overriding both would double-count consumption.

    async def readuntil(self, separator: bytes = b"\n"):
        data = await super().readuntil(separator)
        self._note_consumed(data)
        return data


class _PipeWriter:
    """StreamWriter contract over a peer's _PipeReader buffer."""

    HIGH_WATER = 4 << 20  # drain() backpressure threshold (bytes buffered)
    DRAIN_DEADLINE = 10.0  # max seconds stuck above high-water before fault

    def __init__(self, peer_reader: _PipeReader):
        self._peer = peer_reader
        self._closed = False

    def write(self, data: bytes) -> None:
        if not self._closed:
            # by-reference when already immutable; copy only mutable views
            self._peer.feed_data(
                data if isinstance(data, bytes) else bytes(data)
            )

    async def drain(self) -> None:
        # Backpressure analog of TCP's: park until the peer has consumed
        # down to the high-water mark, so a fast sender can't grow the
        # peer's buffer without bound.  A peer that is alive but wedged
        # (not reading, not faulting) must not livelock senders forever:
        # after DRAIN_DEADLINE above high-water the connection faults,
        # matching the 10 s bounds on the TCP handshake paths.
        deadline = asyncio.get_event_loop().time() + self.DRAIN_DEADLINE
        while not self._closed and self._peer.pending > self.HIGH_WATER:
            if asyncio.get_event_loop().time() >= deadline:
                self.close()
                raise ConnectionResetError(
                    "in-process peer stalled above high-water for "
                    f"{self.DRAIN_DEADLINE}s"
                )
            self._peer.drained.clear()
            try:
                await asyncio.wait_for(self._peer.drained.wait(), 0.1)
            except asyncio.TimeoutError:
                pass

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._peer.feed_eof()

    def is_closing(self) -> bool:
        return self._closed


def _pipe_pair():
    """Two cross-connected (reader, writer) stream pairs."""
    a_reads = _PipeReader()
    b_reads = _PipeReader()
    return (a_reads, _PipeWriter(b_reads)), (b_reads, _PipeWriter(a_reads))


class _InProcListener:
    def __init__(self, stack: "InProcStack", addr: str):
        self._stack = stack
        self._addr = addr
        self._handlers: set[asyncio.Task] = set()

    def _spawn(self, client_cb, reader, writer) -> None:
        task = asyncio.get_event_loop().create_task(client_cb(reader, writer))
        self._handlers.add(task)
        task.add_done_callback(self._handlers.discard)

    def close(self) -> None:
        self._stack._listeners.pop(self._addr, None)
        for t in list(self._handlers):
            t.cancel()

    async def wait_closed(self) -> None:
        await asyncio.gather(*self._handlers, return_exceptions=True)


class InProcStack(NetworkStack):
    """In-process pipes with a process-wide listener registry.  Addresses
    are plain strings ("inproc:N" auto-assigned on bind, or any explicit
    string), carried through monmaps/OSDMaps like host:port addrs.
    Registry entries remember their event loop: a listener whose loop is
    gone (a test that died before shutdown) is stale — it is dropped
    rather than poisoning later binds/connects in the same process."""

    _listeners: dict[str, tuple[_InProcListener, object, object]] = {}
    _ports = itertools.count(1)

    @classmethod
    def _prune_stale(cls, addr: str):
        """Entry at addr, dropping it first if its loop died without
        shutdown (a failed test) — stale entries must not poison later
        binds/connects in the same process."""
        entry = cls._listeners.get(addr)
        if entry is not None and entry[2].is_closed():
            cls._listeners.pop(addr, None)
            return None
        return entry

    async def connect(self, addr: str):
        entry = self._prune_stale(addr)
        # A live listener on a FOREIGN loop is refused without touching
        # the registry: cross-loop pipes would race two schedulers.
        if entry is None or entry[2] is not asyncio.get_event_loop():
            raise ConnectionRefusedError(f"no inproc listener at {addr}")
        listener, client_cb, _loop = entry
        (c_reader, c_writer), (s_reader, s_writer) = _pipe_pair()
        listener._spawn(client_cb, s_reader, s_writer)
        return c_reader, c_writer

    async def listen(self, addr: str, client_cb):
        if not addr or addr.endswith(":0"):
            addr = f"inproc:{next(self._ports)}"
        if self._prune_stale(addr) is not None:
            raise OSError(f"inproc address {addr} in use")
        listener = _InProcListener(self, addr)
        self._listeners[addr] = (listener, client_cb, asyncio.get_event_loop())
        return listener, addr


STACKS = {"posix": PosixStack, "inproc": InProcStack}

# ms_type spellings (the reference's "async+posix" etc., ceph_osd.cc:541)
_ALIASES = {"async+posix": "posix", "async+inproc": "inproc"}


def make_stack(kind: str | NetworkStack) -> NetworkStack:
    """ms_type -> stack instance (Stack.cc NetworkStack::create)."""
    if isinstance(kind, NetworkStack):
        return kind
    kind = _ALIASES.get(kind, kind)
    cls = STACKS.get(kind)
    if cls is None:
        raise ValueError(f"unknown ms_type {kind!r} (have {sorted(STACKS)})")
    return cls()
