"""Messenger — mirror of /root/reference/src/msg + src/msg/async.

The distributed communication backend (SURVEY.md §2.5): an async
event-loop messenger speaking a v2-style segmented, crc32c-protected
frame protocol, with typed messages, dispatcher chains, per-peer
policies/throttles, and probabilistic fault injection
(`ms_inject_socket_failures`).

TPU-native division of labor (§2.5 "TPU-native equivalent"): this
messenger carries host-level control and chunk traffic between daemons;
bulk intra-pod data movement rides ICI via JAX collectives
(ceph_tpu/parallel), which this layer deliberately does NOT reimplement.
"""

from .message import Message, decode_message, encode_message, message_type
from .messenger import Connection, Dispatcher, Messenger, Policy

__all__ = [
    "Connection",
    "Dispatcher",
    "Message",
    "Messenger",
    "Policy",
    "decode_message",
    "encode_message",
    "message_type",
]
