"""Wire frames — mirror of src/msg/async/frames_v2.h.

Reference: msgr2 frames (/root/reference/src/msg/async/frames_v2.h:35)
carry up to 4 segments behind a fixed preamble holding the tag, segment
count and lengths, crc32c-protected; segment payloads get their own
crc32c in an epilogue.  CRC mode is mirrored here (secure/AES-GCM mode is
out of scope; the hook point is `ms_crc_data`).

Frame layout:
  preamble (28 B): magic "CT" | version u8 | tag u8 | flags u8 | pad u8 |
                   4 x seg_len u32 | preamble crc32c u32
  segments:        seg_count x raw bytes
  epilogue:        seg_count x crc32c u32   (omitted when flags bit 0 unset)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from ..utils.crc32c import crc32c

MAGIC = b"CT"
VERSION = 2

# frame tags (frames_v2.h Tag enum analog)
TAG_HELLO = 1
TAG_MESSAGE = 2
TAG_ACK = 3
TAG_KEEPALIVE = 4

FLAG_CRC_DATA = 1

_PREAMBLE = struct.Struct("<2sBBBB4II")  # magic, ver, tag, flags, pad, lens, crc
PREAMBLE_SIZE = _PREAMBLE.size
MAX_SEGMENTS = 4


class FrameError(Exception):
    pass


@dataclass
class Frame:
    tag: int
    segments: list[bytes]

    def pack(self, crc_data: bool = True) -> bytes:
        if len(self.segments) > MAX_SEGMENTS:
            raise FrameError(f"{len(self.segments)} segments > {MAX_SEGMENTS}")
        lens = [len(s) for s in self.segments] + [0] * (
            MAX_SEGMENTS - len(self.segments)
        )
        flags = FLAG_CRC_DATA if crc_data else 0
        head = struct.pack(
            "<2sBBBB4I", MAGIC, VERSION, self.tag, flags, len(self.segments), *lens
        )
        out = [head, struct.pack("<I", crc32c(head))]
        out.extend(self.segments)
        if crc_data:
            for s in self.segments:
                out.append(struct.pack("<I", crc32c(s)))
        return b"".join(out)


def preamble_info(buf: bytes) -> tuple[int, int, list[int]]:
    """Parse+verify a preamble -> (tag, flags, segment lengths)."""
    if len(buf) < PREAMBLE_SIZE:
        raise FrameError("short preamble")
    magic, ver, tag, flags, seg_count, l0, l1, l2, l3 = struct.unpack(
        "<2sBBBB4I", buf[: PREAMBLE_SIZE - 4]
    )
    (crc,) = struct.unpack("<I", buf[PREAMBLE_SIZE - 4 : PREAMBLE_SIZE])
    if magic != MAGIC:
        raise FrameError(f"bad magic {magic!r}")
    if ver != VERSION:
        raise FrameError(f"bad version {ver}")
    if crc32c(buf[: PREAMBLE_SIZE - 4]) != crc:
        raise FrameError("preamble crc mismatch")
    if seg_count > MAX_SEGMENTS:
        raise FrameError(f"bad segment count {seg_count}")
    return tag, flags, [l0, l1, l2, l3][:seg_count]


async def read_frame(reader) -> Frame:
    """Read one frame from an asyncio StreamReader, verifying CRCs."""
    head = await reader.readexactly(PREAMBLE_SIZE)
    tag, flags, seg_lens = preamble_info(head)
    segments = [await reader.readexactly(n) if n else b"" for n in seg_lens]
    if flags & FLAG_CRC_DATA:
        for i, seg in enumerate(segments):
            (crc,) = struct.unpack("<I", await reader.readexactly(4))
            if crc32c(seg) != crc:
                raise FrameError(f"segment {i} crc mismatch")
    return Frame(tag, segments)


def frame_from_bytes(buf: bytes) -> Frame:
    """Parse one complete frame from a byte string (the secure/compressed
    on-wire path decrypts whole records, then parses here).  Truncated
    input raises FrameError, never struct.error."""
    tag, flags, seg_lens = preamble_info(buf[:PREAMBLE_SIZE])
    need = PREAMBLE_SIZE + sum(seg_lens)
    if flags & FLAG_CRC_DATA:
        need += 4 * len(seg_lens)
    if len(buf) < need:
        raise FrameError(f"frame body truncated ({len(buf)} < {need})")
    off = PREAMBLE_SIZE
    segments = []
    for n in seg_lens:
        segments.append(buf[off : off + n])
        off += n
    if flags & FLAG_CRC_DATA:
        for i, seg in enumerate(segments):
            (crc,) = struct.unpack_from("<I", buf, off)
            off += 4
            if crc32c(seg) != crc:
                raise FrameError(f"segment {i} crc mismatch")
    return Frame(tag, segments)
