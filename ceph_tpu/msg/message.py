"""Typed messages — mirror of src/messages/ + Message base.

Reference: /root/reference/src/msg/Message.h (Message with header {type,
priority, seq, src}, front/data payload split) and the 170 typed classes
under src/messages/, each versioned via WRITE_CLASS_ENCODER
(src/include/encoding.h:188).

Concrete classes declare FIELDS — a declarative field spec the base turns
into versioned encode/decode — instead of hand-writing both sides of the
wire format for every message.  Field codecs:
  "u8" "u16" "u32" "u64" "i64" "f64" "bool" "str" "bytes"
  ("list", codec)              homogeneous list
  ("map", kcodec, vcodec)      sorted map
  ("opt", codec)               optional (None allowed)
  an Encodable subclass        nested versioned struct
"""

from __future__ import annotations

from typing import Any, Type

from ..common.encoding import Decoder, Encodable, Encoder

# message priorities (Message.h)
PRIO_LOW = 64
PRIO_DEFAULT = 127
PRIO_HIGH = 196
PRIO_HIGHEST = 255

_REGISTRY: dict[int, Type["Message"]] = {}


def message_type(type_id: int):
    """Register a message class under a wire type id (the reference's
    CEPH_MSG_* / MSG_* constants + decode_message switch,
    src/msg/Message.cc)."""

    def wrap(cls: Type["Message"]) -> Type["Message"]:
        if type_id in _REGISTRY:
            raise ValueError(f"message type {type_id} already registered")
        cls.TYPE = type_id
        _REGISTRY[type_id] = cls
        return cls

    return wrap


def _encode_field(enc: Encoder, codec, value) -> None:
    if isinstance(codec, str):
        if codec == "bool":
            enc.boolean(value)
        elif codec == "str":
            enc.string(value)
        elif codec == "bytes":
            enc.bytes_(bytes(value))
        else:
            getattr(enc, codec)(value)
    elif isinstance(codec, tuple):
        kind = codec[0]
        if kind == "list":
            enc.list_(value, lambda e, v: _encode_field(e, codec[1], v))
        elif kind == "map":
            enc.u32(len(value))
            for k in sorted(value):
                _encode_field(enc, codec[1], k)
                _encode_field(enc, codec[2], value[k])
        elif kind == "opt":
            enc.boolean(value is not None)
            if value is not None:
                _encode_field(enc, codec[1], value)
        else:
            raise TypeError(f"unknown field codec {codec}")
    elif isinstance(codec, type) and issubclass(codec, Encodable):
        value.encode(enc)
    else:
        raise TypeError(f"unknown field codec {codec}")


def _decode_field(dec: Decoder, codec):
    if isinstance(codec, str):
        if codec == "bool":
            return dec.boolean()
        if codec == "str":
            return dec.string()
        if codec == "bytes":
            return dec.bytes_()
        return getattr(dec, codec)()
    if isinstance(codec, tuple):
        kind = codec[0]
        if kind == "list":
            return dec.list_(lambda d: _decode_field(d, codec[1]))
        if kind == "map":
            n = dec.u32()
            return {
                _decode_field(dec, codec[1]): _decode_field(dec, codec[2])
                for _ in range(n)
            }
        if kind == "opt":
            return _decode_field(dec, codec[1]) if dec.boolean() else None
        raise TypeError(f"unknown field codec {codec}")
    if isinstance(codec, type) and issubclass(codec, Encodable):
        return codec.decode(dec)
    raise TypeError(f"unknown field codec {codec}")


class Message(Encodable):
    """Base message.  Subclasses set FIELDS and are @message_type()'d.

    Envelope fields (header analog) are filled by the messenger on send:
    src (entity name), seq, priority.
    """

    TYPE: int = 0
    VERSION = 1
    COMPAT = 1
    FIELDS: list[tuple[str, Any]] = []
    priority = PRIO_DEFAULT
    # trace context (common/tracer.py inject/extract): rides the envelope
    # like the reference's jspan/blkin trace info so one op's spans link
    # across daemons; 0 = untraced
    trace_id = 0
    span_id = 0
    # head-sampling decision carried with the context (ISSUE 10):
    # 0 = no decision (untraced / legacy sender), 1 = sampled (keep),
    # 2 = head-sampled out (downstream spans stay provisional)
    trace_sampled = 0
    # end-to-end op deadline (ISSUE 17): absolute time.monotonic() stamp
    # set by the client; receivers shed already-expired work instead of
    # executing it.  Valid because every daemon shares one process clock
    # (the MOSDPing.stamp precedent).  0.0 = no deadline
    deadline = 0.0

    def __init__(self, **kwargs):
        self.src = ""
        self.seq = 0
        for name, _ in self.FIELDS:
            setattr(self, name, None)
        for k, v in kwargs.items():
            if k not in {n for n, _ in self.FIELDS} | {
                "src", "seq", "priority", "trace_id", "span_id",
                "trace_sampled", "deadline",
            }:
                raise TypeError(f"{type(self).__name__} has no field {k}")
            setattr(self, k, v)

    def encode(self, enc: Encoder) -> None:
        enc.start(self.VERSION, self.COMPAT)
        for name, codec in self.FIELDS:
            _encode_field(enc, codec, getattr(self, name))
        enc.finish()

    @classmethod
    def decode(cls, dec: Decoder) -> "Message":
        dec.start(cls.VERSION)
        msg = cls.__new__(cls)
        msg.src = ""
        msg.seq = 0
        for name, codec in cls.FIELDS:
            setattr(msg, name, _decode_field(dec, codec))
        dec.finish()
        return msg

    def __repr__(self) -> str:
        fields = ", ".join(
            f"{n}={getattr(self, n)!r}" for n, _ in self.FIELDS[:4]
        )
        return f"{type(self).__name__}({fields})"


def encode_message(msg: Message) -> tuple[bytes, bytes]:
    """-> (envelope, payload) segments for the frame layer."""
    env = (
        Encoder()
        .u32(msg.TYPE)
        .string(msg.src)
        .u64(msg.seq)
        .u8(msg.priority)
        .u64(msg.trace_id)
        .u64(msg.span_id)
        .u8(msg.trace_sampled)
        .f64(msg.deadline)
        .tobytes()
    )
    return env, msg.tobytes()


def decode_message(envelope: bytes, payload: bytes) -> Message:
    d = Decoder(envelope)
    type_id = d.u32()
    src = d.string()
    seq = d.u64()
    priority = d.u8()
    trace_id = d.u64()
    span_id = d.u64()
    trace_sampled = d.u8()
    deadline = d.f64()
    cls = _REGISTRY.get(type_id)
    if cls is None:
        raise ValueError(f"unknown message type {type_id}")
    msg = cls.decode(Decoder(payload))
    msg.src = src
    msg.seq = seq
    msg.priority = priority
    msg.trace_id = trace_id
    msg.span_id = span_id
    msg.trace_sampled = trace_sampled
    msg.deadline = deadline
    return msg
