"""exception-swallowing: an ``except Exception:`` handler that leaves no
trace is an invisible failure.

A broad handler is fine when it re-raises, references the caught
exception (reply/store/format — the failure reaches someone), calls a
reporting function (``dout``/``clog``/logger methods/
``mark_degraded``), bumps a counter (``.inc(...)`` or an augmented
assignment), or is itself inside a loud context.  Anything else
swallows the failure byte-for-byte: the op completes wrong, the beacon
silently stops, and nothing anywhere records that it happened.
"""

from __future__ import annotations

import ast

from .. import Finding, SourceTree

# call names/attrs that count as "the failure left a trace"
REPORT_CALLS = {
    "dout", "_dout", "log", "clog", "clog_error", "log_error",
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "mark_degraded", "record_error", "print", "fail",
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    """except Exception / except BaseException / bare except — including
    tuple forms containing one of them."""
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in ("Exception", "BaseException") for n in names)


def _leaves_trace(handler: ast.ExceptHandler) -> bool:
    alias = handler.name
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if alias and isinstance(node, ast.Name) and node.id == alias \
                and isinstance(node.ctx, ast.Load):
            return True  # the exception reaches a reply/store/format
        if isinstance(node, ast.AugAssign):
            return True  # counter bump
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            if name in REPORT_CALLS or name == "inc":
                return True
    return False


class ExceptionSwallowPass:
    PASS_ID = "exception-swallowing"
    DESCRIBE = (
        "except Exception: handlers that neither re-raise, log, count, "
        "reference the exception, nor mark DEGRADED"
    )

    def __call__(self, tree: SourceTree) -> list[Finding]:
        findings: list[Finding] = []
        for sf in tree.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not _is_broad(node) or _leaves_trace(node):
                    continue
                scope = sf.scope_of(node)
                findings.append(Finding(
                    pass_id=self.PASS_ID,
                    file=sf.rel,
                    line=node.lineno,
                    key=f"{sf.rel}::{scope}",
                    message=(
                        "broad except handler swallows the failure "
                        "invisibly — re-raise, log (dout/clog), count a "
                        "perf counter, or allowlist with the reason the "
                        "silence is safe"
                    ),
                ))
        return findings
