"""ledger-discipline: device-buffer residency outside the HBM mempool
ledger is invisible residency.

ISSUE 13 built ``common/mempool.py`` so every byte resident on the
device is attributable to a named pool.  That property only holds if
new code keeps the discipline: a ``jax.device_put`` in the data-path
packages (``ops/``, ``codec/``, ``parallel/``, ``compressor/``)
commits host bytes to HBM, and unless the result is threaded through a
mempool-tracked
helper — ``track_buffer(...)`` wrapping the call, or an explicit
``ledger().alloc(...)`` handle in the same function — the bytes exist
but no ledger pool knows, ``dump_mempools`` under-reports, and the
pressure layer trims against a lie.

The pass flags every ``device_put`` call in those packages that is
neither (a) an argument of a ``track_buffer``/``tracked_device_put``
call nor (b) inside a function that also takes an explicit ``.alloc``
handle.  Intentional untracked sites get an allowlist entry with a
reason (``analysis/allowlists/ledger-discipline.allow``), like every
other pass.
"""

from __future__ import annotations

import ast

from .. import Finding, SourceTree

# packages whose device_put calls must be ledger-tracked: the EC data
# path's HBM holders, plus the compressor package now that the device
# plugin (ISSUE 20) places block batches through the offload runtime.
# Matched as path components so the fixture trees in tests
# (pkg/ops/x.py) scope the same way the live tree does.
_SCOPED_DIRS = {"ops", "codec", "parallel", "compressor"}

_TRACKED_WRAPPERS = {"track_buffer", "tracked_device_put", "_hbm_track"}

# names a ledger factory goes by at call sites: `<factory>().alloc(...)`
# is the explicit-handle spelling the pass accepts.  A bare `.alloc` on
# an arbitrary receiver (slots.alloc(), arena.alloc(n)) must NOT count
# — it would silence the only gate enforcing the ledger invariant.
_LEDGER_FACTORIES = {"ledger", "_hbm_ledger", "hbm_ledger", "_hbm"}


def _callable_name(fn: ast.AST) -> str:
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _in_scope(rel: str) -> bool:
    parts = rel.split("/")
    return any(p in _SCOPED_DIRS for p in parts[:-1])


class LedgerDisciplinePass:
    PASS_ID = "ledger-discipline"
    DESCRIBE = (
        "jax.device_put / device-buffer retention in ops//codec//"
        "parallel//compressor/ outside a mempool-tracked helper "
        "(track_buffer or an explicit ledger alloc handle)"
    )

    def __call__(self, tree: SourceTree) -> list[Finding]:
        findings: list[Finding] = []
        for sf in tree.files:
            if not _in_scope(sf.rel):
                continue
            wrapped = self._wrapped_calls(sf)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                if _callable_name(node.func) != "device_put":
                    continue
                if id(node) in wrapped:
                    continue
                if self._function_allocs(sf, node):
                    continue
                findings.append(Finding(
                    pass_id=self.PASS_ID,
                    file=sf.rel,
                    line=node.lineno,
                    key=f"{sf.rel}::{sf.scope_of(node)}::device_put",
                    message=(
                        "device_put commits bytes to HBM outside the "
                        "mempool ledger — wrap it in track_buffer(...) "
                        "or account it with ledger().alloc(...) so "
                        "dump_mempools and the pressure layer see the "
                        "residency"
                    ),
                ))
        return findings

    @staticmethod
    def _wrapped_calls(sf) -> set[int]:
        """ids of device_put Call nodes that appear inside an argument
        of a track_buffer/tracked_device_put call."""
        out: set[int] = set()
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if _callable_name(node.func) not in _TRACKED_WRAPPERS:
                continue
            # positional AND keyword arguments: track_buffer(buf=...)
            # is tracked too
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Call) and \
                            _callable_name(sub.func) == "device_put":
                        out.add(id(sub))
        return out

    @staticmethod
    def _function_allocs(sf, call: ast.Call) -> bool:
        """True when the enclosing (non-lambda) function also takes an
        explicit LEDGER handle — an ``.alloc(...)`` whose receiver is a
        ledger factory call (``ledger().alloc(...)`` /
        ``_hbm_ledger().alloc(...)``, the device_cache.put shape: the
        device_put result is accounted a few lines later under the
        cache lock).  An ``.alloc`` on any other receiver
        (slots.alloc(), arena.alloc(n)) does NOT count, and a
        track_buffer call elsewhere in the function does NOT excuse a
        bare device_put next to it — per-call wrapping is checked by
        _wrapped_calls, so counting it here would let one tracked
        placement silence every untracked sibling."""
        func = sf.enclosing_function(call)
        if func is None:
            return False
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "alloc":
                    recv = node.func.value
                    if isinstance(recv, ast.Call) and \
                            _callable_name(recv.func) in _LEDGER_FACTORIES:
                        return True
        return False
