"""config-option coherence: the option table, the code, and the docs
agree.

Four checks over ``common/options.py``'s table (generalizing the
options slice of the metrics lint):

- ``unread``: an option no code ever reads is dead weight — or worse,
  an operator knob that silently does nothing.
- ``unwired-runtime``: an option declared ``runtime=True`` must either
  be re-read per use (a read inside a non-``__init__`` function) or
  have a config observer (``add_observer``) — an init-time-only read
  means a runtime ``config set`` silently changes nothing.
- ``undocumented``: every option name appears in ``docs/``
  (docs/OPTIONS.md is the index this pass enforces).
- ``unregistered-read``: ``conf.get("name")`` with a literal not in the
  table — a typo'd knob that can only fail at runtime, if ever.

Option-name "reads" are any string literal equal to the name anywhere
outside ``options.py`` — plus f-string literal PREFIXES ending in ``_``
(``f"ec_tpu_sched_{lane}_{knob}"`` wires the whole family), matching
how the observer registrations are actually written.
"""

from __future__ import annotations

import ast

from .. import Finding, SourceTree

OPTIONS_FILE = "common/options.py"


def _load_real_options():
    from ceph_tpu.common.options import OPTIONS

    return {
        name: {"runtime": opt.runtime} for name, opt in OPTIONS.items()
    }


class _Read:
    __slots__ = ("file", "line", "scope", "in_observer")

    def __init__(self, file, line, scope, in_observer):
        self.file, self.line = file, line
        self.scope, self.in_observer = scope, in_observer


class OptionsCoherencePass:
    PASS_ID = "config-coherence"
    DESCRIBE = (
        "every option read somewhere, observer-wired or re-read per use "
        "if runtime-mutable, documented in docs/, and no unregistered "
        "name read"
    )

    def __init__(self, options: dict | None = None):
        # injectable for fixture tests; None = the live table
        self._options = options

    def __call__(self, tree: SourceTree) -> list[Finding]:
        options = self._options
        if options is None:
            options = _load_real_options()
        reads: dict[str, list[_Read]] = {name: [] for name in options}
        prefix_reads: list[tuple[str, _Read]] = []
        conf_get_literals: list[tuple[str, object, object]] = []
        opt_line: dict[str, int] = {}

        for sf in tree.files:
            is_options_file = sf.rel.endswith(OPTIONS_FILE)
            observer_spans = _observer_string_nodes(sf)
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    s = node.value
                    if is_options_file:
                        if s in options:
                            opt_line.setdefault(s, node.lineno)
                        continue
                    rd = _Read(sf.rel, node.lineno,
                               _live_scope(sf, node),
                               id(node) in observer_spans)
                    if s in options:
                        reads[s].append(rd)
                    # f-string literal prefix: covers name families
                    if id(node) in _joined_fragments(sf) and \
                            s.endswith("_"):
                        prefix_reads.append((s, rd))
                if isinstance(node, ast.Call) and not is_options_file:
                    lit = _conf_get_literal(node)
                    if lit is not None:
                        conf_get_literals.append((lit, sf, node))

        findings: list[Finding] = []
        for name, meta in sorted(options.items()):
            live_prefix = [
                rd for frag, rd in prefix_reads if name.startswith(frag)
            ]
            all_reads = reads[name] + live_prefix
            line = opt_line.get(name, 1)
            if not all_reads:
                findings.append(Finding(
                    pass_id=self.PASS_ID,
                    file=OPTIONS_FILE, line=line,
                    key=f"unread::{name}",
                    message=(
                        f"option `{name}` is never read anywhere in the "
                        "package — dead knob (wire it or remove it)"
                    ),
                ))
                continue
            if meta["runtime"]:
                wired = any(rd.in_observer for rd in all_reads) or any(
                    rd.scope != "<module>"
                    and not rd.scope.split(".")[-1] == "__init__"
                    for rd in all_reads
                )
                if not wired:
                    findings.append(Finding(
                        pass_id=self.PASS_ID,
                        file=OPTIONS_FILE, line=line,
                        key=f"unwired-runtime::{name}",
                        message=(
                            f"runtime-mutable option `{name}` is only read "
                            "at init time and has no config observer — a "
                            "runtime `config set` silently changes nothing"
                        ),
                    ))
        docs = tree.docs_text()
        for name in sorted(options):
            if name not in docs:
                findings.append(Finding(
                    pass_id=self.PASS_ID,
                    file=OPTIONS_FILE, line=opt_line.get(name, 1),
                    key=f"undocumented::{name}",
                    message=(
                        f"option `{name}` is not documented anywhere under "
                        "docs/ (docs/OPTIONS.md is the index)"
                    ),
                ))
        for lit, sf, node in conf_get_literals:
            if lit not in options:
                findings.append(Finding(
                    pass_id=self.PASS_ID,
                    file=sf.rel, line=node.lineno,
                    key=f"unregistered-read::{lit}",
                    message=(
                        f"conf.get({lit!r}) reads a name that is not in "
                        "the option table — typo'd knob"
                    ),
                ))
        return findings


def _live_scope(sf, node) -> str:
    """Scope qualname, with reads inside a Lambda counted as their own
    (deferred) scope — `Reserver(lambda: conf.get("osd_max_backfills"))`
    re-reads at every call, which is runtime-mutable-safe."""
    import ast as _ast

    cur = node
    while cur in sf.parents:
        cur = sf.parents[cur]
        if isinstance(cur, _ast.Lambda):
            return "<lambda>"
    return sf.scope_of(node)


def _conf_get_literal(node: ast.Call) -> str | None:
    """`<...>conf.get("lit")` / `conf["lit"]`-style reads (the receiver
    must be named conf/config so plain dict .get()s don't false-trip)."""
    fn = node.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "get"):
        return None
    recv = fn.value
    recv_name = recv.attr if isinstance(recv, ast.Attribute) else (
        recv.id if isinstance(recv, ast.Name) else "")
    if recv_name not in ("conf", "config", "_conf"):
        return None
    if node.args and isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _observer_string_nodes(sf) -> set[int]:
    """ids of string-constant nodes that appear inside an
    add_observer(...) call's arguments."""
    out: set[int] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            fn = node.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if attr != "add_observer":
                continue
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        out.add(id(sub))
    return out


def _joined_fragments(sf) -> set[int]:
    """ids of string constants that are literal fragments of f-strings
    (JoinedStr) — the prefix-wiring spelling."""
    cache = getattr(sf, "_joined_cache", None)
    if cache is None:
        cache = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.JoinedStr):
                for v in node.values:
                    if isinstance(v, ast.Constant):
                        cache.add(id(v))
        sf._joined_cache = cache
    return cache
