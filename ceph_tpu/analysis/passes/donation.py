"""donation-lifetime: a buffer donated to the device is dead to the
host.

``jax.jit(..., donate_argnums=(k,))`` transfers ownership of argument k
to the runtime at call time — XLA may alias the output onto it, and a
later host read of the same variable observes whatever the kernel
scribbled (or raises a deleted-buffer error only when jax feels like
it).  This pass tracks the package's donating callables — defs
decorated with a ``donate_argnums`` jit, names bound to
``jax.jit(..., donate_argnums=...)``, and calls through factories
invoked with ``donate=True`` — and flags any read of a donated
variable that is sequentially after the donating call in the same
function scope (same-branch, not re-bound in between).
"""

from __future__ import annotations

import ast

from .. import Finding, SourceTree


def _callable_name(fn: ast.AST) -> str:
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _donated_positions(call: ast.Call) -> tuple[int, ...] | None:
    """The donate_argnums tuple of a jax.jit(...) call, if present."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Tuple):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        out.append(e.value)
                return tuple(out)
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            return ()  # present but unresolvable: treat arg 0 as donated
    return None


def _jit_donations(call: ast.Call) -> tuple[int, ...] | None:
    """donate positions when `call` is jax.jit(...)/partial(jax.jit,...)
    with donate_argnums; None otherwise."""
    name = _callable_name(call.func)
    if name == "jit":
        return _donated_positions(call)
    if name == "partial" and call.args and \
            _callable_name(call.args[0]) == "jit":
        return _donated_positions(call)
    return None


def _collect_donating_callables(sf) -> dict[str, tuple[int, ...]]:
    """name -> donated positions, for decorated defs and jit-bound
    names in this module."""
    out: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    pos = _jit_donations(dec)
                    if pos is not None:
                        out[node.name] = pos or (0,)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _jit_donations(node.value)
            if pos is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = pos or (0,)
    return out


def _stmt_index_path(sf, node: ast.AST) -> list[tuple[ast.AST, str, int]]:
    """Path of (parent, field, index) statement coordinates from the
    module down to `node` — the basis for branch-aware ordering."""
    chain = sf.ancestors(node) + [node]
    path = []
    for parent, child in zip(chain, chain[1:]):
        for field, value in ast.iter_fields(parent):
            if isinstance(value, list) and child in value:
                path.append((parent, field, value.index(child)))
                break
    return path


def _sequentially_after(sf, first: ast.AST, later: ast.AST) -> bool:
    """True when `later` executes after `first` in straight-line order:
    they share a statement list downstream of their common ancestor (or
    body→finalbody/orelse of a Try or loop), and `later`'s position is
    greater.  Sibling branches (if/else arms, except handlers) are NOT
    sequential."""
    pa = _stmt_index_path(sf, first)
    pb = _stmt_index_path(sf, later)
    for (na, fa, xa), (nb, fb, xb) in zip(pa, pb):
        if na is not nb:
            return False  # diverged without a shared statement list
        if fa == fb:
            if xa == xb:
                continue  # same statement: descend further
            return xb > xa
        # different fields of the same parent node
        if isinstance(na, ast.Try):
            # try-body -> finally always runs after; try-body -> else
            # runs after normal completion.  body -> handler is NOT
            # sequential (the donation may not have happened).
            return (fa, fb) in (("body", "finalbody"), ("body", "orelse"),
                                ("handlers", "finalbody"),
                                ("orelse", "finalbody"))
        if isinstance(na, (ast.For, ast.AsyncFor, ast.While)):
            return (fa, fb) == ("body", "orelse")
        return False  # if/else arms and everything else: parallel
    return False


def _rebound_between(func: ast.AST, name: str, sf,
                     call: ast.Call, read: ast.Name) -> bool:
    """Was `name` re-assigned sequentially between the call and the
    read?  A rebind kills the donated binding — the read is fresh."""
    for node in ast.walk(func):
        if isinstance(node, ast.Name) and node.id == name and \
                isinstance(node.ctx, ast.Store):
            if node is read:
                continue
            if _sequentially_after(sf, call, node) and \
                    _sequentially_after(sf, node, read):
                return True
    return False


class DonationLifetimePass:
    PASS_ID = "donation-lifetime"
    DESCRIBE = (
        "host reads of a buffer after it was passed in a donate_argnums/"
        "donate=True position (use-after-donation)"
    )

    def __call__(self, tree: SourceTree) -> list[Finding]:
        # donating callables are collected package-wide (a decorated def
        # in ops/ is called from codec/), keyed by bare name
        donating: dict[str, tuple[int, ...]] = {}
        for sf in tree.files:
            donating.update(_collect_donating_callables(sf))
        findings: list[Finding] = []
        for sf in tree.files:
            for func in ast.walk(sf.tree):
                if not isinstance(func, (ast.FunctionDef,
                                         ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                # local bindings shadow the package-wide map
                local = dict(donating)
                if not isinstance(func, ast.Lambda):
                    local.update(_collect_donating_callables_scope(func))
                for call in ast.walk(func):
                    if not isinstance(call, ast.Call):
                        continue
                    donated_args = self._donated_args(call, local)
                    for arg in donated_args:
                        if not isinstance(arg, ast.Name):
                            continue
                        findings.extend(self._reads_after(
                            sf, func, call, arg.id
                        ))
        return findings

    @staticmethod
    def _enclosing_stmt(sf, node: ast.AST) -> ast.stmt | None:
        cur = node
        while cur in sf.parents:
            if isinstance(cur, ast.stmt):
                return cur
            cur = sf.parents[cur]
        return None

    @staticmethod
    def _donated_args(call: ast.Call, donating) -> list[ast.AST]:
        """Argument expressions donated by this call."""
        name = _callable_name(call.func)
        if name in donating:
            pos = donating[name]
            return [call.args[i] for i in pos if i < len(call.args)]
        # factory(..., donate=True)(buf): the outer call's args are all
        # donated — the factory built a donating executable
        if isinstance(call.func, ast.Call):
            for kw in call.func.keywords:
                if kw.arg == "donate" and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is True:
                    return list(call.args)
        return []

    def _reads_after(self, sf, func, call: ast.Call,
                     varname: str) -> list[Finding]:
        # `x, p = donating(x, p)` immediately rebinds the donated name to
        # the call's RESULT — the canonical safe donation idiom; later
        # reads see the fresh buffer, not the dead one
        stmt = self._enclosing_stmt(sf, call)
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name) and sub.id == varname \
                            and isinstance(sub.ctx, ast.Store):
                        return []
        out = []
        for node in ast.walk(func):
            if not (isinstance(node, ast.Name) and node.id == varname
                    and isinstance(node.ctx, ast.Load)):
                continue
            # the donating call's own argument reads don't count
            if node.lineno == call.lineno and any(
                    node is a or node in ast.walk(a) for a in call.args):
                continue
            if not _sequentially_after(sf, call, node):
                continue
            if _rebound_between(func, varname, sf, call, node):
                continue
            fname = getattr(func, "name", "<lambda>")
            out.append(Finding(
                pass_id=self.PASS_ID,
                file=sf.rel,
                line=node.lineno,
                key=f"{sf.rel}::{sf.scope_of(node)}::{varname}",
                message=(
                    f"`{varname}` read after being donated to the device "
                    f"at line {call.lineno} — the buffer may alias the "
                    "kernel's output or already be deleted "
                    "(use-after-donation)"
                ),
            ))
        return out


def _collect_donating_callables_scope(func) -> dict[str, tuple[int, ...]]:
    """Scope-local `f = jax.jit(..., donate_argnums=...)` bindings."""
    out: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _jit_donations(node.value)
            if pos is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = pos or (0,)
    return out
