"""lock-discipline: every lock routes through the lockdep factory, and
no blocking device wait runs under a held lock.

A bare ``threading.Lock()`` / ``threading.RLock()`` / ``asyncio.Lock()``
(or a zero-arg ``threading.Condition()``, which embeds one) constructed
anywhere but ``common/lockdep.py`` bypasses lock-order validation — the
dynamic lockdep tier (CEPH_TPU_LOCKDEP=1) can only see locks created by
``make_lock`` / ``make_rlock`` / ``make_async_lock``.  Separately, a
blocking device wait (``block_until_ready``, ``device_put``,
``.result()``) inside a ``with <lock>:`` body serializes every sibling
of that lock behind the device — the priority-inversion shape the
launch scheduler exists to prevent.
"""

from __future__ import annotations

import ast

from .. import Finding, SourceTree

FACTORY_FILE = "common/lockdep.py"  # the one legitimate constructor site

_BARE = {
    ("threading", "Lock"),
    ("threading", "RLock"),
    ("asyncio", "Lock"),
    ("threading", "Condition"),
    ("asyncio", "Condition"),
}
_DEVICE_WAITS = {"block_until_ready", "device_put", "result"}


def _bare_lock_call(node: ast.Call) -> str | None:
    """`threading.Lock()` etc -> "threading.Lock", else None."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        pair = (fn.value.id, fn.attr)
        if pair in _BARE:
            # Condition(lock) wrapping an instrumented lock is fine —
            # only the zero-arg form fabricates its own hidden RLock
            if fn.attr == "Condition" and (node.args or node.keywords):
                return None
            return ".".join(pair)
    return None


def _looks_like_lock(expr: ast.AST) -> bool:
    """Heuristic for `with <expr>:` guarding a critical section."""
    if isinstance(expr, ast.Name):
        return "lock" in expr.id.lower()
    if isinstance(expr, ast.Attribute):
        return "lock" in expr.attr.lower()
    return False


class LockDisciplinePass:
    PASS_ID = "lock-discipline"
    DESCRIBE = (
        "bare Lock()/RLock()/asyncio.Lock() outside the lockdep factory; "
        "blocking device waits while holding a lock"
    )

    def __call__(self, tree: SourceTree) -> list[Finding]:
        findings: list[Finding] = []
        for sf in tree.files:
            if sf.rel.endswith(FACTORY_FILE):
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    kind = _bare_lock_call(node)
                    if kind is not None:
                        scope = sf.scope_of(node)
                        findings.append(Finding(
                            pass_id=self.PASS_ID,
                            file=sf.rel,
                            line=node.lineno,
                            key=f"{sf.rel}::{scope}::{kind}",
                            message=(
                                f"bare {kind}() constructed outside "
                                "common/lockdep.py — use make_lock/"
                                "make_rlock/make_async_lock so lock-order "
                                "validation sees it"
                            ),
                        ))
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    if not any(
                        _looks_like_lock(item.context_expr)
                        for item in node.items
                    ):
                        continue
                    for sub in ast.walk(node):
                        if not isinstance(sub, ast.Call):
                            continue
                        fn = sub.func
                        attr = fn.attr if isinstance(fn, ast.Attribute) \
                            else (fn.id if isinstance(fn, ast.Name) else "")
                        if attr in _DEVICE_WAITS:
                            scope = sf.scope_of(sub)
                            findings.append(Finding(
                                pass_id=self.PASS_ID,
                                file=sf.rel,
                                line=sub.lineno,
                                key=f"{sf.rel}::{scope}::wait.{attr}",
                                message=(
                                    f"blocking device wait `{attr}` while "
                                    "holding a lock — every sibling of the "
                                    "lock serializes behind the device"
                                ),
                            ))
        return findings
