"""The pass inventory.  A pass is a callable ``(SourceTree) ->
list[Finding]`` carrying ``PASS_ID`` and ``DESCRIBE`` attributes; adding
one means writing the module, importing it here, and appending to
``ALL_PASSES`` (docs/TESTING.md "Adding a pass")."""

from __future__ import annotations

from .donation import DonationLifetimePass
from .exceptions import ExceptionSwallowPass
from .ledger import LedgerDisciplinePass
from .locks import LockDisciplinePass
from .options_coherence import OptionsCoherencePass
from .purity import JitPurityPass

ALL_PASSES = [
    DonationLifetimePass(),
    JitPurityPass(),
    ExceptionSwallowPass(),
    LockDisciplinePass(),
    OptionsCoherencePass(),
    LedgerDisciplinePass(),
]

PASS_BY_ID = {p.PASS_ID: p for p in ALL_PASSES}
