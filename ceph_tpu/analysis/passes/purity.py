"""jit-purity: host side effects inside traced closures bake in one
value forever.

A ``time.time()``, ``np.random`` draw, lock acquisition, ``faultpoint``
check, perf-counter ``.inc`` or global mutation inside a function that
jax traces (``@jax.jit``, ``jax.jit(f)``, ``shard_map`` bodies) runs
ONCE — at trace time — and its value is burned into the compiled
executable.  The fault never fires again, the timestamp never advances,
the counter counts compiles instead of launches.  Scope: the kernel
dirs (``ops/``, ``codec/``, ``parallel/``), where every jitted function
must be pure array math.
"""

from __future__ import annotations

import ast

from .. import Finding, SourceTree

SCOPE_DIRS = ("ops/", "codec/", "parallel/")

_TIME_FNS = {"time", "monotonic", "perf_counter", "perf_counter_ns",
             "time_ns", "process_time"}
_JIT_WRAPPERS = {"jit", "shard_map", "_shard_map", "pmap"}


def _in_scope(rel: str) -> bool:
    return any(f"/{d}" in rel or rel.startswith(d) for d in SCOPE_DIRS)


def _callable_name(fn: ast.AST) -> str:
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _is_jit_call(node: ast.Call) -> bool:
    """jax.jit(...), functools.partial(jax.jit, ...), shard_map(...)."""
    name = _callable_name(node.func)
    if name in _JIT_WRAPPERS:
        return True
    if name == "partial" and node.args:
        return _callable_name(node.args[0]) in _JIT_WRAPPERS
    return False


def _jitted_functions(sf) -> list[tuple[ast.AST, str]]:
    """(function node, how) for every lexically-traced function body:
    decorated defs, `jax.jit(f)` / `shard_map(f, ...)` over a local def
    or lambda."""
    out = []
    local_defs: dict[str, ast.AST] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_defs.setdefault(node.name, node)
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit_call(dec):
                    out.append((node, "decorator"))
                elif _callable_name(dec) in _JIT_WRAPPERS:
                    out.append((node, "decorator"))
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and _is_jit_call(node):
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    out.append((arg, "wrapped"))
                elif isinstance(arg, ast.Name) and arg.id in local_defs:
                    out.append((local_defs[arg.id], "wrapped"))
    return out


def _impurities(func: ast.AST) -> list[tuple[ast.AST, str]]:
    found = []
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute):
                base = fn.value
                if isinstance(base, ast.Name) and base.id == "time" \
                        and fn.attr in _TIME_FNS:
                    found.append((node, f"host clock time.{fn.attr}()"))
                elif isinstance(base, ast.Attribute) and \
                        base.attr == "random" and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id in ("np", "numpy"):
                    found.append((node, f"np.random.{fn.attr}() host RNG"))
                elif isinstance(base, ast.Name) and base.id == "random":
                    found.append((node, f"random.{fn.attr}() host RNG"))
                elif fn.attr == "acquire":
                    found.append((node, "lock acquisition"))
                elif fn.attr == "inc":
                    found.append((node, "perf-counter .inc() mutation"))
            elif isinstance(fn, ast.Name):
                if fn.id in ("faultpoint", "_faultpoint"):
                    found.append((node, "faultpoint() check"))
                elif fn.id == "print":
                    found.append((node, "print() host I/O"))
        elif isinstance(node, ast.Global):
            found.append((node, "global-variable mutation"))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                attr = expr.attr if isinstance(expr, ast.Attribute) else (
                    expr.id if isinstance(expr, ast.Name) else "")
                if "lock" in attr.lower():
                    found.append((node, "lock held inside the trace"))
    return found


class JitPurityPass:
    PASS_ID = "jit-purity"
    DESCRIBE = (
        "host side effects (clocks, RNG, locks, faultpoints, counters) "
        "reachable inside jax.jit/shard_map closures in ops/, codec/, "
        "parallel/"
    )

    def __call__(self, tree: SourceTree) -> list[Finding]:
        findings: list[Finding] = []
        for sf in tree.files:
            if not _in_scope(sf.rel):
                continue
            seen: set[int] = set()
            for func, _how in _jitted_functions(sf):
                if id(func) in seen:
                    continue
                seen.add(id(func))
                fname = getattr(func, "name", "<lambda>")
                for node, what in _impurities(func):
                    findings.append(Finding(
                        pass_id=self.PASS_ID,
                        file=sf.rel,
                        line=node.lineno,
                        key=f"{sf.rel}::{fname}::{what.split('(')[0].strip()}",
                        message=(
                            f"{what} inside traced function `{fname}` — "
                            "runs once at trace time and bakes its value "
                            "into the executable"
                        ),
                    ))
        return findings
