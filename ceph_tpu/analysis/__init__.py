"""Pass-based static analysis over the ceph_tpu package (ISSUE 12).

Nine PRs in, the correctness of the TPU data path rests on cross-cutting
invariants no unit test can see locally: donated device buffers must
never be read after dispatch, jitted closures must stay pure, every
`except Exception:` must leave a trace, every lock must route through
the `common/lockdep.py` factory so ordering stays validated, and the
option table must stay coherent with the code and docs.  The reference
enforces the lock half dynamically with lockdep under
`-DCEPH_DEBUG_MUTEX`; this package is the static twin — the framework
the one-off lints (`tests/test_metrics_lint.py`,
`tests/test_faultpoint_lint.py`) grew into.

Design:

- :class:`SourceTree` parses every package file ONCE (AST + parent
  links + scope qualnames); passes share it.
- A pass is a callable ``(tree) -> list[Finding]`` with a ``PASS_ID``
  and a one-line ``DESCRIBE``.  Each :class:`Finding` carries
  ``file:line``, the pass id, a human message, and a STABLE ``key``
  (file + enclosing scope + pass-specific detail — not the line number,
  so allowlists survive unrelated edits).
- Allowlists live in ``analysis/allowlists/<pass_id>.allow`` — one
  ``key | reason`` per line, reason MANDATORY (the loader refuses an
  entry without one).  A stale entry (matching no current finding) is
  itself a finding: suppressions must die with the code they excused.
- ``python -m ceph_tpu.analysis`` runs everything and exits nonzero on
  any unallowlisted finding; ``--json`` emits the machine report
  tier-1 consumes (tests/test_static_analysis.py).
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path

PKG_ROOT = Path(__file__).resolve().parent.parent       # ceph_tpu/
REPO_ROOT = PKG_ROOT.parent
ALLOWLIST_DIR = Path(__file__).resolve().parent / "allowlists"

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


@dataclass
class Finding:
    """One violation: where, which pass, what — plus the stable
    allowlist key."""

    pass_id: str
    file: str          # repo-relative path
    line: int
    key: str           # stable allowlist key (no line numbers)
    message: str
    allowed: bool = False
    reason: str = ""   # allowlist reason when allowed

    def to_json(self) -> dict:
        return {
            "pass": self.pass_id,
            "file": self.file,
            "line": self.line,
            "key": self.key,
            "message": self.message,
            "allowed": self.allowed,
            **({"reason": self.reason} if self.allowed else {}),
        }

    def __str__(self) -> str:
        flag = " [allowlisted: %s]" % self.reason if self.allowed else ""
        return f"{self.file}:{self.line}: [{self.pass_id}] {self.message}{flag}"


class SourceFile:
    """One parsed module: AST with parent links and scope qualnames."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self.text = path.read_text()
        self.tree = ast.parse(self.text, filename=str(path))
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

    def ancestors(self, node: ast.AST) -> list[ast.AST]:
        """Chain from the module root down to (excluding) `node`."""
        chain = []
        cur = node
        while cur in self.parents:
            cur = self.parents[cur]
            chain.append(cur)
        chain.reverse()
        return chain

    def scope_of(self, node: ast.AST) -> str:
        """Qualname of the enclosing function/class scope, or
        "<module>".  The allowlist key component: stable across line
        churn, precise enough to not over-suppress."""
        names = []
        cur = node
        while cur in self.parents:
            cur = self.parents[cur]
            if isinstance(cur, _SCOPE_NODES):
                names.append(cur.name)
        if not names:
            return "<module>"
        return ".".join(reversed(names))

    def enclosing_function(
        self, node: ast.AST
    ) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
        cur = node
        while cur in self.parents:
            cur = self.parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
        return None


class SourceTree:
    """Every .py file under a package root, parsed once and shared by
    all passes."""

    def __init__(self, root: Path | str = PKG_ROOT,
                 repo_root: Path | str | None = None):
        self.root = Path(root)
        self.repo_root = Path(repo_root) if repo_root else self.root.parent
        self.files: list[SourceFile] = []
        for path in sorted(self.root.rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = str(path.relative_to(self.repo_root))
            self.files.append(SourceFile(path, rel))

    def docs_text(self) -> str:
        """Concatenated docs/*.md (the config-coherence pass's doc
        universe)."""
        docs = self.repo_root / "docs"
        if not docs.is_dir():
            return ""
        return "\n".join(
            p.read_text() for p in sorted(docs.glob("*.md"))
        )


def load_allowlist(path: Path) -> dict[str, str]:
    """Parse one `<key> | <reason>` allowlist file.  The reason string
    is MANDATORY — findings are never silently suppressed."""
    entries: dict[str, str] = {}
    if not path.is_file():
        return entries
    for lineno, raw in enumerate(path.read_text().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        key, sep, reason = line.partition("|")
        key, reason = key.strip(), reason.strip()
        if not sep or not reason:
            raise ValueError(
                f"{path.name}:{lineno}: allowlist entry {key!r} has no "
                "reason — every suppression must say why "
                "(`<key> | <reason>`)"
            )
        if key in entries:
            raise ValueError(f"{path.name}:{lineno}: duplicate key {key!r}")
        entries[key] = reason
    return entries


def run_analysis(
    tree: SourceTree | None = None,
    passes=None,
    allowlist_dir: Path | str | None = ALLOWLIST_DIR,
) -> dict:
    """Run passes over the tree; apply allowlists; return the report.

    Report shape::

        {"findings": [...unallowlisted...], "allowlisted": [...],
         "stale_allowlist": [...], "passes": {id: counts}, "ok": bool}
    """
    from .passes import ALL_PASSES

    if tree is None:
        tree = SourceTree()
    if passes is None:
        passes = ALL_PASSES
    open_findings: list[Finding] = []
    allowed: list[Finding] = []
    stale: list[dict] = []
    per_pass: dict[str, dict] = {}
    for p in passes:
        findings = p(tree)
        entries = {}
        if allowlist_dir is not None:
            entries = load_allowlist(Path(allowlist_dir) / f"{p.PASS_ID}.allow")
        used: set[str] = set()
        for f in findings:
            if f.key in entries:
                f.allowed = True
                f.reason = entries[f.key]
                used.add(f.key)
                allowed.append(f)
            else:
                open_findings.append(f)
        for key, reason in entries.items():
            if key not in used:
                stale.append({
                    "pass": p.PASS_ID,
                    "key": key,
                    "reason": reason,
                    "message": (
                        f"stale allowlist entry {key!r} matches no current "
                        "finding — delete it (suppressions die with the "
                        "code they excused)"
                    ),
                })
        per_pass[p.PASS_ID] = {
            "describe": p.DESCRIBE,
            "findings": sum(1 for f in findings if not f.allowed),
            "allowlisted": sum(1 for f in findings if f.allowed),
        }
    return {
        "findings": [f.to_json() for f in open_findings],
        "allowlisted": [f.to_json() for f in allowed],
        "stale_allowlist": stale,
        "passes": per_pass,
        "ok": not open_findings and not stale,
    }


def render_report(report: dict, as_json: bool = False) -> str:
    if as_json:
        return json.dumps(report, indent=2, sort_keys=True)
    lines = []
    for f in report["findings"]:
        lines.append(
            f"{f['file']}:{f['line']}: [{f['pass']}] {f['message']}\n"
            f"    key: {f['key']}"
        )
    for s in report["stale_allowlist"]:
        lines.append(f"[{s['pass']}] {s['message']}")
    total = len(report["findings"])
    stale = len(report["stale_allowlist"])
    lines.append(
        f"{total} finding(s), {stale} stale allowlist entr(ies), "
        f"{len(report['allowlisted'])} allowlisted"
    )
    return "\n".join(lines)
