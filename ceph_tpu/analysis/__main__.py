"""`python -m ceph_tpu.analysis` — run every pass, print findings,
exit nonzero on any unallowlisted finding or stale allowlist entry.

    python -m ceph_tpu.analysis                # human output
    python -m ceph_tpu.analysis --json         # machine report to stdout
    python -m ceph_tpu.analysis --json out.json
    python -m ceph_tpu.analysis --pass lock-discipline
"""

from __future__ import annotations

import argparse
import sys

from . import ALLOWLIST_DIR, SourceTree, render_report, run_analysis
from .passes import ALL_PASSES, PASS_BY_ID


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.analysis",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--json", nargs="?", const="-", default=None,
                    metavar="PATH",
                    help="emit the JSON report (to PATH, or stdout)")
    ap.add_argument("--pass", dest="passes", action="append", default=None,
                    choices=sorted(PASS_BY_ID),
                    help="run only this pass (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="list the pass inventory and exit")
    ap.add_argument("--root", default=None, metavar="DIR",
                    help="package root to analyze (default: the "
                         "installed ceph_tpu/); allowlists are NOT "
                         "applied to foreign roots")
    args = ap.parse_args(argv)

    if args.list:
        for p in ALL_PASSES:
            print(f"{p.PASS_ID}: {p.DESCRIBE}")
        return 0

    passes = ALL_PASSES
    if args.passes:
        passes = [PASS_BY_ID[pid] for pid in args.passes]
    if args.root is not None:
        tree, allow_dir = SourceTree(args.root), None
    else:
        tree, allow_dir = SourceTree(), ALLOWLIST_DIR
    report = run_analysis(tree, passes=passes, allowlist_dir=allow_dir)
    if args.json is not None:
        text = render_report(report, as_json=True)
        if args.json == "-":
            print(text)
        else:
            with open(args.json, "w") as f:
                f.write(text + "\n")
            print(f"report written to {args.json}")
    else:
        print(render_report(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
