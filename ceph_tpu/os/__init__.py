"""Object store — mirror of /root/reference/src/os + src/kv.

Transactions-as-values applied atomically to collections of objects
(SURVEY.md §2.6): `Transaction` is an encodable op list, collections are
PG shards (coll_t(spg_t(pgid, shard))), and stores implement the
`ObjectStore` contract (queue_transactions / read / getattr / omap).

Backends: `MemStore` (the in-RAM store the reference's unit tests run
against, src/os/memstore/), `FileStore` (object data in flat files + a
log-structured KV for metadata — the FileStore-era design), and
`BlueStore` (the production engine: raw block space + bitmap extent
allocator + deferred-write WAL + per-block crc32c, src/os/bluestore/).
"""

from .bluestore import BlueStore, make_store
from .kv import FileKV, KeyValueDB, MemKV
from .memstore import MemStore
from .filestore import FileStore
from .objectstore import ObjectStore, StoreError
from .transaction import Transaction

__all__ = [
    "BlueStore",
    "FileKV",
    "FileStore",
    "KeyValueDB",
    "MemKV",
    "MemStore",
    "ObjectStore",
    "StoreError",
    "Transaction",
    "make_store",
]
