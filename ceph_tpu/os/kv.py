"""KeyValueDB — mirror of src/kv/KeyValueDB.h.

Reference: the abstraction BlueStore and the mon store sit on (RocksDB via
src/kv/RocksDBStore.h).  Two backends here: `MemKV` (sorted dict) and
`FileKV`, a log-structured persistent store — an append-only record log
replayed at open and compacted when garbage dominates, standing in for
RocksDB's WAL+SST mechanics at the scale this framework needs (mon
state, PG metadata, store metadata).
"""

from __future__ import annotations

import os
import struct
from typing import Iterator

from ..utils.crc32c import crc32c


class KeyValueDB:
    """get/set/rm over (prefix, key) pairs with ordered iteration
    (KeyValueDB.h Transaction/Iterator surface, flattened)."""

    def get(self, prefix: str, key: str) -> bytes | None:
        raise NotImplementedError

    def set(self, prefix: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def rm(self, prefix: str, key: str) -> None:
        raise NotImplementedError

    def iterate(self, prefix: str) -> Iterator[tuple[str, bytes]]:
        """Sorted (key, value) pairs under a prefix."""
        raise NotImplementedError

    def set_batch(self, prefix: str, kv: dict[str, bytes]) -> None:
        for k, v in kv.items():
            self.set(prefix, k, v)

    def apply_batch(self, ops: list[tuple[int, str, str, bytes]]) -> None:
        """Apply a batch of (op, prefix, key, value) with op 1=set, 2=rm.
        Durable backends make the whole batch atomic (a torn batch applies
        none of it) — the KeyValueDB::Transaction commit contract BlueStore
        relies on for its metadata commit point."""
        for op, prefix, key, value in ops:
            if op == 1:
                self.set(prefix, key, value)
            else:
                self.rm(prefix, key)

    def close(self) -> None:
        pass


class _DictKV(KeyValueDB):
    """Shared dict-backed read side for both backends."""

    def __init__(self) -> None:
        self._data: dict[tuple[str, str], bytes] = {}

    def get(self, prefix: str, key: str) -> bytes | None:
        return self._data.get((prefix, key))

    def iterate(self, prefix: str) -> Iterator[tuple[str, bytes]]:
        for (p, k) in sorted(self._data):
            if p == prefix:
                yield k, self._data[(p, k)]


class MemKV(_DictKV):
    def set(self, prefix: str, key: str, value: bytes) -> None:
        self._data[(prefix, key)] = bytes(value)

    def rm(self, prefix: str, key: str) -> None:
        self._data.pop((prefix, key), None)


# FileKV record: u8 op (1=set, 2=rm) | u32 klen | u32 vlen | key | value | crc32c
# op 3 = atomic batch: payload (in `value`) is a sequence of embedded
# records (same head layout, no per-record crc); one crc guards the whole
# batch, so a torn batch is discarded in full — never applied partially.
_HEAD = struct.Struct("<BII")


class FileKV(_DictKV):
    """Append-only log KV with replay-on-open and threshold compaction.

    Torn tails (a crash mid-append) are detected by the per-record crc
    and truncated away on open — the WAL property BlueFS/RocksDB provide
    the reference (SURVEY.md §5 checkpoint/resume).
    """

    COMPACT_RATIO = 4  # compact when log records > live keys * ratio

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._records = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._replay()
        self._f = open(self.path, "ab")

    def _replay(self) -> None:
        if not os.path.exists(self.path):
            return
        good_end = 0
        with open(self.path, "rb") as f:
            buf = f.read()
        off = 0
        while off + _HEAD.size <= len(buf):
            op, klen, vlen = _HEAD.unpack_from(buf, off)
            end = off + _HEAD.size + klen + vlen + 4
            if op not in (1, 2, 3) or end > len(buf):
                break
            rec = buf[off : end - 4]
            (crc,) = struct.unpack_from("<I", buf, end - 4)
            if crc32c(rec) != crc:
                break  # torn tail
            if op == 3:
                payload = buf[off + _HEAD.size + klen : end - 4]
                for sop, sprefix, sk, sval in self._iter_batch(payload):
                    if sop == 1:
                        self._data[(sprefix, sk)] = sval
                    else:
                        self._data.pop((sprefix, sk), None)
            else:
                key = buf[off + _HEAD.size : off + _HEAD.size + klen].decode()
                prefix, _, k = key.partition("\x00")
                if op == 1:
                    self._data[(prefix, k)] = buf[off + _HEAD.size + klen : end - 4]
                else:
                    self._data.pop((prefix, k), None)
            self._records += 1
            good_end = end
            off = end
        if good_end < len(buf):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)

    def _append(self, op: int, prefix: str, key: str, value: bytes) -> None:
        kb = f"{prefix}\x00{key}".encode()
        rec = _HEAD.pack(op, len(kb), len(value)) + kb + value
        self._f.write(rec + struct.pack("<I", crc32c(rec)))
        self._f.flush()
        os.fsync(self._f.fileno())
        self._records += 1
        if self._records > max(len(self._data), 16) * self.COMPACT_RATIO:
            self._compact()

    def _compact(self) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for (prefix, k), v in sorted(self._data.items()):
                kb = f"{prefix}\x00{k}".encode()
                rec = _HEAD.pack(1, len(kb), len(v)) + kb + v
                f.write(rec + struct.pack("<I", crc32c(rec)))
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        self._records = len(self._data)

    def set(self, prefix: str, key: str, value: bytes) -> None:
        self._data[(prefix, key)] = bytes(value)
        self._append(1, prefix, key, bytes(value))

    def rm(self, prefix: str, key: str) -> None:
        if (prefix, key) in self._data:
            del self._data[(prefix, key)]
            self._append(2, prefix, key, b"")

    @staticmethod
    def _iter_batch(payload: bytes):
        off = 0
        while off + _HEAD.size <= len(payload):
            op, klen, vlen = _HEAD.unpack_from(payload, off)
            end = off + _HEAD.size + klen + vlen
            if op not in (1, 2) or end > len(payload):
                break  # malformed embed; crc already vouched, be defensive
            key = payload[off + _HEAD.size : off + _HEAD.size + klen].decode()
            prefix, _, k = key.partition("\x00")
            yield op, prefix, k, payload[off + _HEAD.size + klen : end]
            off = end

    def apply_batch(self, ops: list[tuple[int, str, str, bytes]]) -> None:
        """Atomic multi-op commit: one op-3 record, one crc — a crash mid-
        append discards the entire batch on replay (the commit point for
        BlueStore metadata transactions)."""
        if not ops:
            return
        parts = []
        for op, prefix, key, value in ops:
            kb = f"{prefix}\x00{key}".encode()
            parts.append(_HEAD.pack(op, len(kb), len(value)) + kb + value)
            if op == 1:
                self._data[(prefix, key)] = bytes(value)
            else:
                self._data.pop((prefix, key), None)
        self._append(3, "", "", b"".join(parts))

    def close(self) -> None:
        self._f.close()
