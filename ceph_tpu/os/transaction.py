"""ObjectStore transactions — mirror of src/os/Transaction.{h,cc}.

Reference: a Transaction is a serialized op list applied atomically
(/root/reference/src/os/ObjectStore.h:232 queue_transactions; op codes in
Transaction.h OP_*).  ECTransaction encodes one of these per shard and
ships it inside ECSubWrite (src/osd/ECTransaction.cc:37-95 writing each
shard's chunk with alloc hints).

Ops are (code, coll, oid, args...) tuples; the encodable form rides
MOSDECSubOpWrite.txn.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..common.encoding import Decoder, Encodable, Encoder

# op codes (Transaction.h OP_* analog)
OP_TOUCH = 1
OP_WRITE = 2
OP_ZERO = 3
OP_TRUNCATE = 4
OP_REMOVE = 5
OP_SETATTR = 6
OP_RMATTR = 7
OP_OMAP_SETKEYS = 8
OP_OMAP_RMKEYS = 9
OP_MKCOLL = 10
OP_RMCOLL = 11
OP_CLONE = 12
OP_WRITE_APPEND = 13  # append-only fast path (EC shard writes)
OP_OMAP_CLEAR = 14

# alloc hints (ObjectStore.h CEPH_OSD_ALLOC_HINT_FLAG_*)
ALLOC_HINT_SEQUENTIAL_WRITE = 1
ALLOC_HINT_APPEND_ONLY = 2


@dataclass
class Op:
    code: int
    coll: str = ""
    oid: str = ""
    off: int = 0
    length: int = 0
    data: bytes = b""
    name: str = ""  # attr name / clone target
    keys: dict[str, bytes] = field(default_factory=dict)
    hints: int = 0
    # EC-transaction fusion (ISSUE 20): per-BLOCK crc32c of `data`
    # precomputed in the same offload launch window the chunk was
    # encoded in — an AggTicket (or array) resolving to uint32 digests,
    # consumed by BlueStore for block-aligned raw-stored writes.  A
    # process-local optimization hint only: NOT encoded (a decoded
    # transaction recomputes), never trusted for non-aligned or
    # compressed stores.
    csums: object = None


class Transaction(Encodable):
    """An atomic batch of mutations (Transaction-as-value)."""

    def __init__(self) -> None:
        self.ops: list[Op] = []

    def __len__(self) -> int:
        return len(self.ops)

    def empty(self) -> bool:
        return not self.ops

    # -- builders (Transaction.h API analog) ---------------------------------

    def touch(self, coll: str, oid: str) -> "Transaction":
        self.ops.append(Op(OP_TOUCH, coll, oid))
        return self

    def write(
        self,
        coll: str,
        oid: str,
        off: int,
        data: bytes,
        hints: int = 0,
        csums: object = None,
    ) -> "Transaction":
        self.ops.append(
            Op(
                OP_WRITE,
                coll,
                oid,
                off=off,
                length=len(data),
                data=bytes(data),
                hints=hints,
                csums=csums,
            )
        )
        return self

    def append(self, coll: str, oid: str, data: bytes) -> "Transaction":
        """EC shard chunk append (ECTransaction writes at
        logical_to_prev_chunk_offset with APPEND_ONLY hints)."""
        self.ops.append(
            Op(
                OP_WRITE_APPEND,
                coll,
                oid,
                length=len(data),
                data=bytes(data),
                hints=ALLOC_HINT_SEQUENTIAL_WRITE | ALLOC_HINT_APPEND_ONLY,
            )
        )
        return self

    def zero(self, coll: str, oid: str, off: int, length: int) -> "Transaction":
        self.ops.append(Op(OP_ZERO, coll, oid, off=off, length=length))
        return self

    def truncate(self, coll: str, oid: str, size: int) -> "Transaction":
        self.ops.append(Op(OP_TRUNCATE, coll, oid, off=size))
        return self

    def remove(self, coll: str, oid: str) -> "Transaction":
        self.ops.append(Op(OP_REMOVE, coll, oid))
        return self

    def setattr(self, coll: str, oid: str, name: str, value: bytes) -> "Transaction":
        self.ops.append(Op(OP_SETATTR, coll, oid, name=name, data=bytes(value)))
        return self

    def rmattr(self, coll: str, oid: str, name: str) -> "Transaction":
        self.ops.append(Op(OP_RMATTR, coll, oid, name=name))
        return self

    def omap_setkeys(self, coll: str, oid: str, keys: dict[str, bytes]) -> "Transaction":
        self.ops.append(Op(OP_OMAP_SETKEYS, coll, oid, keys=dict(keys)))
        return self

    def omap_rmkeys(self, coll: str, oid: str, keys: list[str]) -> "Transaction":
        self.ops.append(
            Op(OP_OMAP_RMKEYS, coll, oid, keys={k: b"" for k in keys})
        )
        return self

    def omap_clear(self, coll: str, oid: str) -> "Transaction":
        self.ops.append(Op(OP_OMAP_CLEAR, coll, oid))
        return self

    def create_collection(self, coll: str) -> "Transaction":
        self.ops.append(Op(OP_MKCOLL, coll))
        return self

    def remove_collection(self, coll: str) -> "Transaction":
        self.ops.append(Op(OP_RMCOLL, coll))
        return self

    def clone(self, coll: str, oid: str, target: str) -> "Transaction":
        self.ops.append(Op(OP_CLONE, coll, oid, name=target))
        return self

    def append_txn(self, other: "Transaction") -> "Transaction":
        """Transaction::append — merge another transaction's ops."""
        self.ops.extend(other.ops)
        return self

    # -- encoding ------------------------------------------------------------

    def encode(self, enc: Encoder) -> None:
        enc.start(1, 1)
        enc.list_(
            self.ops,
            lambda e, op: (
                e.u8(op.code),
                e.string(op.coll),
                e.string(op.oid),
                e.u64(op.off),
                e.u64(op.length),
                e.bytes_(op.data),
                e.string(op.name),
                e.map_(op.keys, lambda e2, k: e2.string(k), lambda e2, v: e2.bytes_(v)),
                e.u8(op.hints),
            ),
        )
        enc.finish()

    @classmethod
    def decode(cls, dec: Decoder) -> "Transaction":
        dec.start(1)
        t = cls()
        t.ops = dec.list_(
            lambda d: Op(
                code=d.u8(),
                coll=d.string(),
                oid=d.string(),
                off=d.u64(),
                length=d.u64(),
                data=d.bytes_(),
                name=d.string(),
                keys=d.map_(lambda d2: d2.string(), lambda d2: d2.bytes_()),
                hints=d.u8(),
            )
        )
        dec.finish()
        return t
