"""FileStore — a minimal persistent ObjectStore.

Stands in for BlueStore (SURVEY.md §2.6) at this framework's scale:
object data in flat files, xattrs/omap/collection metadata in a
log-structured FileKV, and a write-ahead journal giving transactions the
atomicity BlueStore gets from its WAL+RocksDB commit point
(/root/reference/src/os/bluestore/: deferred writes + kv commit).

Crash model: a transaction is journaled (fsync) before any file mutation;
on mount, journaled-but-unapplied transactions are replayed.  Appends are
resolved to absolute offsets *before* journaling so replay is idempotent
(every journaled op overwrites a range or is a remove/truncate).  A
transaction whose apply raises is treated as aborted: its journal entry
is dropped and the error propagates (the reference treats transaction
application failure as a fatal bug — ObjectStore.h "failure is not an
option").
"""

from __future__ import annotations

import os
from dataclasses import replace

from . import transaction as tx
from .kv import FileKV
from .objectstore import ObjectStore, StoreError
from .transaction import Transaction


def _enc(name: str) -> str:
    return name.encode("utf-8").hex()


class FileStore(ObjectStore):
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._kv = FileKV(os.path.join(path, "meta.kv"))
        self._journal = FileKV(os.path.join(path, "journal.kv"))
        self._journal_seq = 0
        self._replaying = False

    # -- lifecycle -----------------------------------------------------------

    def mount(self) -> None:
        """Replay unapplied journal entries (BlueStore deferred replay).
        A replay failure drops the entry rather than poisoning the mount —
        the entry was already applied or belongs to an aborted txn."""
        self._replaying = True
        try:
            for seq_key, txn_bytes in list(self._journal.iterate("txn")):
                txn = Transaction.frombytes(txn_bytes)
                try:
                    for op in txn.ops:
                        self._apply_op(op)
                except StoreError:
                    pass
                self._journal.rm("txn", seq_key)
        finally:
            self._replaying = False

    def umount(self) -> None:
        self._kv.close()
        self._journal.close()

    # -- transaction durability ----------------------------------------------

    def queue_transaction(self, txn: Transaction, on_commit=None) -> None:
        if txn.ops:
            # pre-journal write-fault seam, matching the other backends
            self._faultpoint("os.write", txn.ops[0].coll, txn.ops[0].oid)
        txn = self._resolve_appends(txn)
        self._journal_seq += 1
        key = f"{self._journal_seq:016d}"
        self._journal.set("txn", key, txn.tobytes())
        try:
            for op in txn.ops:
                self._apply_op(op)
        except StoreError:
            self._journal.rm("txn", key)  # aborted, not committed
            raise
        self._journal.rm("txn", key)
        if on_commit is not None:
            on_commit()

    def _resolve_appends(self, txn: Transaction) -> Transaction:
        """Rewrite OP_WRITE_APPEND to absolute-offset OP_WRITE so journal
        replay after a crash cannot double-append."""
        if not any(op.code == tx.OP_WRITE_APPEND for op in txn.ops):
            return txn
        sizes: dict[tuple[str, str], int] = {}
        out = Transaction()
        for op in txn.ops:
            if op.code == tx.OP_WRITE_APPEND:
                key = (op.coll, op.oid)
                if key not in sizes:
                    sizes[key] = self._size(op.coll, op.oid)
                op = replace(op, code=tx.OP_WRITE, off=sizes[key])
                sizes[key] += op.length
            elif op.code == tx.OP_TRUNCATE:
                sizes[(op.coll, op.oid)] = op.off
            elif op.code in (tx.OP_WRITE, tx.OP_ZERO):
                key = (op.coll, op.oid)
                if key in sizes:
                    sizes[key] = max(sizes[key], op.off + op.length)
            elif op.code == tx.OP_REMOVE:
                sizes[(op.coll, op.oid)] = 0
            out.ops.append(op)
        return out

    # -- paths ---------------------------------------------------------------

    def _cdir(self, coll: str) -> str:
        return os.path.join(self.path, "c_" + _enc(coll))

    def _opath(self, coll: str, oid: str) -> str:
        return os.path.join(self._cdir(coll), _enc(oid))

    def _require_coll(self, coll: str) -> str:
        d = self._cdir(coll)
        if not os.path.isdir(d):
            raise StoreError(2, f"collection {coll} does not exist")
        return d

    def _require_obj(self, coll: str, oid: str) -> str:
        self._require_coll(coll)
        p = self._opath(coll, oid)
        if not os.path.exists(p):
            raise StoreError(2, f"object {coll}/{oid} does not exist")
        return p

    # -- primitives ----------------------------------------------------------

    def _touch(self, coll: str, oid: str) -> None:
        self._require_coll(coll)
        open(self._opath(coll, oid), "ab").close()

    def _write(self, coll: str, oid: str, off: int, data: bytes) -> None:
        self._require_coll(coll)
        p = self._opath(coll, oid)
        with open(p, "r+b" if os.path.exists(p) else "w+b") as f:
            f.seek(0, 2)
            size = f.tell()
            if size < off:
                f.write(b"\x00" * (off - size))
            f.seek(off)
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    def _truncate(self, coll: str, oid: str, size: int) -> None:
        self._require_coll(coll)
        p = self._opath(coll, oid)
        with open(p, "r+b" if os.path.exists(p) else "w+b") as f:
            f.truncate(size)

    def _remove(self, coll: str, oid: str) -> None:
        p = self._opath(coll, oid)
        if os.path.exists(p):
            os.unlink(p)
        self._kv.rm("xattr", f"{coll}\x01{oid}")
        self._kv.rm("omap", f"{coll}\x01{oid}")

    def _attrs_key(self, coll: str, oid: str) -> str:
        return f"{coll}\x01{oid}"

    def _load_attrmap(self, prefix: str, coll: str, oid: str) -> dict[str, bytes]:
        raw = self._kv.get(prefix, self._attrs_key(coll, oid))
        if not raw:
            return {}
        from ..common.encoding import Decoder

        return Decoder(raw).map_(lambda d: d.string(), lambda d: d.bytes_())

    def _store_attrmap(
        self, prefix: str, coll: str, oid: str, attrs: dict[str, bytes]
    ) -> None:
        from ..common.encoding import Encoder

        enc = Encoder()
        enc.map_(attrs, lambda e, k: e.string(k), lambda e, v: e.bytes_(v))
        self._kv.set(prefix, self._attrs_key(coll, oid), enc.tobytes())

    def _setattr(self, coll: str, oid: str, name: str, value: bytes) -> None:
        self._touch(coll, oid)  # MemStore parity: create-on-setattr
        attrs = self._load_attrmap("xattr", coll, oid)
        attrs[name] = bytes(value)
        self._store_attrmap("xattr", coll, oid, attrs)

    def _rmattr(self, coll: str, oid: str, name: str) -> None:
        self._require_obj(coll, oid)
        attrs = self._load_attrmap("xattr", coll, oid)
        attrs.pop(name, None)
        self._store_attrmap("xattr", coll, oid, attrs)

    def _omap_set(self, coll: str, oid: str, keys: dict[str, bytes]) -> None:
        self._touch(coll, oid)
        omap = self._load_attrmap("omap", coll, oid)
        omap.update(keys)
        self._store_attrmap("omap", coll, oid, omap)

    def _omap_rm(self, coll: str, oid: str, keys) -> None:
        self._require_obj(coll, oid)
        omap = self._load_attrmap("omap", coll, oid)
        for k in keys:
            omap.pop(k, None)
        self._store_attrmap("omap", coll, oid, omap)

    def _mkcoll(self, coll: str) -> None:
        d = self._cdir(coll)
        if os.path.isdir(d):
            if not self._replaying:
                raise StoreError(17, f"collection {coll} exists")
            return
        os.makedirs(d)

    def _rmcoll(self, coll: str) -> None:
        d = self._cdir(coll)
        if os.path.isdir(d):
            for f in os.listdir(d):
                oid = bytes.fromhex(f).decode()
                self._kv.rm("xattr", self._attrs_key(coll, oid))
                self._kv.rm("omap", self._attrs_key(coll, oid))
                os.unlink(os.path.join(d, f))
            os.rmdir(d)

    def _clone(self, coll: str, oid: str, target: str) -> None:
        data = self.read(coll, oid)
        self._truncate(coll, target, 0)  # target becomes an exact copy
        self._write(coll, target, 0, data)
        self._store_attrmap(
            "xattr", coll, target, self._load_attrmap("xattr", coll, oid)
        )
        self._store_attrmap(
            "omap", coll, target, self._load_attrmap("omap", coll, oid)
        )

    # -- reads ---------------------------------------------------------------

    def read(self, coll: str, oid: str, off: int = 0, length: int = 0) -> bytes:
        p = self._require_obj(coll, oid)
        with open(p, "rb") as f:
            f.seek(off)
            return f.read() if length == 0 else f.read(length)

    def stat(self, coll: str, oid: str) -> int:
        return os.path.getsize(self._require_obj(coll, oid))

    def getattr(self, coll: str, oid: str, name: str) -> bytes:
        self._require_obj(coll, oid)
        attrs = self._load_attrmap("xattr", coll, oid)
        if name not in attrs:
            raise StoreError(61, f"no attr {name} on {coll}/{oid}")
        return attrs[name]

    def getattrs(self, coll: str, oid: str) -> dict[str, bytes]:
        self._require_obj(coll, oid)
        return self._load_attrmap("xattr", coll, oid)

    def omap_get(self, coll: str, oid: str) -> dict[str, bytes]:
        self._require_obj(coll, oid)
        return self._load_attrmap("omap", coll, oid)

    def list_objects(self, coll: str) -> list[str]:
        d = self._require_coll(coll)
        return sorted(bytes.fromhex(f).decode() for f in os.listdir(d))

    def count_objects(self, coll: str) -> int:
        # no decode/sort — one readdir, for stat polling
        return len(os.listdir(self._require_coll(coll)))

    def list_collections(self) -> list[str]:
        out = []
        for d in os.listdir(self.path):
            if d.startswith("c_"):
                out.append(bytes.fromhex(d[2:]).decode())
        return sorted(out)
