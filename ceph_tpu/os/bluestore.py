"""BlueStore-lite — block-oriented object store: allocator + WAL + checksums.

The TPU-framework re-design of the reference's production storage engine
(/root/reference/src/os/bluestore/BlueStore.cc; 19.6k LoC there, scoped
here to the triad that defines the design):

- **Raw block space + extent allocator.**  Object data lives in a single
  flat block file carved into `BLOCK` (4 KiB) units handed out by a
  bitmap allocator (src/os/bluestore/BitmapAllocator.h).  There is no
  per-object file: an object is an onode (metadata record in the KV DB)
  pointing at physical extents.  The free list is rebuilt at mount by
  scanning onodes + pending WAL — the authoritative-metadata recovery
  BlueStore's FreelistManager formalizes.
- **Two write paths** (BlueStore::_do_write big/small split):
  *COW direct* — writes that allocate (new blocks, or large overwrites)
  go to freshly allocated blocks, fsync'd BEFORE the metadata commit;
  a crash leaves the new blocks unreferenced and the old state intact.
  *Deferred WAL* — small overwrites of already-allocated blocks ride the
  metadata commit as WAL records (bluestore_deferred_transaction_t) and
  are applied to the block file after commit; mount replays unapplied
  records (idempotent whole-slot images — BLOCK bytes raw, or the
  block's clen-byte compressed form).
- **Per-block checksums** (BlueStore csum_type=crc32c, per csum-block):
  every stored block carries a crc32c in the onode extent map computed
  over the STORED form (compressed or raw), verified on every read
  before any decompression; a flipped bit in the block file surfaces
  as EIO instead of silent corruption.
- **Blob compression** (BlueStore _do_alloc_write compression): with
  bluestore_compression_algorithm set, a block image is stored
  compressed when it beats bluestore_compression_required_ratio; the
  onode entry records the stored length.
- **Metadata in the KV DB** (RocksDB in the reference, FileKV here):
  onodes, collections, and WAL records commit in ONE atomic batch
  (KeyValueDB::Transaction) — the transaction's commit point.

Logical layout: block index `i` of an object maps to one physical block
slot; the in-memory map is {block_index: (phys_off, crc, clen)} — clen 0
for a raw BLOCK, else the compressed stored length — and serializes as
runs.  Every write replaces a block's WHOLE stored image (read-modify-
write at block granularity), so WAL replay needs no byte-level merging.
Bytes at logical offsets >= the object size are undefined-on-disk but
never observable: reads clamp to size and overlays treat them as zeros
(hole semantics).
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass, field

from ..utils.crc32c import crc32c
from .kv import FileKV, KeyValueDB, MemKV
from .objectstore import ObjectStore, StoreError
from .transaction import OP_WRITE, Transaction

BLOCK = 4096
# Overwrites up to this many bytes take the deferred-WAL path
# (bluestore_prefer_deferred_size).
DEFERRED_MAX = 64 * 1024
# Initial block-file capacity; grows on demand (the reference sizes the
# device up front; a dev-store grows like BlueStore-on-file).
INITIAL_BLOCKS = 1024

_ONODE = "O"  # onode records:      key "<coll>\x00<oid>"
_COLL = "C"   # collection markers: key "<coll>"
_WAL = "W"    # deferred writes:    key "<seq:016x>", value u64 poff + image


class SimulatedCrash(RuntimeError):
    """Raised by the crash-injection test seam (_crash_point)."""


@dataclass
class Onode:
    size: int = 0
    # logical block index -> (physical byte offset, crc32c of STORED
    # bytes, stored length).  clen == 0 means a raw BLOCK; clen > 0 means
    # the slot holds clen bytes compressed with the store's algorithm
    # (BlueStore blob compression, scoped to one block per blob).
    blocks: dict[int, tuple[int, int, int]] = field(default_factory=dict)
    xattrs: dict[str, bytes] = field(default_factory=dict)
    omap: dict[str, bytes] = field(default_factory=dict)

    def encode(self) -> bytes:
        runs = []
        for bidx in sorted(self.blocks):
            poff, crc, clen = self.blocks[bidx]
            if runs and runs[-1][0] + len(runs[-1][2]) == bidx and runs[-1][1] + len(
                runs[-1][2]
            ) * BLOCK == poff:
                runs[-1][2].append(crc)
                runs[-1][3].append(clen)
            else:
                runs.append([bidx, poff, [crc], [clen]])
        return json.dumps(
            {
                "size": self.size,
                "runs": runs,
                "xattrs": {k: v.hex() for k, v in self.xattrs.items()},
                "omap": {k: v.hex() for k, v in self.omap.items()},
            }
        ).encode()

    @classmethod
    def decode(cls, blob: bytes) -> "Onode":
        info = json.loads(blob.decode())
        o = cls(size=info["size"])
        for run in info["runs"]:
            bidx, poff, crcs = run[0], run[1], run[2]
            clens = run[3] if len(run) > 3 else [0] * len(crcs)
            for i, crc in enumerate(crcs):
                o.blocks[bidx + i] = (poff + i * BLOCK, crc, clens[i])
        o.xattrs = {k: bytes.fromhex(v) for k, v in info["xattrs"].items()}
        o.omap = {k: bytes.fromhex(v) for k, v in info["omap"].items()}
        return o


class BitmapAllocator:
    """Free-block bitmap (BitmapAllocator): first-fit run allocation."""

    def __init__(self, n_blocks: int):
        self.free = [True] * n_blocks
        self._hint = 0

    def grow(self, n_blocks: int) -> None:
        self.free.extend([True] * n_blocks)

    def mark_used(self, block: int) -> None:
        while block >= len(self.free):  # device grown by a previous life
            self.grow(INITIAL_BLOCKS)
        self.free[block] = False

    def release(self, block: int) -> None:
        self.free[block] = True
        self._hint = min(self._hint, block)

    def allocate(self, count: int) -> list[int] | None:
        """`count` block indices (not necessarily contiguous), or None."""
        out = []
        i = self._hint
        n = len(self.free)
        scanned_from_start = self._hint == 0
        while len(out) < count:
            if i >= n:
                if scanned_from_start:
                    return None
                i, n = 0, self._hint  # wrap to the region before the hint
                scanned_from_start = True
                continue
            if self.free[i]:
                out.append(i)
            i += 1
        for b in out:
            self.free[b] = False
        self._hint = out[-1] + 1 if out else self._hint
        return out

    def num_free(self) -> int:
        return sum(self.free)


def make_store(conf) -> ObjectStore:
    """Instantiate the configured backend (`osd_objectstore` +
    `osd_data`), the ceph-osd --mkfs/boot store selection."""
    from .filestore import FileStore
    from .memstore import MemStore

    kind = conf.get("osd_objectstore")
    data = conf.get("osd_data")
    if kind == "bluestore":
        return BlueStore(
            data or None,
            compression=conf.get("bluestore_compression_algorithm"),
            compression_required_ratio=conf.get(
                "bluestore_compression_required_ratio"
            ),
            csum_offload=bool(conf.get("bluestore_csum_offload")),
        )
    if kind == "filestore":
        if not data:
            raise ValueError("filestore requires osd_data")
        return FileStore(data)
    return MemStore()


class BlueStore(ObjectStore):
    """dir/ holds `block` (flat data file) and `kv` (FileKV metadata)."""

    def __init__(
        self,
        path: str | None = None,
        compression: str = "none",
        compression_required_ratio: float = 0.875,
        csum_offload: bool = False,
    ):
        from ..compressor import get_compressor

        self.path = path
        # blob compression (BlueStore _do_alloc_write compression path):
        # a block is stored compressed only when it shrinks below the
        # required ratio; csums always cover the stored form
        self._compressor = get_compressor(compression or "none")
        self._required_ratio = compression_required_ratio
        # device checksum offload (bluestore_csum_offload): large writes
        # and read-verify sweeps batch their per-block crc32c through the
        # shared offload runtime instead of the host table loop
        self._csum_offload = bool(csum_offload)
        # identical-content overwrites whose stored form was provably
        # unchanged (store-form + csum + block write all elided)
        self.csum_compute_skips = 0
        # blocks whose stored csum came from an EC-transaction-fused
        # digest (computed in the encode's launch window, not here)
        self.csum_fused_blocks = 0
        self.db: KeyValueDB = MemKV() if path is None else None  # set at mount
        self._block_f = None
        self.alloc = BitmapAllocator(INITIAL_BLOCKS)
        self._onodes: dict[tuple[str, str], Onode] = {}  # cache (loaded lazily)
        self._colls: set[str] = set()
        self._obj_count: dict[str, int] = {}
        self._wal_seq = 0
        # per-transaction staging
        self._batch: list[tuple[int, str, str, bytes]] = []
        self._dirty: set[tuple[str, str]] = set()
        self._direct: list[tuple[int, bytes]] = []   # (poff, image) pre-commit
        self._deferred: list[tuple[int, bytes]] = [] # (poff, image) post-commit
        # staged images readable before they hit the block file (so e.g. a
        # clone after a write in the same transaction sees the new bytes)
        self._staged: dict[int, bytes] = {}
        # frees take effect only after the commit point: a failed staging
        # must never let a still-referenced block be re-allocated
        self._to_release: list[int] = []
        # objects deleted in the staged txn: their (not yet batch-applied)
        # KV records must not resurrect through the db.get fallback
        self._staged_rm: set[tuple[str, str]] = set()
        self._crash_point: str | None = None  # crash-injection test seam

    def _store_form(self, image: bytes) -> tuple[bytes, int]:
        """(stored bytes, clen) for a full-block image: the compressed
        form when the algorithm is on AND it beats the required ratio
        (bluestore_compression_required_ratio), else the raw block
        (clen 0)."""
        if self._compressor.name == "none":
            return image, 0
        comp = self._compressor.compress(image)
        if len(comp) <= int(BLOCK * self._required_ratio):
            return comp, len(comp)
        return image, 0

    def set_csum_offload(self, enabled: bool) -> None:
        """Runtime observer target for `bluestore_csum_offload`."""
        self._csum_offload = bool(enabled)

    def _store_forms(self, images: list[bytes]) -> list[tuple[bytes, int]]:
        """Batched `_store_form`: compressors exposing `compress_batch`
        (the device plugin) get ONE call for the whole block range so
        their transforms coalesce into shared offload launches; the
        required-ratio gate is applied per block exactly as in the
        scalar path."""
        if not images:
            return []
        if self._compressor.name == "none":
            return [(img, 0) for img in images]
        batch = getattr(self._compressor, "compress_batch", None)
        if batch is not None:
            comps = batch(images)
        else:
            comps = [self._compressor.compress(img) for img in images]
        limit = int(BLOCK * self._required_ratio)
        return [
            (comp, len(comp)) if len(comp) <= limit else (img, 0)
            for img, comp in zip(images, comps)
        ]

    def _csum_batch(self, stored: list[bytes]) -> list[int]:
        """crc32c over a batch of stored forms — one offload-runtime
        submission per stored-length group when the knob is armed, else
        the host table loop (byte-identical either way)."""
        if self._csum_offload:
            from ..ops.checksum_offload import checksum_blocks

            return checksum_blocks(stored, offload=True)
        return [crc32c(s) for s in stored]

    # -- mount / umount --------------------------------------------------------

    def mount(self) -> None:
        if self.path is None:
            if self._block_f is None:
                import io

                self._block_f = io.BytesIO()
                self.db = MemKV()
            return
        os.makedirs(self.path, exist_ok=True)
        self.db = FileKV(os.path.join(self.path, "kv"))
        bpath = os.path.join(self.path, "block")
        if not os.path.exists(bpath):
            with open(bpath, "wb") as f:
                f.truncate(INITIAL_BLOCKS * BLOCK)
        self._block_f = open(bpath, "r+b")
        n_blocks = os.path.getsize(bpath) // BLOCK
        self.alloc = BitmapAllocator(n_blocks)
        self._colls = {k for k, _ in self.db.iterate(_COLL)}
        self._obj_count = dict.fromkeys(self._colls, 0)
        # Authoritative free list: every block referenced by an onode is
        # used (FreelistManager rebuild).
        for key, blob in self.db.iterate(_ONODE):
            coll = key.partition("\x00")[0]
            self._obj_count[coll] = self._obj_count.get(coll, 0) + 1
            o = Onode.decode(blob)
            for poff, _crc, _cl in o.blocks.values():
                self.alloc.mark_used(poff // BLOCK)
        # Replay deferred writes that committed but may not have reached
        # the block file (BlueStore::_deferred_replay).  Idempotent: each
        # record is a full-block image.
        replayed = []
        for key, val in list(self.db.iterate(_WAL)):
            (poff,) = struct.unpack_from("<Q", val)
            image = val[8:]
            self.alloc.mark_used(poff // BLOCK)
            self._block_write(poff, image)
            self._wal_seq = max(self._wal_seq, int(key, 16) + 1)
            replayed.append(key)
        self._block_sync()
        self.db.apply_batch([(2, _WAL, key, b"") for key in replayed])

    def umount(self) -> None:
        if self._block_f is not None and self.path is not None:
            self._block_f.close()
            self._block_f = None
        if self.db is not None and self.path is not None:
            self.db.close()
        self._onodes.clear()

    # -- block file ------------------------------------------------------------

    def _block_write(self, poff: int, data: bytes) -> None:
        self._block_f.seek(poff)
        self._block_f.write(data)

    def _block_read(self, poff: int, length: int) -> bytes:
        self._block_f.seek(poff)
        return self._block_f.read(length)

    def _block_sync(self) -> None:
        if self.path is not None:
            self._block_f.flush()
            os.fsync(self._block_f.fileno())

    def _ensure_capacity(self, nblocks: int) -> list[int]:
        got = self.alloc.allocate(nblocks)
        if got is not None:
            return got
        grow = max(INITIAL_BLOCKS, nblocks)
        old = len(self.alloc.free)
        self.alloc.grow(grow)
        if self.path is not None:
            self._block_f.seek(0, 2)
        # extend the file lazily; writes past EOF grow it
        got = self.alloc.allocate(nblocks)
        assert got is not None, (old, grow, nblocks)
        return got

    # -- onode access ----------------------------------------------------------

    @staticmethod
    def _okey(coll: str, oid: str) -> str:
        return f"{coll}\x00{oid}"

    def _get_onode(self, coll: str, oid: str, create: bool = False) -> Onode:
        if coll not in self._colls:
            raise StoreError(2, f"no collection {coll}")
        ck = (coll, oid)
        o = self._onodes.get(ck)
        if o is None and ck not in self._staged_rm:
            blob = self.db.get(_ONODE, self._okey(coll, oid))
            if blob is not None:
                o = Onode.decode(blob)
                self._onodes[ck] = o
        if o is None:
            if not create:
                raise StoreError(2, f"no object {coll}/{oid}")
            o = Onode()
            self._onodes[ck] = o
            self._staged_rm.discard(ck)
            self._obj_count[coll] = self._obj_count.get(coll, 0) + 1
        self._dirty.add(ck)
        return o

    # -- transaction application ----------------------------------------------

    def queue_transaction(self, txn: Transaction, on_commit=None) -> None:
        """Stage every op, then commit in BlueStore's order: direct data →
        fsync → one atomic KV batch (the commit point) → deferred WAL
        application → WAL cleanup (BlueStore::_txc_state_proc)."""
        if txn.ops:
            # same pre-apply seam as the base class: an injected write
            # fault fails the transaction whole, before staging
            self._faultpoint("os.write", txn.ops[0].coll, txn.ops[0].oid)
        self._batch, self._dirty = [], set()
        self._direct, self._deferred = [], []
        self._staged, self._to_release = {}, []
        self._staged_rm = set()
        colls_snap, counts_snap = set(self._colls), dict(self._obj_count)
        try:
            for op in txn.ops:
                self._apply_op(op)
        except Exception:
            self._colls, self._obj_count = colls_snap, counts_snap
            # caller bug (ObjectStore "failure is not an option"): drop the
            # staged txn; committed state is untouched.  Blocks allocated
            # during staging stay marked used (leaked until the next mount's
            # free-list rebuild) — safe over clever.
            self._reload_dirty()
            raise
        for poff, image in self._direct:
            self._block_write(poff, image)
        if self._direct:
            self._block_sync()
        for ck in self._dirty:
            coll, oid = ck
            o = self._onodes.get(ck)
            if o is not None:
                self._batch.append((1, _ONODE, self._okey(coll, oid), o.encode()))
        wal_keys = []
        for poff, image in self._deferred:
            key = f"{self._wal_seq:016x}"
            self._wal_seq += 1
            wal_keys.append(key)
            self._batch.append((1, _WAL, key, struct.pack("<Q", poff) + image))
        self.db.apply_batch(self._batch)  # ← commit point
        if self._crash_point == "after_commit":
            # test seam: a power cut between the KV commit and the deferred
            # block-file application — mount-time WAL replay must finish the
            # job (the crash window BlueStore's deferred_replay covers)
            raise SimulatedCrash("after_commit")
        for poff, image in self._deferred:
            self._block_write(poff, image)
        if self._deferred:
            self._block_sync()
            # one atomic (single-fsync) cleanup record, not N appends
            self.db.apply_batch([(2, _WAL, key, b"") for key in wal_keys])
        for blk in self._to_release:
            self.alloc.release(blk)
        self._batch, self._dirty = [], set()
        self._direct, self._deferred = [], []
        self._staged, self._to_release = {}, []
        self._staged_rm = set()
        if on_commit is not None:
            on_commit()

    def _reload_dirty(self) -> None:
        for ck in self._dirty:
            self._onodes.pop(ck, None)
        self._dirty.clear()
        self._batch, self._direct, self._deferred = [], [], []
        self._staged, self._to_release = {}, []
        self._staged_rm = set()

    # -- primitives ------------------------------------------------------------

    def _touch(self, coll: str, oid: str) -> None:
        self._get_onode(coll, oid, create=True)

    def _logical_block(self, o: Onode, bidx: int) -> bytes:
        """Stored content of logical block `bidx`, crc-verified; zeros for
        holes.  Bytes beyond o.size are NOT masked here (callers clamp)."""
        ent = o.blocks.get(bidx)
        if ent is None:
            return b"\x00" * BLOCK
        poff, crc, clen = ent
        stored = self._staged.get(poff)
        if stored is None:
            # _block_read returns at most the requested bytes; a short raw
            # read (lazily-grown file) zero-pads, a short compressed read
            # is caught by the crc below
            stored = self._block_read(poff, clen or BLOCK)
            if not clen and len(stored) < BLOCK:
                stored = stored + b"\x00" * (BLOCK - len(stored))  # lazy file
        # csum covers the STORED bytes (compressed or raw), so corruption
        # is caught before decompression can amplify it
        if crc32c(stored) != crc:
            raise StoreError(5, f"csum mismatch at block {bidx} (poff {poff})")
        if clen:
            return self._compressor.decompress(stored)
        return stored

    def _valid_block(self, o: Onode, bidx: int) -> bytes:
        """Block content with bytes at logical offsets >= size zeroed —
        the overlay source for read-modify-write."""
        data = self._logical_block(o, bidx)
        end = o.size - bidx * BLOCK
        if end <= 0:
            return b"\x00" * BLOCK
        if end < BLOCK:
            return data[:end] + b"\x00" * (BLOCK - end)
        return data

    def _write(
        self, coll: str, oid: str, off: int, data: bytes, csums=None
    ) -> None:
        """`csums` (EC-transaction fusion): per-BLOCK crc32c of `data`,
        precomputed in the encode's offload launch window — an AggTicket
        or uint32 array, trusted only for block-aligned writes whose
        stored form stays raw (stored bytes == image bytes)."""
        if not data:
            self._get_onode(coll, oid, create=True)
            return
        o = self._get_onode(coll, oid, create=True)
        b0, b1 = off // BLOCK, (off + len(data) - 1) // BLOCK
        # Assemble full-block images for the affected range, keeping the
        # pre-overlay content of live blocks for the unchanged-skip check.
        images: dict[int, bytearray] = {}
        orig: dict[int, bytes] = {}
        for b in range(b0, b1 + 1):
            prev = self._valid_block(o, b)
            if b in o.blocks:
                orig[b] = prev
            images[b] = bytearray(prev)
        cur = off
        dpos = 0
        while dpos < len(data):
            b = cur // BLOCK
            boff = cur % BLOCK
            n = min(BLOCK - boff, len(data) - dpos)
            images[b][boff : boff + n] = data[dpos : dpos + n]
            cur += n
            dpos += n
        # Identical-content overwrite: a live block entirely below the
        # current size whose image is unchanged keeps its stored form,
        # csum, and physical slot — nothing to recompute or rewrite.
        # (Blocks straddling o.size are never skipped: their stored tail
        # bytes may be stale, and a size extension would expose them.)
        skip = {
            b
            for b in images
            if b in orig
            and (b + 1) * BLOCK <= o.size
            and bytes(images[b]) == orig[b]
        }
        self.csum_compute_skips += len(skip)
        todo = [b for b in sorted(images) if b not in skip]
        all_mapped = all(b in o.blocks for b in images)
        # One batched store-form + one batched csum pass for the whole
        # range (the device compressor / csum service coalesce these
        # into shared offload launches when armed).
        forms = self._store_forms([bytes(images[b]) for b in todo])
        crcs = [0] * len(todo)
        pre = None
        if csums is not None and off % BLOCK == 0 and len(data) % BLOCK == 0:
            pre = csums.result() if hasattr(csums, "result") else csums
        need = []
        for i, b in enumerate(todo):
            if pre is not None and forms[i][1] == 0:
                # raw-stored fully-overwritten block: the fused digest
                # covers exactly the stored bytes
                crcs[i] = int(pre[b - b0])
                self.csum_fused_blocks += 1
            else:
                need.append(i)
        if need:
            digs = self._csum_batch([forms[i][0] for i in need])
            for i, dig in zip(need, digs):
                crcs[i] = dig
        if all_mapped and len(data) <= DEFERRED_MAX:
            # deferred WAL overwrite in place
            for i, b in enumerate(todo):
                poff = o.blocks[b][0]
                stored, clen = forms[i]
                o.blocks[b] = (poff, crcs[i], clen)
                self._deferred.append((poff, stored))
                self._staged[poff] = stored
        else:
            # COW: fresh blocks for the (non-skipped) affected range
            newblocks = self._ensure_capacity(len(todo))
            for i, (b, nb) in enumerate(zip(todo, newblocks)):
                old = o.blocks.get(b)
                if old is not None:
                    self._to_release.append(old[0] // BLOCK)
                stored, clen = forms[i]
                o.blocks[b] = (nb * BLOCK, crcs[i], clen)
                self._direct.append((nb * BLOCK, stored))
                self._staged[nb * BLOCK] = stored
        o.size = max(o.size, off + len(data))

    def _apply_op(self, op) -> None:
        # thread the fused-csum hint through to _write; every other op
        # takes the shared application loop
        if op.code == OP_WRITE and getattr(op, "csums", None) is not None:
            self._write(op.coll, op.oid, op.off, op.data, csums=op.csums)
            return
        super()._apply_op(op)

    def _truncate(self, coll: str, oid: str, size: int) -> None:
        o = self._get_onode(coll, oid, create=True)
        if size < o.size:
            keep = (size + BLOCK - 1) // BLOCK
            for b in [b for b in o.blocks if b >= keep]:
                self._to_release.append(o.blocks.pop(b)[0] // BLOCK)
            o.size = size
            # Scrub the kept partial block: a later size extension that
            # never rewrites this block (truncate up, or a write landing in
            # a different block) must read zeros here, not pre-truncate
            # bytes.
            tail = size % BLOCK
            b = size // BLOCK
            if tail and b in o.blocks:
                image = self._logical_block(o, b)[:tail] + b"\x00" * (BLOCK - tail)
                poff = o.blocks[b][0]
                stored, clen = self._store_form(image)
                o.blocks[b] = (poff, crc32c(stored), clen)
                self._deferred.append((poff, stored))
                self._staged[poff] = stored
        o.size = size

    def _remove(self, coll: str, oid: str) -> None:
        """Idempotent like MemStore/FileStore: recovery's push handler and
        the objectstore tool remove-before-recreate unconditionally."""
        if coll not in self._colls:
            raise StoreError(2, f"no collection {coll}")
        ck = (coll, oid)
        try:
            o = self._get_onode(coll, oid)
        except StoreError:
            return
        for poff, _crc, _cl in o.blocks.values():
            self._to_release.append(poff // BLOCK)
        self._onodes.pop(ck, None)
        self._dirty.discard(ck)
        self._staged_rm.add(ck)
        self._obj_count[coll] -= 1
        self._batch.append((2, _ONODE, self._okey(coll, oid), b""))

    def _setattr(self, coll: str, oid: str, name: str, value: bytes) -> None:
        self._get_onode(coll, oid, create=True).xattrs[name] = bytes(value)

    def _rmattr(self, coll: str, oid: str, name: str) -> None:
        self._get_onode(coll, oid).xattrs.pop(name, None)

    def _omap_set(self, coll: str, oid: str, keys: dict[str, bytes]) -> None:
        o = self._get_onode(coll, oid, create=True)
        for k, v in keys.items():
            o.omap[k] = bytes(v)

    def _omap_rm(self, coll: str, oid: str, keys) -> None:
        o = self._get_onode(coll, oid)
        for k in keys:
            o.omap.pop(k, None)

    def _mkcoll(self, coll: str) -> None:
        if coll in self._colls:
            raise StoreError(17, f"collection {coll} exists")  # EEXIST
        self._colls.add(coll)
        self._obj_count.setdefault(coll, 0)
        self._batch.append((1, _COLL, coll, b""))

    def _rmcoll(self, coll: str) -> None:
        if coll not in self._colls:
            raise StoreError(2, f"no collection {coll}")
        for oid in self.list_objects(coll):
            self._remove(coll, oid)
        self._colls.discard(coll)
        self._obj_count.pop(coll, None)
        self._batch.append((2, _COLL, coll, b""))

    def _clone(self, coll: str, src: str, dst: str) -> None:
        data = self.read(coll, src, 0, 0)
        # reset target, then write through the normal (COW) path
        d = self._get_onode(coll, dst, create=True)
        for poff, _crc, _cl in d.blocks.values():
            self._to_release.append(poff // BLOCK)
        d.blocks.clear()
        d.size = 0
        src_o = self._get_onode(coll, src)
        d.xattrs = dict(src_o.xattrs)
        d.omap = dict(src_o.omap)
        if data:
            self._write(coll, dst, 0, data)

    # -- reads -----------------------------------------------------------------

    def read(self, coll: str, oid: str, off: int = 0, length: int = 0) -> bytes:
        self._faultpoint("os.read", coll, oid)
        o = self._peek_onode(coll, oid)
        end = o.size if length == 0 else min(off + length, o.size)
        if off >= end:
            return b""
        b_first, b_last = off // BLOCK, (end - 1) // BLOCK
        blocks = self._logical_blocks(o, b_first, b_last)
        parts = []
        cur = off
        for b in range(b_first, b_last + 1):
            lo = cur - b * BLOCK
            hi = min(BLOCK, end - b * BLOCK)
            parts.append(blocks[b - b_first][lo:hi])
            cur = (b + 1) * BLOCK
        return b"".join(parts)

    def _logical_blocks(
        self, o: Onode, b_first: int, b_last: int
    ) -> list[bytes]:
        """`_logical_block` over a contiguous range with ONE batched
        verification-csum pass: when csum offload is armed the whole
        range's stored forms ride the offload runtime (grouped by stored
        length) instead of one host crc per block.  Holes read zeros;
        a digest mismatch raises the same EIO as the scalar path."""
        out: list[bytes | None] = [None] * (b_last - b_first + 1)
        mapped: list[tuple[int, int, int, int, int, bytes]] = []
        for b in range(b_first, b_last + 1):
            ent = o.blocks.get(b)
            if ent is None:
                out[b - b_first] = b"\x00" * BLOCK
                continue
            poff, crc, clen = ent
            stored = self._staged.get(poff)
            if stored is None:
                stored = self._block_read(poff, clen or BLOCK)
                if not clen and len(stored) < BLOCK:
                    stored = stored + b"\x00" * (BLOCK - len(stored))
            mapped.append((b - b_first, b, poff, crc, clen, stored))
        if mapped:
            digs = self._csum_batch([m[5] for m in mapped])
            for (idx, bidx, poff, crc, clen, stored), dig in zip(mapped, digs):
                if dig != crc:
                    raise StoreError(
                        5, f"csum mismatch at block {bidx} (poff {poff})"
                    )
                out[idx] = (
                    self._compressor.decompress(stored) if clen else stored
                )
        return out

    def _peek_onode(self, coll: str, oid: str) -> Onode:
        """Read-side onode lookup: no create, no dirty-marking."""
        if coll not in self._colls:
            raise StoreError(2, f"no collection {coll}")
        ck = (coll, oid)
        o = self._onodes.get(ck)
        if o is None:
            if ck in self._staged_rm:
                raise StoreError(2, f"no object {coll}/{oid}")
            blob = self.db.get(_ONODE, self._okey(coll, oid))
            if blob is None:
                raise StoreError(2, f"no object {coll}/{oid}")
            o = Onode.decode(blob)
            self._onodes[ck] = o
        return o

    def stat(self, coll: str, oid: str) -> int:
        return self._peek_onode(coll, oid).size

    def getattr(self, coll: str, oid: str, name: str) -> bytes:
        o = self._peek_onode(coll, oid)
        if name not in o.xattrs:
            raise StoreError(61, f"no attr {name}")  # ENODATA
        return o.xattrs[name]

    def getattrs(self, coll: str, oid: str) -> dict[str, bytes]:
        return dict(self._peek_onode(coll, oid).xattrs)

    def omap_get(self, coll: str, oid: str) -> dict[str, bytes]:
        return dict(self._peek_onode(coll, oid).omap)

    def list_objects(self, coll: str) -> list[str]:
        if coll not in self._colls:
            raise StoreError(2, f"no collection {coll}")
        out = set()
        prefix = f"{coll}\x00"
        for key, _ in self.db.iterate(_ONODE):
            if key.startswith(prefix):
                out.add(key[len(prefix):])
        for (c, oid) in self._onodes:
            if c == coll:
                out.add(oid)
        # cached-but-removed are impossible: _remove drops the cache entry
        return sorted(out)

    def count_objects(self, coll: str) -> int:
        if coll not in self._colls:
            raise StoreError(2, f"no collection {coll}")
        return self._obj_count.get(coll, 0)

    def list_collections(self) -> list[str]:
        return sorted(self._colls)
