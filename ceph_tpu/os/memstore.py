"""MemStore — mirror of src/os/memstore/MemStore.{h,cc}.

The in-RAM backend the reference's ObjectStore unit tests run against
(SURVEY.md §2.6); same role here: fast, deterministic storage for OSD
and EC-backend tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .objectstore import ObjectStore, StoreError


@dataclass
class _Object:
    data: bytearray = field(default_factory=bytearray)
    xattrs: dict[str, bytes] = field(default_factory=dict)
    omap: dict[str, bytes] = field(default_factory=dict)


class MemStore(ObjectStore):
    def __init__(self) -> None:
        self._colls: dict[str, dict[str, _Object]] = {}

    # -- primitives ----------------------------------------------------------

    def _coll(self, coll: str) -> dict[str, _Object]:
        c = self._colls.get(coll)
        if c is None:
            raise StoreError(2, f"collection {coll} does not exist")
        return c

    def _obj(self, coll: str, oid: str, create: bool = False) -> _Object:
        c = self._coll(coll)
        o = c.get(oid)
        if o is None:
            if not create:
                raise StoreError(2, f"object {coll}/{oid} does not exist")
            o = c[oid] = _Object()
        return o

    def _touch(self, coll: str, oid: str) -> None:
        self._obj(coll, oid, create=True)

    def _write(self, coll: str, oid: str, off: int, data: bytes) -> None:
        o = self._obj(coll, oid, create=True)
        end = off + len(data)
        if len(o.data) < end:
            o.data.extend(b"\x00" * (end - len(o.data)))
        o.data[off:end] = data

    def _truncate(self, coll: str, oid: str, size: int) -> None:
        o = self._obj(coll, oid, create=True)
        if len(o.data) > size:
            del o.data[size:]
        else:
            o.data.extend(b"\x00" * (size - len(o.data)))

    def _remove(self, coll: str, oid: str) -> None:
        self._coll(coll).pop(oid, None)

    def _setattr(self, coll: str, oid: str, name: str, value: bytes) -> None:
        self._obj(coll, oid, create=True).xattrs[name] = bytes(value)

    def _rmattr(self, coll: str, oid: str, name: str) -> None:
        self._obj(coll, oid).xattrs.pop(name, None)

    def _omap_set(self, coll: str, oid: str, keys: dict[str, bytes]) -> None:
        self._obj(coll, oid, create=True).omap.update(keys)

    def _omap_rm(self, coll: str, oid: str, keys) -> None:
        omap = self._obj(coll, oid).omap
        for k in keys:
            omap.pop(k, None)

    def _mkcoll(self, coll: str) -> None:
        if coll in self._colls:
            raise StoreError(17, f"collection {coll} exists")
        self._colls[coll] = {}

    def _rmcoll(self, coll: str) -> None:
        self._colls.pop(coll, None)

    def _clone(self, coll: str, oid: str, target: str) -> None:
        src = self._obj(coll, oid)
        c = self._coll(coll)
        c[target] = _Object(
            bytearray(src.data), dict(src.xattrs), dict(src.omap)
        )

    # -- reads ---------------------------------------------------------------

    def read(self, coll: str, oid: str, off: int = 0, length: int = 0) -> bytes:
        self._faultpoint("os.read", coll, oid)
        o = self._obj(coll, oid)
        if length == 0:
            return bytes(o.data[off:])
        return bytes(o.data[off : off + length])

    def stat(self, coll: str, oid: str) -> int:
        return len(self._obj(coll, oid).data)

    def getattr(self, coll: str, oid: str, name: str) -> bytes:
        attrs = self._obj(coll, oid).xattrs
        if name not in attrs:
            raise StoreError(61, f"no attr {name} on {coll}/{oid}")  # ENODATA
        return attrs[name]

    def getattrs(self, coll: str, oid: str) -> dict[str, bytes]:
        return dict(self._obj(coll, oid).xattrs)

    def omap_get(self, coll: str, oid: str) -> dict[str, bytes]:
        return dict(self._obj(coll, oid).omap)

    def list_objects(self, coll: str) -> list[str]:
        return sorted(self._coll(coll))

    def count_objects(self, coll: str) -> int:
        return len(self._coll(coll))

    def list_collections(self) -> list[str]:
        return sorted(self._colls)
