"""ObjectStore contract + transaction application engine.

Reference: /root/reference/src/os/ObjectStore.h:63 — the abstract
storage backend: `queue_transactions` (:232), `read` (:473), `getattr`
(:581), collection management, omap.  Errors are negative errnos
surfaced here as StoreError.

The op-application loop is shared by all backends; each backend supplies
the primitive object/collection storage.
"""

from __future__ import annotations

import errno as _errno
from typing import Callable, Iterable

from . import transaction as tx
from .transaction import Op, Transaction


class StoreError(Exception):
    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(
            f"{msg} (errno {self.errno}, {_errno.errorcode.get(abs(err), '?')})"
        )


class ObjectStore:
    """Abstract store.  Backends implement the _-prefixed primitives;
    the public surface mirrors ObjectStore.h."""

    @staticmethod
    def _faultpoint(point: str, coll: str, oid: str) -> None:
        """Media-error injection seam (os.read / os.write): an armed
        fault surfaces as StoreError, exactly the errno a dying disk
        would hand the objectstore (test-erasure-eio.sh semantics)."""
        from ..common.fault_injector import InjectedFailure, faultpoint

        try:
            faultpoint(point)
        except InjectedFailure as e:
            raise StoreError(
                abs(e.errno), f"injected {point} fault on {coll}/{oid}"
            ) from e

    def mount(self) -> None:
        pass

    def umount(self) -> None:
        pass

    # -- mutations -----------------------------------------------------------

    def queue_transaction(
        self, txn: Transaction, on_commit: Callable[[], None] | None = None
    ) -> None:
        """Apply ops in order, then fire on_commit (ObjectStore.h:232
        queue_transactions; callbacks are the on_commit contexts).

        Contract note (matches the reference's "failure is not an
        option", ObjectStore.h): a mid-transaction error indicates a
        caller bug; ops already applied are NOT rolled back and
        on_commit does not fire.  Durable backends additionally drop the
        journal entry so the aborted txn never replays."""
        if txn.ops:
            # write-fault seam, checked BEFORE any op lands: an injected
            # media error fails the whole transaction atomically (per-op
            # injection would tear it, since apply does not roll back)
            self._faultpoint("os.write", txn.ops[0].coll, txn.ops[0].oid)
        for op in txn.ops:
            self._apply_op(op)
        self._persist(txn)
        if on_commit is not None:
            on_commit()

    def _apply_op(self, op: Op) -> None:
        if op.code == tx.OP_TOUCH:
            self._touch(op.coll, op.oid)
        elif op.code == tx.OP_WRITE:
            self._write(op.coll, op.oid, op.off, op.data)
        elif op.code == tx.OP_WRITE_APPEND:
            self._write(op.coll, op.oid, self._size(op.coll, op.oid), op.data)
        elif op.code == tx.OP_ZERO:
            self._write(op.coll, op.oid, op.off, b"\x00" * op.length)
        elif op.code == tx.OP_TRUNCATE:
            self._truncate(op.coll, op.oid, op.off)
        elif op.code == tx.OP_REMOVE:
            self._remove(op.coll, op.oid)
        elif op.code == tx.OP_SETATTR:
            self._setattr(op.coll, op.oid, op.name, op.data)
        elif op.code == tx.OP_RMATTR:
            self._rmattr(op.coll, op.oid, op.name)
        elif op.code == tx.OP_OMAP_SETKEYS:
            self._omap_set(op.coll, op.oid, op.keys)
        elif op.code == tx.OP_OMAP_RMKEYS:
            self._omap_rm(op.coll, op.oid, list(op.keys))
        elif op.code == tx.OP_OMAP_CLEAR:
            self._omap_rm(op.coll, op.oid, list(self.omap_get(op.coll, op.oid)))
        elif op.code == tx.OP_MKCOLL:
            self._mkcoll(op.coll)
        elif op.code == tx.OP_RMCOLL:
            self._rmcoll(op.coll)
        elif op.code == tx.OP_CLONE:
            self._clone(op.coll, op.oid, op.name)
        else:
            raise StoreError(22, f"unknown op code {op.code}")

    def _persist(self, txn: Transaction) -> None:
        """Hook for durable backends (WAL/commit point)."""

    # -- reads (ObjectStore.h read-side surface) -----------------------------

    def read(self, coll: str, oid: str, off: int = 0, length: int = 0) -> bytes:
        """ObjectStore.h:473; length 0 = to EOF; returns ENOENT for
        missing objects."""
        raise NotImplementedError

    def stat(self, coll: str, oid: str) -> int:
        """Object size, or raise ENOENT."""
        raise NotImplementedError

    def exists(self, coll: str, oid: str) -> bool:
        try:
            self.stat(coll, oid)
            return True
        except StoreError:
            return False

    def getattr(self, coll: str, oid: str, name: str) -> bytes:
        raise NotImplementedError

    def getattrs(self, coll: str, oid: str) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, coll: str, oid: str) -> dict[str, bytes]:
        raise NotImplementedError

    def list_objects(self, coll: str) -> list[str]:
        raise NotImplementedError

    def count_objects(self, coll: str) -> int:
        """Object count for a collection.  Backends override with an O(1)
        path where they can (stat polling must not enumerate the store)."""
        return len(self.list_objects(coll))

    def list_collections(self) -> list[str]:
        raise NotImplementedError

    def collection_exists(self, coll: str) -> bool:
        return coll in self.list_collections()

    # -- backend primitives --------------------------------------------------

    def _touch(self, coll: str, oid: str) -> None:
        raise NotImplementedError

    def _write(self, coll: str, oid: str, off: int, data: bytes) -> None:
        raise NotImplementedError

    def _size(self, coll: str, oid: str) -> int:
        """Size for append; 0 when the object doesn't exist yet."""
        try:
            return self.stat(coll, oid)
        except StoreError:
            return 0

    def _truncate(self, coll: str, oid: str, size: int) -> None:
        raise NotImplementedError

    def _remove(self, coll: str, oid: str) -> None:
        raise NotImplementedError

    def _setattr(self, coll: str, oid: str, name: str, value: bytes) -> None:
        raise NotImplementedError

    def _rmattr(self, coll: str, oid: str, name: str) -> None:
        raise NotImplementedError

    def _omap_set(self, coll: str, oid: str, keys: dict[str, bytes]) -> None:
        raise NotImplementedError

    def _omap_rm(self, coll: str, oid: str, keys: Iterable[str]) -> None:
        raise NotImplementedError

    def _mkcoll(self, coll: str) -> None:
        raise NotImplementedError

    def _rmcoll(self, coll: str) -> None:
        raise NotImplementedError

    def _clone(self, coll: str, oid: str, target: str) -> None:
        raise NotImplementedError
