"""ceph_tpu — a TPU-native erasure-coding framework.

From-scratch rebuild of the capability surface of Ceph's erasure-code subsystem
(reference mounted at /root/reference), designed TPU-first:

- GF(2^8) Reed-Solomon/Cauchy/LRC/SHEC/CLAY codecs whose hot loops are
  bitsliced XOR-matmuls on the MXU (ceph_tpu.ops), not per-byte table lookups.
- A codec interface/base/registry stack mirroring the semantics of the
  reference's `ErasureCodeInterface` / `ErasureCode` / `ErasureCodePluginRegistry`
  (/root/reference/src/erasure-code/) so everything above the codec boundary
  (stripe engine, tools, benchmarks) is plugin-agnostic.
- Stripe math + hinfo CRC (ceph_tpu.stripe) mirroring src/osd/ECUtil.{h,cc}.
- Data-parallel stripe-batch sharding across a TPU mesh (ceph_tpu.parallel).
"""

__version__ = "0.1.0"

from . import gf  # noqa: F401
