"""orchestrator mgr module — mirror of src/pybind/mgr/orchestrator + a
local backend (the cephadm-analog).

The reference splits orchestration into an interface module (the `orch`
command family: ps, device ls, apply) and pluggable backends (cephadm,
rook) that realize desired state.  Same split here: OrchestratorModule
holds SERVICE SPECS (desired state) and reconciles them each tick
against observed daemons through a registered backend.  The in-process
backend (tests, vstart) spawns/stops daemon objects; a production
backend would shell out, exactly like cephadm does.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ServiceSpec:
    """Desired state for one service (python-common ServiceSpec)."""

    service_type: str  # "osd" | "mon" | "mgr" | "mds"
    count: int = 1
    unmanaged: bool = False
    extra: dict = field(default_factory=dict)

    @property
    def service_name(self) -> str:
        return self.service_type


class OrchBackend:
    """Backend interface (orchestrator._interface.Orchestrator): realize
    desired daemon counts.  Implementations own daemon lifecycle."""

    async def scale(self, service_type: str, current: int, target: int) -> None:
        raise NotImplementedError

    def inventory(self) -> list[dict]:
        """Host/device inventory (orch device ls)."""
        return []


from .modules import MgrModule


class OrchestratorModule(MgrModule):
    NAME = "orchestrator"

    SCALE_BACKOFF = 5.0  # seconds between scale attempts per service
    MAX_EVENTS = 100

    def __init__(self):
        super().__init__()
        self.specs: dict[str, ServiceSpec] = {}
        self.backend: OrchBackend | None = None
        self._reconciling = False
        self._last_scale: dict[str, float] = {}
        self.events: list[str] = []  # orch status history (bounded, deduped)

    def set_backend(self, backend: OrchBackend) -> None:
        self.backend = backend

    # -- orch command surface (orchestrator_cli) -----------------------------

    def apply(self, spec: ServiceSpec) -> str:
        """`orch apply <type> --count N` — record desired state; the
        reconcile loop realizes it."""
        self.specs[spec.service_name] = spec
        return f"Scheduled {spec.service_name} update (count {spec.count})"

    def ps(self) -> list[dict]:
        """`orch ps` — observed daemons."""
        out = []
        for osd, info in sorted(self.mgr.osdmap.osds.items()):
            out.append(
                {
                    "daemon_type": "osd",
                    "daemon_id": str(osd),
                    "status": "running" if info.up else "stopped",
                    "addr": info.addr,
                }
            )
        for d in self.mgr.list_daemons():
            kind, _, ident = d.partition(".")
            if kind != "osd":
                out.append(
                    {"daemon_type": kind, "daemon_id": ident, "status": "running"}
                )
        return out

    def device_ls(self) -> list[dict]:
        return self.backend.inventory() if self.backend else []

    def observed_count(self, service_type: str) -> int:
        if service_type == "osd":
            return sum(1 for i in self.mgr.osdmap.osds.values() if i.up)
        return sum(
            1 for d in self.mgr.list_daemons() if d.startswith(service_type + ".")
        )

    # -- reconcile loop (cephadm serve()) ------------------------------------

    def _event(self, msg: str) -> None:
        """Append deduped (vs the latest entry) and bounded — persistent
        drift must not grow the log or spam one line per tick."""
        if not self.events or self.events[-1] != msg:
            self.events.append(msg)
            if len(self.events) > self.MAX_EVENTS:
                del self.events[: -self.MAX_EVENTS]

    async def reconcile(self) -> None:
        if self.backend is None or self._reconciling:
            return
        import asyncio

        now = asyncio.get_event_loop().time()
        self._reconciling = True
        try:
            for spec in list(self.specs.values()):
                if spec.unmanaged:
                    continue
                have = self.observed_count(spec.service_type)
                if have == spec.count:
                    self._last_scale.pop(spec.service_name, None)
                    continue
                # Backoff between attempts: drift the backend cannot close
                # (e.g. a down daemon it can't replace) must not trigger a
                # scale call every 1-second tick.
                last = self._last_scale.get(spec.service_name, 0.0)
                if now - last < self.SCALE_BACKOFF:
                    continue
                self._last_scale[spec.service_name] = now
                self._event(
                    f"scaling {spec.service_name}: {have} -> {spec.count}"
                )
                await self.backend.scale(spec.service_type, have, spec.count)
        finally:
            self._reconciling = False

    async def tick(self) -> None:  # driven by the mgr module loop
        await self.reconcile()
