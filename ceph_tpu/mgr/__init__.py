"""Manager daemon + module runtime (SURVEY.md §2.7; src/mgr +
src/pybind/mgr)."""

from .clog import ClogModule
from .dashboard import DashboardModule
from .iostat import IostatModule
from .metrics_history import MetricsHistoryModule
from .mgr import Mgr
from .modules import MgrModule
from .orchestrator import OrchBackend, OrchestratorModule, ServiceSpec
from .progress import ProgressModule
from .telemetry import TelemetryModule

__all__ = [
    "ClogModule",
    "DashboardModule",
    "IostatModule",
    "MetricsHistoryModule",
    "Mgr",
    "MgrModule",
    "OrchBackend",
    "OrchestratorModule",
    "ProgressModule",
    "ServiceSpec",
    "TelemetryModule",
]
