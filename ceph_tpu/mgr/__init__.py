"""Manager daemon + module runtime (SURVEY.md §2.7; src/mgr +
src/pybind/mgr)."""

from .mgr import Mgr
from .modules import MgrModule

__all__ = ["Mgr", "MgrModule"]
