"""Manager daemon + module runtime (SURVEY.md §2.7; src/mgr +
src/pybind/mgr)."""

from .mgr import Mgr
from .modules import MgrModule
from .telemetry import TelemetryModule

__all__ = ["Mgr", "MgrModule", "TelemetryModule"]
