"""balancer mgr module — mirror of src/pybind/mgr/balancer.

The reference's balancer evens PG distribution across OSDs, in
`crush-compat` mode by adjusting per-OSD reweights and in `upmap` mode
with explicit pg-upmap entries.  This module implements the
crush-compat strategy: score the current PG distribution, and when the
spread exceeds the threshold, nudge overloaded OSDs' reweights down via
`osd reweight` mon commands (Module.optimize / do_crush_compat).
"""

from __future__ import annotations

from ..common.log import dout
from ..crush.crush import WEIGHT_ONE
from ..osd.osdmap import PG_NONE
from .modules import MgrModule


class BalancerModule(MgrModule):
    NAME = "balancer"

    def __init__(self, threshold: float = 1.2, max_adjustments: int = 2):
        super().__init__()
        self.mode = "crush-compat"
        self.active_mode = False  # like `balancer on` (default off: advise)
        self.threshold = threshold  # max/mean PG ratio triggering a move
        self.max_adjustments = max_adjustments  # per tick (upmap_max_optimizations)
        self.last_plan: list[dict] = []
        self.map_errors = 0  # unmappable PGs skipped (visible, not silent)

    # -- scoring ---------------------------------------------------------------

    def pg_counts(self) -> dict[int, int]:
        """PGs per OSD over all pools (Module.calc_pg_upmaps input)."""
        osdmap = self.mgr.osdmap
        counts = {o: 0 for o, info in osdmap.osds.items() if info.up and info.in_}
        for pool in osdmap.pools.values():
            for ps in range(pool.pg_num):
                try:
                    _u, _up, acting, _p = osdmap.pg_to_up_acting_osds(pool.id, ps)
                except Exception as e:
                    # CRUSH can legitimately fail to map a PG mid-churn,
                    # but the failure must leave a trace (ISSUE 12):
                    # balancing on a silently partial count set would
                    # "even out" load that is actually unmapped
                    self.map_errors += 1
                    dout("mgr", 4,
                         f"balancer: pg {pool.id}.{ps} unmappable: {e!r}")
                    continue
                for osd in acting:
                    if osd != PG_NONE and osd in counts:
                        counts[osd] += 1
        return counts

    def score(self) -> float:
        """max/mean ratio; 1.0 = perfectly even (Module.calc_eval)."""
        counts = self.pg_counts()
        if not counts or sum(counts.values()) == 0:
            return 1.0
        mean = sum(counts.values()) / len(counts)
        return max(counts.values()) / mean if mean else 1.0

    # -- planning --------------------------------------------------------------

    def optimize(self) -> list[dict]:
        """Build a reweight plan without executing it (`balancer eval` +
        `balancer optimize`)."""
        counts = self.pg_counts()
        plan: list[dict] = []
        if len(counts) < 2:
            return plan
        mean = sum(counts.values()) / len(counts)
        if mean == 0:
            return plan
        osdmap = self.mgr.osdmap
        over = sorted(
            (o for o, c in counts.items() if c / mean > self.threshold),
            key=lambda o: -counts[o],
        )
        for osd in over[: self.max_adjustments]:
            cur = osdmap.osds[osd].weight / WEIGHT_ONE
            # proportional nudge toward the mean, floored (do_crush_compat's
            # step-scaled adjustment)
            new = max(0.5, round(cur * mean / counts[osd], 2))
            if new < cur:
                plan.append({"osd": osd, "from": cur, "to": new})
        return plan

    async def tick(self) -> None:
        self.last_plan = self.optimize()
        if not self.last_plan:
            self.clear_health_check("BALANCER_UNEVEN")
            return
        summary = ", ".join(
            f"osd.{p['osd']} {p['from']:.2f}->{p['to']:.2f}" for p in self.last_plan
        )
        if not self.active_mode:
            self.set_health_check(
                "BALANCER_UNEVEN", "warning", f"pg distribution uneven; plan: {summary}"
            )
            return
        for p in self.last_plan:
            rv, rs, _ = await self.mgr.mon_command(
                {"prefix": "osd reweight", "id": p["osd"], "weight": p["to"]}
            )
            if rv != 0:
                dout("mgr", 1, f"balancer: reweight osd.{p['osd']} failed: {rs}")
        dout("mgr", 5, f"balancer: applied {summary}")
