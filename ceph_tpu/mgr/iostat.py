"""iostat mgr module — workload attribution at the cluster level
(ISSUE 10; the src/pybind/mgr/iostat analog grown a tenant dimension).

Every OSD's status blob carries the cumulative per-pool / per-client IO
counters its `IOAccountant` (common/io_accounting.py) sampled on the op
reply and recovery paths.  This module merges them across OSDs each
tick:

- **Rates**: per-(pool, op class) IOPS and bytes/sec as EMAs of the
  inter-tick deltas (the mgr/progress.py smoothing shape), restart-safe
  (a daemon whose counters rebased to zero re-anchors instead of
  contributing negative deltas).
- **Windowed p99**: per-pool latency from the merged log2 histograms,
  computed over the last `mgr_iostat_window_sec` of samples — the
  `iostat` number an operator steers by, not a boot-to-now average.
- **Top clients**: the N heaviest (pool, client) pairs by IOPS, bytes,
  or p99 (`mgr_iostat_top_clients` bounds scrape cardinality).
- **SLOs**: per-pool latency targets (`mgr_slo_latency_target_ms`
  default + `mgr_slo_pool_latency_targets` overrides, runtime-mutable)
  evaluated as multi-window burn rates: burn = (fraction of ops over
  target) / (1 - `mgr_slo_objective`).  ``SLO_LATENCY_BREACH``
  (HEALTH_WARN) raises when BOTH the fast and the slow window burn
  above `mgr_slo_burn_threshold` — the fast window confirms the pain is
  current, the slow one that it is not a blip — and clears when either
  recovers.

Surfaces: the mgr asok (`iostat` / `iostat top`), the PGMap digest
(`iostat` + `slo` slices → mon `status` and the mon-side health check),
and the module-metrics hook (`ceph_tpu_pool_*` / `ceph_tpu_top_client_*`
families on the prometheus scrape).
"""

from __future__ import annotations

import time
from collections import deque

from ..common.io_accounting import OP_CLASSES
from ..common.log import dout
from ..common.perf_counters import histogram_sample_lines
from .modules import MgrModule

# EMA weight of the newest inter-tick rate sample (progress.py shape)
_RATE_ALPHA = 0.3
_RATE_MIN_DT = 0.01


def _hist_parts(dump: dict) -> tuple[list, list[int], float, int]:
    """(le bounds, NON-cumulative per-bucket counts, sum, count) from a
    PerfHistogram.dump() payload."""
    h = (dump or {}).get("histogram") or {}
    buckets = h.get("buckets") or []
    les = [le for le, _ in buckets]
    counts: list[int] = []
    prev = 0
    for _le, cum in buckets:
        counts.append(int(cum) - prev)
        prev = int(cum)
    return les, counts, float(h.get("sum", 0.0)), int(h.get("count", 0))


def _p_from_counts(les: list, counts: list[int], q: float) -> float | None:
    """Quantile upper bound from non-cumulative bucket counts; None when
    empty or when the quantile lands in the +Inf overflow bucket."""
    total = sum(counts)
    if not total:
        return None
    want = q * total
    cum = 0
    for le, c in zip(les, counts):
        cum += c
        if cum >= want:
            return None if le == "+Inf" else float(le)
    return None


def _bad_count(les: list, counts: list[int], target_sec: float) -> int:
    """Samples PROVABLY slower than `target_sec`: a log2 bucket counts
    as bad only when its LOWER bound is at or past the target.  The
    bucket straddling the target counts good — log2 buckets cannot
    split, and counting the straddler bad would snap the effective
    target down to the previous power-of-two boundary (up to 2x
    stricter than configured: every 9 ms op "breaching" a 10 ms
    target)."""
    bad = 0
    lower = 0.0
    for le, c in zip(les, counts):
        if lower >= target_sec:
            bad += c
        if le != "+Inf":
            lower = float(le)
    return bad


class _Series:
    """One merged cumulative series (a (pool, class) or (pool, client)
    key): cluster-wide totals + EMA rates + a snapshot ring for
    windowed deltas."""

    __slots__ = (
        "ops", "bytes", "lat_sum", "lat_count", "lat_counts", "les",
        "ops_rate", "bytes_rate", "snaps", "last_seen",
    )

    def __init__(self) -> None:
        self.ops = 0
        self.bytes = 0
        self.lat_sum = 0.0
        self.lat_count = 0
        self.lat_counts: list[int] = []
        self.les: list = []
        self.ops_rate = 0.0
        self.bytes_rate = 0.0
        # (t, ops, bytes, lat_count, tuple(lat_counts)) snapshots for
        # windowed p99 / burn rates; trimmed to the slow SLO window
        self.snaps: deque = deque()
        self.last_seen = 0.0

    def add_delta(
        self, d_ops: int, d_bytes: int, d_counts: list[int],
        d_sum: float, d_count: int, les: list,
    ) -> None:
        self.ops += d_ops
        self.bytes += d_bytes
        self.lat_sum += d_sum
        self.lat_count += d_count
        if les and not self.les:
            self.les = list(les)
            self.lat_counts = [0] * len(les)
        if d_counts and len(d_counts) == len(self.lat_counts):
            for i, c in enumerate(d_counts):
                self.lat_counts[i] += c

    def sample_rates(self, d_ops: int, d_bytes: int, dt: float) -> None:
        if dt < _RATE_MIN_DT:
            return
        for attr, delta in (("ops_rate", d_ops), ("bytes_rate", d_bytes)):
            inst = delta / dt
            prev = getattr(self, attr)
            setattr(
                self, attr,
                inst if prev == 0.0
                else _RATE_ALPHA * inst + (1 - _RATE_ALPHA) * prev,
            )

    def snapshot(self, now: float, keep_sec: float) -> None:
        self.snaps.append(
            (now, self.ops, self.bytes, self.lat_count,
             tuple(self.lat_counts))
        )
        while self.snaps and now - self.snaps[0][0] > keep_sec:
            self.snaps.popleft()

    def window_delta(
        self, now: float, window_sec: float
    ) -> tuple[float, int, int, int, list[int]]:
        """(elapsed, d_ops, d_bytes, d_lat_count, d_lat_counts) vs the
        NEWEST snapshot at or before the window start, so the delta
        always covers at least the window — when snapshots are sparser
        than the window (tick cadence > window), the effective window
        stretches to the snapshot cadence instead of collapsing to the
        zero-delta of the just-taken snapshot.  Before any snapshot has
        aged past the window start (warm-up), the OLDEST snapshot
        anchors the delta: the first fold after a mgr (re)start imports
        each OSD's entire boot-to-now cumulative history in one delta,
        and burning hours of history against a seconds-wide window
        would raise a spurious SLO_LATENCY_BREACH on every failover.
        Activity between series birth and its first snapshot is the
        only blind spot."""
        cutoff = now - window_sec
        base = None
        for snap in self.snaps:  # oldest -> newest
            if snap[0] <= cutoff:
                base = snap
            else:
                break
        if base is None:
            base = self.snaps[0] if self.snaps else (cutoff, 0, 0, 0, ())
        t0, ops0, bytes0, lc0, counts0 = base
        d_counts = [
            c - (counts0[i] if i < len(counts0) else 0)
            for i, c in enumerate(self.lat_counts)
        ]
        return (
            max(now - t0, 0.0), self.ops - ops0, self.bytes - bytes0,
            self.lat_count - lc0, d_counts,
        )


class IostatModule(MgrModule):
    NAME = "iostat"

    # stop rendering a (pool, client) row this long after its last
    # advance (a departed client must not pin scrape cardinality)
    CLIENT_IDLE_EXPIRE_SEC = 600.0

    # drop a _prev delta anchor this long after its key last appeared
    # in a live daemon's blob (see the prune step in tick())
    PREV_PRUNE_SEC = 60.0

    def __init__(
        self,
        window_sec: float | None = None,
        top_n: int | None = None,
        slo_target_ms: float | None = None,
        slo_pool_targets: str | None = None,
        slo_objective: float | None = None,
        slo_burn_threshold: float | None = None,
        slo_fast_window_sec: float | None = None,
        slo_slow_window_sec: float | None = None,
    ):
        """Explicit constructor values pin the knob (tests, embedded
        harnesses); None tracks the mgr's live config each tick — the
        runtime-mutable pattern the progress module uses."""
        super().__init__()
        self._pins = {
            "mgr_iostat_window_sec": window_sec,
            "mgr_iostat_top_clients": top_n,
            "mgr_slo_latency_target_ms": slo_target_ms,
            "mgr_slo_pool_latency_targets": slo_pool_targets,
            "mgr_slo_objective": slo_objective,
            "mgr_slo_burn_threshold": slo_burn_threshold,
            "mgr_slo_fast_window_sec": slo_fast_window_sec,
            "mgr_slo_slow_window_sec": slo_slow_window_sec,
        }
        from ..common.options import OPTIONS

        self._conf = {
            name: OPTIONS[name].default if pin is None else pin
            for name, pin in self._pins.items()
        }
        # (pid, op class) -> _Series ; (pid, client) -> _Series
        self.pools: dict[tuple[str, str], _Series] = {}
        self.clients: dict[tuple[str, str], _Series] = {}
        # per-(daemon, kind, pid, key) previous cumulative blob values
        self._prev: dict[tuple, dict] = {}
        self._last_tick = 0.0
        # pools currently breaching (hysteresis + clear detection)
        self.breaches: dict[str, dict] = {}
        self.config_errors = 0  # skipped config reads (visible, not silent)

    # -- config ----------------------------------------------------------------

    def _refresh_config(self) -> None:
        conf = getattr(self.mgr, "conf", None)
        for name, pin in self._pins.items():
            if pin is not None:
                continue
            if conf is None:
                continue
            try:
                self._conf[name] = conf.get(name)
            except Exception as e:
                # stripped test configs miss keys — but the skip must
                # leave a trace, or a typo'd option name would silently
                # pin the default forever (ISSUE 12)
                self.config_errors += 1
                dout("mgr", 4, f"iostat: config read {name!r}: {e!r}")

    def _pool_names(self) -> dict[str, str]:
        osdmap = getattr(self.mgr, "osdmap", None)
        if osdmap is None:
            return {}
        return {str(p.id): p.name for p in osdmap.pools.values()}

    def slo_target_sec(self, pid: str) -> float:
        """This pool's latency target in SECONDS, honoring per-pool
        overrides matched by id or name; 0 = SLO disabled for it."""
        names = self._pool_names()
        name = names.get(pid, "")
        for entry in str(
            self._conf["mgr_slo_pool_latency_targets"]
        ).split(","):
            key, _, ms = entry.strip().partition(":")
            if not key or not ms:
                continue
            if key == pid or (name and key == name):
                try:
                    return float(ms) / 1e3
                except ValueError:
                    continue
        return float(self._conf["mgr_slo_latency_target_ms"]) / 1e3

    # -- aggregation -----------------------------------------------------------

    def tick(self) -> None:
        now = time.monotonic()
        self._refresh_config()
        keep = max(
            float(self._conf["mgr_slo_slow_window_sec"]),
            float(self._conf["mgr_iostat_window_sec"]),
        ) + 5.0
        dt = now - self._last_tick if self._last_tick else 0.0
        self._last_tick = now
        live = getattr(self.mgr, "_daemon_report_live", None)
        deltas: dict[tuple, list] = {}
        reporting: set[str] = set()
        for daemon in self.mgr.list_daemons():
            if live is not None and not live(daemon):
                continue
            status = self.mgr.get_daemon_status(daemon)
            if status.get("pool_io") or status.get("client_io"):
                reporting.add(daemon)
            for kind, blob_key in (("pool", "pool_io"), ("client", "client_io")):
                blob = status.get(blob_key) or {}
                for pid, entries in blob.items():
                    for key, rec in entries.items():
                        self._fold(deltas, (kind, pid, key), daemon, rec)
        # prune _prev anchors the OSD provably dropped: under client
        # churn (every client restart is a new reqid key) the dict would
        # otherwise grow for the life of the mgr.  A key absent from a
        # LIVE, still-reporting daemon's blob was evicted OSD-side
        # (folded into _other — its old cumulative totals can never be
        # reported again), so its anchor is dead weight after a grace
        # period.  Down daemons keep their anchors: a partition heal
        # resumes deltas against them, where a pruned anchor would
        # re-import boot-to-now history as one double-counting delta.
        for pkey, rec in list(self._prev.items()):
            if (
                pkey[0] in reporting
                and now - rec.get("t", now) > self.PREV_PRUNE_SEC
            ):
                del self._prev[pkey]
        for (kind, pid, key), d in deltas.items():
            table = self.pools if kind == "pool" else self.clients
            series = table.get((pid, key))
            d_ops, d_bytes, d_counts, d_sum, d_count, les, imported = d
            if series is None:
                # the OSDs keep reporting expired clients' (unchanged)
                # cumulative records forever; a zero delta must not
                # resurrect the series as a permanent zero row.  A
                # returning client restarts its mgr-side totals from the
                # moment it reappears — the expiry semantics ("who is
                # driving load NOW") apply to totals too.
                if not (d_ops or d_bytes or d_count):
                    continue
                series = table[(pid, key)] = _Series()
            series.add_delta(d_ops, d_bytes, d_counts, d_sum, d_count, les)
            if d_ops or d_bytes:
                series.last_seen = now
            # a first-sight fold imported a daemon's boot-to-now
            # cumulative history as one delta — totals want it, but
            # feeding it to the EMA would report hours of ops as one
            # tick's IOPS after a mgr failover (the window-delta warm-up
            # anchor already shields the SLO/p99 path; this shields the
            # rate path).  Rates resume from the next genuine delta.
            if not imported:
                series.sample_rates(d_ops, d_bytes, dt)
        for table in (self.pools, self.clients):
            for series in table.values():
                series.snapshot(now, keep)
        # idle clients expire so the top-N views and the scrape reflect
        # who is driving load NOW
        for key, series in list(self.clients.items()):
            if series.last_seen and now - series.last_seen > self.CLIENT_IDLE_EXPIRE_SEC:
                del self.clients[key]
        self._evaluate_slo(now)

    def _fold(self, deltas: dict, key: tuple, daemon: str, rec: dict) -> None:
        """Delta one daemon's cumulative record against its previous
        report; counter regressions (daemon restart) re-anchor."""
        les, counts, lat_sum, lat_count = _hist_parts(rec.get("lat"))
        cur = {
            "ops": int(rec.get("ops", 0)),
            "bytes": int(rec.get("bytes", 0)),
            "sum": lat_sum,
            "count": lat_count,
            "counts": counts,
            "t": self._last_tick,  # prune clock (refreshed every fold)
        }
        pkey = (daemon,) + key
        prev = self._prev.get(pkey)
        self._prev[pkey] = cur
        if (
            prev is None
            or cur["ops"] < prev["ops"]
            or cur["count"] < prev["count"]
            or len(prev["counts"]) != len(counts)
        ):
            # first sight or restart: the whole cumulative value is the
            # delta (first sight) / re-anchor without contribution
            # (restart would double-count the pre-restart history)
            if prev is not None:
                return
            prev = {"ops": 0, "bytes": 0, "sum": 0.0, "count": 0,
                    "counts": [0] * len(counts)}
            first_sight = True
        else:
            first_sight = False
        d = deltas.setdefault(
            key, [0, 0, [0] * len(counts), 0.0, 0, les, False]
        )
        if first_sight:
            d[6] = True
        d[0] += cur["ops"] - prev["ops"]
        d[1] += max(cur["bytes"] - prev["bytes"], 0)
        for i, c in enumerate(counts):
            if i < len(d[2]):
                d[2][i] += c - prev["counts"][i]
        d[3] += cur["sum"] - prev["sum"]
        d[4] += cur["count"] - prev["count"]
        if les and not d[5]:
            d[5] = les

    # -- SLO evaluation --------------------------------------------------------

    def _burn_rate(
        self, pid: str, now: float, window_sec: float, target_sec: float
    ) -> float:
        """Burn rate for one pool over one window: bad-op fraction
        across the client-visible classes (read + write; recovery is
        the cluster's own traffic) over the error budget."""
        budget = max(1.0 - float(self._conf["mgr_slo_objective"]), 1e-9)
        bad = total = 0
        for cls in ("read", "write"):
            series = self.pools.get((pid, cls))
            if series is None:
                continue
            _dt, _do, _db, d_count, d_counts = series.window_delta(
                now, window_sec
            )
            total += d_count
            bad += _bad_count(series.les, d_counts, target_sec)
        if total <= 0:
            return 0.0
        return (bad / total) / budget

    def _evaluate_slo(self, now: float) -> None:
        threshold = float(self._conf["mgr_slo_burn_threshold"])
        fast_w = float(self._conf["mgr_slo_fast_window_sec"])
        slow_w = float(self._conf["mgr_slo_slow_window_sec"])
        names = self._pool_names()
        breaches: dict[str, dict] = {}
        for pid in sorted({p for p, _c in self.pools}):
            target = self.slo_target_sec(pid)
            if target <= 0.0:
                continue
            fast = self._burn_rate(pid, now, fast_w, target)
            slow = self._burn_rate(pid, now, slow_w, target)
            if fast > threshold and slow > threshold:
                breaches[pid] = {
                    "pool": names.get(pid, pid),
                    "target_ms": round(target * 1e3, 3),
                    "burn_fast": round(fast, 3),
                    "burn_slow": round(slow, 3),
                    "p99_ms": self._pool_p99_ms(pid, now),
                }
        self.breaches = breaches
        if breaches:
            from ..common import health

            self.set_health_check(
                "SLO_LATENCY_BREACH",
                "HEALTH_WARN",
                health.slo_breach_summary(breaches) or "",
                health.slo_breach_detail(breaches),
            )
        else:
            self.clear_health_check("SLO_LATENCY_BREACH")

    def worst_burn_rate(self, window: str = "slow") -> float:
        """Max burn rate across SLO-enabled pools (chaos/bench tracked
        key `slo_worst_burn_rate`); 0.0 when no pool has a target."""
        now = time.monotonic()
        w = float(
            self._conf[
                "mgr_slo_fast_window_sec" if window == "fast"
                else "mgr_slo_slow_window_sec"
            ]
        )
        worst = 0.0
        for pid in {p for p, _c in self.pools}:
            target = self.slo_target_sec(pid)
            if target > 0.0:
                worst = max(worst, self._burn_rate(pid, now, w, target))
        return worst

    # -- rendered views --------------------------------------------------------

    def _pool_p99_ms(self, pid: str, now: float) -> float | None:
        """Windowed p99 across read+write, in ms (None = no samples in
        the window, or the tail overflowed the histogram range)."""
        window = float(self._conf["mgr_iostat_window_sec"])
        les: list = []
        merged: list[int] = []
        for cls in ("read", "write"):
            series = self.pools.get((pid, cls))
            if series is None:
                continue
            _dt, _do, _db, _dc, d_counts = series.window_delta(now, window)
            if not les:
                les = series.les
                merged = list(d_counts)
            elif len(d_counts) == len(merged):
                merged = [a + b for a, b in zip(merged, d_counts)]
        p99 = _p_from_counts(les, merged, 0.99)
        return None if p99 is None else round(p99 * 1e3, 3)

    def iostat(self) -> dict[str, dict]:
        """The per-pool `iostat` view: rates per class, windowed p99,
        cumulative totals — the mgr asok payload, the mon `status`
        slice, and what the acceptance test reconciles against the
        OSD-side counters."""
        now = time.monotonic()
        names = self._pool_names()
        out: dict[str, dict] = {}
        for (pid, cls), series in sorted(self.pools.items()):
            rec = out.get(pid)
            if rec is None:
                # computed once per pool, not per (pool, class) row —
                # the window merge is the expensive part of this view
                rec = out[pid] = {
                    "pool": names.get(pid, pid),
                    "p99_ms": self._pool_p99_ms(pid, now),
                    "ops_total": 0,
                    "bytes_total": 0,
                }
            rec[f"{cls}_ops_per_sec"] = round(series.ops_rate, 3)
            rec[f"{cls}_bytes_per_sec"] = round(series.bytes_rate, 1)
            rec[f"{cls}_ops"] = series.ops
            rec[f"{cls}_bytes"] = series.bytes
            rec["ops_total"] += series.ops
            rec["bytes_total"] += series.bytes
        return out

    def top_clients(
        self, n: int | None = None, by: str = "ops_rate"
    ) -> list[dict]:
        """Top-N (pool, client) rows by `ops_rate` (IOPS), `bytes_rate`,
        or `p99` — who is driving the load."""
        n = int(self._conf["mgr_iostat_top_clients"]) if n is None else n
        window = float(self._conf["mgr_iostat_window_sec"])
        now = time.monotonic()
        names = self._pool_names()
        rows = []
        for (pid, client), series in self.clients.items():
            # windowed p99, like the pool view: the lifetime cumulative
            # histogram would rank by stale history — a startup blip
            # (or a failover's boot-to-now import) keeping a busy
            # client "slowest" forever is not "who is slow NOW"
            _dt, _do, _db, _dc, d_counts = series.window_delta(
                now, window
            )
            p99 = _p_from_counts(series.les, d_counts, 0.99)
            # p99 is None for BOTH "no samples" and "quantile in the
            # +Inf overflow bucket"; for ranking, an overflowed client
            # is the SLOWEST (worse than any finite bound), not 0
            p99_rank = (
                p99 if p99 is not None
                else float("inf") if sum(d_counts) else 0.0
            )
            rows.append(
                (
                    p99_rank,
                    {
                        "pool_id": pid,
                        "pool": names.get(pid, pid),
                        "client": client,
                        "ops_per_sec": round(series.ops_rate, 3),
                        "bytes_per_sec": round(series.bytes_rate, 1),
                        "p99_ms": None if p99 is None
                        else round(p99 * 1e3, 3),
                        "ops": series.ops,
                        "bytes": series.bytes,
                    },
                )
            )
        key = {
            "ops_rate": lambda pr: pr[1]["ops_per_sec"],
            "bytes_rate": lambda pr: pr[1]["bytes_per_sec"],
            "p99": lambda pr: pr[0],
        }.get(by) or (lambda pr: pr[1]["ops_per_sec"])
        rows.sort(key=key, reverse=True)
        return [r for _rank, r in rows[: max(n, 0)]]

    def iostat_digest(self) -> dict:
        """The `iostat` slice of the mgr's PGMap digest: per-pool rates
        + top clients, what mon `status` renders."""
        return {
            "pools": self.iostat(),
            "top_clients": self.top_clients(),
        }

    def slo_digest(self) -> dict:
        """The `slo` digest slice the mon-side SLO_LATENCY_BREACH check
        reads (raise/clear like PG_RECOVERY_STALLED)."""
        return {
            "breaches": self.breaches,
            "worst_burn_rate": round(self.worst_burn_rate("slow"), 3),
            "worst_burn_rate_fast": round(self.worst_burn_rate("fast"), 3),
        }

    # -- prometheus ------------------------------------------------------------

    def prometheus_metrics(self) -> list[tuple[str, str, str, list[str]]]:
        """Module-metrics hook: the canonical workload-attribution
        families.  Cumulative ops/bytes are counters; rates, p99 and
        burn gauges rise and fall.  Families render even when empty so
        the scrape's family set is stable from the first tick."""
        now = time.monotonic()
        ops_rows: list[str] = []
        bytes_rows: list[str] = []
        lat_rows: list[str] = []
        rate_rows: list[str] = []
        brate_rows: list[str] = []
        p99_rows: list[str] = []
        for (pid, cls), series in sorted(self.pools.items()):
            labels = f'pool="{pid}",op="{cls}"'
            ops_rows.append(f"ceph_tpu_pool_ops{{{labels}}} {series.ops}")
            bytes_rows.append(
                f"ceph_tpu_pool_bytes{{{labels}}} {series.bytes}"
            )
            rate_rows.append(
                f"ceph_tpu_pool_ops_rate{{{labels}}} "
                f"{series.ops_rate:.3f}"
            )
            brate_rows.append(
                f"ceph_tpu_pool_bytes_rate{{{labels}}} "
                f"{series.bytes_rate:.1f}"
            )
            if series.les:
                cum = 0
                buckets = []
                for le, c in zip(series.les, series.lat_counts):
                    cum += c
                    buckets.append([le, cum])
                lat_rows.extend(
                    histogram_sample_lines(
                        "ceph_tpu_pool_latency_seconds",
                        {
                            "buckets": buckets,
                            "sum": series.lat_sum,
                            "count": series.lat_count,
                        },
                        labels,
                    )
                )
        for pid in sorted({p for p, _c in self.pools}):
            p99 = self._pool_p99_ms(pid, now)
            if p99 is not None:
                p99_rows.append(
                    f'ceph_tpu_pool_p99_latency_seconds{{pool="{pid}"}} '
                    f"{p99 / 1e3:.6f}"
                )
        burn_rows: list[str] = []
        target_rows: list[str] = []
        for pid in sorted({p for p, _c in self.pools}):
            target = self.slo_target_sec(pid)
            if target <= 0.0:
                continue
            target_rows.append(
                f'ceph_tpu_pool_slo_target_seconds{{pool="{pid}"}} '
                f"{target:.6f}"
            )
            for window, w in (
                ("fast", float(self._conf["mgr_slo_fast_window_sec"])),
                ("slow", float(self._conf["mgr_slo_slow_window_sec"])),
            ):
                burn_rows.append(
                    f"ceph_tpu_pool_slo_burn_rate"
                    f'{{pool="{pid}",window="{window}"}} '
                    f"{self._burn_rate(pid, now, w, target):.3f}"
                )
        top_ops: list[str] = []
        top_bytes: list[str] = []
        for row in self.top_clients():
            labels = f'pool="{row["pool_id"]}",client="{row["client"]}"'
            top_ops.append(
                f"ceph_tpu_top_client_ops_rate{{{labels}}} "
                f'{row["ops_per_sec"]:.3f}'
            )
            top_bytes.append(
                f"ceph_tpu_top_client_bytes_rate{{{labels}}} "
                f'{row["bytes_per_sec"]:.1f}'
            )
        return [
            ("ceph_tpu_pool_ops", "counter",
             "per-pool ops by op class (read/write/recovery)", ops_rows),
            ("ceph_tpu_pool_bytes", "counter",
             "per-pool bytes by op class", bytes_rows),
            ("ceph_tpu_pool_latency_seconds", "histogram",
             "per-pool op latency by op class (merged log2 histogram)",
             lat_rows),
            ("ceph_tpu_pool_ops_rate", "gauge",
             "per-pool smoothed IOPS by op class", rate_rows),
            ("ceph_tpu_pool_bytes_rate", "gauge",
             "per-pool smoothed bytes/sec by op class", brate_rows),
            ("ceph_tpu_pool_p99_latency_seconds", "gauge",
             "per-pool windowed p99 op latency", p99_rows),
            ("ceph_tpu_pool_slo_target_seconds", "gauge",
             "per-pool latency SLO target", target_rows),
            ("ceph_tpu_pool_slo_burn_rate", "gauge",
             "per-pool SLO burn rate by window (fast/slow)", burn_rows),
            ("ceph_tpu_top_client_ops_rate", "gauge",
             "top-N clients by smoothed IOPS", top_ops),
            ("ceph_tpu_top_client_bytes_rate", "gauge",
             "top-N clients by smoothed bytes/sec", top_bytes),
        ]
