"""dashboard mgr module — mirror of src/pybind/mgr/dashboard.

The reference dashboard is a full web UI (cherrypy + Angular, ~100k LoC);
this module keeps its architectural role — an HTTP window onto live
cluster state served FROM the active mgr — with the REST layer and a
minimal index page, dropping the SPA.  Routes mirror the reference's
/api endpoints (dashboard/controllers/*): health, osds, pools, pgs,
daemons, config.
"""

from __future__ import annotations

import json

from ..common.log import dout
from .modules import HttpServedModule, MgrModule


class DashboardModule(HttpServedModule, MgrModule):
    NAME = "dashboard"

    def __init__(self, port: int = 0):
        MgrModule.__init__(self)
        HttpServedModule.__init__(self, port)
        self.map_errors = 0  # unmappable PGs skipped (visible, not silent)

    # -- REST payloads (dashboard/controllers/{health,osd,pool,...}.py) ------

    def api_health(self) -> dict:
        """The /api/health payload: the mgr's full check set — module
        checks AND the digest-derived ones (SLOW_OPS, OSD_DOWN, ...) —
        each with severity, summary, and the per-entity detail lines
        mon `health detail` would print.  Overall status derives from
        common/health.py's single severity rule: the old module-only
        merge compared against literal "warning"/"error" strings no
        check ever used, so the dashboard banner read HEALTH_OK while
        the cluster burned."""
        from ..common import health

        checks = {
            code: {
                "severity": info.get("severity", "HEALTH_WARN"),
                "summary": info.get("summary", ""),
                "detail": list(info.get("detail") or []),
            }
            for code, info in self.mgr.health_checks().items()
        }
        m = self.mgr.osdmap
        return {
            "status": health.overall_status(checks),
            "checks": checks,
            "osdmap_epoch": m.epoch,
            "num_osds": len(m.osds),
            "num_up_osds": m.num_up_osds(),
            "num_pools": len(m.pools),
        }

    def api_osds(self) -> list[dict]:
        return [
            {
                "osd": osd,
                "up": info.up,
                "in": info.in_,
                "weight": info.weight,
                "addr": info.addr,
            }
            for osd, info in sorted(self.mgr.osdmap.osds.items())
        ]

    def api_pools(self) -> list[dict]:
        out = []
        for p in self.mgr.osdmap.pools.values():
            out.append(
                {
                    "id": p.id,
                    "name": p.name,
                    "type": "erasure" if p.is_erasure() else "replicated",
                    "size": p.size,
                    "pg_num": p.pg_num,
                    "erasure_code_profile": p.erasure_code_profile,
                    "cache_mode": p.cache_mode,
                    "tier_of": p.tier_of,
                    "read_tier": p.read_tier,
                }
            )
        return out

    def api_pgs(self) -> list[dict]:
        m = self.mgr.osdmap
        out = []
        for p in m.pools.values():
            for ps in range(p.pg_num):
                try:
                    up, primary, acting, _ = m.pg_to_up_acting_osds(p.id, ps)
                except Exception as e:
                    self.map_errors += 1
                    dout("mgr", 4,
                         f"dashboard: pg {p.id}.{ps} unmappable: {e!r}")
                    continue
                out.append(
                    {
                        "pgid": f"{p.id}.{ps}",
                        "up": up,
                        "acting": acting,
                        "primary": primary,
                    }
                )
        return out

    def api_daemons(self) -> list[dict]:
        return [
            {"daemon": d, "status": self.mgr.get_daemon_status(d)}
            for d in self.mgr.list_daemons()
        ]

    def api_perf_history(self) -> dict:
        """The /api/perf_history payload (ISSUE 14): the metrics-history
        module's series inventory, store meta-stats, and the raised
        trend sentinels — the dashboard window onto `perf history ls`.
        Empty when the module isn't registered (modules are opt-in)."""
        from .modules import find_module

        mod = find_module(self.mgr, "metrics_history")
        if mod is None:
            return {"series": [], "stats": {}, "sentinels": {}}
        return {
            **mod.history_ls(),
            "sentinels": mod.history_digest()["sentinels"],
        }

    def api_log(self) -> dict:
        """The /api/log payload (dashboard/controllers/logs.py analog):
        the clog module's recent committed entries plus the health-event
        digest.  Empty when the module isn't registered (opt-in)."""
        from .modules import find_module

        mod = find_module(self.mgr, "clog")
        if mod is None:
            return {"entries": [], "counts": {}, "events_total": 0,
                    "muted": []}
        return {"entries": mod.log_last(n=50), **mod.clog_digest()}

    def prometheus_metrics(self) -> list[tuple[str, str, str, list[str]]]:
        """Module-metrics hook: `map_errors` (PGs skipped as unmappable
        in api_pgs) was a module-local counter nobody could see — a
        CRUSH map that silently stopped mapping PGs deserves a scrape
        family, not a buried attribute."""
        return [
            ("ceph_tpu_dashboard_map_errors", "counter",
             "PGs the dashboard could not map to OSDs (skipped rows in "
             "/api/pgs)",
             [f"ceph_tpu_dashboard_map_errors {self.map_errors}"]),
        ]

    def render(self, path: str) -> tuple[int, str, str]:
        """(status, content-type, body) for a request path."""
        routes = {
            "/api/health": self.api_health,
            "/api/osds": self.api_osds,
            "/api/pools": self.api_pools,
            "/api/pgs": self.api_pgs,
            "/api/daemons": self.api_daemons,
            "/api/perf_history": self.api_perf_history,
            "/api/log": self.api_log,
        }
        fn = routes.get(path)
        if fn is not None:
            return 200, "application/json", json.dumps(fn())
        if path == "/":
            h = self.api_health()
            rows = "".join(
                f"<tr><td>osd.{o['osd']}</td><td>{'up' if o['up'] else 'down'}"
                f"</td><td>{'in' if o['in'] else 'out'}</td></tr>"
                for o in self.api_osds()
            )
            body = (
                "<html><head><title>ceph_tpu dashboard</title></head><body>"
                f"<h1>Cluster: {h['status']}</h1>"
                f"<p>epoch {h['osdmap_epoch']} — {h['num_up_osds']}/"
                f"{h['num_osds']} OSDs up — {h['num_pools']} pools</p>"
                f"<table border=1><tr><th>daemon</th><th>state</th><th>membership"
                f"</th></tr>{rows}</table>"
                "<p>API: /api/health /api/osds /api/pools /api/pgs "
                "/api/daemons /api/perf_history /api/log</p>"
                "</body></html>"
            )
            return 200, "text/html", body
        return 404, "text/plain", "not found"
