"""metrics_history mgr module — the time dimension of the metrics
stack (ISSUE 14; the insights/healthcheck-history role the reference
keeps in the mgr).

Every tick the module samples the same per-daemon MMgrReport
perf/status snapshots the prometheus module folds, derives rates from
the cumulative counters (restart-safe, like the iostat delta fold), and
appends them into a cardinality-bounded multi-resolution
``common/tsdb.py`` store — per-daemon series plus a cluster aggregate.
On top of the stored history it evaluates **trend sentinels**:

- ``TPU_THROUGHPUT_REGRESSION`` — encode/decode GB/s over the recent
  window falls below ``mgr_trend_regression_ratio`` of its trailing
  baseline while launch volume persists (the device got slower, not
  idler);
- ``TPU_OCCUPANCY_COLLAPSE`` — device occupancy collapses vs its
  baseline under sustained launch volume;
- ``TPU_QUEUE_WAIT_INFLATION`` — mean launch queue-wait inflates past
  ``mgr_trend_queue_wait_factor`` x baseline (the scheduler is backing
  up even though the device keeps launching).

All three raise/clear mgr -> mon exactly like ``PG_RECOVERY_STALLED``:
wording built once in ``common/health.py``, shipped in the PGMap
digest's ``history`` slice, rendered by mon `health`/`status` and the
mgr healthcheck gauge.

Failover warm-start (the PR 8 lesson applied to trends): a fresh module
imports each daemon's boot-to-now cumulative counters as a first-sight
anchor — never a rate sample — and the sentinels hold fire until a FULL
evaluation window (baseline + recent) of genuinely observed history
exists, so a mgr failover can never alarm on imported totals.

Query surface: mgr asok ``perf history ls`` / ``perf history get``,
the dashboard ``/api/perf_history`` route, and ``ceph_tpu_history_*``
meta-gauges on the scrape (series count, retained points, byte bound,
evictions — the fixed-memory witness).
"""

from __future__ import annotations

import time

from ..common import health
from ..common.log import dout
from ..common.tsdb import TimeSeriesStore
from .modules import MgrModule

# minimum elapsed seconds between two snapshots of one daemon for a
# rate sample (duplicate same-tick folds are anchor refreshes)
_RATE_MIN_DT = 0.01

# drop a (daemon, counter) rate anchor this long after its daemon last
# reported: the tsdb store LRU-caps its series, but the anchor dict
# would otherwise grow one entry per daemon EVER seen — invisible to
# the very meta-gauges that witness the store's bound.  A pruned
# daemon that returns simply re-anchors first-sight (one lost rate
# sample, never a double-count — rates carry no cumulative totals).
_ANCHOR_PRUNE_SEC = 600.0

# absolute queue-wait floor (ms): inflation below this is noise, not a
# backlog — a 0.02 ms -> 0.1 ms swing must not page anyone
_QUEUE_WAIT_FLOOR_MS = 1.0

# (family, perf counter key) pairs derived as RATES from cumulative
# counters; bytes-based families scale to GB/s
_RATE_FAMILIES = (
    ("encode_gbps", "ec_dispatch.bytes", 1e-9),
    ("decode_gbps", "ec_dispatch.decode_bytes", 1e-9),
    ("launches_per_sec", "ec_dispatch.launches", 1.0),
    ("fallback_per_sec", "ec_dispatch.fallback_launches", 1.0),
    ("op_rate", "op", 1.0),
)

# (family, perf counter key) level gauges copied as-is
_GAUGE_FAMILIES = (
    ("occupancy", "ec_dispatch.device_occupancy"),
    ("queue_wait_ms", "ec_dispatch.flight_mean_queue_wait_ms"),
)

SENTINEL_CODES = (
    "TPU_THROUGHPUT_REGRESSION",
    "TPU_OCCUPANCY_COLLAPSE",
    "TPU_QUEUE_WAIT_INFLATION",
)


class MetricsHistoryModule(MgrModule):
    NAME = "metrics_history"

    def __init__(
        self,
        max_series: int | None = None,
        ring_slots: int | None = None,
        resolutions: str | None = None,
        window_sec: float | None = None,
        baseline_sec: float | None = None,
        regression_ratio: float | None = None,
        occupancy_ratio: float | None = None,
        queue_wait_factor: float | None = None,
        min_launch_rate: float | None = None,
    ):
        """Explicit constructor values pin the knob (tests, embedded
        harnesses); None tracks the mgr's live config each tick — the
        runtime-mutable pattern the iostat module uses."""
        super().__init__()
        self._pins = {
            "mgr_history_max_series": max_series,
            "mgr_history_ring_slots": ring_slots,
            "mgr_history_resolutions": resolutions,
            "mgr_trend_window_sec": window_sec,
            "mgr_trend_baseline_sec": baseline_sec,
            "mgr_trend_regression_ratio": regression_ratio,
            "mgr_trend_occupancy_ratio": occupancy_ratio,
            "mgr_trend_queue_wait_factor": queue_wait_factor,
            "mgr_trend_min_launch_rate": min_launch_rate,
        }
        from ..common.options import OPTIONS

        self._conf = {
            name: OPTIONS[name].default if pin is None else pin
            for name, pin in self._pins.items()
        }
        self.store = TimeSeriesStore(
            max_series=int(self._conf["mgr_history_max_series"]),
            slots=int(self._conf["mgr_history_ring_slots"]),
            resolutions=str(self._conf["mgr_history_resolutions"]),
        )
        # per-(daemon, counter) previous cumulative value + timestamp
        self._prev: dict[tuple[str, str], tuple[float, float]] = {}
        # first GENUINE (post-import) cluster sample: the sentinel
        # warm-up anchor — baselines seed from the first snapshot and
        # sentinels hold fire until a full evaluation window exists
        self._first_sample_t: float | None = None
        self.sentinels: dict[str, dict] = {}  # currently-raised, by code
        self.sentinels_fired = 0  # raise TRANSITIONS (chaos tracked key)
        self.config_errors = 0  # skipped config reads (visible, not silent)

    # -- config ----------------------------------------------------------------

    def _refresh_config(self) -> None:
        conf = getattr(self.mgr, "conf", None)
        for name, pin in self._pins.items():
            if pin is not None or conf is None:
                continue
            try:
                self._conf[name] = conf.get(name)
            except Exception as e:
                # stripped test configs miss keys — the skip must leave
                # a trace, or a typo'd option name would silently pin
                # the default forever (ISSUE 12)
                self.config_errors += 1
                dout("mgr", 4, f"metrics_history: config read {name!r}: {e!r}")
        self.store.configure(
            max_series=int(self._conf["mgr_history_max_series"]),
            slots=int(self._conf["mgr_history_ring_slots"]),
            resolutions=str(self._conf["mgr_history_resolutions"]),
        )

    # -- sampling --------------------------------------------------------------

    def tick(self) -> None:
        now = time.monotonic()
        self._refresh_config()
        live = getattr(self.mgr, "_daemon_report_live", None)
        # cluster aggregates: rate families sum across daemons; level
        # gauges average across the daemons reporting them
        agg_rates: dict[str, float] = {}
        agg_gauges: dict[str, list[float]] = {}
        slow_total = 0
        any_report = False
        for daemon in self.mgr.list_daemons():
            if live is not None and not live(daemon):
                continue
            perf = self.mgr.get_daemon_perf(daemon) or {}
            status = self.mgr.get_daemon_status(daemon) or {}
            labels = {"daemon": daemon}
            reported = False
            for family, counter, scale in _RATE_FAMILIES:
                value = perf.get(counter)
                if not isinstance(value, (int, float)):
                    continue
                reported = True
                rate = self._counter_rate(daemon, counter, float(value), now)
                if rate is None:
                    continue  # first sight / restart: anchor, no sample
                rate *= scale
                self.store.append(family, labels, now, rate)
                agg_rates[family] = agg_rates.get(family, 0.0) + rate
            for family, counter in _GAUGE_FAMILIES:
                value = perf.get(counter)
                if not isinstance(value, (int, float)):
                    continue
                reported = True
                self.store.append(family, labels, now, float(value))
                agg_gauges.setdefault(family, []).append(float(value))
            slow = (status.get("slow_ops") or {}).get("count")
            if isinstance(slow, (int, float)):
                self.store.append("slow_ops", labels, now, float(slow))
                slow_total += int(slow)
                reported = True
            any_report = any_report or reported
        if any_report:
            # cluster series carry NO label values — the telemetry
            # perf-envelope reads only these (privacy contract)
            for family, rate in agg_rates.items():
                self.store.append(family, {}, now, rate)
            for family, values in agg_gauges.items():
                self.store.append(family, {}, now, sum(values) / len(values))
            self.store.append("slow_ops", {}, now, float(slow_total))
            if self._first_sample_t is None and agg_rates:
                # the first RATE sample marks genuine observed history:
                # the import tick itself only anchored counters
                self._first_sample_t = now
        # prune rate anchors of churned daemons (anchors refresh every
        # tick a daemon reports, so a stale timestamp means the daemon
        # is gone — or down long enough that a fresh first-sight anchor
        # on return is the correct, sample-free behavior anyway)
        for key, (t0, _v) in list(self._prev.items()):
            if now - t0 > _ANCHOR_PRUNE_SEC:
                del self._prev[key]
        self._evaluate_sentinels(now)

    def _counter_rate(
        self, daemon: str, counter: str, value: float, now: float
    ) -> float | None:
        """Per-second rate of one cumulative counter vs its previous
        snapshot.  First sight (mgr failover importing boot-to-now
        totals) and counter regressions (daemon restart) re-anchor and
        return None — the trend store must never record hours of
        history as one tick's throughput."""
        key = (daemon, counter)
        prev = self._prev.get(key)
        self._prev[key] = (now, value)
        if prev is None:
            return None
        t0, v0 = prev
        dt = now - t0
        if value < v0 or dt < _RATE_MIN_DT:
            return None
        return (value - v0) / dt

    # -- sentinels -------------------------------------------------------------

    def _windows(self) -> tuple[float, float]:
        # floored only against degenerate (zero/negative) windows —
        # sub-second pins are legitimate for embedded harnesses
        recent = max(float(self._conf["mgr_trend_window_sec"]), 0.05)
        baseline = max(float(self._conf["mgr_trend_baseline_sec"]), recent)
        return recent, baseline

    def _trend(self, family: str, now: float) -> tuple[float | None, float | None]:
        """(recent avg, trailing baseline avg) of one cluster series."""
        recent, baseline = self._windows()
        cur = self.store.window_value(
            family, {}, start_ago=recent, end_ago=0.0, now=now
        )
        base = self.store.window_value(
            family, {}, start_ago=recent + baseline, end_ago=recent, now=now
        )
        return cur, base

    def _evaluate_sentinels(self, now: float) -> None:
        recent, baseline = self._windows()
        if (
            self._first_sample_t is None
            or now - self._first_sample_t < recent + baseline
        ):
            # warm-up: baselines are still seeding from the first
            # genuine snapshot — a sentinel raised off a partial window
            # would be the trend twin of the cold-EMA false alarm PR 8
            # fixed for SLO burn rates
            return
        ratio = float(self._conf["mgr_trend_regression_ratio"])
        occ_ratio = float(self._conf["mgr_trend_occupancy_ratio"])
        qw_factor = float(self._conf["mgr_trend_queue_wait_factor"])
        min_launch = float(self._conf["mgr_trend_min_launch_rate"])
        cur_launch, base_launch = self._trend("launches_per_sec", now)
        # "launch volume persists": BOTH windows ran at least
        # min_launch/sec, and the recent one at least half the baseline
        # cadence.  A load drop (fewer launches) is not a regression —
        # the device got idler, not slower — and an IDLE baseline has
        # nothing for the recent window to regress FROM: without the
        # baseline-volume gate, the first busy window after an idle
        # spell would trivially pass `cur >= 0.5 * 0` and every
        # sentinel could fire on a perfectly healthy warm-up.
        volume_ok = (
            cur_launch is not None
            and base_launch is not None
            and cur_launch >= min_launch
            and base_launch >= min_launch
            and cur_launch >= 0.5 * base_launch
        )
        raised: dict[str, dict] = {}
        if volume_ok:
            regressions = {}
            for kind in ("encode", "decode"):
                cur, base = self._trend(f"{kind}_gbps", now)
                if (
                    cur is not None and base is not None and base > 0.0
                    and ratio > 0.0 and cur < ratio * base
                ):
                    regressions[kind] = {
                        "current_gbps": round(cur, 4),
                        "baseline_gbps": round(base, 4),
                        "ratio": round(cur / base, 4),
                        "launches_per_sec": round(cur_launch, 3),
                    }
            if regressions:
                raised["TPU_THROUGHPUT_REGRESSION"] = {
                    "summary": health.throughput_regression_summary(
                        regressions
                    ),
                    "detail": health.throughput_regression_detail(
                        regressions
                    ),
                    "data": regressions,
                }
            cur, base = self._trend("occupancy", now)
            if (
                cur is not None and base is not None and base > 0.01
                and occ_ratio > 0.0 and cur < occ_ratio * base
            ):
                data = {
                    "current": round(cur, 4),
                    "baseline": round(base, 4),
                    "ratio": round(cur / base, 4),
                    "launches_per_sec": round(cur_launch, 3),
                }
                raised["TPU_OCCUPANCY_COLLAPSE"] = {
                    "summary": health.occupancy_collapse_summary(data),
                    "detail": health.occupancy_collapse_detail(data),
                    "data": data,
                }
            cur, base = self._trend("queue_wait_ms", now)
            # the baseline is floored at the absolute noise floor: a
            # near-zero-wait baseline must require cur > factor x floor
            # (not factor x 0.001 ms) before "inflation" means backlog
            # rather than the first real queueing of a busy spell
            base_floored = max(base or 0.0, _QUEUE_WAIT_FLOOR_MS)
            if (
                cur is not None and base is not None
                and qw_factor > 0.0
                and cur > qw_factor * base_floored
            ):
                data = {
                    "current_ms": round(cur, 3),
                    "baseline_ms": round(base, 3),
                    "factor": round(cur / base_floored, 2),
                }
                raised["TPU_QUEUE_WAIT_INFLATION"] = {
                    "summary": health.queue_wait_inflation_summary(data),
                    "detail": health.queue_wait_inflation_detail(data),
                    "data": data,
                }
        for code in SENTINEL_CODES:
            rec = raised.get(code)
            if rec is not None:
                if code not in self.sentinels:
                    self.sentinels_fired += 1
                self.set_health_check(
                    code, "HEALTH_WARN", rec["summary"], rec["detail"]
                )
            else:
                self.clear_health_check(code)
        self.sentinels = raised

    # -- query surface ---------------------------------------------------------

    def history_ls(self) -> dict:
        """`perf history ls`: live series + the store's meta stats."""
        return {"series": self.store.series_ls(), "stats": self.store.stats()}

    def history_get(
        self,
        family: str,
        daemon: str | None = None,
        window: float = 300.0,
        step: float = 0.0,
        aggregate: str = "avg",
    ) -> dict:
        """`perf history get`: one series re-bucketed to `step` with the
        requested aggregate.  `daemon=None` reads the cluster-aggregate
        series."""
        labels = {"daemon": daemon} if daemon else {}
        return self.store.query(
            family,
            labels,
            window=float(window),
            step=float(step),
            aggregate=aggregate,
        )

    def history_digest(self) -> dict:
        """The `history` slice of the mgr's PGMap digest: the raised
        sentinels (summary + detail built by common/health.py, so mon
        `health` renders the identical wording) and the store's
        meta-stats."""
        return {
            "sentinels": {
                code: {
                    "summary": rec["summary"],
                    "detail": rec["detail"],
                    "data": rec["data"],
                }
                for code, rec in self.sentinels.items()
            },
            "sentinels_fired": self.sentinels_fired,
            "stats": self.store.stats(),
        }

    # -- prometheus ------------------------------------------------------------

    def prometheus_metrics(self) -> list[tuple[str, str, str, list[str]]]:
        """Module-metrics hook: the `ceph_tpu_history_*` meta-gauges —
        the fixed-memory witness (series count, retained points, byte
        bound, evictions) plus one always-rendered activity row per
        sentinel code."""
        stats = self.store.stats()
        sentinel_rows = [
            f'ceph_tpu_history_sentinel_active{{sentinel="{code}"}} '
            f"{int(code in self.sentinels)}"
            for code in SENTINEL_CODES
        ]
        return [
            ("ceph_tpu_history_series", "gauge",
             "time-series store: live series count (LRU-capped)",
             [f"ceph_tpu_history_series {stats['series']}"]),
            ("ceph_tpu_history_points", "gauge",
             "time-series store: retained downsample buckets",
             [f"ceph_tpu_history_points {stats['points']}"]),
            ("ceph_tpu_history_bytes", "gauge",
             "time-series store: estimated resident bytes (the fixed "
             "bound the ring geometry enforces)",
             [f"ceph_tpu_history_bytes {stats['bytes']}"]),
            ("ceph_tpu_history_evictions", "counter",
             "time-series store: series evicted by the cardinality cap",
             [f"ceph_tpu_history_evictions {stats['evictions']}"]),
            ("ceph_tpu_history_appends", "counter",
             "time-series store: samples appended",
             [f"ceph_tpu_history_appends {stats['appends']}"]),
            ("ceph_tpu_history_sentinel_active", "gauge",
             "trend sentinels currently raised (1 = raised)",
             sentinel_rows),
            ("ceph_tpu_history_sentinels_fired", "counter",
             "trend-sentinel raise transitions since module start",
             [f"ceph_tpu_history_sentinels_fired {self.sentinels_fired}"]),
        ]
