"""progress mgr module — mirror of src/pybind/mgr/progress.

The reference module turns long-running background work (recovery,
backfill, rebalance) into progress bars with completion estimates
(`ceph progress` / the `ceph -s` progress block).  Same here, fed from
the OSD status blobs (ISSUE 8): every primary reports per-PG
recovery/backfill/scrub events (objects/bytes done vs total,
PG.progress_status), and this module

- tracks each (pgid, kind) event across reports: completion fraction,
  an exponentially-smoothed objects/sec rate, and an ETA derived from
  the remaining work at that rate;
- aggregates a cluster-wide bar (total done / total objects across all
  active events);
- raises ``PG_RECOVERY_STALLED`` (HEALTH_WARN) when a recovery or
  backfill event reports no advance — objects, bytes, or newly
  discovered work — for ``mgr_progress_stall_sec``; the check clears on
  the next observed advance or when the event completes;
- exports prometheus gauges through the module-metrics hook
  (``ceph_tpu_progress_fraction`` / ``ceph_tpu_progress_rate_objects``
  / ``ceph_tpu_progress_eta_seconds``) and ships the rendered summary
  into the mgr's PGMap digest so `ceph_cli status` shows the bars.
"""

from __future__ import annotations

import time

from ..common.log import dout
from .modules import MgrModule

# rate smoothing: EMA weight of the newest inter-report sample.  High
# enough to react to a recovery speeding up, low enough that one bursty
# report doesn't swing the ETA wildly.
_RATE_ALPHA = 0.3
# minimum elapsed seconds between reports for a rate sample (duplicate
# same-tick reports are baseline updates, never samples)
_RATE_MIN_DT = 0.01

# how long regressing same-total reports are treated as failover-stale
# blobs before they are accepted as a genuinely new episode.  Stale
# overlap lasts ~one status heartbeat (the demoted primary's next
# report drops the event); a new episode persists far longer.
_REGRESS_WINDOW = 2.5


class _Event:
    """One tracked (pgid, kind) progress event."""

    __slots__ = (
        "pgid", "kind", "started", "last_change", "done", "total",
        "bytes_done", "rate", "last_seen", "_observed", "_regress_since",
        "_last_done_change",
    )

    def __init__(self, pgid: str, kind: str, now: float):
        self.pgid = pgid
        self.kind = kind
        self.started = now
        self.last_change = now  # last observed ADVANCE (stall anchor)
        self._last_done_change = now  # last OBJECTS advance (rate clock)
        self.done = 0
        self.total = 0
        self.bytes_done = 0
        self.rate = 0.0  # objects/sec, EMA
        self.last_seen = now  # last report carrying this event
        self._observed = False  # first report seeds counts, not a rate
        self._regress_since: float | None = None  # regressing-report clock

    def observe(self, ev: dict, now: float) -> None:
        done = int(ev.get("objects_done", 0))
        total = int(ev.get("objects_total", 0))
        bytes_done = int(ev.get("bytes_done", 0))
        if self._observed and done < self.done:
            if total == self.total:
                # a regressing report with the SAME total is (briefly)
                # a stale blob from the event's previous reporter —
                # primary failover overlap lasts ~one heartbeat.
                # Accepting it would lower the baseline and let the
                # next fresh report register a fake advance, masking
                # PG_RECOVERY_STALLED.  But a regression that PERSISTS
                # past the window is a genuinely new episode that
                # happens to reuse the total (rapid flap) — dropping it
                # forever would freeze the bar and raise a FALSE stall.
                if self._regress_since is None:
                    self._regress_since = now
                    return
                if now - self._regress_since < _REGRESS_WINDOW:
                    return
            # new episode on this key (different total, or a persistent
            # same-total regression): rebase everything — the old rate
            # and start time belong to another episode
            self.done = done
            self.total = max(total, done)
            self.bytes_done = bytes_done
            self.rate = 0.0
            self.started = now
            self.last_change = now
            self._last_done_change = now
            self.last_seen = now
            self._regress_since = None
            return
        self._regress_since = None
        # bytes/total baselines are MONOTONE within an episode: a stale
        # blob with equal done but lower bytes/total (failover overlap)
        # must not lower them, or the next fresh-but-unchanged report
        # would register a fake advance and re-arm the stall clock.
        # The one allowed shrink: a completion report (done == total)
        # collapses the high-water total down to done so the event can
        # classify as completed at expiry.
        if self._observed and total == done and done >= self.done:
            total = max(done, self.done)
        else:
            total = max(total, self.total)
        bytes_done = max(bytes_done, self.bytes_done)
        advanced = (
            done > self.done
            or bytes_done > self.bytes_done
            or total > self.total  # new work discovered still means alive
        )
        # a rate sample needs two reports AND real elapsed time: the
        # first report only seeds the baseline, and a duplicate report
        # in the same tick (a stale blob from the old primary next to
        # the new primary's fresh one) has dt ~ 0 — dividing by it
        # would explode the EMA to millions of objects/sec and poison
        # the ETA for many ticks.  The sample divides by the time since
        # the last ADVANCE, not the last report: a recovery advancing
        # one object per 10 heartbeats must sample 0.1 obj/s, not the
        # 1 obj/s a per-report dt would fabricate.
        dt = now - self.last_seen
        if self._observed and done > self.done and dt >= _RATE_MIN_DT:
            # the dt guard filters duplicate reports; the divisor is
            # time since the last OBJECTS advance specifically — the
            # stall anchor (last_change) also resets on bytes/total
            # advances, and dividing by that would overstate objects/sec
            # whenever bytes trickle between object completions
            sample = (done - self.done) / max(
                _RATE_MIN_DT, now - self._last_done_change
            )
            self.rate = (
                sample
                if self.rate == 0.0
                else _RATE_ALPHA * sample + (1 - _RATE_ALPHA) * self.rate
            )
        if done > self.done:
            self._last_done_change = now
        if advanced:
            self.last_change = now
        self.done = done
        self.total = max(total, done)
        self.bytes_done = bytes_done
        self.last_seen = now
        self._observed = True

    def fraction(self) -> float:
        if self.total <= 0:
            return 0.0
        return min(1.0, self.done / self.total)

    def eta_seconds(self) -> float | None:
        """Remaining objects over the smoothed rate; None until a rate
        exists (no ETA beats a bogus one)."""
        if self.rate <= 0.0:
            return None
        return max(0.0, (self.total - self.done) / self.rate)

    def render(self, now: float, stall_sec: float) -> dict:
        # a stalled event renders NO rate/ETA: the EMA's last positive
        # value next to stalled=true would be contradictory operator
        # output (a finite ETA for work that is not advancing)
        stalled = self.is_stalled(now, stall_sec)
        eta = None if stalled else self.eta_seconds()
        return {
            "pgid": self.pgid,
            "kind": self.kind,
            "objects_done": self.done,
            "objects_total": self.total,
            "bytes_done": self.bytes_done,
            "fraction": round(self.fraction(), 4),
            "rate_objects_per_sec": 0.0 if stalled else round(self.rate, 3),
            "eta_seconds": None if eta is None else round(eta, 1),
            "elapsed_seconds": round(now - self.started, 1),
            "stalled": stalled,
        }

    def is_stalled(self, now: float, stall_sec: float) -> bool:
        """Recovery/backfill that stopped advancing for the window.
        Scrubs are excluded: a chunk blocked behind client writes is
        throttling, not a stuck PG."""
        if stall_sec <= 0 or self.kind not in ("recovery", "backfill"):
            return False
        return now - self.last_change >= stall_sec

    def key(self) -> tuple[str, str]:
        return (self.pgid, self.kind)


class ProgressModule(MgrModule):
    NAME = "progress"

    # events missing from this many seconds of reports are complete
    # (the OSD stops reporting an event when the work finishes)
    EVENT_EXPIRE_SEC = 5.0

    def __init__(self, stall_sec: float | None = None):
        super().__init__()
        # an explicit constructor value pins the window (tests, embedded
        # harnesses); otherwise it tracks the mgr's live config
        self._stall_pinned = stall_sec is not None
        if stall_sec is None:
            from ..common.options import OPTIONS

            stall_sec = float(OPTIONS["mgr_progress_stall_sec"].default)
        self.stall_sec = float(stall_sec)
        self.events: dict[tuple[str, str], _Event] = {}
        # whole-OSD rebuild bars (ISSUE 15): one _Event per victim set,
        # aggregated each tick from the daemons' recovery_storm status
        # slices (every surviving primary contributes its share of the
        # failed OSD's rebuild; the sum is the whole-OSD bar)
        self.storms: dict[str, _Event] = {}
        self.completed = 0  # events that ran to completion (gauge)
        self.expired = 0    # events dropped mid-flight (reporter died)
        self.config_errors = 0  # skipped config reads (visible, not silent)

    # -- aggregation -----------------------------------------------------------

    def _refresh_config(self) -> None:
        """mgr_progress_stall_sec is runtime-mutable: re-read it from
        the mgr's Config each tick so `config set` takes effect without
        a module reload."""
        if self._stall_pinned:
            return
        conf = getattr(self.mgr, "conf", None)
        if conf is None:
            return
        try:
            self.stall_sec = float(conf.get("mgr_progress_stall_sec"))
        except Exception as e:
            # stripped test configs miss the key — trace the skip so a
            # typo'd option can't silently pin the default (ISSUE 12)
            self.config_errors += 1
            dout("mgr", 4, f"progress: config read failed: {e!r}")

    def tick(self) -> None:
        now = time.monotonic()
        self._refresh_config()
        seen: set[tuple[str, str]] = set()
        # a down daemon's frozen status must not keep refreshing its
        # events (the event would never expire and a stall could never
        # clear) — the same liveness rule the slow-ops/tpu-degraded
        # digest slices apply (Mgr._daemon_report_live)
        live = getattr(self.mgr, "_daemon_report_live", None)
        # per-victim whole-OSD rebuild accumulators (ISSUE 15): summed
        # across daemons this tick, then observed as one event each
        storm_sums: dict[str, dict] = {}
        for daemon in self.mgr.list_daemons():
            if live is not None and not live(daemon):
                continue
            status = self.mgr.get_daemon_status(daemon)
            for pgid, events in (status.get("progress") or {}).items():
                for ev in events:
                    kind = str(ev.get("kind", "recovery"))
                    key = (pgid, kind)
                    seen.add(key)
                    tracked = self.events.get(key)
                    if tracked is None:
                        tracked = self.events[key] = _Event(pgid, kind, now)
                    tracked.observe(ev, now)
            storm = status.get("recovery_storm") or {}
            if storm.get("objects_total"):
                victims = storm.get("victims") or []
                skey = "+".join(victims) if victims else "cluster"
                agg = storm_sums.setdefault(
                    skey, {"objects_done": 0, "objects_total": 0}
                )
                agg["objects_done"] += int(storm.get("objects_done", 0))
                agg["objects_total"] += int(storm.get("objects_total", 0))
        storm_seen: set[str] = set()
        for skey, agg in storm_sums.items():
            storm_seen.add(skey)
            tracked = self.storms.get(skey)
            if tracked is None:
                tracked = self.storms[skey] = _Event(skey, "storm", now)
            tracked.observe(agg, now)
        for skey, ev in list(self.storms.items()):
            if (
                skey not in storm_seen
                and now - ev.last_seen > self.EVENT_EXPIRE_SEC
            ):
                del self.storms[skey]
                # same completion rule as recovery events below: the
                # controller re-emits a final done==total bar, so a
                # storm that vanished below total lost its reporter
                # mid-rebuild — that is `expired`, not success
                if ev.total and ev.done >= ev.total:
                    self.completed += 1
                else:
                    self.expired += 1
        for key, ev in list(self.events.items()):
            if key not in seen and now - ev.last_seen > self.EVENT_EXPIRE_SEC:
                del self.events[key]
                # recovery emits an explicit final done==total report
                # (PG._recovery_final_reports), so a recovery that
                # vanished below total lost its reporter mid-flight —
                # that is `expired`.  Backfill/scrub stop reporting the
                # moment their last chunk lands (cursor/objects lag one
                # report), so their disappearance IS completion.
                if ev.kind != "recovery" or (ev.total and ev.done >= ev.total):
                    self.completed += 1
                else:
                    self.expired += 1
        self._update_health(now)

    def _update_health(self, now: float) -> None:
        slice_ = self.stalled_slice(now)
        if slice_:
            from ..common import health

            self.set_health_check(
                "PG_RECOVERY_STALLED",
                "HEALTH_WARN",
                health.recovery_stalled_summary(slice_) or "",
                health.recovery_stalled_detail(slice_),
            )
        else:
            self.clear_health_check("PG_RECOVERY_STALLED")

    # -- rendered surfaces -----------------------------------------------------

    def stalled_slice(self, now: float | None = None) -> dict[str, dict]:
        """{"<pgid>:<kind>": {pgid, kind, stalled_for_sec, objects_done,
        objects_total}} — the digest slice the mon-side health check
        renders from.  Keyed by (pgid, kind) so a PG whose recovery AND
        backfill both stall reports both, not whichever iterated last."""
        now = time.monotonic() if now is None else now
        return {
            f"{ev.pgid}:{ev.kind}": {
                "pgid": ev.pgid,
                "kind": ev.kind,
                "stalled_for_sec": round(now - ev.last_change, 1),
                "objects_done": ev.done,
                "objects_total": ev.total,
            }
            for ev in self.events.values()
            if ev.is_stalled(now, self.stall_sec)
        }

    def progress_digest(self) -> dict:
        """The `progress` slice of the mgr's PGMap digest (MMonMgrReport):
        what `ceph_cli status` renders as per-PG bars + the cluster-wide
        aggregate, and what the mon's PG_RECOVERY_STALLED check reads."""
        now = time.monotonic()
        events = [
            ev.render(now, self.stall_sec)
            for ev in sorted(self.events.values(), key=_Event.key)
        ]
        total = sum(e["objects_total"] for e in events)
        done = sum(e["objects_done"] for e in events)
        return {
            "events": events,
            "completed": self.completed,
            "expired": self.expired,
            "cluster": {
                "objects_done": done,
                "objects_total": total,
                "fraction": round(done / total, 4) if total else 1.0,
            },
            "stalled": self.stalled_slice(now),
            # whole-OSD rebuild bars (ISSUE 15): kept out of the
            # cluster aggregate above — the same objects already count
            # through their per-PG recovery events
            "storms": [
                ev.render(now, self.stall_sec)
                for ev in sorted(self.storms.values(), key=_Event.key)
            ],
        }

    def prometheus_metrics(self) -> list[tuple[str, str, str, list[str]]]:
        """Module-metrics hook the prometheus module renders: one gauge
        family per progress dimension, labeled by pgid + kind."""
        now = time.monotonic()
        frac: list[str] = []
        rate: list[str] = []
        eta: list[str] = []
        for ev in sorted(
            list(self.events.values()) + list(self.storms.values()),
            key=_Event.key,
        ):
            # built from render()'s already-gated fields so the scrape
            # can never desynchronize from the `status` bars (stalled
            # events show rate 0 / no ETA on BOTH surfaces); the storm
            # bars ride the same families labeled kind="storm"
            r = ev.render(now, self.stall_sec)
            labels = f'pgid="{ev.pgid}",kind="{ev.kind}"'
            frac.append(
                f"ceph_tpu_progress_fraction{{{labels}}} {r['fraction']:.4f}"
            )
            rate.append(
                f"ceph_tpu_progress_rate_objects{{{labels}}} "
                f"{r['rate_objects_per_sec']:.3f}"
            )
            if r["eta_seconds"] is not None:
                eta.append(
                    f"ceph_tpu_progress_eta_seconds{{{labels}}} "
                    f"{r['eta_seconds']:.1f}"
                )
        return [
            ("ceph_tpu_progress_fraction", "gauge",
             "completion fraction of active recovery/backfill/scrub", frac),
            ("ceph_tpu_progress_rate_objects", "gauge",
             "smoothed objects/sec of active progress events", rate),
            ("ceph_tpu_progress_eta_seconds", "gauge",
             "estimated seconds to completion of active progress events",
             eta),
            ("ceph_tpu_progress_active", "gauge",
             "number of active progress events",
             [f"ceph_tpu_progress_active {len(self.events)}"]),
        ]
