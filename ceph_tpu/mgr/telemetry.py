"""Telemetry mgr module — mirror of src/pybind/mgr/telemetry.

The reference's telemetry module assembles an anonymized cluster report
(cluster shape, pool/EC configuration, daemon versions, crash digests,
usage — never object names or user data) and, only when explicitly
enabled, posts it upstream.  This module keeps the report assembly and
the opt-in gate; the transport is a local report log (this environment
has no egress, and the reference also supports exactly this
`telemetry show`-without-send workflow).

Privacy contract mirrored from the reference: the report carries a
salted-hash cluster id, counts and shapes only — no names, addresses
beyond count, or payload-derived values.
"""

from __future__ import annotations

import hashlib
import json
import secrets
import time

from .modules import MgrModule

REPORT_INTERVAL = 60.0  # scaled-down telemetry interval


class TelemetryModule(MgrModule):
    NAME = "telemetry"

    def __init__(self, enabled: bool = False):
        super().__init__()
        self.enabled = enabled  # off unless the operator opts in
        self.last_report: dict | None = None
        self.reports: list[dict] = []  # the "sent" log (no egress here)
        self._last_sent = 0.0
        # Cluster salt (the reference's persisted report id): random so a
        # fixed salt can't make cluster_id a publicly recomputable hash of
        # the fsid, but cluster-persistent so reports from the same cluster
        # stay correlated across mgr failovers.  The durable home is the
        # centralized config DB (`telemetry_salt`, pushed by the
        # ConfigMonitor like the reference's mgr kv store); the random
        # value is the fallback for unconfigured clusters and is only
        # per-instance.
        self._salt: str | None = None

    def on(self) -> None:
        """`ceph telemetry on` — explicit opt-in."""
        self.enabled = True

    def off(self) -> None:
        self.enabled = False

    def _get_salt(self) -> str:
        configured = None
        conf = getattr(self.mgr, "conf", None)
        if conf is not None:
            try:
                configured = conf.get("telemetry_salt")
            except KeyError:
                configured = None
        if configured:
            return str(configured)
        if self._salt is None:
            self._salt = secrets.token_hex(16)
        return self._salt

    def _cluster_id(self) -> str:
        fsid = getattr(self.mgr.osdmap, "fsid", "") or "unset"
        return hashlib.sha256((self._get_salt() + fsid).encode()).hexdigest()[:16]

    def compile_report(self) -> dict:
        """telemetry's report assembly (module.py compile_report): shapes
        and counts, nothing identifying."""
        m = self.mgr.osdmap
        pools = []
        for p in m.pools.values():
            pools.append(
                {
                    "type": "erasure" if p.is_erasure() else "replicated",
                    "pg_num": p.pg_num,
                    "size": p.size,
                    "erasure_code_profile": sorted(
                        m.erasure_code_profiles.get(
                            p.erasure_code_profile, {}
                        ).items()
                    )
                    if p.erasure_code_profile
                    else [],
                }
            )
        up = sum(1 for o in m.osds.values() if o.up)
        report = {
            "cluster_id": self._cluster_id(),
            "ts": time.time(),
            "osd": {"count": len(m.osds), "up": up},
            "pools": pools,
            "daemons_reporting": len(self.mgr.daemons),
            "health_checks": sorted(
                {code for mod in self.mgr.modules for code in mod.health_checks}
            ),
            "perf_envelope": self._perf_envelope(),
        }
        self.last_report = report
        return report

    def _perf_envelope(self) -> dict:
        """Performance-envelope slice (ISSUE 14): shapes and counts
        only, honoring the privacy contract — series/eviction COUNTS
        from the metrics-history store and cluster-aggregate PEAKS
        (the label-free series: no daemon names, pool names, or client
        ids can reach the report).  Empty when the module isn't
        registered."""
        from .modules import find_module

        mod = find_module(self.mgr, "metrics_history")
        if mod is None:
            return {}
        stats = mod.store.stats()
        env = {
            "history_series": stats["series"],
            "history_points": stats["points"],
            "history_evictions": stats["evictions"],
            "sentinels_fired": mod.sentinels_fired,
        }
        # peaks over the store's full retention, cluster series only
        # ({} labels — built exclusively from aggregate sums/means)
        retention = 10 * 24 * 3600.0  # >= any configured retention
        for key, family in (
            ("peak_encode_gbps", "encode_gbps"),
            ("peak_decode_gbps", "decode_gbps"),
            ("peak_occupancy", "occupancy"),
            ("peak_queue_wait_ms", "queue_wait_ms"),
        ):
            peak = mod.store.window_value(
                family, {}, start_ago=retention, end_ago=0.0,
                aggregate="max",
            )
            if peak is not None:
                env[key] = round(peak, 4)
        return env

    def tick(self) -> None:
        if not self.enabled:
            return
        now = time.time()
        if now - self._last_sent < REPORT_INTERVAL:
            return
        self._last_sent = now
        self.reports.append(self.compile_report())

    # `ceph telemetry show` equivalent for the admin socket / CLI
    def show(self) -> str:
        return json.dumps(self.compile_report(), indent=2)
