"""Mgr daemon — mirror of src/mgr/ (MgrStandby/Mgr/DaemonServer).

Structure mirrored:

- Boot: beacon to the monitors (MMgrBeacon → MgrMonitor); the mon map
  decides who is active; standbys keep beaconing and take over on
  failover (MgrStandby::send_beacon).
- **DaemonServer** (src/mgr/DaemonServer.cc): receives MMgrReport from
  every daemon, keeping per-daemon perf-counter and status state
  (DaemonStateIndex analog) that modules consume.
- **Module runtime** (src/mgr/PyModuleRegistry + src/pybind/mgr):
  modules register on the active mgr and get a `serve`-style periodic
  `tick()` plus access to the daemon state, the osdmap, and mon
  commands — the same surface the reference's MgrModule exposes
  (cluster maps via `self.get()`, `mon_command`, perf counters).
"""

from __future__ import annotations

import asyncio
import json
import time

from ..common.clog import ClusterLogClient
from ..common.config import Config
from ..common.log import dout
from ..mon.client import MonClient
from ..mon.monmap import MonMap
from ..msg.messages import (
    MMgrBeacon,
    MMgrMap,
    MMgrReport,
    MMonMgrReport,
    MOSDMap,
)
from ..msg.messenger import Connection, Dispatcher, Messenger
from ..osd.osdmap import OSDMap, advance_map


class DaemonState:
    """One daemon's latest report (DaemonStateIndex entry)."""

    def __init__(self) -> None:
        self.perf: dict = {}
        self.status: dict = {}
        self.last_report = 0.0


class Mgr(Dispatcher):
    def __init__(
        self,
        name: str,
        monmap: MonMap,
        conf: Config | None = None,
        addr: str = "127.0.0.1:0",
    ):
        self.name = name
        self.monmap = monmap
        self.conf = conf or Config({"name": f"mgr.{name}"})
        self._bind_addr = addr
        stack = self.conf.get("ms_type")
        self.msgr = Messenger(f"mgr.{name}", stack=stack)
        self.monc = MonClient(f"mgr.{name}", monmap, stack=stack)
        self.clogc = ClusterLogClient(f"mgr.{name}", send=self.monc.send_log)
        self.osdmap = OSDMap()
        self.mgrmap_epoch = 0
        self.active = False
        self.daemons: dict[str, DaemonState] = {}
        self.modules: list = []
        self._tasks: list[asyncio.Task] = []
        self._running = False
        self.beacon_interval = 1.0
        self.admin_socket = None

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> None:
        await self.msgr.bind(self._bind_addr)
        self.msgr.add_dispatcher_head(self)
        self.monc.on_osdmap = self._on_osdmap
        self.monc.msgr.add_dispatcher_tail(self)  # mgrmap arrives here
        self._running = True
        await self.monc.subscribe("osdmap")
        await self.monc.subscribe("mgrmap")
        self._tasks.append(asyncio.create_task(self._beacon_loop()))
        self._tasks.append(asyncio.create_task(self._module_loop()))
        await self._start_admin_socket()

    async def _start_admin_socket(self) -> None:
        """Mgr admin socket (the `ceph tell mgr.*` surface): the iostat
        / top-clients views live here so an operator can ask "who is
        driving the load" without a prometheus stack (ISSUE 10)."""
        try:
            path = self.conf.get("admin_socket")
        except KeyError:
            path = ""
        if not path:
            return
        from ..common.admin_socket import AdminSocket

        sock = AdminSocket(path)
        from .modules import find_module

        def _module(name: str):
            module = find_module(self, name)
            if module is None:
                raise ValueError(f"{name} module not registered")
            return module

        sock.register(
            "iostat top",
            lambda cmd: {
                "clients": _module("iostat").top_clients(
                    n=int(cmd["n"]) if "n" in cmd else None,
                    by=cmd.get("by", "ops_rate"),
                )
            },
            "top-N clients by IOPS/bytes/p99 (args: n, "
            "by=ops_rate|bytes_rate|p99)",
        )
        sock.register(
            "iostat",
            lambda cmd: {"pools": _module("iostat").iostat()},
            "per-pool IO rates, windowed p99, cumulative totals",
        )
        # metrics-history query surface (ISSUE 14): the stored series
        # and their multi-resolution windows, from the operator path
        sock.register(
            "perf history ls",
            lambda cmd: _module("metrics_history").history_ls(),
            "list stored perf time series + store meta stats",
        )
        sock.register(
            "perf history get",
            lambda cmd: _module("metrics_history").history_get(
                cmd.get("series", "encode_gbps"),
                daemon=cmd.get("daemon") or None,
                window=float(cmd.get("window", 300.0)),
                step=float(cmd.get("step", 0.0)),
                aggregate=cmd.get("aggregate", "avg"),
            ),
            "one series re-bucketed over a window (args: series, "
            "daemon, window, step, aggregate=avg|min|max|last|sum)",
        )
        await sock.start()
        self.admin_socket = sock

    async def stop(self) -> None:
        try:
            await asyncio.wait_for(self.clogc.flush(), timeout=0.5)
        except Exception as e:
            # best-effort: the mon may already be gone at shutdown
            dout("mgr", 5, f"final clog flush failed: {e}")
        self._running = False
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        if self.admin_socket is not None:
            await self.admin_socket.stop()
            self.admin_socket = None
        await self.msgr.shutdown()
        await self.monc.msgr.shutdown()

    async def wait_for_active(self, timeout: float = 5.0) -> None:
        deadline = time.monotonic() + timeout
        while not self.active:
            if time.monotonic() > deadline:
                raise TimeoutError(f"mgr.{self.name} never became active")
            await asyncio.sleep(0.02)

    # -- beacons / maps --------------------------------------------------------

    async def _beacon_loop(self) -> None:
        while self._running:
            beacon = MMgrBeacon(name=self.name, addr=self.msgr.addr)
            digest = None
            if self.active:
                # PGMap digest to the mons (MMonMgrReport): what `ceph df`
                # and mon-side health read
                digest = MMonMgrReport(
                    digest=json.dumps(self.pg_digest()).encode()
                )
            for mon_name in self.monmap.ranks:
                try:
                    await self.monc.msgr.send_to(self.monmap.addrs[mon_name], beacon)
                    if digest is not None:
                        await self.monc.msgr.send_to(
                            self.monmap.addrs[mon_name], digest
                        )
                except ConnectionError:
                    continue
            try:
                await self.monc.resubscribe()
            except ConnectionError:
                pass
            await asyncio.sleep(self.beacon_interval)

    def pg_digest(self) -> dict:
        """Aggregate the OSDs' reported pool stats into the df shape:
        STORED (primary-only logical bytes), OBJECTS (primary-only head
        count), USED (raw bytes summed over every replica/shard)."""
        pools: dict[str, dict] = {}
        names = {str(p.id): p.name for p in self.osdmap.pools.values()}
        for st in self.daemons.values():
            status = st.status or {}
            for key, field in (
                ("pool_stored", "stored"),
                ("pool_heads", "objects"),
                ("pool_bytes", "used_raw"),
            ):
                for pid, v in (status.get(key) or {}).items():
                    # a pool deleted mid-report has stats from OSDs that
                    # have not yet dropped its PGs but no name in our
                    # osdmap: keep the record id-keyed and flagged
                    # rather than fabricating a "pool<N>" name that
                    # could shadow (or be shadowed by) a real pool.
                    # The "id:" prefix keeps the key out of the name
                    # namespace entirely — pool NAMES are arbitrary
                    # strings, so a live pool literally named "7" must
                    # not merge with deleted pool id 7
                    name = names.get(pid)
                    rec = pools.setdefault(
                        name if name is not None else f"id:{pid}",
                        {"stored": 0, "objects": 0, "used_raw": 0},
                    )
                    if name is None:
                        rec["deleted"] = True
                        rec["id"] = int(pid)
                    rec[field] += v
        osds = {
            daemon: sum((st.status or {}).get("pool_bytes", {}).values())
            for daemon, st in self.daemons.items()
            if daemon.startswith("osd.")
        }
        return {
            "pools": pools,
            "osds": osds,  # per-daemon raw bytes (`ceph osd df`)
            "total_used_raw": sum(p["used_raw"] for p in pools.values()),
            # per-daemon slow-request counts (OpTracker complaint ages);
            # the mon-side SLOW_OPS health check reads this slice
            "slow_ops": self.slow_ops_by_daemon(),
            # daemons whose device backend is DEGRADED (EC dispatch on
            # the host fallback); the mon-side TPU_BACKEND_DEGRADED
            # check reads this slice
            "tpu_degraded": self.tpu_degraded_by_daemon(),
            # daemons over their HBM residency target (the mempool
            # ledger's pressure verdict, ISSUE 13); the mon-side
            # TPU_HBM_PRESSURE check reads this slice
            "hbm_pressure": self.hbm_pressure_by_daemon(),
            # per-PG scrub inconsistencies from the primaries' status
            # blobs; the mon-side OSD_SCRUB_ERRORS / PG_DAMAGED
            # HEALTH_ERR checks read this slice
            "scrub_errors": self.scrub_errors_by_pg(),
            # per-PG recovery/backfill/scrub bars with rate + ETA from
            # the progress module (ISSUE 8); `ceph_cli status` renders
            # them and the mon's PG_RECOVERY_STALLED check reads the
            # `stalled` sub-slice.  Empty when no module is registered.
            "progress": self.progress_digest(),
            # per-pool IO rates + top clients from the iostat module
            # (ISSUE 10); `ceph_cli status` renders the pool rates and
            # operators read top-N through the mgr asok
            "iostat": self._module_digest("iostat_digest"),
            # per-pool SLO burn-rate slice: the mon-side
            # SLO_LATENCY_BREACH check reads `breaches`
            "slo": self._module_digest("slo_digest"),
            # gray-failure slice (ISSUE 17): per-daemon laggy-peer views
            # and hedge/shed ledgers from the OSD status blobs — the
            # evidence trail beside the mon's own OSD_SLOW_PEER state
            # (which rides the direct MOSDFailure(laggy) path, not this
            # digest) and the chaos harness's hedge-rate assertions
            "slow_peers": self.slow_peers_by_daemon(),
            # trend-sentinel slice from the metrics-history module
            # (ISSUE 14): raised TPU_THROUGHPUT_REGRESSION /
            # TPU_OCCUPANCY_COLLAPSE / TPU_QUEUE_WAIT_INFLATION checks
            # with wording built in common/health.py, plus the store's
            # meta-stats; the mon renders them like PG_RECOVERY_STALLED
            "history": self._module_digest("history_digest"),
        }

    def _module_digest(self, hook: str) -> dict:
        """A registered module's digest slice by hook name, or {} when
        no module provides it (modules are opt-in, like the
        reference's)."""
        for module in self.modules:
            digest = getattr(module, hook, None)
            if digest is not None:
                return digest()
        return {}

    def progress_digest(self) -> dict:
        """The registered progress module's digest slice, or {} when the
        module isn't loaded."""
        return self._module_digest("progress_digest")

    def slow_peers_by_daemon(self) -> dict[str, dict]:
        """Per-daemon gray-failure views (ISSUE 17): which peers each
        OSD currently flags laggy plus its hedge/deadline-shed counters.
        Daemons seeing no laggy peers and holding all-zero ledgers are
        elided; a down daemon's stale view is dropped like slow-ops."""
        out: dict[str, dict] = {}
        for daemon, st in self.daemons.items():
            sp = (st.status or {}).get("slow_peers") or {}
            if not sp.get("laggy") and not any(
                v for k, v in sp.items() if k != "laggy"
            ):
                continue
            if not self._daemon_report_live(daemon):
                continue
            out[daemon] = dict(sp)
        return out

    def tpu_degraded_by_daemon(self) -> dict[str, dict]:
        """Daemons reporting a DEGRADED device backend (the OSD status'
        tpu_backend blob, ops/guard.py verdict).  A down daemon's stale
        report is dropped like the slow-ops slice: its process — and
        with it the degraded runtime — is gone."""
        out: dict[str, dict] = {}
        for daemon, st in self.daemons.items():
            backend = (st.status or {}).get("tpu_backend") or {}
            if not backend.get("degraded"):
                continue
            if not self._daemon_report_live(daemon):
                continue
            out[daemon] = {
                "degraded_for_sec": float(backend.get("degraded_for_sec", 0.0)),
                "reason": str(backend.get("reason", "")),
                "fallback_launches": int(backend.get("fallback_launches", 0)),
            }
        return out

    def hbm_pressure_by_daemon(self) -> dict[str, dict]:
        """Daemons reporting HBM mempool pressure (the OSD status'
        hbm_pressure blob, common/mempool.py verdict).  A down daemon's
        stale report drops like the degraded slice — its process, and
        with it the resident device memory, is gone."""
        out: dict[str, dict] = {}
        for daemon, st in self.daemons.items():
            pressure = (st.status or {}).get("hbm_pressure") or {}
            if not pressure.get("pressure"):
                continue
            if not self._daemon_report_live(daemon):
                continue
            out[daemon] = {
                "ratio": float(pressure.get("ratio", 0.0)),
                "target_bytes": int(pressure.get("target_bytes", 0)),
                "total_bytes": int(pressure.get("total_bytes", 0)),
                "stage": int(pressure.get("stage", 0)),
                "stage_name": str(pressure.get("stage_name", "")),
                "pools": dict(pressure.get("pools") or {}),
            }
        return out

    def scrub_errors_by_pg(self) -> dict[str, dict]:
        """Per-PG scrub inconsistencies reported by the primaries (the
        OSD status blobs' scrub_errors slice).  Stale reports from down
        daemons drop like the other health slices; a PG whose primary
        moved clears when the new primary's clean report arrives."""
        out: dict[str, dict] = {}
        for daemon, st in self.daemons.items():
            if not self._daemon_report_live(daemon):
                continue
            for pgid, rec in ((st.status or {}).get("scrub_errors") or {}).items():
                out[pgid] = rec
        return out

    def _daemon_report_live(self, daemon: str) -> bool:
        """False when a daemon's last report is provably stale — a down
        OSD's process (and with it its in-flight ops, degraded runtime,
        ...) is gone, so its final status must not survive into the
        digest slices health checks read."""
        if daemon.startswith("osd."):
            try:
                info = self.osdmap.osds.get(int(daemon[4:]))
            except ValueError:
                info = None
            if info is not None and not info.up:
                return False
        return True

    def slow_ops_by_daemon(self) -> dict[str, dict]:
        """Daemons currently reporting slow requests (count + oldest age),
        the DaemonServer side of the OSD's `N slow requests` complaint."""
        out: dict[str, dict] = {}
        for daemon, st in self.daemons.items():
            slow = (st.status or {}).get("slow_ops") or {}
            if not slow.get("count"):
                continue
            if not self._daemon_report_live(daemon):
                continue
            out[daemon] = {
                "count": int(slow["count"]),
                "oldest_sec": float(slow.get("oldest_sec", 0.0)),
            }
        return out

    def health_checks(self) -> dict[str, dict]:
        """Mgr-visible health checks in the reference's check shape
        ({code: {severity, summary}}): what the prometheus module exports
        as the ceph_tpu_healthcheck gauge.  SLOW_OPS mirrors the mon-side
        check computed from the same digest; module checks (e.g. the
        autoscaler's POOL_PG_NUM) merge in."""
        from ..common import health

        checks: dict[str, dict] = {}
        slow = self.slow_ops_by_daemon()
        summary = health.slow_ops_summary(slow)
        if summary:
            checks["SLOW_OPS"] = {
                "severity": "HEALTH_WARN",
                "summary": summary,
                "detail": health.slow_ops_detail(slow),
            }
        down = health.down_in_osds(self.osdmap)
        if down:
            checks["OSD_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(down)} osds down",
                "detail": [f"osd.{o} is down" for o in down],
            }
        degraded = self.tpu_degraded_by_daemon()
        summary = health.tpu_degraded_summary(degraded)
        if summary:
            checks["TPU_BACKEND_DEGRADED"] = {
                "severity": "HEALTH_WARN",
                "summary": summary,
                "detail": health.tpu_degraded_detail(degraded),
            }
        pressured = self.hbm_pressure_by_daemon()
        summary = health.hbm_pressure_summary(pressured)
        if summary:
            checks["TPU_HBM_PRESSURE"] = {
                "severity": "HEALTH_WARN",
                "summary": summary,
                "detail": health.hbm_pressure_detail(pressured),
            }
        scrub = self.scrub_errors_by_pg()
        summary = health.osd_scrub_errors_summary(scrub)
        if summary:
            # data damage is an ERR, not a WARN, in the reference too:
            # an inconsistent PG is serving reads off shards that
            # disagree.  Severity derives from health.ERR_CHECKS so the
            # mon's overall_status and this gauge stay in lockstep.
            checks["OSD_SCRUB_ERRORS"] = {
                "severity": health.check_severity("OSD_SCRUB_ERRORS"),
                "summary": summary,
                "detail": health.pg_damaged_detail(scrub),
            }
            checks["PG_DAMAGED"] = {
                "severity": health.check_severity("PG_DAMAGED"),
                "summary": health.pg_damaged_summary(scrub),
                "detail": health.pg_damaged_detail(scrub),
            }
        for module in self.modules:
            checks.update(getattr(module, "health_checks", {}) or {})
        return checks

    def _on_osdmap(self, msg: MOSDMap) -> None:
        self.osdmap = advance_map(self.osdmap, msg)

    # -- dispatch --------------------------------------------------------------

    def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, MMgrMap):
            if msg.epoch > self.mgrmap_epoch:
                self.mgrmap_epoch = msg.epoch
                was = self.active
                self.active = msg.active_name == self.name
                if self.active and not was:
                    dout("mgr", 1, f"mgr.{self.name} is now active")
                    if self._running:
                        self.clogc.info(f"mgr.{self.name} is now active")
            return True
        if isinstance(msg, MMgrReport):
            st = self.daemons.setdefault(msg.daemon, DaemonState())
            try:
                st.perf = json.loads(msg.perf.decode() or "{}")
                st.status = json.loads(msg.status.decode() or "{}")
            except json.JSONDecodeError:
                return True
            st.last_report = time.monotonic()
            return True
        return False

    # -- module runtime --------------------------------------------------------

    def register_module(self, module) -> None:
        module.mgr = self
        self.modules.append(module)

    async def _module_loop(self) -> None:
        while self._running:
            await asyncio.sleep(1.0)
            if not self.active:
                continue
            for module in self.modules:
                try:
                    result = module.tick()
                    if asyncio.iscoroutine(result):
                        await result
                except Exception as e:  # a module must not kill the mgr
                    dout("mgr", 0, f"module {module.NAME} raised {e!r}")

    # -- module-facing surface (MgrModule API analog) --------------------------

    def get_daemon_perf(self, daemon: str) -> dict:
        st = self.daemons.get(daemon)
        return st.perf if st else {}

    def get_daemon_status(self, daemon: str) -> dict:
        st = self.daemons.get(daemon)
        return st.status if st else {}

    def list_daemons(self) -> list[str]:
        return sorted(self.daemons)

    async def mon_command(self, cmd: dict, timeout: float = 5.0):
        return await self.monc.command(cmd, timeout)
