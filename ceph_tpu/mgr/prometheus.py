"""prometheus mgr module — mirror of src/pybind/mgr/prometheus.

The reference's module exports every daemon's perf counters plus cluster
state in Prometheus text exposition format over HTTP.  Same here: the
module renders `scrape()` from DaemonServer state and (optionally)
serves it on a TCP port via a minimal HTTP/1.0 responder, the analog of
the reference's cherrypy server (module.py StandbyModule/Module).
"""

from __future__ import annotations

from ..common.log import dout
from ..common.perf_counters import histogram_sample_lines
from .modules import HttpServedModule, MgrModule


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _perf_type(counter: str) -> str:
    """Family type for a scalar perf value.  Most daemon perf scalars
    are monotonic counters, but the flight-recorder utilization exports
    rise AND fall (occupancy is a fraction; a dump_flight reset rebases
    everything) — announcing those as counters would make PromQL
    rate()/increase() read every dip as a counter reset."""
    name = counter.rsplit(".", 1)[-1]
    if (
        "occupancy" in name
        or "mean_queue_wait" in name
        or "busy_seconds" in name
        or "flight_records" in name
        or name == "backend_degraded"
        # launch-scheduler queue depth rises and falls with the queue
        or name == "queue_depth"
        # trace-sampling exports (ISSUE 10): the live knobs and the
        # provisional-trace depth are levels, not monotone counters
        or name in ("sample_rate", "budget_per_sec", "pending_traces")
        # pipeline ring + device-cache levels (ISSUE 11): the configured
        # depth, the current in-flight count, and the cache's resident
        # footprint all rise AND fall
        or name in ("depth", "inflight", "resident_bytes", "entries")
        # recovery-storm levels (ISSUE 15): the adaptive wave size, the
        # engagement flag and the local burn rate are levels; the
        # wave/shed/ramp/storm totals stay counters
        or name in ("wave_objects", "engaged", "burn_rate")
        # padding-waste exports (ISSUE 18): the global ratio and every
        # per-label `pad_waste.<label>` slice are fractions that rise
        # AND fall as the bucketed pad targets learn
        or "waste" in counter
        # offload-runtime registry levels (ISSUE 20): a service's pending
        # submission count drains to zero, and the registered-service
        # count is a level, not a monotone total
        or name in ("pending", "services")
    ):
        return "gauge"
    return "counter"


class PrometheusModule(HttpServedModule, MgrModule):
    NAME = "prometheus"

    def __init__(self, port: int = 0):
        MgrModule.__init__(self)
        HttpServedModule.__init__(self, port)
        self.scrape_errors = 0  # module families lost (visible, not silent)

    # -- exposition ------------------------------------------------------------

    def scrape(self) -> str:
        """The /metrics payload (module.py collect).

        Exposition contract (validated by tests/test_metrics_lint.py):
        every family gets exactly one HELP + TYPE block, families never
        repeat, and histogram families carry cumulative `le` buckets
        ending in +Inf plus `_sum`/`_count` — real Prometheus histograms,
        so `histogram_quantile()` works on op latency out of the box."""
        mgr = self.mgr
        # family name -> (type, help, [sample lines]); insertion-ordered so
        # each family renders as one HELP/TYPE block with all its samples
        families: dict[str, tuple[str, str, list[str]]] = {}

        def family(name: str, ftype: str, help_: str) -> list[str]:
            if name not in families:
                families[name] = (ftype, help_, [])
            return families[name][2]

        # cluster-level gauges (ceph_osd_up/ceph_osd_in analogs)
        osdmap = mgr.osdmap
        up = family("ceph_tpu_osd_up", "gauge", "OSD up state")
        in_ = family("ceph_tpu_osd_in", "gauge", "OSD in state")
        for osd, info in sorted(osdmap.osds.items()):
            up.append(f'ceph_tpu_osd_up{{osd="{osd}"}} {int(info.up)}')
            in_.append(f'ceph_tpu_osd_in{{osd="{osd}"}} {int(info.in_)}')
        family("ceph_tpu_osdmap_epoch", "counter", "current osdmap epoch").append(
            f"ceph_tpu_osdmap_epoch {osdmap.epoch}"
        )
        # health checks (ceph_health_detail analog): one gauge sample per
        # ACTIVE check; absent when the check clears
        checks = mgr.health_checks()
        hc = family(
            "ceph_tpu_healthcheck", "gauge",
            "active cluster health checks (1 = raised)",
        )
        for code, info in sorted(checks.items()):
            sev = info.get("severity", "HEALTH_WARN")
            hc.append(
                f'ceph_tpu_healthcheck{{name="{code}",severity="{sev}"}} 1'
            )
        # pool stats from the PGMap digest (ceph_pool_stored/objects/
        # bytes_used analogs of the reference exporter)
        digest = mgr.pg_digest()
        for metric, field_, help_ in (
            ("pool_stored_bytes", "stored", "logical bytes stored (STORED)"),
            ("pool_objects", "objects", "head objects"),
            ("pool_used_raw_bytes", "used_raw", "raw bytes incl. replicas"),
        ):
            rows = family(f"ceph_tpu_{metric}", "gauge", help_)
            for pool, st in sorted(digest["pools"].items()):
                rows.append(
                    f'ceph_tpu_{metric}{{pool="{pool}"}} {st[field_]}'
                )
        # HBM mempool ledger families (ISSUE 13): per-daemon, per-pool
        # residency gauges from the OSD status blobs' hbm_mempools
        # slice, plus the pressure verdict.  Labeled families (pool as
        # a label) rather than one family per pool, so PromQL can
        # sum/topk across pools — the promised ceph_tpu_mempool_* /
        # pressure-ratio scrape surface.
        mem_bytes = family(
            "ceph_tpu_mempool_bytes", "gauge",
            "HBM mempool ledger: bytes resident per pool",
        )
        mem_buffers = family(
            "ceph_tpu_mempool_buffers", "gauge",
            "HBM mempool ledger: buffers resident per pool",
        )
        mem_peak = family(
            "ceph_tpu_mempool_peak_bytes", "gauge",
            "HBM mempool ledger: peak bytes per pool since reset",
        )
        hbm_ratio = family(
            "ceph_tpu_hbm_pressure_ratio", "gauge",
            "HBM residency over target (0 when no target set)",
        )
        hbm_target = family(
            "ceph_tpu_hbm_target_bytes", "gauge",
            "configured ec_tpu_hbm_target_bytes (0 = pressure off)",
        )
        for daemon in mgr.list_daemons():
            status = mgr.get_daemon_status(daemon)
            for pool, st in sorted((status.get("hbm_mempools") or {}).items()):
                labels = f'daemon="{daemon}",pool="{pool}"'
                mem_bytes.append(
                    f'ceph_tpu_mempool_bytes{{{labels}}} {st.get("bytes", 0)}'
                )
                mem_buffers.append(
                    f'ceph_tpu_mempool_buffers{{{labels}}} '
                    f'{st.get("buffers", 0)}'
                )
                mem_peak.append(
                    f'ceph_tpu_mempool_peak_bytes{{{labels}}} '
                    f'{st.get("peak_bytes", 0)}'
                )
            pressure = status.get("hbm_pressure") or {}
            if pressure:
                hbm_ratio.append(
                    f'ceph_tpu_hbm_pressure_ratio{{daemon="{daemon}"}} '
                    f'{pressure.get("ratio", 0.0)}'
                )
                hbm_target.append(
                    f'ceph_tpu_hbm_target_bytes{{daemon="{daemon}"}} '
                    f'{pressure.get("target_bytes", 0)}'
                )
        # module-exported families (the reference's MgrModule
        # add_metric analog): any registered module exposing
        # `prometheus_metrics() -> [(family, type, help, samples)]`
        # contributes — the progress module's per-PG gauges ride this
        for module in mgr.modules:
            metrics = getattr(module, "prometheus_metrics", None)
            if metrics is None:
                continue
            try:
                families_out = metrics()
            except Exception as e:
                # same contract as Mgr._module_loop: one faulty module
                # loses its own families, never the whole exposition —
                # but the loss is logged + counted, not invisible
                self.scrape_errors += 1
                dout("mgr", 1,
                     f"prometheus: module "
                     f"{getattr(module, 'NAME', '?')} metrics raised "
                     f"{e!r}")
                continue
            for name, ftype, help_, rows in families_out:
                family(name, ftype, help_).extend(rows)
        # per-daemon perf counters, grouped into families across daemons
        for daemon in mgr.list_daemons():
            perf = mgr.get_daemon_perf(daemon)
            for counter, value in sorted(perf.items()):
                metric = f"ceph_tpu_{_sanitize(counter)}"
                if isinstance(value, dict) and "histogram" in value:
                    family(
                        metric, "histogram", f"perf histogram {counter}"
                    ).extend(
                        histogram_sample_lines(
                            metric, value["histogram"], f'daemon="{daemon}"'
                        )
                    )
                    continue
                if isinstance(value, dict) and "histogram2d" in value:
                    # 2D size x latency grids have no Prometheus family
                    # shape; they stay on the admin socket (dump_histograms)
                    continue
                if isinstance(value, dict):  # long-run avg {avgcount, sum}
                    family(
                        f"{metric}_sum", "counter", f"perf counter {counter} sum"
                    ).append(
                        f'{metric}_sum{{daemon="{daemon}"}} {value.get("sum", 0)}'
                    )
                    family(
                        f"{metric}_count", "counter",
                        f"perf counter {counter} sample count",
                    ).append(
                        f'{metric}_count{{daemon="{daemon}"}} {value.get("avgcount", 0)}'
                    )
                    continue
                family(
                    metric, _perf_type(counter), f"perf counter {counter}"
                ).append(f'{metric}{{daemon="{daemon}"}} {value}')
        out: list[str] = []
        for name, (ftype, help_, rows) in families.items():
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {ftype}")
            out.extend(rows)
        return "\n".join(out) + "\n"

    # -- HTTP endpoint (scaffold in modules.HttpServedModule) ----------------

    def render(self, path: str) -> tuple[int, str, str]:
        """Every path serves the exposition (the reference's exporter also
        answers /metrics only, with / as a convenience)."""
        return 200, "text/plain; version=0.0.4", self.scrape()
