"""prometheus mgr module — mirror of src/pybind/mgr/prometheus.

The reference's module exports every daemon's perf counters plus cluster
state in Prometheus text exposition format over HTTP.  Same here: the
module renders `scrape()` from DaemonServer state and (optionally)
serves it on a TCP port via a minimal HTTP/1.0 responder, the analog of
the reference's cherrypy server (module.py StandbyModule/Module).
"""

from __future__ import annotations

from .modules import HttpServedModule, MgrModule


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


class PrometheusModule(HttpServedModule, MgrModule):
    NAME = "prometheus"

    def __init__(self, port: int = 0):
        MgrModule.__init__(self)
        HttpServedModule.__init__(self, port)

    # -- exposition ------------------------------------------------------------

    def scrape(self) -> str:
        """The /metrics payload (module.py collect)."""
        out: list[str] = []
        mgr = self.mgr
        # cluster-level gauges (ceph_osd_up/ceph_osd_in analogs)
        osdmap = mgr.osdmap
        out.append("# HELP ceph_tpu_osd_up OSD up state")
        out.append("# TYPE ceph_tpu_osd_up gauge")
        for osd, info in sorted(osdmap.osds.items()):
            out.append(f'ceph_tpu_osd_up{{osd="{osd}"}} {int(info.up)}')
        out.append("# HELP ceph_tpu_osd_in OSD in state")
        out.append("# TYPE ceph_tpu_osd_in gauge")
        for osd, info in sorted(osdmap.osds.items()):
            out.append(f'ceph_tpu_osd_in{{osd="{osd}"}} {int(info.in_)}')
        out.append("# HELP ceph_tpu_osdmap_epoch current osdmap epoch")
        out.append("# TYPE ceph_tpu_osdmap_epoch counter")
        out.append(f"ceph_tpu_osdmap_epoch {osdmap.epoch}")
        # pool stats from the PGMap digest (ceph_pool_stored/objects/
        # bytes_used analogs of the reference exporter)
        digest = mgr.pg_digest()
        for metric, field_, help_ in (
            ("pool_stored_bytes", "stored", "logical bytes stored (STORED)"),
            ("pool_objects", "objects", "head objects"),
            ("pool_used_raw_bytes", "used_raw", "raw bytes incl. replicas"),
        ):
            out.append(f"# HELP ceph_tpu_{metric} {help_}")
            out.append(f"# TYPE ceph_tpu_{metric} gauge")
            for pool, st in sorted(digest["pools"].items()):
                out.append(
                    f'ceph_tpu_{metric}{{pool="{pool}"}} {st[field_]}'
                )
        # per-daemon perf counters
        seen_types: set[str] = set()
        for daemon in mgr.list_daemons():
            perf = mgr.get_daemon_perf(daemon)
            for counter, value in sorted(perf.items()):
                metric = f"ceph_tpu_{_sanitize(counter)}"
                if isinstance(value, dict):  # long-run avg {avgcount, sum}
                    value = value.get("sum", 0)
                if metric not in seen_types:
                    seen_types.add(metric)
                    out.append(f"# TYPE {metric} counter")
                out.append(f'{metric}{{daemon="{daemon}"}} {value}')
        return "\n".join(out) + "\n"

    # -- HTTP endpoint (scaffold in modules.HttpServedModule) ----------------

    def render(self, path: str) -> tuple[int, str, str]:
        """Every path serves the exposition (the reference's exporter also
        answers /metrics only, with / as a convenience)."""
        return 200, "text/plain; version=0.0.4", self.scrape()
