"""clog mgr module — the mgr-side window onto the committed cluster log.

The reference mgr subscribes to the mons' log channel (ClusterLogClient
consumers like the dashboard's audit log and the prometheus exporter's
recent-events view).  Same role here: the module subscribes to the mon
"log" stream, keeps a bounded ring of recent committed entries for the
dashboard's /api/log route, counts committed traffic per
(channel, severity) for the ceph_tpu_clog_messages_total family, and
polls the mons' `health history` for the event/mute scrape families
(ceph_tpu_health_events_total / ceph_tpu_health_muted).
"""

from __future__ import annotations

import json

from ..common.clog import severity_rank
from ..common.log import dout
from .modules import MgrModule

RECENT_KEEP = 100  # bounded dashboard ring (mon keeps the real tail)


class ClogModule(MgrModule):
    NAME = "clog"

    def __init__(self) -> None:
        super().__init__()
        from collections import deque

        self.recent = deque(maxlen=RECENT_KEEP)
        # committed entries by (channel, severity) — counter families must
        # only ever grow, so replayed tails (initial push after a
        # resubscribe) are deduped by each entity's monotone seq
        self.counts: dict[tuple[str, str], int] = {}
        self._seen_seq: dict[str, int] = {}  # who -> highest seq counted
        self.events_total = 0
        self.muted: dict[str, dict] = {}  # code -> mute record
        self._wired = False
        self._poll_errors = 0

    # -- log stream ------------------------------------------------------------

    def _wire(self) -> None:
        """Chain onto the mgr's MonClient log callback (keeps any
        previously installed consumer) and register the subscription;
        the beacon loop's resubscribe() carries it across mon failover."""
        monc = self.mgr.monc
        prev = monc.on_log

        def on_log(msg) -> None:
            if prev is not None:
                prev(msg)
            self._absorb(msg)

        monc.on_log = on_log
        self._wired = True

    def _absorb(self, msg) -> None:
        try:
            entries = json.loads(msg.entries.decode() or "[]")
        except json.JSONDecodeError:
            return
        for e in entries:
            if not isinstance(e, dict):
                continue
            who = str(e.get("who", "?"))
            seq = int(e.get("seq", 0))
            if seq <= self._seen_seq.get(who, -1):
                continue  # replayed tail (initial push) — already counted
            self._seen_seq[who] = seq
            key = (str(e.get("channel", "cluster")), str(e.get("prio", "info")))
            self.counts[key] = self.counts.get(key, 0) + 1
            self.recent.append(e)

    # -- tick ------------------------------------------------------------------

    async def tick(self) -> None:
        if not self._wired:
            self._wire()
            # subscribe() registers "log" in the want-set even if this
            # send is lost; the beacon loop's resubscribe() self-heals
            await self.mgr.monc.subscribe("log")
        try:
            rv, _, out = await self.mgr.mon_command(
                {"prefix": "health history", "num": 0}, timeout=2.0
            )
            if rv != 0:
                raise RuntimeError(f"rv={rv}")
            body = json.loads(out)
        except Exception as e:
            self._poll_errors += 1
            dout("mgr", 10, f"clog: health history poll failed: {e!r}")
            return
        self.events_total = max(
            self.events_total, int(body.get("events_total", 0))
        )
        self.muted = dict(body.get("mutes") or {})

    # -- surfacing -------------------------------------------------------------

    def log_last(
        self, n: int = 20, channel: str = "", severity: str = ""
    ) -> list[dict]:
        """The dashboard's /api/log slice: newest-last, same exact-match
        channel/severity filters the mon's `log last` applies."""
        out = [
            e
            for e in self.recent
            if (not channel or e.get("channel") == channel)
            and (not severity or e.get("prio") == severity)
        ]
        return out[-max(n, 0):]

    def clog_digest(self) -> dict:
        return {
            "counts": {
                f"{ch}.{prio}": n for (ch, prio), n in sorted(self.counts.items())
            },
            "events_total": self.events_total,
            "muted": sorted(self.muted),
        }

    def prometheus_metrics(self) -> list[tuple[str, str, str, list[str]]]:
        msg_rows = [
            f'ceph_tpu_clog_messages_total{{channel="{ch}",severity="{prio}"}} {n}'
            for (ch, prio), n in sorted(
                self.counts.items(),
                key=lambda kv: (kv[0][0], severity_rank(kv[0][1])),
            )
        ]
        muted_rows = [
            f'ceph_tpu_health_muted{{code="{code}"}} 1'
            for code in sorted(self.muted)
        ]
        return [
            ("ceph_tpu_clog_messages_total", "counter",
             "committed cluster-log entries by channel and severity",
             msg_rows),
            ("ceph_tpu_health_events_total", "counter",
             "health-check transitions recorded in the mon event history",
             [f"ceph_tpu_health_events_total {self.events_total}"]),
            ("ceph_tpu_health_muted", "gauge",
             "currently muted health checks (1 = muted; absent otherwise)",
             muted_rows),
        ]
