"""Mgr module base — mirror of the MgrModule surface
(src/pybind/mgr/mgr_module.py)."""

from __future__ import annotations


class MgrModule:
    """Base class modules subclass (mgr_module.py MgrModule): `tick()`
    is the `serve()` loop body, called on the ACTIVE mgr about once a
    second; `self.mgr` is the daemon handle (maps, daemon state, mon
    commands); health checks surface like the reference's
    `set_health_checks`."""

    NAME = "module"

    def __init__(self) -> None:
        self.mgr = None  # set by Mgr.register_module
        self.health_checks: dict[str, dict] = {}

    def tick(self) -> None:  # may be async
        pass

    def set_health_check(self, code: str, severity: str, summary: str) -> None:
        self.health_checks[code] = {"severity": severity, "summary": summary}

    def clear_health_check(self, code: str) -> None:
        self.health_checks.pop(code, None)
