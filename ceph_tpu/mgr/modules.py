"""Mgr module base — mirror of the MgrModule surface
(src/pybind/mgr/mgr_module.py)."""

from __future__ import annotations

import asyncio


class HttpServedModule:
    """Shared HTTP/1.0 scaffold for modules exposing an endpoint (the
    cherrypy analog): subclasses implement `render(path) -> (status,
    content_type, body)` and inherit serve()/shutdown().  One copy of the
    request parse / response framing, used by prometheus and dashboard."""

    def __init__(self, port: int = 0):
        self.port = port
        self._server = None
        self.addr = ""

    def render(self, path: str) -> tuple[int, str, str]:
        raise NotImplementedError

    async def serve(self, host: str = "127.0.0.1") -> str:
        async def handle(reader, writer):
            try:
                line = await reader.readline()
                parts = line.decode("latin1").split()
                path = parts[1] if len(parts) >= 2 else "/"
                while (await reader.readline()).strip():
                    pass  # drain request headers
                status, ctype, body = self.render(path.split("?")[0])
                payload = body.encode()
                writer.write(
                    f"HTTP/1.0 {status} {'OK' if status == 200 else 'NO'}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(payload)}\r\n\r\n".encode()
                    + payload
                )
                await writer.drain()
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
            finally:
                writer.close()

        self._server = await asyncio.start_server(handle, host, self.port)
        sock = self._server.sockets[0].getsockname()
        self.addr = f"{sock[0]}:{sock[1]}"
        return self.addr

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None


def find_module(mgr, name: str):
    """The registered module with this NAME, or None.  The one lookup
    shared by the mgr asok, the dashboard routes, and the telemetry
    envelope — modules are opt-in, so every caller decides its own
    miss behavior, but the scan lives once."""
    for module in getattr(mgr, "modules", []) or []:
        if getattr(module, "NAME", "") == name:
            return module
    return None


class MgrModule:
    """Base class modules subclass (mgr_module.py MgrModule): `tick()`
    is the `serve()` loop body, called on the ACTIVE mgr about once a
    second; `self.mgr` is the daemon handle (maps, daemon state, mon
    commands); health checks surface like the reference's
    `set_health_checks`."""

    NAME = "module"

    def __init__(self) -> None:
        self.mgr = None  # set by Mgr.register_module
        self.health_checks: dict[str, dict] = {}

    def tick(self) -> None:  # may be async
        pass

    def set_health_check(
        self,
        code: str,
        severity: str,
        summary: str,
        detail: list[str] | None = None,
    ) -> None:
        self.health_checks[code] = {
            "severity": severity,
            "summary": summary,
            "detail": list(detail or []),
        }

    def clear_health_check(self, code: str) -> None:
        self.health_checks.pop(code, None)
