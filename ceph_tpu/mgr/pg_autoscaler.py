"""pg_autoscaler mgr module — mirror of src/pybind/mgr/pg_autoscaler.

The reference recommends (and in `on` mode applies) per-pool pg_num so
each OSD carries about `mon_target_pg_per_osd` PGs, rounding to powers
of two and only acting when the ideal differs from the actual by >3x
(module.py _get_pool_pg_targets).  This module reproduces that math.

Mode semantics: the default is **warn** (recommendations surface as a
health check); `on` applies `osd pool set pg_num` — which this
framework restricts to empty pools, since PG splitting (the reference's
data-migration machinery behind pg_num changes) is not implemented.
"""

from __future__ import annotations

from ..common.log import dout
from .modules import MgrModule

TARGET_PG_PER_OSD = 100  # mon_target_pg_per_osd


def _nearest_power_of_two(n: float) -> int:
    if n <= 1:
        return 1
    lo = 1 << (int(n).bit_length() - 1)
    hi = lo << 1
    return hi if n - lo > hi - n else lo


class PgAutoscalerModule(MgrModule):
    NAME = "pg_autoscaler"

    def __init__(self, mode: str = "warn"):
        super().__init__()
        self.mode = mode  # "warn" | "on" | "off"
        self.last_recommendations: dict[str, dict] = {}

    def recommend(self) -> dict[str, dict]:
        """pool -> {current, ideal, should_adjust}
        (pg_autoscaler _get_pool_pg_targets)."""
        osdmap = self.mgr.osdmap
        n_osds = max(
            1, sum(1 for i in osdmap.osds.values() if i.up and i.in_)
        )
        pools = list(osdmap.pools.values())
        out: dict[str, dict] = {}
        if not pools:
            return out
        # Without utilization stats, pools split the PG budget evenly
        # (the reference biases by stored bytes; equal-share is the
        # zero-data prior it also starts from).
        budget = n_osds * TARGET_PG_PER_OSD
        for pool in pools:
            replication = pool.size
            ideal_raw = budget / max(1, replication) / len(pools)
            ideal = max(1, _nearest_power_of_two(ideal_raw))
            current = pool.pg_num
            # only flag >3x divergence (the reference's threshold)
            should = ideal > current * 3 or current > ideal * 3
            out[pool.name] = {
                "current": current,
                "ideal": ideal,
                "should_adjust": should,
            }
        return out

    async def tick(self) -> None:
        recs = self.recommend()
        self.last_recommendations = recs
        flagged = {
            name: r for name, r in recs.items() if r["should_adjust"]
        }
        if not flagged:
            self.clear_health_check("POOL_PG_NUM")
            return
        summary = ", ".join(
            f"{name}: {r['current']} -> {r['ideal']}" for name, r in flagged.items()
        )
        if self.mode != "on":
            self.set_health_check(
                "POOL_PG_NUM", "warning", f"pg_num suboptimal ({summary})"
            )
            return
        skipped = []
        for name, r in flagged.items():
            # The mon interlock requires `yes_i_really_mean_it` as the
            # caller's assertion that the pool is EMPTY (pg_num changes remap
            # every object with no PG-split migration).  Only assert it when
            # the OSD status reports actually verify emptiness; pools that
            # cannot be verified degrade to the warn-mode health check.
            if not self._pool_verified_empty(name):
                dout(
                    "mgr",
                    4,
                    f"pg_autoscaler: {name} not verifiably empty; not applying",
                )
                skipped.append(f"{name}: {r['current']} -> {r['ideal']}")
                continue
            rv, rs, _ = await self.mgr.mon_command(
                {
                    "prefix": "osd pool set",
                    "pool": name,
                    "var": "pg_num",
                    "val": str(r["ideal"]),
                    "yes_i_really_mean_it": True,
                }
            )
            if rv != 0:
                dout("mgr", 1, f"pg_autoscaler: {name} pg_num set refused: {rs}")
                skipped.append(f"{name}: {r['current']} -> {r['ideal']}")
        if skipped:
            self.set_health_check(
                "POOL_PG_NUM",
                "warning",
                f"pg_num suboptimal, not auto-applied ({', '.join(skipped)})",
            )
        else:
            self.clear_health_check("POOL_PG_NUM")

    def _pool_verified_empty(self, pool_name: str) -> bool:
        """True only when every up+in OSD has reported a status blob and all
        of them show zero objects for the pool.  An OSD that has not yet
        reported (or predates pool_objects) makes the pool unverifiable."""
        osdmap = self.mgr.osdmap
        pool = next(
            (p for p in osdmap.pools.values() if p.name == pool_name), None
        )
        if pool is None:
            return False
        pid = str(pool.id)
        if not any(i.up and i.in_ for i in osdmap.osds.values()):
            # No up+in OSD is reporting at all — nothing can vouch that the
            # pool is empty, so treat it as unverifiable rather than letting
            # the loop below pass vacuously.
            return False
        for osd_id, info in osdmap.osds.items():
            if not (info.up and info.in_):
                # A down/out OSD may still hold this pool's only copies of
                # data that no reporting OSD sees — unverifiable, not empty.
                return False
            status = self.mgr.get_daemon_status(f"osd.{osd_id}")
            counts = status.get("pool_objects")
            if counts is None:
                return False
            if counts.get(pid, 0) != 0:
                return False
        return True
