"""Stripe math + batched stripe codec driver — mirror of `ECUtil`.

Reference: /root/reference/src/osd/ECUtil.{h,cc}.  `StripeInfo` reproduces
stripe_info_t's offset algebra (stripe_width = k x chunk_size; byte B of the
logical object lives in chunk (B / chunk_size) % k of stripe B / stripe_width,
ErasureCodeInterface.h:39-58).  The codec drivers replace the reference's
per-stripe hot loop (`ECUtil::encode` calling ec->encode once per stripe,
ECUtil.cc:123-162) with ONE device launch over the whole stripe batch: the
object reshapes to (stripes, k, chunk_size) and the bitsliced kernel treats
stripes as the batch axis — this is the deep-batching design the 40 GB/s
target depends on (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from ceph_tpu.codec.base import EINVAL, EIO
from ceph_tpu.codec.interface import EcError, ErasureCodeInterface
from ceph_tpu.codec.matrix_codec import MatrixCodecMixin


def _matrix_fast_path(ec: ErasureCodeInterface) -> bool:
    """Single-launch device path applies to matrix codecs whose raw chunk
    order is the logical order (no `mapping=` remap); remapped codecs go
    through their own chunk-level interface, which is mapping-aware."""
    return isinstance(ec, MatrixCodecMixin) and not ec.get_chunk_mapping()


class StripeInfo:
    """stripe_info_t: logical <-> chunk offset algebra (ECUtil.h:27-80)."""

    def __init__(self, stripe_width: int, chunk_size: int):
        assert stripe_width % chunk_size == 0
        self.stripe_width = stripe_width
        self.chunk_size = chunk_size
        self.k = stripe_width // chunk_size

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - offset % self.stripe_width

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.stripe_width

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def offset_len_to_stripe_bounds(self, offset: int, length: int) -> tuple[int, int]:
        """Smallest stripe-aligned (offset, length) covering the range."""
        start = self.logical_to_prev_stripe_offset(offset)
        end = self.logical_to_next_stripe_offset(offset + length)
        return start, end - start

    def logical_to_chunk_position(self, offset: int) -> tuple[int, int, int]:
        """(stripe index, chunk index within stripe, offset within chunk)."""
        stripe, within = divmod(offset, self.stripe_width)
        chunk, off = divmod(within, self.chunk_size)
        return stripe, chunk, off


class PendingEncode:
    """A LAUNCHED stripe encode whose device work may still be running.

    On the matrix fast path the parity is a live device array (JAX dispatch
    is asynchronous — the launch returned while the chip works); `ready()`
    polls completion without blocking and `result()` materializes the
    per-shard chunk dict, blocking only until this launch finishes.  This
    is the device-side half of the AIO-style encode pipeline the reference
    gets from queued librados AIO in front of `ec_encode_data`
    (ECBackend.h:536-555 pipeline invariants)."""

    def __init__(self, shaped: np.ndarray, parity, k: int, m: int, want: set[int]):
        self._shaped = shaped
        self._parity = parity  # device array (fast path) or host ndarray
        self._k, self._m = k, m
        self._want = want
        self._result: dict[int, np.ndarray] | None = None
        # the span active at LAUNCH time (codec/tracing.py active_span);
        # the reap may run from an event-loop callback with no scope, so
        # the D2H side must remember where it belongs in the trace
        from ..codec.tracing import active_span

        self._span = active_span()

    def ready(self) -> bool:
        if self._result is not None:
            return True
        is_ready = getattr(self._parity, "is_ready", None)
        return True if is_ready is None else bool(is_ready())

    def launched(self) -> bool:
        """False while the parity sits in an EncodeAggregator window (the
        device hasn't been asked yet — only a flush will make it ready).
        Plain device arrays are launched by construction."""
        if self._result is not None:
            return True
        return bool(getattr(self._parity, "launched", True))

    def result(self) -> dict[int, np.ndarray]:
        if self._result is None:
            from ..codec.tracing import wait_span

            with wait_span(self._span):
                parity = np.asarray(self._parity)  # blocks until launch done
            self._span = None
            out: dict[int, np.ndarray] = {}
            for i in range(self._k):
                out[i] = np.ascontiguousarray(self._shaped[:, i, :]).reshape(-1)
            for i in range(self._m):
                out[self._k + i] = np.ascontiguousarray(parity[:, i, :]).reshape(-1)
            self._result = {i: out[i] for i in self._want}
            self._parity = self._shaped = None
        return self._result


def encode_launch(
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    data: bytes | np.ndarray,
    want: set[int] | None = None,
    aggregator=None,
) -> PendingEncode:
    """Launch a batched stripe encode WITHOUT materializing the parity.

    Matrix codecs dispatch one device launch and return immediately with a
    live handle; layered/array codecs (lrc, clay) compute eagerly (their
    chunk-level interfaces materialize internally) and the PendingEncode is
    born ready.

    With an `aggregator` (codec.matrix_codec.EncodeAggregator), the stripe
    batch is SUBMITTED instead of launched: concurrent small encodes from
    different writes coalesce into one padded device dispatch when the
    aggregation window fills or a barrier flushes (the PendingEncode's
    handle is the aggregator ticket, same poll/materialize surface)."""
    raw = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else np.asarray(data, dtype=np.uint8).ravel()
    if raw.size % sinfo.stripe_width:
        raise EcError(EINVAL, f"length {raw.size} not stripe aligned")
    k = ec.get_data_chunk_count()
    n = ec.get_chunk_count()
    m = n - k
    assert k == sinfo.k
    stripes = raw.size // sinfo.stripe_width
    shaped = raw.reshape(stripes, k, sinfo.chunk_size)
    if want is None:
        want = set(range(n))
    if _matrix_fast_path(ec) and m > 0:
        if aggregator is not None:
            return PendingEncode(shaped, aggregator.submit(ec, shaped), k, m, want)
        return PendingEncode(shaped, ec.encode_array(shaped), k, m, want)
    shards = [np.empty((stripes, sinfo.chunk_size), dtype=np.uint8) for _ in range(n)]
    for s in range(stripes):
        chunks = ec.encode(set(range(n)), shaped[s].reshape(-1))
        for i in range(n):
            shards[i][s] = chunks[i]
    pend = PendingEncode(shaped, None, 0, 0, want)
    pend._result = {i: shards[i].reshape(-1) for i in want}
    return pend


def encode(
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    data: bytes | np.ndarray,
    want: set[int] | None = None,
) -> dict[int, np.ndarray]:
    """Batched stripe encode: object -> per-shard concatenated chunks.

    `data` length must be a multiple of stripe_width (the caller pads, as
    ECTransaction does before encode_and_write).  Matrix codecs take the
    single-launch path; layered/array codecs (lrc, clay) fall back to
    per-stripe encode_chunks, still one python loop over stripes but device
    work batched inside each codec.
    """
    return encode_launch(sinfo, ec, data, want).result()


def encode_delta_launch(
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    data: bytes | np.ndarray,
    cache,
    cache_obj,
    old_gen,
    new_gen,
    cache_off: int,
    want: set[int] | None = None,
) -> PendingEncode | None:
    """RMW encode via the fully on-device delta path (ISSUE 18), or None
    when the path does not apply — the caller falls back to
    ``encode_launch`` (the materialize path), which is byte-identical by
    construction (same chosen plane program on both paths).

    Applies when the DEVICE chunk cache holds EVERY shard of the region
    — the k pre-write data chunks AND the m parity chunks — at the op's
    pre-write generation ``old_gen``.  Then:

    - the NEW data chunks commit to the cache at ``new_gen`` (the only
      host bytes that move; counted as cache insertions, and the next
      RMW's read leg wants them resident anyway),
    - ONE fused launch computes parity_new = parity_old ^
      Encode(data_old ^ data_new) entirely in HBM
      (MatrixCodecMixin.encode_delta_device),
    - the new parity replaces the cached parity in place at ``new_gen``
      (DeviceChunkCache.replace — no device_put),
    - and the committed flight record (group ``#delta``, flags ``delta``
      + ``cache_hit``) shows h2d_s == 0 and d2h_s == 0: the launch
      itself staged nothing through the host.

    Any miss, put failure, fault or DEGRADED backend returns None; the
    materialize path then re-encodes from the merged bytes under its own
    guard/fallback machinery."""
    if cache is None or old_gen is None or new_gen is None:
        return None
    raw = (
        np.frombuffer(data, dtype=np.uint8)
        if isinstance(data, (bytes, bytearray))
        else np.asarray(data, dtype=np.uint8).ravel()
    )
    if raw.size % sinfo.stripe_width:
        return None
    k = ec.get_data_chunk_count()
    n = ec.get_chunk_count()
    m = n - k
    if not (_matrix_fast_path(ec) and m > 0) or k != sinfo.k:
        return None
    from ceph_tpu.ops.guard import DeviceTimeout, device_guard

    if device_guard().degraded:
        return None
    stripes = raw.size // sinfo.stripe_width
    shard_len = stripes * sinfo.chunk_size
    shaped = raw.reshape(stripes, k, sinfo.chunk_size)
    if want is None:
        want = set(range(n))
    resident = cache.get_resident_many(
        cache_obj, range(n), old_gen, off=cache_off, length=shard_len
    )
    if resident is None:
        return None
    import time

    from ceph_tpu.common.fault_injector import faultpoint
    from ceph_tpu.ops.flight_recorder import flight_recorder, new_record

    def _fit(buf):
        return buf[:shard_len] if int(buf.size) > shard_len else buf

    fr = flight_recorder()
    rec = new_record(
        "encode", group="#delta", tickets=1, stripes=stripes,
        batch=stripes, nbytes=raw.size,
    )
    rec["flags"]["delta"] = True
    rec["flags"]["cache_hit"] = True
    try:
        with fr.active_scope(rec):
            # commit the new data chunks first: their device buffers are
            # operands of the launch.  A failed put (pressure, DEGRADED
            # flip) aborts the whole path pre-dispatch.
            new_bufs = []
            for i in range(k):
                if not cache.put(
                    cache_obj, i, new_gen, shaped[:, i, :], off=cache_off
                ):
                    return None
                buf = cache.get(cache_obj, i, new_gen, off=cache_off)
                if buf is None:
                    return None
                new_bufs.append(_fit(buf))
            t0 = time.monotonic()
            rec["dispatch_ts"] = t0
            faultpoint("codec.launch")
            parity = device_guard().call(
                lambda: ec.encode_delta_device(
                    [_fit(resident[i]) for i in range(k)],
                    new_bufs,
                    [_fit(resident[k + i]) for i in range(m)],
                    sinfo.chunk_size,
                ),
                what="delta dispatch",
            )
            # generation bump IN PLACE: the delta output never leaves
            # HBM — each parity row re-enters the cache at new_gen with
            # no device_put (the next cache-hit RMW deltas again)
            for i in range(m):
                cache.replace(
                    cache_obj, k + i, new_gen,
                    parity[:, i, :].reshape(-1), off=cache_off,
                )
            # the dispatch is async: kernel_s is the synchronous enqueue
            # slice; h2d_s and d2h_s stay 0 — this launch staged nothing
            rec["kernel_s"] = time.monotonic() - t0
            rec["complete_ts"] = time.monotonic()
            fr.commit(rec)
            return PendingEncode(shaped, parity, k, m, want)
    except DeviceTimeout as e:
        # the dispatch wedged: degrade now (clears this cache) so the
        # materialize fallback goes straight to the host oracle instead
        # of paying a second deadline wait on the same wedged runtime
        device_guard().mark_degraded(f"delta dispatch: {e}")
        return None
    except BaseException as e:
        # faultpoint or runtime error: the materialize path takes over
        # (its own guard re-runs the host oracle), and its invalidate
        # drops the half-committed new-generation puts.  Visible, not
        # silent: the fallback is logged and the materialize launch that
        # follows commits its own flight record.
        from ceph_tpu.common.log import dout

        dout("osd", 1, f"delta encode fell back to materialize: {e!r}")
        return None


class PendingDecode:
    """A LAUNCHED (or aggregator-windowed) batched stripe decode whose
    device work may still be running — the decode twin of PendingEncode.

    `handle` is a live device array or a DecodeAggregator ticket;
    `assemble(rec)` turns the materialized (stripes, nerrs, chunk) rows
    into the caller's result shape.  Codecs without a device fast path
    decode eagerly and the PendingDecode is born ready (`result=`)."""

    def __init__(self, handle, assemble, result=None):
        self._handle = handle
        self._assemble = assemble
        self._result = result
        # the span active at LAUNCH time, so a reap from an event-loop
        # callback attributes its wait to the right place in the trace
        from ..codec.tracing import active_span

        self._span = active_span() if handle is not None else None

    def ready(self) -> bool:
        if self._result is not None:
            return True
        is_ready = getattr(self._handle, "is_ready", None)
        return True if is_ready is None else bool(is_ready())

    def launched(self) -> bool:
        """False while the decode still sits in a DecodeAggregator window
        (only a flush will make it ready)."""
        if self._result is not None:
            return True
        return bool(getattr(self._handle, "launched", True))

    def result(self):
        if self._result is None:
            from ..codec.tracing import wait_span

            with wait_span(self._span):
                rec = np.asarray(self._handle)  # blocks until launch done
            self._result = self._assemble(rec)
            self._handle = self._assemble = self._span = None
        return self._result


def decode_concat_launch(
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    shards: Mapping[int, np.ndarray],
    aggregator=None,
    chunk_cache=None,
    cache_key: tuple | None = None,
    cache_off: int = 0,
) -> PendingDecode:
    """Launch a batched client-read decode WITHOUT materializing the
    reconstruction; resolves to the logical bytes.  With an `aggregator`
    (codec.matrix_codec.DecodeAggregator) the survivor batch is SUBMITTED
    instead of launched, so concurrent same-erasure-pattern degraded
    reads coalesce into one padded device dispatch.

    With a `chunk_cache` (ops/device_cache.DeviceChunkCache) and a
    `cache_key` = (object token, generation), the missing data chunks
    are consulted in HBM FIRST — a full hit serves the reconstruction
    with one D2H copy and NO launch, NO H2D (the repeated-degraded-read
    fast path, ISSUE 11) — and a miss's reconstructed rows are cached
    at materialize time for the next read of the same generation."""
    lengths = {len(v) for v in shards.values()}
    if len(lengths) != 1:
        raise EcError(EINVAL, "shards must have equal length")
    shard_len = lengths.pop()
    if shard_len % sinfo.chunk_size:
        raise EcError(EINVAL, f"shard length {shard_len} not chunk aligned")
    stripes = shard_len // sinfo.chunk_size
    k = ec.get_data_chunk_count()
    n = ec.get_chunk_count()
    have = {
        i: np.asarray(v, dtype=np.uint8).reshape(stripes, sinfo.chunk_size)
        for i, v in shards.items()
    }
    # Logical data chunk i lives at raw position chunk_index(i).
    chunk_index = getattr(ec, "chunk_index", lambda i: i)
    data_raw = [chunk_index(i) for i in range(k)]
    data = np.empty((stripes, k, sinfo.chunk_size), dtype=np.uint8)
    missing_raw = [r for r in data_raw if r not in have]
    for i, r in enumerate(data_raw):
        if r in have:
            data[:, i, :] = have[r]
    if not missing_raw:
        return PendingDecode(None, None, result=data.reshape(-1))
    use_cache = (
        chunk_cache is not None
        and chunk_cache.enabled
        and cache_key is not None
        and cache_key[1] is not None
    )
    if use_cache:
        cached = chunk_cache.fetch_many(
            cache_key[0], missing_raw, cache_key[1], off=cache_off,
            length=shard_len, kind="decode", stripes=stripes,
        )
        if cached is not None:
            for i, r in enumerate(data_raw):
                if r not in have:
                    data[:, i, :] = cached[r][:shard_len].reshape(
                        stripes, sinfo.chunk_size
                    )
            return PendingDecode(None, None, result=data.reshape(-1))
    # The decode plan needs the full erasure set (every shard we don't
    # have), not just the wanted data shards.
    erasures = [i for i in range(n) if i not in have]
    if _matrix_fast_path(ec):
        idx = ec.decode_index(erasures)
        if any(i not in have for i in idx):
            raise EcError(EIO, f"missing survivor shards {idx}")
        survivors = np.stack([have[i] for i in idx], axis=1)  # (S, k, cs)
        if aggregator is not None:
            handle = aggregator.submit(ec, erasures, survivors)
        else:
            handle = ec.decode_array(erasures, survivors)

        def _assemble(rec: np.ndarray) -> np.ndarray:
            if use_cache:
                # cache every reconstructed row (data AND parity) so the
                # next same-generation degraded read / recovery decode
                # of this object skips its H2D leg entirely
                for p, e in enumerate(erasures):
                    chunk_cache.put(
                        cache_key[0], e, cache_key[1],
                        rec[:, p, :], off=cache_off,
                    )
            for p, e in enumerate(erasures):
                if e < k:
                    data[:, e, :] = rec[:, p, :]
            return data.reshape(-1)

        return PendingDecode(handle, _assemble)
    for s in range(stripes):
        decoded = ec.decode(
            set(missing_raw), {i: buf[s] for i, buf in have.items()}
        )
        for i, r in enumerate(data_raw):
            if r in decoded:
                data[s, i, :] = decoded[r]
    return PendingDecode(None, None, result=data.reshape(-1))


def decode_concat(
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    shards: Mapping[int, np.ndarray],
) -> np.ndarray:
    """Batched client-read decode: per-shard chunk streams -> logical bytes
    (mirror of ECUtil::decode's concat overload, ECUtil.cc:12-48)."""
    return decode_concat_launch(sinfo, ec, shards).result()


def decode_shards_launch(
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    shards: Mapping[int, np.ndarray],
    need: set[int],
    aggregator=None,
    chunk_cache=None,
    cache_key: tuple | None = None,
) -> PendingDecode:
    """Launch a recovery decode WITHOUT materializing the rebuilt shards;
    resolves to {shard: stream} for `need`.  With an `aggregator`, the
    survivor batch is SUBMITTED: per-object decodes during recovery and
    backfill — where ONE erasure pattern repeats across every object in
    the PG — coalesce into one padded device launch when the window fills
    or a barrier flushes (ECBackend.flush_decodes / any ticket reap)."""
    lengths = {len(v) for v in shards.values()}
    if len(lengths) != 1:
        raise EcError(EINVAL, "shards must have equal length")
    shard_len = lengths.pop()
    stripes = shard_len // sinfo.chunk_size
    have = {
        i: np.asarray(v, dtype=np.uint8).reshape(stripes, sinfo.chunk_size)
        for i, v in shards.items()
    }
    missing = sorted(i for i in need if i not in have)
    out = {i: have[i].reshape(-1) for i in need if i in have}
    if not missing:
        return PendingDecode(None, None, result=out)
    use_cache = (
        chunk_cache is not None
        and chunk_cache.enabled
        and cache_key is not None
        and cache_key[1] is not None
    )
    if use_cache:
        # whole-shard consult (off 0): a recovery decode right after a
        # full-extent degraded read of the same generation rides HBM
        cached = chunk_cache.fetch_many(
            cache_key[0], missing, cache_key[1], off=0, length=shard_len,
            kind="decode", stripes=stripes,
        )
        if cached is not None:
            for e in missing:
                out[e] = cached[e][:shard_len]
            return PendingDecode(None, None, result=out)
    if _matrix_fast_path(ec):
        erasures = [i for i in range(ec.get_chunk_count()) if i not in have]
        idx = ec.decode_index(erasures)
        if any(i not in have for i in idx):
            raise EcError(EIO, f"missing survivor shards {idx}")
        survivors = np.stack([have[i] for i in idx], axis=1)
        if aggregator is not None:
            handle = aggregator.submit(ec, erasures, survivors)
        else:
            handle = ec.decode_array(erasures, survivors)

        def _assemble(rec: np.ndarray) -> dict[int, np.ndarray]:
            if use_cache:
                for p, e in enumerate(erasures):
                    chunk_cache.put(
                        cache_key[0], e, cache_key[1],
                        rec[:, p, :], off=0,
                    )
            for p, e in enumerate(erasures):
                if e in need:
                    out[e] = np.ascontiguousarray(rec[:, p, :]).reshape(-1)
            return out

        return PendingDecode(handle, _assemble)
    rebuilt = {e: np.empty((stripes, sinfo.chunk_size), dtype=np.uint8) for e in missing}
    for s in range(stripes):
        decoded = ec.decode(
            set(missing), {i: buf[s] for i, buf in have.items()}
        )
        for e in missing:
            rebuilt[e][s] = decoded[e]
    for e in missing:
        out[e] = rebuilt[e].reshape(-1)
    return PendingDecode(None, None, result=out)


def decode_shards(
    sinfo: StripeInfo,
    ec: ErasureCodeInterface,
    shards: Mapping[int, np.ndarray],
    need: set[int],
) -> dict[int, np.ndarray]:
    """Recovery decode: rebuild whole target shards (data or parity) from
    surviving shard streams (ECUtil::decode's per-shard overload,
    ECUtil.cc:50-121)."""
    return decode_shards_launch(sinfo, ec, shards, need).result()
