"""HashInfo — per-shard cumulative crc32c digests.

Mirror of /root/reference/src/osd/ECUtil.h:101-160: one cumulative crc32c per
shard plus the total logical chunk size, persisted alongside the object (the
reference keeps it in the `hinfo_key` xattr, ECUtil.cc:238) and verified on
every shard read (ECBackend.cc:1023-1156 `handle_sub_read`).  Digests chain
on append, so append-only writes update in O(appended bytes).
"""

from __future__ import annotations

import json

import numpy as np

from ceph_tpu.utils.crc32c import crc32c


class HashInfo:
    SEED = 0xFFFFFFFF  # reference seeds per-shard digests with -1

    def __init__(self, num_chunks: int):
        self.cumulative_shard_hashes = [self.SEED & 0xFFFFFFFF] * num_chunks
        self.total_chunk_size = 0

    def append(self, old_size: int, to_append: dict[int, bytes | np.ndarray]) -> None:
        """Chain `to_append[shard]` onto each shard digest.

        old_size is the shard-local offset the append starts at; like the
        reference, appends must be sequential (ECUtil.h append asserts)."""
        assert old_size == self.total_chunk_size, (old_size, self.total_chunk_size)
        sizes = {len(v) for v in to_append.values()}
        assert len(sizes) == 1, "all shards must append equally"
        size = sizes.pop()
        for shard, buf in to_append.items():
            self.cumulative_shard_hashes[shard] = crc32c(
                buf if isinstance(buf, (bytes, bytearray)) else np.asarray(buf),
                self.cumulative_shard_hashes[shard],
            )
        self.total_chunk_size += size

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def get_total_chunk_size(self) -> int:
        return self.total_chunk_size

    def verify_chunk(self, shard: int, data: bytes | np.ndarray) -> bool:
        """Whole-shard verification: digest of data from seed must match."""
        got = crc32c(
            data if isinstance(data, (bytes, bytearray)) else np.asarray(data),
            self.SEED,
        )
        return got == self.cumulative_shard_hashes[shard]

    # -- persistence (the xattr analog) -------------------------------------

    def encode(self) -> bytes:
        return json.dumps(
            {
                "v": 1,
                "hashes": self.cumulative_shard_hashes,
                "size": self.total_chunk_size,
            }
        ).encode()

    @classmethod
    def decode(cls, blob: bytes) -> "HashInfo":
        obj = json.loads(blob.decode())
        hi = cls(len(obj["hashes"]))
        hi.cumulative_shard_hashes = [int(x) & 0xFFFFFFFF for x in obj["hashes"]]
        hi.total_chunk_size = int(obj["size"])
        return hi
