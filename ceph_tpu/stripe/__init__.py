"""Stripe engine: offset algebra, batched codec drivers, integrity digests."""

from .hashinfo import HashInfo
from .stripe import StripeInfo, decode_concat, decode_shards, encode

__all__ = ["HashInfo", "StripeInfo", "decode_concat", "decode_shards", "encode"]
