"""RBD block layer (src/librbd)."""

from .rbd import RBD, Image, RbdError

__all__ = ["RBD", "Image", "RbdError"]
