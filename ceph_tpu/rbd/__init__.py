"""RBD block layer (src/librbd + src/journal + rbd_mirror)."""

from .mirror import (
    JournaledImage,
    MirrorDaemon,
    enable_journaling,
    promote,
)
from .rbd import RBD, Image, RbdError

__all__ = [
    "RBD",
    "Image",
    "JournaledImage",
    "MirrorDaemon",
    "RbdError",
    "enable_journaling",
    "promote",
]
