"""RBD image journaling + mirroring — mirror of src/journal + src/tools/rbd_mirror.

The reference's rbd journaling feature writes every image mutation into a
per-image journal (src/journal/Journaler; librbd/journal/) BEFORE the
image data, so a peer cluster's `rbd-mirror` daemon can replay the event
stream and converge an exact copy (tools/rbd_mirror/ImageReplayer).  This
module keeps that architecture:

- **Journal**: one append-only RADOS object per image
  (`rbd_journal.<image_id>`), length-prefixed binary records
  `seq u64 | type u8 | off u64 | len u32 | payload` — WRITE carries the
  bytes (journaling's double-write cost, as in the reference), RESIZE
  and SNAP carry their parameters.  A torn tail (crash mid-append) is
  detected by the length prefix and ignored, like Journaler's
  commit-position recovery.
- **Write-ahead**: JournaledImage appends the event before touching data
  objects; replay is idempotent (whole-event overwrite), so an image
  crash between journal append and data write converges on replay.
- **Mirror daemon**: MirrorDaemon replays events past its persisted
  position (`rbd_mirror_position.<image_id>` in the DESTINATION pool —
  the replayer owns its progress, ImageReplayer's commit position) onto
  the peer image, bootstrapping it on first sight.  `sync_once` is one
  replay pass; `run` polls continuously.
- **Promote/demote**: the image header's `primary` flag (mirroring's
  exclusive-primary model scoped down); a demoted image refuses writes.
"""

from __future__ import annotations

import asyncio
import json
import struct

from ..common.errs import EINVAL, ENOENT
from ..common.log import dout
from .rbd import RBD, Image, RbdError

_REC = struct.Struct("<QBQI")  # seq, type, off, payload len
EV_WRITE = 1
EV_RESIZE = 2
EV_SNAP_CREATE = 3
EV_SNAP_REMOVE = 4


def journal_oid(image_id: str) -> str:
    return f"rbd_journal.{image_id}"


def position_oid(image_id: str) -> str:
    return f"rbd_mirror_position.{image_id}"


def commit_oid(image_id: str) -> str:
    """Peer-committed position, recorded in the SOURCE pool so the
    primary can trim its journal (Journaler's client commit records)."""
    return f"rbd_journal_commit.{image_id}"


def pack_event(seq: int, ev_type: int, off: int, payload: bytes) -> bytes:
    return _REC.pack(seq, ev_type, off, len(payload)) + payload


def iter_events(blob: bytes):
    """Yield (seq, type, off, payload); stops at a torn tail."""
    pos = 0
    while pos + _REC.size <= len(blob):
        seq, ev_type, off, ln = _REC.unpack_from(blob, pos)
        end = pos + _REC.size + ln
        if end > len(blob):
            break  # torn append: never acked, drop
        yield seq, ev_type, off, blob[pos + _REC.size : end]
        pos = end


def applied_oid(image_id: str) -> str:
    """The primary's own replay position (librbd's journal commit
    position: events past it were journaled but maybe never applied)."""
    return f"rbd_journal_applied.{image_id}"


async def apply_event(img: Image, ev_type: int, off: int, payload: bytes) -> None:
    """Apply one journal event to an image, idempotently — shared by the
    mirror replayer and the primary's own crash recovery."""
    if ev_type == EV_WRITE:
        if off + len(payload) > img.size:
            await img.resize(off + len(payload))
        await img.write(off, payload)
    elif ev_type == EV_RESIZE:
        await img.resize(off)
    elif ev_type == EV_SNAP_CREATE:
        name = payload.decode()
        if not any(s["name"] == name for s in img.header["snaps"]):
            await img.snap_create(name)
    elif ev_type == EV_SNAP_REMOVE:
        name = payload.decode()
        if any(s["name"] == name for s in img.header["snaps"]):
            await img.snap_remove(name)


class JournaledImage:
    """Write-ahead journaling wrapper over an open Image (librbd's
    journaling feature: ImageCtx->journal interposed on the write path)."""

    def __init__(self, image: Image):
        self.image = image
        self.ioctx = image.ioctx
        self._seq = None  # lazily discovered from the journal tail

    @classmethod
    async def open(cls, rbd: RBD, name: str) -> "JournaledImage":
        img = await rbd.open(name)
        if not img.header.get("journaling"):
            raise RbdError(EINVAL, f"image {name!r} has journaling disabled")
        ji = cls(img)
        await ji._recover()
        return ji

    async def _recover(self) -> None:
        """Replay our own journal past the applied position (librbd's
        open-time journal replay): an event appended before a crash that
        never reached the data objects applies now — the write-ahead
        promise on the PRIMARY side.  Replay is idempotent full-event
        application, so re-running already-applied events is safe."""
        applied = 0
        try:
            raw = await self.ioctx.read(applied_oid(self.image.id))
            applied = json.loads(raw.decode())["applied"]
        except Exception:
            pass
        try:
            blob = await self.ioctx.read(journal_oid(self.image.id))
        except Exception:
            return
        last = applied
        for seq, ev_type, off, payload in iter_events(blob):
            if seq <= applied:
                continue
            await apply_event(self.image, ev_type, off, payload)
            last = seq
        if last != applied:
            await self.ioctx.write_full(
                applied_oid(self.image.id),
                json.dumps({"applied": last}).encode(),
            )

    async def _committed(self) -> int:
        try:
            raw = await self.ioctx.read(commit_oid(self.image.id))
            return json.loads(raw.decode())["committed"]
        except Exception:
            return 0

    async def _next_seq(self) -> int:
        if self._seq is None:
            # sequences stay monotonic across trims: the floor is the
            # peer-committed position, not just what the journal holds
            self._seq = await self._committed()
            try:
                blob = await self.ioctx.read(journal_oid(self.image.id))
                for seq, *_rest in iter_events(blob):
                    self._seq = max(self._seq, seq)
            except Exception:
                pass
        self._seq += 1
        return self._seq

    def _require_primary(self) -> None:
        if not self.image.header.get("primary", True):
            raise RbdError(EINVAL, f"image {self.image.name!r} is not primary")

    async def _append(self, ev_type: int, off: int, payload: bytes) -> None:
        seq = await self._next_seq()
        oid = journal_oid(self.image.id)
        # Trim when every existing event is peer-committed (Journaler's
        # segment expiry): the replayer skips seq <= its position, and
        # sequences never reset, so a reset journal object is safe.
        committed = await self._committed()
        if committed >= seq - 1:
            try:
                await self.ioctx.write_full(oid, b"")
            except Exception:
                pass
        await self.ioctx.append(oid, pack_event(seq, ev_type, off, payload))

    # -- journaled mutations ---------------------------------------------------
    #
    # Validation runs BEFORE the journal append: a rejected mutation must
    # never reach the event stream, or the replica would apply something
    # the primary refused (divergence).

    async def write(self, off: int, data: bytes) -> None:
        self._require_primary()
        if off + len(data) > self.image.size:
            raise RbdError(EINVAL, "write past end of image")
        await self._append(EV_WRITE, off, bytes(data))  # journal FIRST
        await self.image.write(off, data)

    async def resize(self, new_size: int) -> None:
        self._require_primary()
        await self._append(EV_RESIZE, new_size, b"")
        await self.image.resize(new_size)

    async def snap_create(self, name: str) -> None:
        self._require_primary()
        if any(s["name"] == name for s in self.image.header["snaps"]):
            raise RbdError(EINVAL, f"snapshot {name!r} exists")
        await self._append(EV_SNAP_CREATE, 0, name.encode())
        await self.image.snap_create(name)

    async def snap_remove(self, name: str) -> None:
        self._require_primary()
        if not any(s["name"] == name for s in self.image.header["snaps"]):
            raise RbdError(ENOENT, f"snapshot {name!r} not found")
        await self._append(EV_SNAP_REMOVE, 0, name.encode())
        await self.image.snap_remove(name)

    # -- reads pass through ----------------------------------------------------

    async def read(self, off: int, length: int, snap_name=None) -> bytes:
        return await self.image.read(off, length, snap_name)

    async def demote(self) -> None:
        """Primary -> replica (rbd mirror image demote)."""
        self.image.header["primary"] = False
        await self.image._save_header()


async def enable_journaling(rbd: RBD, name: str) -> None:
    """`rbd feature enable <image> journaling`."""
    img = await rbd.open(name)
    img.header["journaling"] = True
    img.header.setdefault("primary", True)
    await img._save_header()


class MirrorDaemon:
    """One-direction image replayer (rbd-mirror's ImageReplayer, scoped to
    a (source pool, destination pool) pair)."""

    def __init__(self, src_ioctx, dst_ioctx):
        self.src = src_ioctx
        self.dst = dst_ioctx
        self.src_rbd = RBD(src_ioctx)
        self.dst_rbd = RBD(dst_ioctx)
        self._running = False
        self.sync_errors = 0  # failed sync passes (visible, not silent)

    async def _position(self, image_id: str) -> int:
        try:
            raw = await self.dst.read(position_oid(image_id))
            return json.loads(raw.decode())["replayed"]
        except Exception:
            return 0

    async def _save_position(self, image_id: str, seq: int) -> None:
        await self.dst.write_full(
            position_oid(image_id), json.dumps({"replayed": seq}).encode()
        )

    async def _bootstrap(self, name: str, src_img: Image) -> Image:
        """First sight of a journaled image: create the non-primary peer
        and FULL-SYNC the current contents (ImageReplayer bootstrap's
        image sync) — bytes written before journaling was enabled exist
        only in the data objects, never in the event stream."""
        try:
            return await self.dst_rbd.open(name)
        except RbdError as e:
            if e.errno != -ENOENT:
                raise
        # snapshot the journal position FIRST: events landing during the
        # copy are both (maybe) in the copy and replayed after — replay
        # is idempotent whole-event overwrite, so that converges
        base_seq = 0
        try:
            blob = await self.src.read(journal_oid(src_img.id))
            for seq, *_rest in iter_events(blob):
                base_seq = max(base_seq, seq)
        except Exception:
            pass
        await self.dst_rbd.create(name, src_img.size, order=src_img.order)
        dst_img = await self.dst_rbd.open(name)
        dst_img.header["primary"] = False
        dst_img.header["journaling"] = True
        await dst_img._save_header()

        async def copy_state(size: int, snap_name: str | None) -> None:
            if dst_img.size != size:
                await dst_img.resize(size)
            step = 1 << src_img.order
            for off in range(0, size, step):
                chunk = await src_img.read(
                    off, min(step, size - off), snap_name=snap_name
                )
                if chunk.strip(b"\x00"):
                    await dst_img.write(off, chunk)

        # snapshot history syncs oldest-first (deep-copy's snap sync),
        # then the head
        for s in sorted(src_img.header["snaps"], key=lambda s: s["id"]):
            await copy_state(s.get("size", src_img.size), s["name"])
            await dst_img.snap_create(s["name"])
        await copy_state(src_img.size, None)
        await self._save_position(src_img.id, base_seq)
        if base_seq:
            # the copy covers everything up to base_seq: record the commit
            # so the primary can trim those events
            try:
                await self.src.write_full(
                    commit_oid(src_img.id),
                    json.dumps({"committed": base_seq}).encode(),
                )
            except Exception:
                pass
        return dst_img

    async def sync_image(self, name: str) -> int:
        """Replay this image's journal events past our position onto the
        peer; returns the number of events applied."""
        src_img = await self.src_rbd.open(name)
        if not src_img.header.get("journaling"):
            return 0
        dst_img = await self._bootstrap(name, src_img)
        if dst_img.header.get("primary", True):
            # a promoted replica owns its own history now: replaying stale
            # source events would clobber post-failover writes
            # (ImageReplayer refuses primary images)
            return 0
        pos = await self._position(src_img.id)
        try:
            blob = await self.src.read(journal_oid(src_img.id))
        except Exception:
            return 0
        applied = 0
        last = pos
        for seq, ev_type, off, payload in iter_events(blob):
            if seq <= pos:
                continue
            await apply_event(dst_img, ev_type, off, payload)
            applied += 1
            last = seq
        if applied:
            await self._save_position(src_img.id, last)
            # record the commit in the SOURCE pool so the primary can trim
            # its journal (Journaler client commit position)
            try:
                await self.src.write_full(
                    commit_oid(src_img.id),
                    json.dumps({"committed": last}).encode(),
                )
            except Exception:
                pass
        return applied

    async def sync_once(self) -> dict[str, int]:
        """One replay pass over every journaled source image."""
        out = {}
        for name in await self.src_rbd.list():
            out[name] = await self.sync_image(name)
        return out

    async def run(self, interval: float = 0.2) -> None:
        """Continuous replay (the daemon loop)."""
        self._running = True
        while self._running:
            try:
                await self.sync_once()
            except Exception as e:
                # source hiccup: retry next tick — logged + counted so a
                # permanently-failing daemon loop is not invisible
                self.sync_errors += 1
                dout("rbd", 1, f"rbd-mirror: sync pass failed: {e!r}")
            await asyncio.sleep(interval)

    def stop(self) -> None:
        self._running = False


async def promote(rbd: RBD, name: str, fence: bool = False) -> None:
    """`rbd mirror image promote` on the replica after failover.

    With `fence`, every OTHER exclusive-lock holder of the image is
    first BLOCKLISTED (osdmap blocklist) and its lock broken — the
    reference's promotion fencing.  Enforcement begins as each OSD
    applies the blocklist epoch (map propagation, the same eventual
    semantics the reference has); the lock break cuts off lock-gated
    I/O immediately, and the committed blocklist guarantees the zombie's
    client instance can never re-acquire or write once the epoch lands.
    The promoting client's own instance is never fenced."""
    img = await rbd.open(name)
    if fence:
        rados = rbd.ioctx.rados
        me = rados.objecter.reqid_name
        fenced = []
        for holder in await img.lock_owners():
            if holder["entity"] == me:
                continue  # never fence the promoting instance itself
            rv, rs, _ = await rados.mon_command(
                {"prefix": "osd blocklist add", "entity": holder["entity"]}
            )
            if rv:
                raise RbdError(-rv, f"fencing {holder['entity']} failed: {rs}")
            fenced.append(holder)
        # wait for the blocklist epoch to reach our own map before
        # breaking locks: break-then-propagate would reopen the window
        # the fence exists to close
        deadline = asyncio.get_event_loop().time() + 10.0
        while fenced and not all(
            h["entity"] in rados.objecter.osdmap.blocklist for h in fenced
        ):
            if asyncio.get_event_loop().time() > deadline:
                raise RbdError(110, "blocklist epoch did not propagate")
            await asyncio.sleep(0.05)
            await rados.objecter.monc.resubscribe()
        for holder in fenced:
            await img.break_lock(holder["entity"], holder["cookie"])
    img.header["primary"] = True
    await img._save_header()
