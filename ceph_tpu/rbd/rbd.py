"""RBD — block images over RADOS, mirror of src/librbd.

Reference structure mirrored (librbd is 110k LoC; this is the core
data-path slice — SURVEY.md §2.7 "Access layers"):

- An image is a **header object** `rbd_header.<id>` holding size/order/
  snapshot metadata (librbd's ImageCtx reads the same from its header),
  plus data objects `rbd_data.<id>.<objno>` each covering `2^order`
  bytes (librbd/io/ObjectRequest.cc object mapping; order default 22 =
  4 MiB).
- I/O maps logical extents onto data objects (io/ImageRequest.cc →
  Striper math with stripe_count=1, the rbd default layout).
- **Snapshots** are copy-on-write: the first write to an object after a
  snapshot preserves the pre-write content under
  `rbd_data.<id>.<objno>@<snap_id>` before the head is modified —
  client-driven COW standing in for the reference's OSD-side SnapSet
  clones (PrimaryLogPG make_writeable); reads from a snapshot pick the
  oldest preserved copy at-or-after it, falling back to head.
- The image directory object `rbd_directory` maps names → ids
  (librbd's rbd_directory omap).

Single-writer images (the reference guards multi-client access with its
exclusive-lock feature; that is the assumed mode here).
"""

from __future__ import annotations

import json
import secrets

from ..common.errs import EEXIST, EINVAL, ENOENT

DIRECTORY_OID = "rbd_directory"
DEFAULT_ORDER = 22  # 4 MiB objects


class RbdError(Exception):
    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(f"{msg} (errno {self.errno})")


class RBD:
    """Pool-level image operations (librbd::RBD)."""

    def __init__(self, ioctx):
        self.ioctx = ioctx

    async def _read_directory(self) -> dict[str, str]:
        try:
            raw = await self.ioctx.read(DIRECTORY_OID)
            return json.loads(raw.decode() or "{}")
        except Exception:
            return {}

    async def _write_directory(self, d: dict[str, str]) -> None:
        await self.ioctx.write_full(DIRECTORY_OID, json.dumps(d).encode())

    async def create(self, name: str, size: int, order: int = DEFAULT_ORDER) -> None:
        """rbd create (librbd::create)."""
        if not 12 <= order <= 26:
            raise RbdError(EINVAL, f"order {order} out of range")
        directory = await self._read_directory()
        if name in directory:
            raise RbdError(EEXIST, f"image {name!r} exists")
        image_id = secrets.token_hex(8)
        header = {
            "id": image_id,
            "size": size,
            "max_size": size,  # high-water mark for cleanup after shrinks
            "order": order,
            "snaps": [],  # [{"id": int, "name": str}]
            "snap_seq": 0,
        }
        await self.ioctx.write_full(
            f"rbd_header.{image_id}", json.dumps(header).encode()
        )
        directory[name] = image_id
        await self._write_directory(directory)

    async def list(self) -> list[str]:
        return sorted(await self._read_directory())

    async def remove(self, name: str) -> None:
        directory = await self._read_directory()
        image_id = directory.get(name)
        if image_id is None:
            raise RbdError(ENOENT, f"image {name!r} not found")
        img = await self.open(name)
        # iterate the LARGEST size the image ever had: a shrunk image's
        # snap objects live past the current end
        span = max(img.size, img.header.get("max_size", img.size))
        objects = (span + img.object_bytes - 1) // img.object_bytes
        for objno in range(objects):
            for oid in [img._data_oid(objno)] + [
                img._snap_oid(objno, s["id"]) for s in img.header["snaps"]
            ]:
                try:
                    await self.ioctx.remove(oid)
                except Exception:
                    pass
        await self.ioctx.remove(f"rbd_header.{image_id}")
        del directory[name]
        await self._write_directory(directory)

    async def open(self, name: str) -> "Image":
        directory = await self._read_directory()
        image_id = directory.get(name)
        if image_id is None:
            raise RbdError(ENOENT, f"image {name!r} not found")
        img = Image(self.ioctx, name, image_id)
        await img._load_header()
        return img


class Image:
    """One open image (librbd::Image / ImageCtx)."""

    def __init__(self, ioctx, name: str, image_id: str):
        self.ioctx = ioctx
        self.name = name
        self.id = image_id
        self.header: dict = {}

    # -- header ----------------------------------------------------------------

    @property
    def _header_oid(self) -> str:
        return f"rbd_header.{self.id}"

    async def _load_header(self) -> None:
        raw = await self.ioctx.read(self._header_oid)
        self.header = json.loads(raw.decode())

    async def _save_header(self) -> None:
        await self.ioctx.write_full(self._header_oid, json.dumps(self.header).encode())

    @property
    def size(self) -> int:
        return self.header["size"]

    @property
    def order(self) -> int:
        return self.header["order"]

    @property
    def object_bytes(self) -> int:
        return 1 << self.order

    def _data_oid(self, objno: int) -> str:
        return f"rbd_data.{self.id}.{objno:016x}"

    def _snap_oid(self, objno: int, snap_id: int) -> str:
        return f"rbd_data.{self.id}.{objno:016x}@{snap_id}"

    def _extents(self, off: int, length: int):
        """Logical range -> [(objno, obj_off, len)] (stripe_count=1)."""
        out = []
        ob = self.object_bytes
        while length > 0:
            objno = off // ob
            obj_off = off % ob
            take = min(ob - obj_off, length)
            out.append((objno, obj_off, take))
            off += take
            length -= take
        return out

    # -- I/O -------------------------------------------------------------------

    async def write(self, off: int, data: bytes) -> None:
        if off + len(data) > self.size:
            raise RbdError(EINVAL, "write past end of image")
        cursor = 0
        for objno, obj_off, ln in self._extents(off, len(data)):
            await self._cow_preserve(objno)
            await self.ioctx.write(
                self._data_oid(objno), data[cursor : cursor + ln], obj_off
            )
            cursor += ln

    async def _cow_preserve(self, objno: int) -> None:
        """Before the first write to an object after the latest snapshot,
        copy its current content to the snap object (the client-side
        stand-in for PrimaryLogPG::make_writeable's clone)."""
        snaps = self.header["snaps"]
        if not snaps:
            return
        latest = snaps[-1]["id"]
        snap_oid = self._snap_oid(objno, latest)
        try:
            await self.ioctx.stat(snap_oid)
            return  # already preserved for this snap
        except Exception:
            pass
        from ..client.rados import RadosError
        from ..common.errs import ENOENT

        try:
            current = await self.ioctx.read(self._data_oid(objno))
        except RadosError as e:
            # ONLY a genuinely absent object preserves as empty; any
            # transport error must propagate, or a zero copy would be
            # permanently recorded as the snapshot's content.
            if e.errno != -ENOENT:
                raise
            current = b""
        # A never-written object preserves as one zero byte: block reads
        # zero-fill past object ends, so it reads identically, and the
        # copy reliably exists for the preserved-check above.
        await self.ioctx.write_full(snap_oid, current or b"\x00")

    async def read(self, off: int, length: int, snap_name: str | None = None) -> bytes:
        if off >= self.size:
            return b""
        length = min(length, self.size - off)
        snap_id = None
        if snap_name is not None:
            snap_id = self._snap_by_name(snap_name)["id"]
        parts = []
        for objno, obj_off, ln in self._extents(off, length):
            data = await self._read_object(objno, snap_id)
            parts.append(data[obj_off : obj_off + ln].ljust(ln, b"\x00"))
        return b"".join(parts)

    async def _read_object(self, objno: int, snap_id: int | None) -> bytes:
        """Snapshot read resolution: the oldest preserved copy with
        snap >= snap_id wins, else the head (librbd's snap read maps to
        the SnapSet clone covering the snap)."""
        from ..client.rados import RadosError
        from ..common.errs import ENOENT

        if snap_id is not None:
            for snap in self.header["snaps"]:
                if snap["id"] >= snap_id:
                    try:
                        return await self.ioctx.read(self._snap_oid(objno, snap["id"]))
                    except RadosError as e:
                        if e.errno != -ENOENT:
                            raise
                        continue  # not preserved under this snap; try newer
        try:
            return await self.ioctx.read(self._data_oid(objno))
        except RadosError as e:
            if e.errno != -ENOENT:
                raise
            return b""

    async def resize(self, new_size: int) -> None:
        """librbd::resize; shrinking drops whole objects past the end —
        after COW-preserving them, so existing snapshots survive the
        shrink (librbd keeps clones across resize)."""
        old = self.size
        if new_size < old:
            ob = self.object_bytes
            first_dead = (new_size + ob - 1) // ob
            last = (old - 1) // ob if old else 0
            for objno in range(first_dead, last + 1):
                await self._cow_preserve(objno)
                try:
                    await self.ioctx.remove(self._data_oid(objno))
                except Exception:
                    pass
            if new_size % ob:
                boundary = new_size // ob
                await self._cow_preserve(boundary)
                try:
                    await self.ioctx.truncate(self._data_oid(boundary), new_size % ob)
                except Exception:
                    pass
        self.header["size"] = new_size
        self.header["max_size"] = max(self.header.get("max_size", old), new_size)
        await self._save_header()

    # -- snapshots ---------------------------------------------------------------

    def _snap_by_name(self, name: str) -> dict:
        for snap in self.header["snaps"]:
            if snap["name"] == name:
                return snap
        raise RbdError(ENOENT, f"snapshot {name!r} not found")

    async def snap_create(self, name: str) -> None:
        """librbd snap_create: allocate a snap id; objects copy-on-write
        lazily as the head is modified."""
        if any(s["name"] == name for s in self.header["snaps"]):
            raise RbdError(EEXIST, f"snapshot {name!r} exists")
        self.header["snap_seq"] += 1
        self.header["snaps"].append(
            {"id": self.header["snap_seq"], "name": name, "size": self.size}
        )
        await self._save_header()

    async def snap_list(self) -> list[str]:
        return [s["name"] for s in self.header["snaps"]]

    async def snap_rollback(self, name: str) -> None:
        """librbd snap_rollback: head objects revert to the snapshot's
        content.  Rollback writes are writes: they COW-preserve first, so
        snapshots newer than the target keep their content."""
        snap = self._snap_by_name(name)
        span = max(self.size, self.header.get("max_size", self.size))
        objects = (span + self.object_bytes - 1) // self.object_bytes
        for objno in range(objects):
            data = await self._read_object(objno, snap["id"])
            await self._cow_preserve(objno)
            await self.ioctx.write_full(self._data_oid(objno), data or b"\x00")
        self.header["size"] = snap.get("size", self.size)
        await self._save_header()

    async def snap_remove(self, name: str) -> None:
        """librbd snap_remove.  A preserved copy at snap X covers every
        snapshot back to the previous copy; removing X must hand the copy
        down to the newest surviving snapshot in that range (the
        reference's SnapSet clone-overlap merge on snap trim), else older
        snapshots would silently read newer data."""
        snap = self._snap_by_name(name)
        remaining = [s for s in self.header["snaps"] if s["name"] != name]
        older = [s for s in remaining if s["id"] < snap["id"]]
        heir = older[-1] if older else None
        span = max(self.size, self.header.get("max_size", self.size))
        objects = (span + self.object_bytes - 1) // self.object_bytes
        for objno in range(objects):
            src = self._snap_oid(objno, snap["id"])
            try:
                data = await self.ioctx.read(src)
            except Exception:
                continue  # never preserved under this snap
            if heir is not None:
                heir_oid = self._snap_oid(objno, heir["id"])
                try:
                    await self.ioctx.stat(heir_oid)
                except Exception:
                    # heir has no own copy: it was covered by X's
                    await self.ioctx.write_full(heir_oid, data)
            await self.ioctx.remove(src)
        self.header["snaps"] = remaining
        await self._save_header()
