"""RBD — block images over RADOS, mirror of src/librbd.

Reference structure mirrored (librbd is 110k LoC; this is the core
data-path slice — SURVEY.md §2.7 "Access layers"):

- An image is a **header object** `rbd_header.<id>` holding size/order/
  snapshot metadata (librbd's ImageCtx reads the same from its header),
  plus data objects `rbd_data.<id>.<objno>` each covering `2^order`
  bytes (librbd/io/ObjectRequest.cc object mapping; order default 22 =
  4 MiB).
- I/O maps logical extents onto data objects (io/ImageRequest.cc →
  Striper math with stripe_count=1, the rbd default layout).
- **Snapshots are SERVER-SIDE**, exactly like librbd's: snap ids come
  from the pool's self-managed snap counter (rados
  selfmanaged_snap_create → OSDMonitor), every data write carries the
  image's SnapContext, and the OSD clones on first-write-after-snap
  (PrimaryLogPG::make_writeable → SnapSet clones).  Snapshot reads pass
  the snap id; rollback/trim use the OSD's ROLLBACK and snap-trim ops.
  Nothing is copied client-side.
- The image directory object `rbd_directory` maps names → ids
  (librbd's rbd_directory omap).

Single-writer images (the reference guards multi-client access with its
exclusive-lock feature; that is the assumed mode here).
"""

from __future__ import annotations

import json
import secrets

from ..client.rados import RadosError
from ..cls import client as cls_client
from ..common.errs import EBUSY, EEXIST, EINVAL, ENOENT

DIRECTORY_OID = "rbd_directory"
CHILDREN_OID = "rbd_children"  # parent "<id>@<snap_id>" -> [child ids]
DEFAULT_ORDER = 22  # 4 MiB objects


class RbdError(Exception):
    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(f"{msg} (errno {self.errno})")


class RBD:
    """Pool-level image operations (librbd::RBD)."""

    def __init__(self, ioctx):
        self.ioctx = ioctx

    async def _read_directory(self) -> dict[str, str]:
        try:
            raw = await self.ioctx.read(DIRECTORY_OID)
            return json.loads(raw.decode() or "{}")
        except Exception:
            return {}

    async def _write_directory(self, d: dict[str, str]) -> None:
        await self.ioctx.write_full(DIRECTORY_OID, json.dumps(d).encode())

    async def create(self, name: str, size: int, order: int = DEFAULT_ORDER) -> None:
        """rbd create (librbd::create)."""
        if not 12 <= order <= 26:
            raise RbdError(EINVAL, f"order {order} out of range")
        directory = await self._read_directory()
        if name in directory:
            raise RbdError(EEXIST, f"image {name!r} exists")
        image_id = secrets.token_hex(8)
        header = {
            "id": image_id,
            "size": size,
            "max_size": size,  # high-water mark for cleanup after shrinks
            "order": order,
            "snaps": [],  # [{"id": int, "name": str, "size": int}]
        }
        await self.ioctx.write_full(
            f"rbd_header.{image_id}", json.dumps(header).encode()
        )
        directory[name] = image_id
        await self._write_directory(directory)

    async def list(self) -> list[str]:
        return sorted(await self._read_directory())

    async def _read_children(self) -> dict[str, list[str]]:
        try:
            raw = await self.ioctx.read(CHILDREN_OID)
            return json.loads(raw.decode() or "{}")
        except Exception:
            return {}

    async def _write_children(self, d: dict[str, list[str]]) -> None:
        await self.ioctx.write_full(
            CHILDREN_OID, json.dumps({k: v for k, v in d.items() if v}).encode()
        )

    async def clone(
        self, parent_name: str, snap_name: str, child_name: str,
        order: int | None = None,
    ) -> None:
        """rbd clone (librbd::clone): a copy-on-write child of a
        PROTECTED parent snapshot.  The child starts as pure metadata —
        reads fall through to the parent's snap until copy-up."""
        parent = await self.open(parent_name)
        snap = parent._snap_by_name(snap_name)
        if not snap.get("protected"):
            raise RbdError(EINVAL, f"snapshot {snap_name!r} is not protected")
        directory = await self._read_directory()
        if child_name in directory:
            raise RbdError(EEXIST, f"image {child_name!r} exists")
        child_id = secrets.token_hex(8)
        overlap = snap.get("size", parent.size)
        header = {
            "id": child_id,
            "size": overlap,
            "max_size": overlap,
            "order": order if order is not None else parent.order,
            "snaps": [],
            "parent": {
                "image_id": parent.id,
                "image_name": parent_name,
                "snap_id": snap["id"],
                "snap_name": snap_name,
                "overlap": overlap,
            },
        }
        await self.ioctx.write_full(
            f"rbd_header.{child_id}", json.dumps(header).encode()
        )
        directory[child_name] = child_id
        await self._write_directory(directory)
        children = await self._read_children()
        children.setdefault(f"{parent.id}@{snap['id']}", []).append(child_id)
        await self._write_children(children)

    async def children(self, parent_name: str, snap_name: str) -> list[str]:
        """rbd children: names of clones of this snapshot."""
        parent = await self.open(parent_name)
        snap = parent._snap_by_name(snap_name)
        ids = (await self._read_children()).get(
            f"{parent.id}@{snap['id']}", []
        )
        directory = await self._read_directory()
        by_id = {v: k for k, v in directory.items()}
        return sorted(by_id.get(i, i) for i in ids)

    async def remove(self, name: str) -> None:
        directory = await self._read_directory()
        image_id = directory.get(name)
        if image_id is None:
            raise RbdError(ENOENT, f"image {name!r} not found")
        img = await self.open(name)
        if any(s.get("protected") for s in img.header["snaps"]):
            raise RbdError(
                EBUSY, f"image {name!r} has protected snapshots"
            )
        if img.header.get("parent"):
            # a clone: unregister from the parent's children first
            p = img.header["parent"]
            children = await self._read_children()
            key = f"{p['image_id']}@{p['snap_id']}"
            children[key] = [
                c for c in children.get(key, []) if c != image_id
            ]
            await self._write_children(children)
        span = max(img.size, img.header.get("max_size", img.size))
        objects = (span + img.object_bytes - 1) // img.object_bytes
        for objno in range(objects):
            oid = img._data_oid(objno)
            # trim every snapshot's clone, then the head (the last trim
            # garbage-collects a whiteout head automatically)
            for s in img.header["snaps"]:
                try:
                    await self.ioctx.snap_trim(oid, s["id"])
                except Exception:
                    pass
            try:
                await self.ioctx.remove(oid)
            except Exception:
                pass
        await self.ioctx.remove(f"rbd_header.{image_id}")
        del directory[name]
        await self._write_directory(directory)

    async def open(self, name: str) -> "Image":
        directory = await self._read_directory()
        image_id = directory.get(name)
        if image_id is None:
            raise RbdError(ENOENT, f"image {name!r} not found")
        img = Image(self.ioctx, name, image_id)
        await img._load_header()
        return img


class Image:
    """One open image (librbd::Image / ImageCtx)."""

    def __init__(self, ioctx, name: str, image_id: str):
        self.ioctx = ioctx
        self.name = name
        self.id = image_id
        self.header: dict = {}
        self._lock_cookie: str | None = None  # our exclusive-lock hold

    # -- header ----------------------------------------------------------------

    @property
    def _header_oid(self) -> str:
        return f"rbd_header.{self.id}"

    async def _load_header(self) -> None:
        raw = await self.ioctx.read(self._header_oid)
        self.header = json.loads(raw.decode())

    async def _save_header(self) -> None:
        await self.ioctx.write_full(self._header_oid, json.dumps(self.header).encode())

    # -- exclusive lock (librbd ManagedLock over cls_lock) ---------------------

    LOCK_NAME = "rbd_lock"  # the lock name librbd registers on the header

    async def lock_acquire(self, cookie: str | None = None) -> None:
        """Acquire the image's exclusive lock (rbd_lock on the header
        object via the lock object class — the reference's ManagedLock /
        exclusive_lock feature).  -EBUSY propagates as RbdError when
        another client owns the image.

        The default cookie is RANDOM per open image (librbd generates
        unique cookies the same way): cls_lock keys holders on (entity,
        cookie), and two same-named clients sharing a fixed cookie would
        both "own" the exclusive lock as renewals of one hold."""
        if cookie is None:
            cookie = self._lock_cookie or f"auto {secrets.token_hex(8)}"
        try:
            await cls_client.lock(
                self.ioctx, self._header_oid, self.LOCK_NAME, cookie=cookie,
                description=f"rbd image {self.name}",
            )
        except RadosError as e:
            # -EBUSY is contention; anything else (header gone, I/O
            # error) must not be misreported as "locked"
            what = (
                f"image {self.name!r} is locked"
                if e.errno == -EBUSY
                else f"image {self.name!r} lock_acquire failed"
            )
            raise RbdError(-e.errno, what) from e
        self._lock_cookie = cookie

    async def lock_release(self, cookie: str | None = None) -> None:
        try:
            await cls_client.unlock(
                self.ioctx, self._header_oid, self.LOCK_NAME,
                cookie=cookie if cookie is not None else (self._lock_cookie or ""),
            )
        except RadosError as e:
            raise RbdError(-e.errno, f"image {self.name!r} unlock failed") from e
        self._lock_cookie = None

    async def lock_owners(self) -> list[dict]:
        """Current holders (rbd lock ls): [{entity, cookie, description}]."""
        try:
            info = await cls_client.get_lock_info(
                self.ioctx, self._header_oid, self.LOCK_NAME
            )
        except RadosError as e:
            raise RbdError(-e.errno, f"image {self.name!r} lock query failed") from e
        return [
            {"entity": h[0], "cookie": h[1], "description": h[2]}
            for h in info["holders"]
        ]

    async def break_lock(self, entity: str, cookie: str) -> None:
        """Forcibly remove another client's hold (rbd lock rm — the
        failover path rbd-mirror promotion uses when the old primary's
        owner died)."""
        try:
            await cls_client.break_lock(
                self.ioctx, self._header_oid, self.LOCK_NAME, entity,
                cookie=cookie,
            )
        except RadosError as e:
            raise RbdError(-e.errno, f"image {self.name!r} break_lock failed") from e

    @property
    def size(self) -> int:
        return self.header["size"]

    @property
    def order(self) -> int:
        return self.header["order"]

    @property
    def object_bytes(self) -> int:
        return 1 << self.order

    def _data_oid(self, objno: int) -> str:
        return f"rbd_data.{self.id}.{objno:016x}"

    def _extents(self, off: int, length: int):
        """Logical range -> [(objno, obj_off, len)] (stripe_count=1)."""
        out = []
        ob = self.object_bytes
        while length > 0:
            objno = off // ob
            obj_off = off % ob
            take = min(ob - obj_off, length)
            out.append((objno, obj_off, take))
            off += take
            length -= take
        return out

    def _snapc(self) -> tuple[int, list[int]]:
        """This image's SnapContext, passed PER CALL (never armed on the
        shared IoCtx: concurrent ops must not race each other's context —
        ImageCtx::snapc rides every individual write in the reference)."""
        ids = sorted((s["id"] for s in self.header["snaps"]), reverse=True)
        return (ids[0] if ids else 0, ids)

    # -- I/O -------------------------------------------------------------------

    async def write(self, off: int, data: bytes) -> None:
        if off + len(data) > self.size:
            raise RbdError(EINVAL, "write past end of image")
        snapc = self._snapc()
        cursor = 0
        has_parent = self.header.get("parent") is not None
        for objno, obj_off, ln in self._extents(off, len(data)):
            if has_parent:
                await self._copy_up(objno)
            await self.ioctx.write(
                self._data_oid(objno),
                data[cursor : cursor + ln],
                obj_off,
                snapc=snapc,
            )
            cursor += ln

    async def read(self, off: int, length: int, snap_name: str | None = None) -> bytes:
        if off >= self.size:
            return b""
        length = min(length, self.size - off)
        snap_id = 0
        if snap_name is not None:
            snap_id = self._snap_by_name(snap_name)["id"]
        parts = []
        for objno, obj_off, ln in self._extents(off, length):
            data = await self._read_object(objno, snap_id)
            parts.append(data[obj_off : obj_off + ln].ljust(ln, b"\x00"))
        return b"".join(parts)

    async def _read_object(self, objno: int, snap_id: int) -> bytes:
        """Block reads zero-fill absent objects/holes; an absent object
        of a CLONE falls through to the parent snapshot within the
        overlap (ObjectRequest's read-from-parent semantics)."""
        from ..client.rados import RadosError

        try:
            return await self.ioctx.read(self._data_oid(objno), snap=snap_id)
        except RadosError as e:
            if e.errno != -ENOENT:
                raise
            return await self._read_parent_object(objno)

    async def _parent(self) -> "Image | None":
        p = self.header.get("parent")
        if p is None:
            return None
        if getattr(self, "_parent_img", None) is None:
            self._parent_img = Image(
                self.ioctx, p.get("image_name", ""), p["image_id"]
            )
            await self._parent_img._load_header()
        return self._parent_img

    async def _read_parent_object(self, objno: int) -> bytes:
        """The child's view of one object as served by the parent snap,
        clipped to the overlap (zeros past it)."""
        p = self.header.get("parent")
        if p is None:
            return b""
        start = objno * self.object_bytes
        if start >= p["overlap"]:
            return b""
        parent = await self._parent()
        data = await parent.read(
            start,
            min(self.object_bytes, p["overlap"] - start),
            snap_name=p["snap_name"],
        )
        return data

    async def _copy_up(self, objno: int) -> None:
        """First write to a parent-backed object copies the parent's
        bytes into the child (ObjectRequest copy-up), so the write lands
        on a child-owned object and the parent stays untouched."""
        from ..client.rados import RadosError

        oid = self._data_oid(objno)
        try:
            await self.ioctx.stat(oid)
            return  # child already owns the object
        except RadosError as e:
            if e.errno != -ENOENT:
                raise
        base = await self._read_parent_object(objno)
        if base.rstrip(b"\x00"):
            await self.ioctx.write(oid, base, 0, snapc=self._snapc())

    async def resize(self, new_size: int) -> None:
        """librbd::resize; shrinking drops whole objects past the end.
        Deletions/truncates carry the SnapContext, so the OSD preserves
        snapshot clones (whiteout heads) before discarding bytes."""
        old = self.size
        if new_size < old:
            snapc = self._snapc()
            ob = self.object_bytes
            first_dead = (new_size + ob - 1) // ob
            last = (old - 1) // ob if old else 0
            for objno in range(first_dead, last + 1):
                try:
                    await self.ioctx.remove(self._data_oid(objno), snapc=snapc)
                except Exception:
                    pass
            if new_size % ob:
                try:
                    await self.ioctx.truncate(
                        self._data_oid(new_size // ob), new_size % ob, snapc=snapc
                    )
                except Exception:
                    pass
        self.header["size"] = new_size
        self.header["max_size"] = max(self.header.get("max_size", old), new_size)
        parent = self.header.get("parent")
        if parent is not None and new_size < parent["overlap"]:
            # shrinking a clone shrinks what the parent still backs
            # (librbd trims the parent overlap on resize)
            parent["overlap"] = new_size
        await self._save_header()

    # -- snapshots ---------------------------------------------------------------

    def _snap_by_name(self, name: str) -> dict:
        for snap in self.header["snaps"]:
            if snap["name"] == name:
                return snap
        raise RbdError(ENOENT, f"snapshot {name!r} not found")

    async def snap_create(self, name: str) -> None:
        """librbd snap_create: allocate a pool snap id (durable via paxos)
        and record it; the OSDs clone lazily as the head is modified."""
        if any(s["name"] == name for s in self.header["snaps"]):
            raise RbdError(EEXIST, f"snapshot {name!r} exists")
        pool = self.ioctx.rados.objecter.osdmap.pools[self.ioctx.pool_id]
        snap_id = await self.ioctx.rados.selfmanaged_snap_create(pool.name)
        self.header["snaps"].append(
            {"id": snap_id, "name": name, "size": self.size}
        )
        await self._save_header()

    async def snap_list(self) -> list[str]:
        return [s["name"] for s in self.header["snaps"]]

    async def snap_rollback(self, name: str) -> None:
        """librbd snap_rollback: every data object reverts server-side to
        its state at the snap (OSD ROLLBACK op); objects born after the
        snap are deleted (they did not exist then).  Deletions carry the
        SnapContext so newer snapshots keep their content."""
        from ..client.rados import RadosError

        snap = self._snap_by_name(name)
        span = max(self.size, self.header.get("max_size", self.size))
        objects = (span + self.object_bytes - 1) // self.object_bytes
        snapc = self._snapc()
        for objno in range(objects):
            oid = self._data_oid(objno)
            try:
                await self.ioctx.stat(oid, snap=snap["id"])
            except RadosError as e:
                if e.errno != -ENOENT:
                    raise
                # absent at the snap: must be absent after rollback
                try:
                    await self.ioctx.remove(oid, snapc=snapc)
                except RadosError as e2:
                    if e2.errno != -ENOENT:
                        raise
                continue
            await self.ioctx.rollback(oid, snap["id"], snapc=snapc)
        self.header["size"] = snap.get("size", self.size)
        await self._save_header()

    async def export(self, snap_name: str | None = None) -> bytes:
        """rbd export: the full image (or a snapshot's view) as bytes,
        read in object-size chunks (rbd export's sequential reader)."""
        out = bytearray()
        off = 0
        while off < self.size:
            take = min(self.object_bytes, self.size - off)
            out += await self.read(off, take, snap_name=snap_name)
            off += take
        return bytes(out)

    async def import_bytes(self, data: bytes) -> None:
        """rbd import payload: write the blob from offset 0 (the caller
        created the image at len(data))."""
        off = 0
        while off < len(data):
            take = min(self.object_bytes, len(data) - off)
            await self.write(off, data[off : off + take])
            off += take

    async def snap_protect(self, name: str) -> None:
        """rbd snap protect: required before cloning; a protected snap
        cannot be removed (librbd snap_protect)."""
        snap = self._snap_by_name(name)
        snap["protected"] = True
        await self._save_header()

    async def snap_unprotect(self, name: str) -> None:
        """rbd snap unprotect: refused while clones of the snap exist
        (librbd snap_unprotect scans rbd_children)."""
        snap = self._snap_by_name(name)
        rbd = RBD(self.ioctx)
        if (await rbd._read_children()).get(f"{self.id}@{snap['id']}"):
            raise RbdError(EBUSY, f"snapshot {name!r} has clones")
        snap["protected"] = False
        await self._save_header()

    async def snap_is_protected(self, name: str) -> bool:
        return bool(self._snap_by_name(name).get("protected"))

    async def flatten(self) -> None:
        """rbd flatten: copy every parent-backed object into the child,
        then sever the parent link (librbd flatten; the child becomes a
        standalone image and the snap can be unprotected)."""
        p = self.header.get("parent")
        if p is None:
            raise RbdError(EINVAL, f"image {self.name!r} has no parent")
        objects = (p["overlap"] + self.object_bytes - 1) // self.object_bytes
        for objno in range(objects):
            await self._copy_up(objno)
        rbd = RBD(self.ioctx)
        children = await rbd._read_children()
        key = f"{p['image_id']}@{p['snap_id']}"
        children[key] = [c for c in children.get(key, []) if c != self.id]
        await rbd._write_children(children)
        del self.header["parent"]
        self._parent_img = None
        await self._save_header()

    async def snap_remove(self, name: str) -> None:
        """librbd snap_remove: per-object server-side snap trim — the OSD
        drops the snap from each clone's coverage and deletes clones no
        snapshot references anymore (the snap-trimmer, scoped to this
        image's objects)."""
        from ..client.rados import RadosError

        snap = self._snap_by_name(name)
        if snap.get("protected"):
            raise RbdError(EBUSY, f"snapshot {name!r} is protected")
        span = max(self.size, self.header.get("max_size", self.size))
        objects = (span + self.object_bytes - 1) // self.object_bytes
        for objno in range(objects):
            try:
                await self.ioctx.snap_trim(self._data_oid(objno), snap["id"])
            except RadosError as e:
                if e.errno != -ENOENT:
                    raise
        self.header["snaps"] = [
            s for s in self.header["snaps"] if s["name"] != name
        ]
        await self._save_header()
