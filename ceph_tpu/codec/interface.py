"""The codec contract — mirror of `ErasureCodeInterface`.

Reference: /root/reference/src/erasure-code/ErasureCodeInterface.h (systematic
codes; object split into k data + m coding chunks; byte B of the object lives
in chunk B/chunk_size at offset B%chunk_size, :39-58).  The reference returns
negative errnos; this Python surface raises `EcError` carrying the same errno
so the native shell (native/) can translate 1:1 at the ABI boundary.

Chunks are numpy uint8 arrays (the bufferlist analog); profiles are
dict[str, str] exactly like `ErasureCodeProfile` (:155).
"""

from __future__ import annotations

import abc
import errno as _errno
from typing import Mapping

import numpy as np

Profile = dict[str, str]


class EcError(Exception):
    """Codec error carrying a negative errno (reference error convention)."""

    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(f"{msg} (errno {self.errno}, {_errno.errorcode.get(abs(err), '?')})")


class ErasureCodeInterface(abc.ABC):
    """Abstract codec contract (ErasureCodeInterface.h:170)."""

    @abc.abstractmethod
    def init(self, profile: Profile) -> None:
        """Initialize from profile; must populate get_profile() (:188)."""

    @abc.abstractmethod
    def get_profile(self) -> Profile:
        """The profile captured at init (:196)."""

    @abc.abstractmethod
    def get_chunk_count(self) -> int:
        """k + m (:227)."""

    @abc.abstractmethod
    def get_data_chunk_count(self) -> int:
        """k (:237)."""

    def get_coding_chunk_count(self) -> int:
        """m (:249)."""
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """>1 only for array codes like CLAY (:259)."""
        return 1

    @abc.abstractmethod
    def get_chunk_size(self, object_size: int) -> int:
        """Chunk size for an object, padded to codec alignment (:278)."""

    @abc.abstractmethod
    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> dict[int, list[tuple[int, int]]]:
        """Chunks (with per-shard subchunk (offset, count) runs) needed to
        satisfy a read (:297).  Raises EcError(EIO) when undecodable."""

    @abc.abstractmethod
    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: Mapping[int, int]
    ) -> set[int]:
        """Cost-aware variant (:326)."""

    @abc.abstractmethod
    def encode(self, want_to_encode: set[int], data: bytes | np.ndarray) -> dict[int, np.ndarray]:
        """Split + pad + encode an object; returns requested chunks (:365)."""

    @abc.abstractmethod
    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        """In-place parity computation over pre-sized chunk buffers (:370)."""

    @abc.abstractmethod
    def decode(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        chunk_size: int = 0,
    ) -> dict[int, np.ndarray]:
        """Recover wanted chunks from available ones (:407)."""

    @abc.abstractmethod
    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        """In-place reconstruction into pre-filled buffers (:411)."""

    @abc.abstractmethod
    def get_chunk_mapping(self) -> list[int]:
        """Chunk remapping vector (:448)."""

    @abc.abstractmethod
    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> np.ndarray:
        """Concatenate decoded data chunks back into the object (:460)."""
