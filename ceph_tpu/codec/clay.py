"""CLAY — Coupled-LAYer MSR regenerating codes.

Re-design of the reference `clay` plugin (/root/reference/src/erasure-code/
clay/ErasureCodeClay.{h,cc}): an (k, m, d) MSR code that repairs one lost
chunk reading only d helpers x 1/q of each chunk (q = d-k+1).  Nodes live on
a (q, t) grid (t = (k+m+nu)/q, nu pads k+m to a multiple of q); each chunk is
q^t sub-chunks ("planes"); coupled chunk values C relate to uncoupled values
U by pairwise 2x2 GF transforms across the grid, and each plane of U is a
codeword of an inner scalar MDS code (ErasureCodeClay.cc:271-296 for the
geometry; :645-739 for layered decoding; :462-642 for single-chunk repair).

TPU-first re-design (not a loop-for-loop translation): chunks live as one
(q*t, q^t, sc) tensor; the pairwise coupling transforms are *batched* —
vectorized gathers build (pairs, sc) arrays and the 2x2 GF multiplies are
table lookups over whole batches — and each round of layered decoding runs
the inner MDS decode for *all planes of equal intersection score in one
bitsliced XOR-matmul launch* (planes are the batch axis).  The sequential
structure that remains (rounds ordered by intersection score, <= m+1 of
them) is inherent to the code, not an implementation artifact.

Profile: k, m, d (default k+m-1), scalar_mds in {jerasure, isa, tpu}
(default jerasure), technique per inner plugin.  The reference also accepts
scalar_mds=shec; SHEC's non-MDS decode does not expose a decode matrix, so
that combination is rejected here (EINVAL) for now.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from ceph_tpu.gf import GF_MUL_TABLE, gf_inv, gf_invert_matrix

from .base import EINVAL, EIO, ErasureCode
from .interface import EcError, Profile
from .matrix_codec import PLAN_CACHE


def _gf_scale(c: int, arr: np.ndarray) -> np.ndarray:
    """Multiply a uint8 array by the GF(2^8) scalar c (table lookup)."""
    return GF_MUL_TABLE[c][arr]


class ErasureCodeClay(ErasureCode):
    DEFAULT_K = "4"
    DEFAULT_M = "2"

    def __init__(self) -> None:
        super().__init__()
        self.k = 0
        self.m = 0
        self.d = 0
        self.q = 0
        self.t = 0
        self.nu = 0
        self.sub_chunk_no = 0
        self._inner = None  # inner scalar MDS codec over (k+nu, m)
        self._pft = None  # 2x2 parity matrix of the pairwise transform

    # -- init ---------------------------------------------------------------

    def parse(self, profile: Profile) -> None:
        super().parse(profile)
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.sanity_check_k_m(self.k, self.m)
        self.d = self.to_int("d", profile, str(self.k + self.m - 1))
        if not (self.k <= self.d <= self.k + self.m - 1):
            raise EcError(
                EINVAL, f"d={self.d} must be within [{self.k}, {self.k + self.m - 1}]"
            )
        scalar_mds = profile.get("scalar_mds") or "jerasure"
        if scalar_mds == "shec":
            raise EcError(
                EINVAL,
                "scalar_mds=shec is not supported by the TPU clay codec "
                "(SHEC's decode is not matrix-planned); use jerasure/isa/tpu",
            )
        if scalar_mds not in ("jerasure", "isa", "tpu"):
            raise EcError(EINVAL, f"scalar_mds={scalar_mds} not supported")
        self.scalar_mds = scalar_mds
        technique = profile.get("technique") or "reed_sol_van"
        self.technique = technique

        self.q = self.d - self.k + 1
        self.nu = (self.q - (self.k + self.m) % self.q) % self.q
        if self.k + self.m + self.nu > 254:
            raise EcError(EINVAL, "k+m+nu must be <= 254")
        self.t = (self.k + self.m + self.nu) // self.q
        self.sub_chunk_no = self.q ** self.t

        # Inner MDS codec over (k+nu) data chunks; same plugin family as the
        # reference wires up (ErasureCodeClay.cc:283-293).
        from . import registry as registry_mod

        registry = registry_mod.instance()
        inner_profile = {
            "k": str(self.k + self.nu),
            "m": str(self.m),
            "technique": technique,
        }
        plugin = "tpu" if scalar_mds == "isa" else scalar_mds
        if plugin == "jerasure":
            inner_profile["w"] = "8"
        self._inner = registry.factory(plugin, inner_profile)
        # Pairwise transform = parity rows of the same family's (2, 2) code
        # (the reference's `pft` instance, ErasureCodeClay.cc:291-293).
        pft_codec = registry.factory(
            plugin, {"k": "2", "m": "2", "technique": technique, **({"w": "8"} if plugin == "jerasure" else {})}
        )
        self._pft = pft_codec.distribution_matrix()[2:]  # (2, 2)
        self._pft_inv = gf_invert_matrix(self._pft)
        assert self._pft_inv is not None
        assert (self._pft != 0).all(), "pairwise transform needs nonzero entries"
        self._plane_digits = self._compute_plane_digits()

    def init(self, profile: Profile) -> None:
        self.parse(profile)
        self._profile = dict(profile)

    def _compute_plane_digits(self) -> np.ndarray:
        """(sub_chunk_no, t) base-q digits; digit y = (z // q^(t-1-y)) % q."""
        z = np.arange(self.sub_chunk_no)
        digits = np.empty((self.sub_chunk_no, self.t), dtype=np.int64)
        for y in range(self.t):
            digits[:, y] = (z // self.q ** (self.t - 1 - y)) % self.q
        return digits

    # -- geometry -----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_chunk_size(self, object_size: int) -> int:
        """round_up(object, sub_chunk_no * k * inner_alignment) / k
        (ErasureCodeClay.cc:90-96)."""
        alignment = self.sub_chunk_no * self.k * self._inner.get_chunk_size(1)
        padded = -(-object_size // alignment) * alignment
        return padded // self.k

    # -- node/plane helpers --------------------------------------------------

    def _ext(self, i: int) -> int:
        """External chunk id -> grid node id (parities shift by nu)."""
        return i if i < self.k else i + self.nu

    def _partner(self, node: int, z: int) -> tuple[int, int]:
        """Coupled partner of grid node `node` at plane z: (node_sw, z_sw)."""
        x, y = node % self.q, node // self.q
        zy = int(self._plane_digits[z, y])
        node_sw = y * self.q + zy
        z_sw = z + (x - zy) * self.q ** (self.t - 1 - y)
        return node_sw, z_sw

    # -- coupling transforms (batched over planes) ---------------------------

    def _compute_U(self, node: int, planes: np.ndarray, C: np.ndarray,
                   U: np.ndarray) -> None:
        """Fill U[node, planes] from coupled values.

        Canonical pair order: position A = larger-x node, B = smaller-x; the
        transform is [U_A; U_B] = P @ [C_A; C_B] with P the (2,2) parity
        matrix (the reference reaches the same values through pft
        decode_chunks with erasures {2,3}, ErasureCodeClay.cc:839-869).
        Vectorized: planes is an int array; dots copy, pairs gather both C
        sides and apply the 2x2 GF map via table lookups.
        """
        x, y = node % self.q, node // self.q
        zy = self._plane_digits[planes, y]
        dots = planes[zy == x]
        if dots.size:
            U[node, dots] = C[node, dots]
        others = planes[zy != x]
        if others.size == 0:
            return
        zy_o = self._plane_digits[others, y]
        node_sw = y * self.q + zy_o
        z_sw = others + (x - zy_o) * self.q ** (self.t - 1 - y)
        c_self = C[node, others]
        c_partner = C[node_sw, z_sw]
        P = self._pft
        is_a = x > zy_o  # node is the larger-x (position A) member
        # U_A = P00 C_A + P01 C_B ; U_B = P10 C_A + P11 C_B
        out = np.where(
            is_a[:, None],
            _gf_scale(int(P[0, 0]), c_self) ^ _gf_scale(int(P[0, 1]), c_partner),
            _gf_scale(int(P[1, 1]), c_self) ^ _gf_scale(int(P[1, 0]), c_partner),
        )
        U[node, others] = out

    def _recover_C(self, node: int, planes: np.ndarray, C: np.ndarray,
                   U: np.ndarray, erased: set[int]) -> None:
        """Fill C[node, planes] for an erased node after U is known.

        Three cases per plane (ErasureCodeClay.cc:684-706): dot -> copy;
        partner alive -> solve the pair equation for this node's C; both
        erased -> invert the full 2x2 (done once per pair, from the larger-x
        side, writing both nodes like get_coupled_from_uncoupled).
        """
        x, y = node % self.q, node // self.q
        zy = self._plane_digits[planes, y]
        dots = planes[zy == x]
        if dots.size:
            C[node, dots] = U[node, dots]
        others = planes[zy != x]
        if others.size == 0:
            return
        zy_o = self._plane_digits[others, y]
        node_sw_arr = y * self.q + zy_o
        z_sw_arr = others + (x - zy_o) * self.q ** (self.t - 1 - y)
        P, Pinv = self._pft, self._pft_inv
        for partner in np.unique(node_sw_arr):
            sel = node_sw_arr == partner
            zs, zsw = others[sel], z_sw_arr[sel]
            if int(partner) not in erased:
                # type-1: partner C known.  If node is A:
                # C_A = P00^-1 (U_A ^ P01 C_B); symmetric for B.
                if x > int(partner) % self.q:
                    inv = gf_inv(int(P[0, 0]))
                    C[node, zs] = _gf_scale(
                        inv, U[node, zs] ^ _gf_scale(int(P[0, 1]), C[partner, zsw])
                    )
                else:
                    inv = gf_inv(int(P[1, 1]))
                    C[node, zs] = _gf_scale(
                        inv, U[node, zs] ^ _gf_scale(int(P[1, 0]), C[partner, zsw])
                    )
            elif x > int(partner) % self.q:
                # both erased: [C_A; C_B] = P^-1 [U_A; U_B]; write both sides
                # once from the A side (reference guards with z_vec[y] < x).
                ua, ub = U[node, zs], U[partner, zsw]
                C[node, zs] = _gf_scale(int(Pinv[0, 0]), ua) ^ _gf_scale(
                    int(Pinv[0, 1]), ub
                )
                C[partner, zsw] = _gf_scale(int(Pinv[1, 0]), ua) ^ _gf_scale(
                    int(Pinv[1, 1]), ub
                )

    # -- layered decode (ErasureCodeClay.cc:645-710) -------------------------

    def _decode_layered(self, erased: set[int], C: np.ndarray) -> None:
        """Recover C[e] for all erased grid nodes in-place.

        C has shape (q*t, sub_chunk_no, sc).  Erasures are padded to exactly
        m with virtual (shortening) nodes.  Rounds are ordered by
        intersection score; within a round everything is batched.
        """
        qt = self.q * self.t
        num = len(erased)
        assert num > 0
        erased = set(erased)
        for i in range(self.k + self.nu, qt):
            if len(erased) >= self.m:
                break
            erased.add(i)
        assert len(erased) == self.m, (erased, self.m)

        # order[z] = number of erased nodes sitting on their own dot.
        order = np.zeros(self.sub_chunk_no, dtype=np.int64)
        for e in erased:
            order += self._plane_digits[:, e // self.q] == e % self.q

        U = np.zeros_like(C)
        erased_sorted = sorted(erased)
        dist = self._inner.distribution_matrix()
        coder, decode_index = PLAN_CACHE.decode_coder(
            dist, erased_sorted, self.k + self.nu
        )
        alive = [i for i in range(qt) if i not in erased]
        for score in range(int(order.max()) + 1):
            planes = np.nonzero(order == score)[0]
            if planes.size == 0:
                continue
            # 1. uncouple all alive nodes on these planes
            for node in alive:
                self._compute_U(node, planes, C, U)
            # 2. inner MDS decode of erased U's — one batched device launch
            #    over (|planes|, k+nu, sc)
            survivors = U[decode_index][:, planes]  # (k+nu, P, sc)
            rec = np.asarray(
                coder(np.ascontiguousarray(survivors.transpose(1, 0, 2)))
            )  # (P, nerr, sc)
            for p, e in enumerate(erased_sorted):
                U[e, planes] = rec[:, p]
            # 3. re-couple erased nodes on these planes
            for e in erased_sorted:
                self._recover_C(e, planes, C, U, erased)

    # -- chunk-level interface ----------------------------------------------

    def _grid_arrays(self, chunks: Mapping[int, np.ndarray], chunk_size: int):
        """(q*t, sub_chunk_no, sc) coupled tensor from external chunk dict;
        virtual shortening nodes are zero."""
        qt = self.q * self.t
        sc = chunk_size // self.sub_chunk_no
        C = np.zeros((qt, self.sub_chunk_no, sc), dtype=np.uint8)
        for i, buf in chunks.items():
            C[self._ext(i)] = np.asarray(buf, dtype=np.uint8).reshape(
                self.sub_chunk_no, sc
            )
        return C

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        chunk_size = len(chunks[0])
        if chunk_size % self.sub_chunk_no:
            raise EcError(EINVAL, f"chunk size {chunk_size} not divisible by "
                                  f"sub_chunk_no {self.sub_chunk_no}")
        C = self._grid_arrays({i: chunks[i] for i in range(self.k)}, chunk_size)
        parity_nodes = {self._ext(i) for i in range(self.k, self.k + self.m)}
        self._decode_layered(parity_nodes, C)
        for i in range(self.k, self.k + self.m):
            np.copyto(chunks[i], C[self._ext(i)].reshape(-1))

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        erasures_ext = [i for i in range(self.k + self.m) if i not in chunks]
        if not erasures_ext:
            return
        if len(erasures_ext) > self.m:
            raise EcError(EIO, f"{len(erasures_ext)} erasures > m={self.m}")
        chunk_size = len(next(iter(chunks.values())))
        C = self._grid_arrays(chunks, chunk_size)
        erased_nodes = {self._ext(i) for i in erasures_ext}
        self._decode_layered(erased_nodes, C)
        for i in erasures_ext:
            np.copyto(decoded[i], C[self._ext(i)].reshape(-1))

    # -- repair path (sub-chunk reads; ErasureCodeClay.cc:304-460) -----------

    def is_repair(self, want_to_read: set[int], available: set[int]) -> bool:
        if want_to_read <= available:
            return False
        if len(want_to_read) > 1:
            return False
        lost = self._ext(next(iter(want_to_read)))
        y = lost // self.q
        for x in range(self.q):
            node = y * self.q + x
            ext = node if node < self.k else node - self.nu
            if node == lost:
                continue
            if self.k <= node < self.k + self.nu:
                continue  # virtual shortening node is always "available"
            if ext not in available:
                return False
        return len(available) >= self.d

    def get_repair_subchunks(self, lost_node: int) -> list[tuple[int, int]]:
        """(offset, count) runs of sub-chunks read from each helper
        (ErasureCodeClay.cc:363-377)."""
        y, x = lost_node // self.q, lost_node % self.q
        seq = self.q ** (self.t - 1 - y)
        runs = []
        index = x * seq
        for _ in range(self.q ** y):
            runs.append((index, seq))
            index += self.q * seq
        return runs

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> dict[int, list[tuple[int, int]]]:
        if not self.is_repair(want_to_read, available):
            return super().minimum_to_decode(want_to_read, available)
        lost_ext = next(iter(want_to_read))
        lost = self._ext(lost_ext)
        runs = self.get_repair_subchunks(lost)
        minimum: dict[int, list[tuple[int, int]]] = {}
        y = lost // self.q
        for x in range(self.q):
            node = y * self.q + x
            if node == lost:
                continue
            if node < self.k:
                minimum[node] = list(runs)
            elif node >= self.k + self.nu:
                minimum[node - self.nu] = list(runs)
        for chunk in sorted(available):
            if len(minimum) >= self.d:
                break
            minimum.setdefault(chunk, list(runs))
        assert len(minimum) == self.d
        return minimum

    def decode(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        chunk_size: int = 0,
    ) -> dict[int, np.ndarray]:
        avail = set(chunks)
        if (
            chunk_size
            and self.is_repair(want_to_read, avail)
            and chunk_size > len(next(iter(chunks.values())))
        ):
            return self._repair(want_to_read, chunks, chunk_size)
        return super().decode(want_to_read, chunks, chunk_size)

    def decode_fragments_batch(
        self,
        want_to_read: set[int],
        helper_chunks: Mapping[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        """Vectorized repair across a whole shard's stripes at once.

        Helper values are (stripes, fragment) uint8 arrays — stripe s's
        repair-plane fragment in row s — and the result maps the lost
        chunk to a (stripes, chunk_size) array.  Each score round runs
        ONE inner-MDS launch over (planes, stripes, k+nu, sc) instead of
        the per-stripe Python loop the OSD recovery path used to drive
        (ISSUE 5 tentpole: stripes are just another batch axis)."""
        if not self.is_repair(want_to_read, set(helper_chunks)):
            raise EcError(EIO, "fragment decode requires a repair-plan read")
        return self._repair(want_to_read, helper_chunks, chunk_size)

    def _repair(
        self,
        want_to_read: set[int],
        helper_chunks: Mapping[int, np.ndarray],
        chunk_size: int,
    ) -> dict[int, np.ndarray]:
        """Single-chunk repair from d helpers' sub-chunk fragments.

        Helpers supply only the repair planes (sub_chunk_no / q of each
        chunk); the lost chunk is rebuilt in full.  Mirrors
        repair_one_lost_chunk (ErasureCodeClay.cc:462-642) with batched
        plane groups: repair planes are processed in intersection-score
        rounds; each round uncouples helpers, runs one batched inner-MDS
        decode, and re-couples — recovering q lost sub-chunks per repair
        plane (the dot plus q-1 shifted partners).

        Helper buffers may be flat (one fragment) or (stripes, fragment)
        2-D (decode_fragments_batch): every transform below is
        elementwise over the trailing axes and the inner-MDS coder takes
        arbitrary leading batch dims, so the stripe axis rides along.
        """
        assert len(want_to_read) == 1 and len(helper_chunks) == self.d
        lost_ext = next(iter(want_to_read))
        lost = self._ext(lost_ext)
        qt = self.q * self.t
        sc = chunk_size // self.sub_chunk_no
        repair_planes = np.array(
            sorted(
                z
                for run in self.get_repair_subchunks(lost)
                for z in range(run[0], run[0] + run[1])
            )
        )
        n_rep = repair_planes.size
        plane_pos = {int(z): i for i, z in enumerate(repair_planes)}
        repair_blocksize = n_rep * sc

        # Scatter helper fragments into full-size C/U tensors (only repair
        # planes are populated); aloof = alive nodes that sent nothing.
        first = np.asarray(next(iter(helper_chunks.values())), dtype=np.uint8)
        lead = first.shape[:-1] if first.ndim == 2 else ()
        C = np.zeros((qt, self.sub_chunk_no, *lead, sc), dtype=np.uint8)
        helpers: set[int] = set()
        for i, buf in helper_chunks.items():
            buf = np.asarray(buf, dtype=np.uint8)
            node = self._ext(i)
            if lead:
                assert buf.shape == (*lead, repair_blocksize), (
                    buf.shape, lead, repair_blocksize,
                )
                # (S, n_rep, sc) -> plane-major (n_rep, S, sc) for the
                # C[node, planes] scatter
                C[node, repair_planes] = buf.reshape(
                    *lead, n_rep, sc
                ).transpose(1, 0, 2)
            else:
                assert buf.size == repair_blocksize, (buf.size, repair_blocksize)
                C[node, repair_planes] = buf.reshape(n_rep, sc)
            helpers.add(node)
        helpers |= set(range(self.k, self.k + self.nu))  # shortening zeros
        aloof = {
            n
            for n in range(qt)
            if n not in helpers and n != lost
        }
        y_lost = lost // self.q
        erased = {y_lost * self.q + x for x in range(self.q)} | aloof
        if len(erased) > self.m:
            raise EcError(EIO, f"repair erasure set {erased} exceeds m={self.m}")

        order = np.zeros(self.sub_chunk_no, dtype=np.int64)
        for e in ({lost} | aloof):
            order += self._plane_digits[:, e // self.q] == e % self.q
        U = np.zeros_like(C)
        erased_sorted = sorted(erased)
        dist = self._inner.distribution_matrix()
        coder, decode_index = PLAN_CACHE.decode_coder(
            dist, erased_sorted, self.k + self.nu
        )
        out = np.zeros((self.sub_chunk_no, *lead, sc), dtype=np.uint8)
        P, Pinv = self._pft, self._pft_inv
        max_order = int(order[repair_planes].max())
        min_order = int(order[repair_planes].min())
        for score in range(min_order, max_order + 1):
            planes = repair_planes[order[repair_planes] == score]
            if planes.size == 0:
                continue
            # 1. uncouple non-erased nodes on these planes (lost-row helpers
            # are in `erased`: their U comes from the MDS decode, like the
            # reference's erasure guard at ErasureCodeClay.cc:540).  A
            # node's partner is either a helper (z_sw also a repair plane),
            # an aloof node (use its U from an earlier round), or the dot.
            for node in sorted(helpers - erased):
                x, y = node % self.q, node // self.q
                zy = self._plane_digits[planes, y]
                dots = planes[zy == x]
                if dots.size:
                    U[node, dots] = C[node, dots]
                others = planes[zy != x]
                if others.size == 0:
                    continue
                zy_o = self._plane_digits[others, y]
                partner_arr = y * self.q + zy_o
                z_sw_arr = others + (x - zy_o) * self.q ** (self.t - 1 - y)
                for partner in np.unique(partner_arr):
                    selm = partner_arr == partner
                    zs, zsw = others[selm], z_sw_arr[selm]
                    is_a = x > int(partner) % self.q
                    if int(partner) in aloof:
                        # know C_self and U_partner (earlier round):
                        # solve pair for U_self.
                        cs = C[node, zs]
                        up = U[partner, zsw]
                        if is_a:
                            # C_B = P11^-1 (U_B ^ P10 C_A); U_A = P00 C_A ^ P01 C_B
                            cb = _gf_scale(
                                gf_inv(int(P[1, 1])),
                                up ^ _gf_scale(int(P[1, 0]), cs),
                            )
                            U[node, zs] = _gf_scale(int(P[0, 0]), cs) ^ _gf_scale(
                                int(P[0, 1]), cb
                            )
                        else:
                            ca = _gf_scale(
                                gf_inv(int(P[0, 0])),
                                up ^ _gf_scale(int(P[0, 1]), cs),
                            )
                            U[node, zs] = _gf_scale(int(P[1, 1]), cs) ^ _gf_scale(
                                int(P[1, 0]), ca
                            )
                    else:
                        cs = C[node, zs]
                        cp = C[partner, zsw]
                        if is_a:
                            U[node, zs] = _gf_scale(int(P[0, 0]), cs) ^ _gf_scale(
                                int(P[0, 1]), cp
                            )
                        else:
                            U[node, zs] = _gf_scale(int(P[1, 1]), cs) ^ _gf_scale(
                                int(P[1, 0]), cp
                            )
            # 2. batched inner MDS decode for erased U's: (|planes|[, S],
            # k+nu, sc) — contraction axis at -2, stripes ride as a
            # leading batch dim.
            survivors = U[decode_index][:, planes]
            rec = np.asarray(
                coder(np.ascontiguousarray(np.moveaxis(survivors, 0, -2)))
            )
            for p, e in enumerate(erased_sorted):
                U[e, planes] = rec[..., p, :]
            # 3. recover lost C sub-chunks: the dot (plane itself) plus the
            # shifted partners via helpers in the lost row.
            out[planes] = U[lost, planes]  # dot: repair planes have
            # z_vec[y_lost] == x_lost
            for x in range(self.q):
                node = y_lost * self.q + x
                if node == lost or node in aloof:
                    continue
                if node not in helpers:
                    continue
                zy = self._plane_digits[planes, y_lost]
                sel = planes  # all repair planes have dot == lost in y_lost
                z_sw = sel + (x - zy) * self.q ** (self.t - 1 - y_lost)
                # helper (x, y_lost): C known at plane z, U decoded at z;
                # solve pair for C_lost at z_sw.
                cs = C[node, sel]
                us = U[node, sel]
                if x > lost % self.q:
                    # helper is A: U_A = P00 C_A ^ P01 C_B -> C_B
                    cb = _gf_scale(
                        gf_inv(int(P[0, 1])), us ^ _gf_scale(int(P[0, 0]), cs)
                    )
                    out[z_sw] = cb
                else:
                    ca = _gf_scale(
                        gf_inv(int(P[1, 0])), us ^ _gf_scale(int(P[1, 1]), cs)
                    )
                    out[z_sw] = ca
        if lead:
            # plane-major (sub_chunk_no, S, sc) -> per-stripe chunks
            return {lost_ext: out.transpose(1, 0, 2).reshape(*lead, -1)}
        return {lost_ext: out.reshape(-1)}
