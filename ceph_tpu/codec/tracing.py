"""Span instrumentation for codec plugins — stage attribution for encode.

The headline claim (≥40 GB/s/chip RS encode) is only auditable when a
trace shows where an encode's time actually goes: the host→device
transfer (H2D), the kernel launch, and — on the reap side, in
stripe/stripe.py — the kernel wait + device→host copy (D2H).
`instrument_codec` wraps a codec instance's hot entry points with
sub-spans attached to the ACTIVE span (common/tracer.py's contextvar),
so a traced client write's `ec:write` span gains

    codec:<plugin>:encode
      ├─ h2d            jnp.asarray staging the input onto the device
      └─ kernel_launch  the async dispatch (returns while the chip works)

children, and the stripe driver's `PendingEncode.result()` adds the
matching `kernel_wait+d2h` when the parity is materialized.  Host-only
codecs (the C `native` plugin's chunk interface) get a single `kernel`
span — the whole call is synchronous host compute.

Zero-cost when tracing is off: with no recorded active span each wrapper
is one contextvar read and a falsy check before tail-calling the
original.
"""

from __future__ import annotations

import contextlib

from ..common import tracer as tracer_mod


def active_span():
    """The active RECORDED span, or None (unrecorded spans would produce
    children the dump never shows — skip the bookkeeping entirely)."""
    sp = tracer_mod.current_span()
    return sp if sp is not None and sp.recorded else None


def wait_span(parent):
    """Context manager for the reap side of an async launch: times the
    kernel wait + device→host copy as a `kernel_wait+d2h` child of
    `parent`, or a no-op when the launch wasn't traced.  One name for
    both the encode reap (PendingEncode.result) and the decode reap
    (decode_concat) so trace tooling can match a single span name."""
    if parent is None:
        return contextlib.nullcontext()
    return parent.child("kernel_wait+d2h")


def instrument_codec(ec, plugin: str):
    """Wrap the device-path (encode_array/decode_array) and chunk-path
    (encode_chunks/decode_chunks) entry points of `ec` with codec-stage
    sub-spans.  Idempotent; returns `ec` for factory tail-calls."""
    if getattr(ec, "_codec_spans_installed", False):
        return ec

    if hasattr(ec, "encode_array"):
        orig_encode_array = ec.encode_array

        def encode_array(data, out=None):
            parent = active_span()
            if parent is None:
                return orig_encode_array(data, out=out)
            import jax.numpy as jnp

            with parent.child(f"codec:{plugin}:encode") as sp:
                sp.keyval("shape", lambda: str(getattr(data, "shape", len(data))))
                with sp.child("h2d"):
                    dev = jnp.asarray(data)
                with sp.child("kernel_launch"):
                    # async dispatch: this times the launch, not the kernel;
                    # the reap side (PendingEncode.result) times the wait
                    return orig_encode_array(dev, out=out)

        ec.encode_array = encode_array

    if hasattr(ec, "decode_array"):
        orig_decode_array = ec.decode_array

        def decode_array(erasures, survivors, out=None):
            parent = active_span()
            if parent is None:
                return orig_decode_array(erasures, survivors, out=out)
            import jax.numpy as jnp

            with parent.child(f"codec:{plugin}:decode") as sp:
                sp.keyval("erasures", lambda: ",".join(map(str, erasures)))
                with sp.child("h2d"):
                    dev = jnp.asarray(survivors)
                with sp.child("kernel_launch"):
                    return orig_decode_array(erasures, dev, out=out)

        ec.decode_array = decode_array

    # chunk-level interface: synchronous host (or C) compute — one span
    for name in ("encode_chunks", "decode_chunks"):
        orig = getattr(ec, name, None)
        if orig is None:
            continue

        def wrapped(*args, _orig=orig, _name=name, **kwargs):
            parent = active_span()
            if parent is None:
                return _orig(*args, **kwargs)
            with parent.child(f"codec:{plugin}:{_name}") as sp:
                sp.event("kernel")
                return _orig(*args, **kwargs)

        setattr(ec, name, wrapped)

    ec._codec_spans_installed = True
    return ec
