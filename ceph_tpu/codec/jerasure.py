"""jerasure-compatible codec family on the TPU kernels.

Re-design of the reference `jerasure` plugin
(/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc};
techniques enumerated at ErasureCodeJerasure.h:81-253) with the same profile
surface: k/m/w plus per-technique knobs.  The CPU reference dispatches into
jerasure/gf-complete SIMD kernels; here every technique reduces to a GF(2^8)
coding matrix (gf/matrix.py reproduces the published jerasure matrix
constructions) applied by the shared bitsliced XOR-matmul device kernels, so
all techniques share one compiled kernel per shape.

Techniques:
- reed_sol_van     Vandermonde-derived systematic MDS (default k=7, m=3, w=8)
- reed_sol_r6_op   RAID-6 optimized (m must be 2); P = XOR row, Q = powers of 2
- cauchy_orig      original Cauchy bitmatrix construction
- cauchy_good      cauchy_orig with column/row scaling to minimize bit-matrix
                   ones (packetsize accepted for profile compat; the TPU
                   kernel has no packet concept)

For the GF(2^8) matrix techniques, w (Galois field width) is fixed at 8: the
TPU field core is GF(2^8), which is the reference default.  w=16/32 profiles
are rejected with EINVAL rather than silently re-encoded differently.

The liberation / blaum_roth / liber8tion techniques
(ErasureCodeJerasure.h:169-253) are packetized GF(2) BIT-MATRIX codes: every
chunk is w packets of `packetsize` bytes and coding XORs whole packets
selected by a (2w, kw) 0/1 matrix (RAID-6, m=2 only).  Their TPU mapping
(`ErasureCodeJerasureBitmatrix`) reshapes chunks to (super-packets, k*w,
packetsize) plane tensors and runs one gf2_plane_matmul launch per encode —
the packet loop of the reference's jerasure_schedule_encode becomes the
batch axis.  Matrix constructions are re-derived in gf/gf2.py (the jerasure
submodule that defines them is not vendored in the reference checkout).
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.gf import (
    jerasure_cauchy_good_matrix,
    jerasure_cauchy_orig_matrix,
    jerasure_r6_matrix,
    jerasure_vandermonde_matrix,
)
from ceph_tpu.gf.gf2 import (
    blaum_roth_bitmatrix,
    liber8tion_bitmatrix,
    liberation_bitmatrix,
)
from ceph_tpu.ops.xor_mm import gf2_plane_matmul

from .base import EINVAL, EIO, ErasureCode
from .interface import EcError, Profile
from .matrix_codec import PLAN_CACHE, MatrixCodecMixin

TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good")
BITMATRIX_TECHNIQUES = ("liberation", "blaum_roth", "liber8tion")


class ErasureCodeJerasure(MatrixCodecMixin, ErasureCode):
    """jerasure techniques as GF(2^8) matrix codecs on TPU."""

    DEFAULT_K = "7"   # ErasureCodeJerasure.h reed_sol_van defaults
    DEFAULT_M = "3"
    DEFAULT_W = "8"

    def __init__(self, technique: str = "reed_sol_van") -> None:
        super().__init__()
        if technique not in TECHNIQUES:
            raise EcError(EINVAL, f"unknown jerasure technique {technique}")
        self.technique = technique
        self.k = 0
        self.m = 0
        self.w = 8

    def parse(self, profile: Profile) -> None:
        super().parse(profile)
        self.invalidate_matrix()
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.w = self.to_int("w", profile, self.DEFAULT_W)
        if self.w != 8:
            raise EcError(EINVAL, f"w={self.w} not supported (GF(2^8) core); use w=8")
        self.sanity_check_k_m(self.k, self.m)
        if self.technique == "reed_sol_r6_op" and self.m != 2:
            # reed_sol_r6 is RAID-6 only (jerasure reed_sol_r6_encode contract).
            raise EcError(EINVAL, f"reed_sol_r6_op requires m=2, got m={self.m}")
        if self.k + self.m > 256:
            # w=8 field bound (jerasure requires k+m <= 2^w).
            raise EcError(EINVAL, f"k+m={self.k + self.m} must be <= 256 with w=8")
        # packetsize accepted for profile compatibility (default 2048,
        # ErasureCodeJerasure.h:141); no behavioral effect on the TPU path.
        self.to_int("packetsize", profile, "2048")

    def build_matrix(self) -> np.ndarray:
        if self.technique == "reed_sol_van":
            return jerasure_vandermonde_matrix(self.k, self.m)
        if self.technique == "reed_sol_r6_op":
            return jerasure_r6_matrix(self.k)
        if self.technique == "cauchy_orig":
            return jerasure_cauchy_orig_matrix(self.k, self.m)
        return jerasure_cauchy_good_matrix(self.k, self.m)

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k


class ErasureCodeJerasureBitmatrix(ErasureCode):
    """liberation / blaum_roth / liber8tion — packetized GF(2) bit-matrix
    RAID-6 codes on the plane-granular XOR-matmul kernel.

    Chunk layout (jerasure bit-matrix convention): a chunk of S*w*packetsize
    bytes is S super-packets of w packets each; coding row r of super-packet
    s is the XOR of the data packets its matrix row selects.  The reference
    walks packets in a C loop with a precomputed XOR schedule
    (jerasure_schedule_encode); here all S super-packets for all rows go in
    one gf2_plane_matmul launch, with S the batch axis on the MXU.
    """

    DEFAULT_PACKETSIZE = "2048"  # ErasureCodeJerasure.h:141

    def __init__(self, technique: str) -> None:
        super().__init__()
        if technique not in BITMATRIX_TECHNIQUES:
            raise EcError(EINVAL, f"unknown bitmatrix technique {technique}")
        self.technique = technique
        self.k = 0
        self.m = 0
        self.w = 0
        self.packetsize = 0
        self._bitmatrix: np.ndarray | None = None

    # defaults per reference class declarations (ErasureCodeJerasure.h)
    def _defaults(self) -> tuple[str, str, str]:
        if self.technique == "liber8tion":
            return "2", "2", "8"
        return "2", "2", "7"

    def parse(self, profile: Profile) -> None:
        super().parse(profile)
        dk, dm, dw = self._defaults()
        self.k = self.to_int("k", profile, dk)
        self.m = self.to_int("m", profile, dm)
        self.w = self.to_int("w", profile, dw)
        self.packetsize = self.to_int("packetsize", profile, self.DEFAULT_PACKETSIZE)
        self.sanity_check_k_m(self.k, self.m)
        if self.m != 2:
            raise EcError(
                EINVAL, f"{self.technique} is RAID-6 only: m must be 2, got {self.m}"
            )
        if self.k > self.w:
            raise EcError(
                EINVAL, f"k={self.k} must be <= w={self.w} ({self.technique})"
            )
        if self.packetsize <= 0 or self.packetsize % 4:
            # check_packetsize: multiple of sizeof(int)
            raise EcError(
                EINVAL, f"packetsize={self.packetsize} must be a positive multiple of 4"
            )
        try:
            if self.technique == "liberation":
                self._bitmatrix = liberation_bitmatrix(self.k, self.w)
            elif self.technique == "blaum_roth":
                self._bitmatrix = blaum_roth_bitmatrix(self.k, self.w)
            else:
                if self.w != 8:
                    raise ValueError(f"liber8tion requires w=8, got w={self.w}")
                self._bitmatrix = liber8tion_bitmatrix(self.k)
                # The published minimum-density liber8tion matrices live in
                # the jerasure submodule, which the reference checkout does
                # not vendor; this plugin fills the same (k, m=2, w=8)
                # envelope with a re-derived MDS bit-matrix.  Same fault
                # tolerance, different parity bytes — so chunks written by
                # upstream jerasure under this profile name are NOT
                # byte-interchangeable.  Say so where profile users see it.
                from ..common.log import dout

                dout(
                    "codec",
                    1,
                    "jerasure technique=liber8tion uses a re-derived MDS "
                    "bit-matrix (published minimum-density matrices not "
                    "vendored); parity bytes are not interchangeable with "
                    "upstream jerasure liber8tion chunks",
                )
        except ValueError as e:
            raise EcError(EINVAL, str(e))

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        # chunks must be whole super-packets; keep the TPU lane alignment too
        import math

        return math.lcm(self.w * self.packetsize, self.ALIGNMENT)

    # -- coding ------------------------------------------------------------

    def _planes(self, arrays: list[np.ndarray]) -> np.ndarray:
        """k chunks of S*w*packetsize bytes -> (S, k*w, packetsize)."""
        w, P = self.w, self.packetsize
        stacked = np.stack([np.asarray(a, dtype=np.uint8) for a in arrays])
        S = stacked.shape[1] // (w * P)
        # (k, S*w*P) -> (k, S, w, P) -> (S, k, w, P) -> (S, k*w, P)
        return (
            stacked.reshape(len(arrays), S, w, P)
            .transpose(1, 0, 2, 3)
            .reshape(S, len(arrays) * w, P)
        )

    def _unplanes(self, planes: np.ndarray, n: int) -> np.ndarray:
        """(S, n*w, P) -> (n, S*w*P) chunk bytes."""
        S, _, P = planes.shape
        return (
            planes.reshape(S, n, self.w, P).transpose(1, 0, 2, 3).reshape(n, -1)
        )

    def _check_size(self, size: int) -> None:
        if size % (self.w * self.packetsize):
            raise EcError(
                EINVAL,
                f"chunk size {size} not a multiple of w*packetsize "
                f"{self.w * self.packetsize}",
            )

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        raw_of = self.chunk_index
        self._check_size(len(chunks[raw_of(0)]))
        planes = self._planes([chunks[raw_of(i)] for i in range(k)])
        coded = np.asarray(gf2_plane_matmul(self._bitmatrix, planes))
        out = self._unplanes(coded, m)
        for i in range(m):
            np.copyto(chunks[raw_of(k + i)], out[i])

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks,
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m, w = self.k, self.m, self.w
        raw_of = self.chunk_index
        erasures = [i for i in range(k + m) if raw_of(i) not in chunks]
        if not erasures:
            return
        if len(erasures) > m:
            raise EcError(EIO, f"{len(erasures)} erasures > m={m}")
        self._check_size(len(next(iter(chunks.values()))))
        dec, decode_index = PLAN_CACHE.gf2_decode_plan(
            self._bitmatrix, k, w, erasures
        )
        planes = self._planes([decoded[raw_of(i)] for i in decode_index])
        rec = np.asarray(gf2_plane_matmul(dec, planes))
        out = self._unplanes(rec, len(erasures))
        for p, e in enumerate(erasures):
            np.copyto(decoded[raw_of(e)], out[p])
