"""jerasure-compatible codec family on the TPU kernels.

Re-design of the reference `jerasure` plugin
(/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc};
techniques enumerated at ErasureCodeJerasure.h:81-253) with the same profile
surface: k/m/w plus per-technique knobs.  The CPU reference dispatches into
jerasure/gf-complete SIMD kernels; here every technique reduces to a GF(2^8)
coding matrix (gf/matrix.py reproduces the published jerasure matrix
constructions) applied by the shared bitsliced XOR-matmul device kernels, so
all techniques share one compiled kernel per shape.

Techniques:
- reed_sol_van     Vandermonde-derived systematic MDS (default k=7, m=3, w=8)
- reed_sol_r6_op   RAID-6 optimized (m must be 2); P = XOR row, Q = powers of 2
- cauchy_orig      original Cauchy bitmatrix construction
- cauchy_good      cauchy_orig with column/row scaling to minimize bit-matrix
                   ones (packetsize accepted for profile compat; the TPU
                   kernel has no packet concept)

w (Galois field width) is fixed at 8: the TPU field core is GF(2^8), which is
the reference default.  w=16/32 profiles are rejected with EINVAL rather than
silently re-encoded differently.  The liberation/blaum_roth/liber8tion
bitmatrix techniques (w prime, packet-layout-dependent) are not yet
implemented.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.gf import (
    jerasure_cauchy_good_matrix,
    jerasure_cauchy_orig_matrix,
    jerasure_r6_matrix,
    jerasure_vandermonde_matrix,
)

from .base import EINVAL, ErasureCode
from .interface import EcError, Profile
from .matrix_codec import MatrixCodecMixin

TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good")


class ErasureCodeJerasure(MatrixCodecMixin, ErasureCode):
    """jerasure techniques as GF(2^8) matrix codecs on TPU."""

    DEFAULT_K = "7"   # ErasureCodeJerasure.h reed_sol_van defaults
    DEFAULT_M = "3"
    DEFAULT_W = "8"

    def __init__(self, technique: str = "reed_sol_van") -> None:
        super().__init__()
        if technique not in TECHNIQUES:
            raise EcError(EINVAL, f"unknown jerasure technique {technique}")
        self.technique = technique
        self.k = 0
        self.m = 0
        self.w = 8

    def parse(self, profile: Profile) -> None:
        super().parse(profile)
        self.invalidate_matrix()
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.w = self.to_int("w", profile, self.DEFAULT_W)
        if self.w != 8:
            raise EcError(EINVAL, f"w={self.w} not supported (GF(2^8) core); use w=8")
        self.sanity_check_k_m(self.k, self.m)
        if self.technique == "reed_sol_r6_op" and self.m != 2:
            # reed_sol_r6 is RAID-6 only (jerasure reed_sol_r6_encode contract).
            raise EcError(EINVAL, f"reed_sol_r6_op requires m=2, got m={self.m}")
        if self.k + self.m > 256:
            # w=8 field bound (jerasure requires k+m <= 2^w).
            raise EcError(EINVAL, f"k+m={self.k + self.m} must be <= 256 with w=8")
        # packetsize accepted for profile compatibility (default 2048,
        # ErasureCodeJerasure.h:141); no behavioral effect on the TPU path.
        self.to_int("packetsize", profile, "2048")

    def build_matrix(self) -> np.ndarray:
        if self.technique == "reed_sol_van":
            return jerasure_vandermonde_matrix(self.k, self.m)
        if self.technique == "reed_sol_r6_op":
            return jerasure_r6_matrix(self.k)
        if self.technique == "cauchy_orig":
            return jerasure_cauchy_orig_matrix(self.k, self.m)
        return jerasure_cauchy_good_matrix(self.k, self.m)

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k
