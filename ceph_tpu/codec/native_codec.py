"""Native-engine RS codec — the isa-style CPU SIMD path.

Mirror of the reference `isa` plugin's division of labor
(/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc): host C++ class
does matrices/caches, the native library does the byte crunching.  Here
the host side is ErasureCodeTpuRs's geometry/matrix logic (identical
math → byte-identical chunks vs the TPU path), and the hot region loops
run in `libec_native.so` (native/ec_native.cc, the ec_encode_data /
region_xor twin), dlopen-loaded through the registry's dynamic path
exactly as the reference loads `libec_isa.so`.

Decode tables are cached per erasure signature in a bounded LRU holding
native table handles (ErasureCodeIsaTableCache's decode LRU, capacity
2516, ErasureCodeIsaTableCache.h:48).
"""

from __future__ import annotations

import ctypes
from collections import OrderedDict
from typing import Mapping

import numpy as np

from ceph_tpu.gf import isa_decode_matrix

from .interface import EcError
from .matrix_codec import DECODE_LRU_CAPACITY
from .rs import ErasureCodeTpuRs

EIO = 5


class _NativeTables:
    """RAII over an ec_tables handle."""

    def __init__(self, lib, rows: int, cols: int, matrix: np.ndarray):
        self._lib = lib
        self.rows = rows
        self.cols = cols
        self._handle = lib.ec_tables_new(
            rows, cols, np.ascontiguousarray(matrix, dtype=np.uint8).tobytes()
        )

    def apply(self, inputs: list[np.ndarray], length: int) -> list[np.ndarray]:
        outs = [np.empty(length, dtype=np.uint8) for _ in range(self.rows)]
        in_arr = (ctypes.c_void_p * self.cols)(*[i.ctypes.data for i in inputs])
        out_arr = (ctypes.c_void_p * self.rows)(*[o.ctypes.data for o in outs])
        self._lib.ec_tables_apply(self._handle, in_arr, out_arr, length)
        return outs

    def __del__(self):
        try:
            self._lib.ec_tables_free(self._handle)
        except Exception:
            pass


class ErasureCodeNative(ErasureCodeTpuRs):
    """RS(k, m) with native (C++) region coding — plugin `native`."""

    def __init__(self, lib: ctypes.CDLL, technique: str = "reed_sol_van") -> None:
        super().__init__(technique=technique)
        self._lib = lib
        self._encode_tables: _NativeTables | None = None
        self._decode_lru: OrderedDict[str, tuple[_NativeTables, list[int]]] = (
            OrderedDict()
        )

    def invalidate_matrix(self) -> None:
        super().invalidate_matrix()
        self._encode_tables = None
        self._decode_lru = OrderedDict()

    # -- hot paths through the native engine ---------------------------------

    def _get_encode_tables(self) -> _NativeTables:
        if self._encode_tables is None:
            mat = self.distribution_matrix()
            self._encode_tables = _NativeTables(
                self._lib, self.m, self.k, mat[self.k :]
            )
        return self._encode_tables

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        data = [
            np.ascontiguousarray(chunks[self.chunk_index(i)], dtype=np.uint8)
            for i in range(k)
        ]
        length = len(data[0])
        if m == 1 and self._xor_row_available():
            # region_xor fast path (ErasureCodeIsa.cc:125-131)
            out = np.empty(length, dtype=np.uint8)
            in_arr = (ctypes.c_void_p * k)(*[d.ctypes.data for d in data])
            self._lib.ec_region_xor(in_arr, k, out.ctypes.data, length)
            np.copyto(chunks[self.chunk_index(k)], out)
            return
        parity = self._get_encode_tables().apply(data, length)
        for i in range(m):
            np.copyto(chunks[self.chunk_index(k + i)], parity[i])

    def _decode_tables(self, erasures: list[int]) -> tuple[_NativeTables, list[int]]:
        # signature string exactly like the reference's "+avail-erased" keys
        # (ErasureCodeIsa.cc:227-240)
        sig = "-" + ",".join(map(str, sorted(erasures)))
        cached = self._decode_lru.get(sig)
        if cached is not None:
            self._decode_lru.move_to_end(sig)
            return cached
        plan = isa_decode_matrix(self.distribution_matrix(), erasures, self.k)
        if plan is None:
            raise EcError(EIO, f"cannot invert decode matrix for {erasures}")
        c_matrix, index = plan
        tables = _NativeTables(self._lib, len(erasures), self.k, c_matrix)
        self._decode_lru[sig] = (tables, index)
        while len(self._decode_lru) > DECODE_LRU_CAPACITY:
            self._decode_lru.popitem(last=False)
        return tables, index

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m = self.k, self.m
        raw_of = self.chunk_index
        erasures = [i for i in range(k + m) if raw_of(i) not in chunks]
        if not erasures:
            return
        if len(erasures) > m:
            raise EcError(EIO, f"{len(erasures)} erasures > m={m}")
        if self._use_xor_decode(erasures):
            sources = [i for i in range(k + m) if raw_of(i) in chunks][:k]
            data = [
                np.ascontiguousarray(decoded[raw_of(i)], dtype=np.uint8)
                for i in sources
            ]
            length = len(data[0])
            out = np.empty(length, dtype=np.uint8)
            in_arr = (ctypes.c_void_p * len(data))(*[d.ctypes.data for d in data])
            self._lib.ec_region_xor(in_arr, len(data), out.ctypes.data, length)
            np.copyto(decoded[raw_of(erasures[0])], out)
            return
        tables, index = self._decode_tables(erasures)
        survivors = [
            np.ascontiguousarray(decoded[raw_of(i)], dtype=np.uint8) for i in index
        ]
        rec = tables.apply(survivors, len(survivors[0]))
        for p, e in enumerate(erasures):
            np.copyto(decoded[raw_of(e)], rec[p])
