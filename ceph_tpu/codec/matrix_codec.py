"""Generic GF(2^8) matrix-codec machinery shared by every matrix technique.

Any systematic code defined by a (k+m, k) distribution matrix (RS, Cauchy,
jerasure variants, SHEC, LRC layers) gets its chunk-level and device-level
paths from this mixin; concrete codecs supply geometry + `build_matrix()`.

Caching mirrors the reference's two-level table cache
(/root/reference/src/erasure-code/isa/ErasureCodeIsaTableCache.{h,cc}):
encode plans per matrix, decode plans in a signature-keyed LRU (capacity 2516,
"sufficient up to (12,4)", ErasureCodeIsaTableCache.h:48) — but a cached
"table" here is a device bit-matrix operand for the shared XOR-matmul kernel,
so any erasure pattern reuses one compiled kernel per shape.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Mapping

import jax.numpy as jnp
import numpy as np

try:  # jax.core.Tracer is being removed from the public surface (jax >= 0.6)
    from jax.core import Tracer as _JaxTracer
except (ImportError, AttributeError):
    from jax._src.core import Tracer as _JaxTracer

from ceph_tpu.gf import expand_matrix, isa_decode_matrix
from ceph_tpu.ops.pallas_gf import CodingPlan
from ceph_tpu.ops.xor_mm import xor_matmul, xor_reduce

from .base import EIO
from .interface import EcError

DECODE_LRU_CAPACITY = 2516


def _trace_local(x) -> bool:
    """True when `x` was created inside a jax.jit/vmap trace.  Trace-local
    values must NEVER enter the process-wide cache: a cached tracer
    poisons every later eager call with UnexpectedTracerError (first hit
    by bench.py's jitted serial chain warming the encode cache)."""
    return isinstance(x, _JaxTracer)

_PLATFORM: str | None = None


def _on_tpu() -> bool:
    """True when the default jax backend is a TPU (cached; backend init is
    expensive and the answer cannot change within a process)."""
    global _PLATFORM
    if _PLATFORM is None:
        try:
            import jax

            _PLATFORM = jax.devices()[0].platform
        except Exception:
            _PLATFORM = "cpu"
    return _PLATFORM == "tpu"


class _DeviceCoder:
    """One cached coding operator: the fused Pallas kernel on TPU for
    lane-aligned chunks, the jnp bitsliced matmul everywhere else.

    This is the dispatch the reference does by linking `ec_encode_data` to
    the best SIMD flavor at plugin load (isa/ErasureCodeIsa.cc:83-91): the
    production `encode_chunks`/`decode_chunks` path and the bulk device path
    both land on the fast kernel — the benchmark measures what ships.
    """

    __slots__ = ("bm", "plan")

    def __init__(self, bm: jnp.ndarray, plan: CodingPlan | None):
        self.bm = bm
        self.plan = plan

    def __call__(self, data: jnp.ndarray) -> jnp.ndarray:
        if self.plan is not None and data.shape[-1] % 128 == 0:
            return self.plan(data)
        return xor_matmul(self.bm, data)


class _GlobalPlanCache:
    """Process-wide encode/decode plan cache keyed by matrix content."""

    def __init__(self) -> None:
        from ceph_tpu.common.lockdep import make_lock

        self._lock = make_lock("plan_cache")
        self._encode: dict[bytes, jnp.ndarray] = {}
        self._encode_coders: dict[bytes, _DeviceCoder] = {}
        self._decode: OrderedDict[tuple[bytes, str], tuple[jnp.ndarray, list[int]]] = (
            OrderedDict()
        )
        self._decode_coders: OrderedDict[tuple, _DeviceCoder] = OrderedDict()

    def _make_coder(self, gf_rows: np.ndarray, bm: jnp.ndarray) -> _DeviceCoder:
        plan = CodingPlan(gf_rows) if _on_tpu() else None
        return _DeviceCoder(bm, plan)

    def _lru_put_coder(self, key, coder: _DeviceCoder) -> None:
        self._decode_coders[key] = coder
        self._decode_coders.move_to_end(key)
        while len(self._decode_coders) > DECODE_LRU_CAPACITY:
            self._decode_coders.popitem(last=False)

    def encode_bit_matrix(self, coding_rows: np.ndarray) -> jnp.ndarray:
        """Per-geometry encode matrices: one entry per codec instance's
        matrix, unbounded like the reference's per-(k,m) encode tables."""
        key = (coding_rows.shape, coding_rows.tobytes())
        with self._lock:
            bm = self._encode.get(key)
        if bm is not None:
            return bm
        bm = jnp.asarray(expand_matrix(coding_rows), dtype=jnp.uint8)
        if _trace_local(bm):
            return bm
        with self._lock:
            self._encode.setdefault(key, bm)
            return self._encode[key]

    def encode_coder(self, coding_rows: np.ndarray) -> _DeviceCoder:
        """Cached coding operator for an encode matrix (TPU plan + jnp bm)."""
        key = (coding_rows.shape, coding_rows.tobytes())
        with self._lock:
            coder = self._encode_coders.get(key)
        if coder is not None:
            return coder
        coder = self._make_coder(coding_rows, self.encode_bit_matrix(coding_rows))
        if _trace_local(coder.bm):
            return coder
        with self._lock:
            return self._encode_coders.setdefault(key, coder)

    def lru_coder(self, matrix: np.ndarray) -> _DeviceCoder:
        """Coding operator for a decode-time matrix, bounded by the decode
        LRU (SHEC's searched inverses and other raw-matrix decode paths)."""
        key = (matrix.shape, matrix.tobytes(), "#raw")
        with self._lock:
            coder = self._decode_coders.get(key)
            if coder is not None:
                self._decode_coders.move_to_end(key)
                return coder
        coder = self._make_coder(matrix, self.lru_bit_matrix(matrix))
        if _trace_local(coder.bm):
            return coder
        with self._lock:
            self._lru_put_coder(key, coder)
        return coder

    def lru_bit_matrix(self, matrix: np.ndarray) -> jnp.ndarray:
        """Bit-matrix for a decode-time matrix, bounded by the decode LRU.

        For codecs whose decode matrices vary per erasure pattern but don't
        go through decode_plan (SHEC's searched inverses) — stored alongside
        the signature-keyed plans so total decode-table memory stays within
        DECODE_LRU_CAPACITY, as the reference's cache guarantees.
        """
        key = (matrix.shape, matrix.tobytes(), "#raw")
        with self._lock:
            cached = self._decode.get(key)
            if cached is not None:
                self._decode.move_to_end(key)
                return cached[0]
        bm = jnp.asarray(expand_matrix(matrix), dtype=jnp.uint8)
        if _trace_local(bm):
            return bm
        with self._lock:
            self._decode[key] = (bm, [])
            self._decode.move_to_end(key)
            while len(self._decode) > DECODE_LRU_CAPACITY:
                self._decode.popitem(last=False)
        return bm

    def gf2_decode_plan(
        self, bitmatrix: np.ndarray, k: int, w: int, erasures: list[int]
    ) -> tuple[np.ndarray, list[int]]:
        """Decode plan for a packetized GF(2) bit-matrix RAID-6 code
        (liberation family): (decode matrix (len(erasures)*w, k*w),
        decode_index).  Shares the one decode LRU so total decode-table
        memory stays within DECODE_LRU_CAPACITY."""
        from ceph_tpu.gf.gf2 import gf2_inv, gf2_matmul

        n = k + bitmatrix.shape[0] // w
        erased = set(erasures)
        decode_index = [c for c in range(n) if c not in erased][:k]
        if len(decode_index) < k:
            raise EcError(EIO, f"not enough survivors for erasures {erasures}")
        key = (bitmatrix.shape, bitmatrix.tobytes(), "#gf2", tuple(erasures))
        with self._lock:
            cached = self._decode.get(key)
            if cached is not None:
                self._decode.move_to_end(key)
                return cached
        # full generator: data identity rows then the coding rows (the
        # bitmatrix already carries both the P-identity and Q blocks)
        full = np.zeros((n * w, k * w), dtype=np.uint8)
        full[: k * w] = np.eye(k * w, dtype=np.uint8)
        full[k * w :] = bitmatrix
        survivors = np.vstack([full[c * w : (c + 1) * w] for c in decode_index])
        inv = gf2_inv(survivors)
        if inv is None:
            raise EcError(EIO, f"singular decode matrix for erasures {erasures}")
        erased_rows = np.vstack([full[c * w : (c + 1) * w] for c in erasures])
        plan = (gf2_matmul(erased_rows, inv), decode_index)
        with self._lock:
            self._decode[key] = plan
            self._decode.move_to_end(key)
            while len(self._decode) > DECODE_LRU_CAPACITY:
                self._decode.popitem(last=False)
        return plan

    def decode_plan(
        self, dist_matrix: np.ndarray, erasures: list[int], k: int
    ) -> tuple[jnp.ndarray, list[int]]:
        bitmat, decode_index, _ = self._decode_entry(dist_matrix, erasures, k)
        return bitmat, decode_index

    def _decode_entry(
        self, dist_matrix: np.ndarray, erasures: list[int], k: int, key=None
    ) -> tuple[jnp.ndarray, list[int], np.ndarray]:
        """(bit-matrix, decode_index, GF decode matrix) for an erasure
        pattern, LRU-cached.  The GF matrix rides along so a coder rebuild
        after a coder-LRU eviction is a cheap re-arrangement, not a second
        Gaussian inversion."""
        if key is None:
            key = self._decode_key(dist_matrix, erasures, k)
        with self._lock:
            cached = self._decode.get(key)
            if cached is not None:
                self._decode.move_to_end(key)
                return cached
        plan = isa_decode_matrix(dist_matrix, erasures, k)
        if plan is None:
            raise EcError(EIO, f"singular decode matrix for erasures {erasures}")
        c, decode_index = plan
        bitmat = jnp.asarray(expand_matrix(c), dtype=jnp.uint8)
        if _trace_local(bitmat):
            return bitmat, decode_index, c
        with self._lock:
            self._decode[key] = (bitmat, decode_index, c)
            self._decode.move_to_end(key)
            while len(self._decode) > DECODE_LRU_CAPACITY:
                self._decode.popitem(last=False)
        return bitmat, decode_index, c

    def _decode_key(
        self, dist_matrix: np.ndarray, erasures: list[int], k: int
    ) -> tuple:
        """Reference signature format, ErasureCodeIsa.cc:233-248 (the
        survivor part uses the first-k-non-erased rows, matching decode_plan's
        key derivation even when isa_decode_matrix picks different rows)."""
        km = dist_matrix.shape[0]
        erased = set(erasures)
        survivors: list[int] = []
        r = 0
        for _ in range(k):
            while r in erased:
                r += 1
            if r >= km:
                raise EcError(EIO, f"not enough survivors for erasures {erasures}")
            survivors.append(r)
            r += 1
        sig = "".join(f"+{r}" for r in survivors) + "".join(
            f"-{e}" for e in erasures
        )
        return (dist_matrix.shape, dist_matrix.tobytes(), sig)

    def decode_coder(
        self, dist_matrix: np.ndarray, erasures: list[int], k: int
    ) -> tuple[_DeviceCoder, list[int]]:
        """Cached coding operator + survivor index for an erasure pattern."""
        key = self._decode_key(dist_matrix, erasures, k)
        bitmat, decode_index, c = self._decode_entry(dist_matrix, erasures, k, key)
        with self._lock:
            coder = self._decode_coders.get(key)
            if coder is not None:
                self._decode_coders.move_to_end(key)
                return coder, decode_index
        coder = self._make_coder(c, bitmat)  # built outside the lock
        if _trace_local(coder.bm):
            return coder, decode_index
        with self._lock:
            self._lru_put_coder(key, coder)
        return coder, decode_index


PLAN_CACHE = _GlobalPlanCache()


class EncodePipeline:
    """Asynchronous chunk-encode hand-off — the completion queue behind
    the synchronous `encode_chunks` interface (SURVEY §7's hard part).

    `submit` stages the host->device transfer and LAUNCHES the encode
    immediately (JAX dispatch is asynchronous: the call returns while the
    device works), so consecutive submissions overlap compute with the
    host-side gather of the next batch — the double-buffering the
    reference gets from queued librados AIO in front of `ec_encode_data`.
    Completions copy parity back into the caller's chunk buffers exactly
    like `encode_chunks`; `poll()` reaps only finished launches
    (non-blocking), `flush()` drains everything.  `depth` bounds
    device-side in-flight work the way an AIO queue depth does.
    """

    def __init__(self, codec: "MatrixCodecMixin", depth: int = 4):
        self.codec = codec
        self.depth = max(1, depth)
        self._tickets = 0
        # in-flight: (ticket, caller chunk dict, device parity array)
        self._inflight: list[tuple[int, Mapping[int, np.ndarray], object]] = []
        # tickets completed inside submit's backpressure path: the next
        # poll()/flush() reports them — a completed ticket is NEVER lost
        self._reaped: list[int] = []

    def submit(self, chunks: Mapping[int, np.ndarray]) -> int:
        """Launch one stripe's encode; returns its ticket.  Blocks only
        when `depth` launches are already in flight (backpressure)."""
        parity_dev = self.codec.encode_array(self.codec._gather(chunks))
        self._tickets += 1
        self._inflight.append((self._tickets, chunks, parity_dev))
        while len(self._inflight) > self.depth:
            self._reaped += self._complete(*self._inflight.pop(0))
        return self._tickets

    def _complete(self, ticket: int, chunks, parity_dev) -> list[int]:
        parity = np.asarray(parity_dev)  # blocks until the launch finishes
        self.codec._scatter(chunks, parity)
        return [ticket]

    def poll(self) -> list[int]:
        """Reap FINISHED launches without blocking (completion queue)."""
        done, self._reaped = self._reaped, []
        while self._inflight:
            ticket, chunks, dev = self._inflight[0]
            ready = getattr(dev, "is_ready", None)
            # unknown readiness means NOT ready: popping would block in
            # _complete and silently defeat the non-blocking contract
            if ready is None or not ready():
                break  # still computing; keep submission order
            self._inflight.pop(0)
            done += self._complete(ticket, chunks, dev)
        return done

    def flush(self) -> list[int]:
        """Drain every in-flight encode (the barrier before a commit)."""
        done, self._reaped = self._reaped, []
        while self._inflight:
            done += self._complete(*self._inflight.pop(0))
        return done


class MatrixCodecMixin:
    """Chunk-level + device-level coding for matrix-defined codecs.

    Host contract: the concrete class provides `self.k`, `self.m`,
    `chunk_index()` (from ErasureCode) and `build_matrix() -> (k+m, k)`
    systematic uint8 distribution matrix.
    """

    _dist_matrix: np.ndarray | None = None

    def build_matrix(self) -> np.ndarray:  # pragma: no cover - abstract
        raise NotImplementedError

    def invalidate_matrix(self) -> None:
        """Drop the cached distribution matrix; call on (re)parse so a
        second init() with new geometry cannot serve the stale matrix."""
        self._dist_matrix = None

    def distribution_matrix(self) -> np.ndarray:
        if self._dist_matrix is None:
            mat = np.asarray(self.build_matrix(), dtype=np.uint8)
            k, m = self.k, self.m
            assert mat.shape == (k + m, k), mat.shape
            assert np.array_equal(mat[:k], np.eye(k, dtype=np.uint8)), (
                "distribution matrix must be systematic"
            )
            self._dist_matrix = mat
        return self._dist_matrix

    def _xor_row_available(self) -> bool:
        """True when parity row 0 is all ones (enables XOR fast paths)."""
        mat = self.distribution_matrix()
        return bool((mat[self.k] == 1).all())

    # -- device-native bulk paths ------------------------------------------

    def encode_array(self, data) -> jnp.ndarray:
        """(..., k, L) uint8 -> (..., m, L) parity, stays on device.

        Dispatches through the cached _DeviceCoder, so on a TPU backend this
        IS the fused Pallas kernel — the production analog of the reference
        plugin's `ec_encode_data` hot call (isa/ErasureCodeIsa.cc:83-91)."""
        mat = self.distribution_matrix()
        if self.m == 1 and self._xor_row_available():
            return xor_reduce(jnp.asarray(data))[..., None, :]
        return PLAN_CACHE.encode_coder(mat[self.k :])(jnp.asarray(data))

    def decode_array(self, erasures: list[int], survivors) -> jnp.ndarray:
        """survivors (..., k, L) in decode_index order -> (..., nerrs, L)."""
        coder, _ = PLAN_CACHE.decode_coder(self.distribution_matrix(), erasures, self.k)
        return coder(jnp.asarray(survivors))

    def decode_index(self, erasures: list[int]) -> list[int]:
        _, idx = PLAN_CACHE.decode_plan(self.distribution_matrix(), erasures, self.k)
        return idx

    # -- chunk-level interface ---------------------------------------------

    def _gather(self, chunks: Mapping[int, np.ndarray]) -> np.ndarray:
        """Stack the k data chunks in encode order (shared by the sync
        interface and the EncodePipeline so the paths cannot drift)."""
        return np.stack(
            [
                np.asarray(chunks[self.chunk_index(i)], dtype=np.uint8)
                for i in range(self.k)
            ]
        )

    def _scatter(self, chunks: Mapping[int, np.ndarray], parity: np.ndarray) -> None:
        for i in range(self.m):
            np.copyto(chunks[self.chunk_index(self.k + i)], parity[i])

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        self._scatter(chunks, np.asarray(self.encode_array(self._gather(chunks))))

    def _use_xor_decode(self, erasures: list[int]) -> bool:
        """Single-erasure XOR path: first k+1 chunks + all-ones parity row 0
        (generalizes ErasureCodeIsa.cc:196-216)."""
        return (
            len(erasures) == 1
            and erasures[0] < self.k + 1
            and self._xor_row_available()
        )

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m = self.k, self.m
        raw_of = self.chunk_index
        erasures = [i for i in range(k + m) if raw_of(i) not in chunks]
        if not erasures:
            return
        if len(erasures) > m:
            raise EcError(EIO, f"{len(erasures)} erasures > m={m}")
        if self._use_xor_decode(erasures):
            sources = [i for i in range(k + m) if raw_of(i) in chunks][:k]
            stack = np.stack(
                [np.asarray(decoded[raw_of(i)], dtype=np.uint8) for i in sources]
            )
            np.copyto(decoded[raw_of(erasures[0])], np.asarray(xor_reduce(stack)))
            return
        idx = self.decode_index(erasures)
        survivors = np.stack(
            [np.asarray(decoded[raw_of(i)], dtype=np.uint8) for i in idx]
        )
        rec = np.asarray(self.decode_array(erasures, survivors))
        for p, e in enumerate(erasures):
            np.copyto(decoded[raw_of(e)], rec[p])
