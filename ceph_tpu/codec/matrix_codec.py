"""Generic GF(2^8) matrix-codec machinery shared by every matrix technique.

Any systematic code defined by a (k+m, k) distribution matrix (RS, Cauchy,
jerasure variants, SHEC, LRC layers) gets its chunk-level and device-level
paths from this mixin; concrete codecs supply geometry + `build_matrix()`.

Caching mirrors the reference's two-level table cache
(/root/reference/src/erasure-code/isa/ErasureCodeIsaTableCache.{h,cc}):
encode plans per matrix, decode plans in a signature-keyed LRU (capacity 2516,
"sufficient up to (12,4)", ErasureCodeIsaTableCache.h:48) — but a cached
"table" here is a device bit-matrix operand for the shared XOR-matmul kernel,
so any erasure pattern reuses one compiled kernel per shape.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Mapping

import jax.numpy as jnp
import numpy as np

try:  # jax.core.Tracer is being removed from the public surface (jax >= 0.6)
    from jax.core import Tracer as _JaxTracer
except (ImportError, AttributeError):
    from jax._src.core import Tracer as _JaxTracer

from ceph_tpu.common.lockdep import make_lock as _lockdep_make_lock
from ceph_tpu.common.mempool import track_buffer as _hbm_track
from ceph_tpu.gf import expand_matrix, isa_decode_matrix
from ceph_tpu.ops.dispatch import record_launch
from ceph_tpu.ops.packed_gf import (
    PACKED_MIN_BYTES,
    PackedPlan,
    PackedVerifyPlan,
    packed_verify_host,
)
from ceph_tpu.ops.pallas_gf import CodingPlan
from ceph_tpu.ops.xor_mm import xor_matmul, xor_reduce

from .base import EIO
from .interface import EcError

DECODE_LRU_CAPACITY = 2516

# Host-oracle decode-plan memo (decode_array_host): pure-numpy expanded
# bit-matrices keyed by (distribution matrix, erasure pattern), bounded
# like the device-side decode LRU but kept fully separate from the
# jnp-backed PLAN_CACHE — degraded mode must never touch the runtime.
_HOST_DECODE_CAPACITY = 256
_HOST_DECODE_PLANS: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
_HOST_DECODE_LOCK = _lockdep_make_lock("host_decode")


def _trace_local(x) -> bool:
    """True when `x` was created inside a jax.jit/vmap trace.  Trace-local
    values must NEVER enter the process-wide cache: a cached tracer
    poisons every later eager call with UnexpectedTracerError (first hit
    by bench.py's jitted serial chain warming the encode cache)."""
    return isinstance(x, _JaxTracer)

_PLATFORM: str | None = None


def _on_tpu() -> bool:
    """True when the default jax backend is a TPU (cached; backend init is
    expensive and the answer cannot change within a process)."""
    global _PLATFORM
    if _PLATFORM is None:
        try:
            import jax

            _PLATFORM = jax.devices()[0].platform
        except Exception:
            _PLATFORM = "cpu"
    return _PLATFORM == "tpu"


class _DeviceCoder:
    """One cached coding operator: the fused Pallas kernel on TPU for
    lane-aligned chunks, the packed-bitplane jnp kernel for bulk work
    everywhere else, the bitsliced matmul for tiny one-off matrices.

    This is the dispatch the reference does by linking `ec_encode_data` to
    the best SIMD flavor at plugin load (isa/ErasureCodeIsa.cc:83-91): the
    production `encode_chunks`/`decode_chunks` path and the bulk device path
    both land on the fast kernel — the benchmark measures what ships.

    The small-input cutoff exists because the packed plan bakes its XOR
    schedule into the compiled program (one compile per matrix), while
    xor_matmul takes the bit-matrix as a runtime operand (one compile per
    shape, any matrix): decode paths that invert a fresh matrix per
    erasure pattern on small chunks stay on the shared kernel.
    """

    __slots__ = ("bm", "plan", "packed", "decode")

    def __init__(
        self,
        bm: jnp.ndarray,
        plan: CodingPlan | None,
        packed: PackedPlan,
        decode: bool = False,
    ):
        self.bm = bm
        self.plan = plan
        self.packed = packed
        # decode-kind coders (built from PLAN_CACHE.decode_coder/lru_coder)
        # also count their dispatches on ops.dispatch.DECODE_LAUNCHES
        self.decode = decode

    def shard_mesh_for(self, shape):
        """Mesh for a sharded dispatch at this input shape, or None for
        the single-device path.  Batched (..., k, L) inputs of at least
        PACKED_MIN_BYTES shard — lead dims collapse into one stripe axis
        (CLAY's (planes, S, k+nu, sc) fragment launches included); the
        threshold/width policy lives in parallel/dispatch.py (the
        ec_tpu_shard_* knobs)."""
        if len(shape) < 3 or int(np.prod(shape)) < PACKED_MIN_BYTES:
            return None
        from ceph_tpu.parallel import dispatch as shard_dispatch

        return shard_dispatch.shard_mesh(int(np.prod(shape[:-2])))

    def _shard_mesh(self, data):
        """shard_mesh_for, guarded against trace-local values: a batch
        traced inside an outer jit (bench.py's serial chain) must stay on
        the in-trace kernel — a device_put of a tracer poisons the
        trace."""
        if _trace_local(data):
            return None
        return self.shard_mesh_for(data.shape)

    def __call__(self, data: jnp.ndarray, out=None) -> jnp.ndarray:
        mesh = self._shard_mesh(data)
        if mesh is not None:
            # sharded dispatch mode (ISSUE 6): place the batch with a
            # NamedSharding over `stripe` and run the fused kernel
            # per-device via shard_map — one launch, the whole mesh
            from ceph_tpu.parallel.sharded import sharded_coder_code

            lead = data.shape[:-2]
            if len(lead) > 1:
                # collapse lead dims into the stripe axis (CLAY batched
                # fragments); a host reshape of the contiguous batch is a
                # view.  Donation skipped: the pooled buffer has the
                # caller's lead geometry, not the flattened one.
                flat = data.reshape(-1, *data.shape[-2:])
                res = sharded_coder_code(self, flat, mesh)
                return res.reshape(*lead, *res.shape[-2:])
            return sharded_coder_code(self, data, mesh, out=out)
        if self.plan is not None and data.shape[-1] % 128 == 0:
            return self.plan(data)
        if int(np.prod(data.shape)) >= PACKED_MIN_BYTES:
            return self.packed(data, out=out)
        lead = data.shape[:-2]
        record_launch(
            int(np.prod(lead)) if lead else 1,
            int(np.prod(data.shape)),
            decode=self.decode,
        )
        return xor_matmul(self.bm, data)


class _GlobalPlanCache:
    """Process-wide encode/decode plan cache keyed by matrix content."""

    def __init__(self) -> None:
        from ceph_tpu.common.lockdep import make_lock

        self._lock = make_lock("plan_cache")
        self._encode: dict[bytes, jnp.ndarray] = {}
        self._encode_coders: dict[bytes, _DeviceCoder] = {}
        self._decode: OrderedDict[tuple[bytes, str], tuple[jnp.ndarray, list[int]]] = (
            OrderedDict()
        )
        self._decode_coders: OrderedDict[tuple, _DeviceCoder] = OrderedDict()
        # verify plans per parity matrix (ISSUE 9): one compiled
        # compare-only kernel per encode matrix, unbounded like the
        # encode tables (the matrix population is the same)
        self._verify_plans: dict[tuple, PackedVerifyPlan] = {}
        # coder lookup hit/miss totals; the perf-smoke tier-1 test asserts
        # a steady-state hit rate so a regression to per-call plan builds
        # fails fast instead of only dilating the bench number
        self._hits = 0
        self._misses = 0

    def _make_coder(
        self, gf_rows: np.ndarray, bm: jnp.ndarray, decode: bool = False
    ) -> _DeviceCoder:
        plan = CodingPlan(gf_rows, decode=decode) if _on_tpu() else None
        return _DeviceCoder(bm, plan, PackedPlan(gf_rows, decode=decode), decode=decode)

    def stats(self) -> dict[str, int]:
        """Coder-cache hit/miss totals (encode + decode lookups)."""
        with self._lock:
            return {"hits": self._hits, "misses": self._misses}

    def reset_stats(self) -> None:
        with self._lock:
            self._hits = 0
            self._misses = 0

    def _lru_put_coder(self, key, coder: _DeviceCoder) -> None:
        self._decode_coders[key] = coder
        self._decode_coders.move_to_end(key)
        while len(self._decode_coders) > DECODE_LRU_CAPACITY:
            self._decode_coders.popitem(last=False)

    def encode_bit_matrix(self, coding_rows: np.ndarray) -> jnp.ndarray:
        """Per-geometry encode matrices: one entry per codec instance's
        matrix, unbounded like the reference's per-(k,m) encode tables."""
        key = (coding_rows.shape, coding_rows.tobytes())
        with self._lock:
            bm = self._encode.get(key)
        if bm is not None:
            return bm
        bm = jnp.asarray(expand_matrix(coding_rows), dtype=jnp.uint8)
        if _trace_local(bm):
            return bm
        _hbm_track(bm, "scratch")
        with self._lock:
            self._encode.setdefault(key, bm)
            return self._encode[key]

    def encode_coder(self, coding_rows: np.ndarray) -> _DeviceCoder:
        """Cached coding operator for an encode matrix (TPU plan + jnp bm)."""
        key = (coding_rows.shape, coding_rows.tobytes())
        with self._lock:
            coder = self._encode_coders.get(key)
            if coder is not None:
                self._hits += 1
            else:
                self._misses += 1
        if coder is not None:
            return coder
        coder = self._make_coder(coding_rows, self.encode_bit_matrix(coding_rows))
        if _trace_local(coder.bm):
            return coder
        with self._lock:
            return self._encode_coders.setdefault(key, coder)

    def verify_coder(self, coding_rows: np.ndarray) -> PackedVerifyPlan:
        """Cached compare-only verify plan for an encode matrix's parity
        rows (ISSUE 9 deep-scrub kernel)."""
        key = (coding_rows.shape, coding_rows.tobytes())
        with self._lock:
            plan = self._verify_plans.get(key)
            if plan is not None:
                self._hits += 1
                return plan
            self._misses += 1
        plan = PackedVerifyPlan(coding_rows)
        with self._lock:
            return self._verify_plans.setdefault(key, plan)

    def lru_coder(self, matrix: np.ndarray) -> _DeviceCoder:
        """Coding operator for a decode-time matrix, bounded by the decode
        LRU (SHEC's searched inverses and other raw-matrix decode paths)."""
        key = (matrix.shape, matrix.tobytes(), "#raw")
        with self._lock:
            coder = self._decode_coders.get(key)
            if coder is not None:
                self._hits += 1
                self._decode_coders.move_to_end(key)
                return coder
            self._misses += 1
        coder = self._make_coder(matrix, self.lru_bit_matrix(matrix), decode=True)
        if _trace_local(coder.bm):
            return coder
        with self._lock:
            self._lru_put_coder(key, coder)
        return coder

    def lru_bit_matrix(self, matrix: np.ndarray) -> jnp.ndarray:
        """Bit-matrix for a decode-time matrix, bounded by the decode LRU.

        For codecs whose decode matrices vary per erasure pattern but don't
        go through decode_plan (SHEC's searched inverses) — stored alongside
        the signature-keyed plans so total decode-table memory stays within
        DECODE_LRU_CAPACITY, as the reference's cache guarantees.
        """
        key = (matrix.shape, matrix.tobytes(), "#raw")
        with self._lock:
            cached = self._decode.get(key)
            if cached is not None:
                self._decode.move_to_end(key)
                return cached[0]
        bm = jnp.asarray(expand_matrix(matrix), dtype=jnp.uint8)
        if _trace_local(bm):
            return bm
        _hbm_track(bm, "scratch")
        with self._lock:
            self._decode[key] = (bm, [])
            self._decode.move_to_end(key)
            while len(self._decode) > DECODE_LRU_CAPACITY:
                self._decode.popitem(last=False)
        return bm

    def gf2_decode_plan(
        self, bitmatrix: np.ndarray, k: int, w: int, erasures: list[int]
    ) -> tuple[np.ndarray, list[int]]:
        """Decode plan for a packetized GF(2) bit-matrix RAID-6 code
        (liberation family): (decode matrix (len(erasures)*w, k*w),
        decode_index).  Shares the one decode LRU so total decode-table
        memory stays within DECODE_LRU_CAPACITY."""
        from ceph_tpu.gf.gf2 import gf2_inv, gf2_matmul

        n = k + bitmatrix.shape[0] // w
        erased = set(erasures)
        decode_index = [c for c in range(n) if c not in erased][:k]
        if len(decode_index) < k:
            raise EcError(EIO, f"not enough survivors for erasures {erasures}")
        key = (bitmatrix.shape, bitmatrix.tobytes(), "#gf2", tuple(erasures))
        with self._lock:
            cached = self._decode.get(key)
            if cached is not None:
                self._decode.move_to_end(key)
                return cached
        # full generator: data identity rows then the coding rows (the
        # bitmatrix already carries both the P-identity and Q blocks)
        full = np.zeros((n * w, k * w), dtype=np.uint8)
        full[: k * w] = np.eye(k * w, dtype=np.uint8)
        full[k * w :] = bitmatrix
        survivors = np.vstack([full[c * w : (c + 1) * w] for c in decode_index])
        inv = gf2_inv(survivors)
        if inv is None:
            raise EcError(EIO, f"singular decode matrix for erasures {erasures}")
        erased_rows = np.vstack([full[c * w : (c + 1) * w] for c in erasures])
        plan = (gf2_matmul(erased_rows, inv), decode_index)
        with self._lock:
            self._decode[key] = plan
            self._decode.move_to_end(key)
            while len(self._decode) > DECODE_LRU_CAPACITY:
                self._decode.popitem(last=False)
        return plan

    def decode_plan(
        self, dist_matrix: np.ndarray, erasures: list[int], k: int
    ) -> tuple[jnp.ndarray, list[int]]:
        bitmat, decode_index, _ = self._decode_entry(dist_matrix, erasures, k)
        return bitmat, decode_index

    def _decode_entry(
        self, dist_matrix: np.ndarray, erasures: list[int], k: int, key=None
    ) -> tuple[jnp.ndarray, list[int], np.ndarray]:
        """(bit-matrix, decode_index, GF decode matrix) for an erasure
        pattern, LRU-cached.  The GF matrix rides along so a coder rebuild
        after a coder-LRU eviction is a cheap re-arrangement, not a second
        Gaussian inversion."""
        if key is None:
            key = self._decode_key(dist_matrix, erasures, k)
        with self._lock:
            cached = self._decode.get(key)
            if cached is not None:
                self._decode.move_to_end(key)
                return cached
        plan = isa_decode_matrix(dist_matrix, erasures, k)
        if plan is None:
            raise EcError(EIO, f"singular decode matrix for erasures {erasures}")
        c, decode_index = plan
        bitmat = jnp.asarray(expand_matrix(c), dtype=jnp.uint8)
        if _trace_local(bitmat):
            return bitmat, decode_index, c
        _hbm_track(bitmat, "scratch")
        with self._lock:
            self._decode[key] = (bitmat, decode_index, c)
            self._decode.move_to_end(key)
            while len(self._decode) > DECODE_LRU_CAPACITY:
                self._decode.popitem(last=False)
        return bitmat, decode_index, c

    def _decode_key(
        self, dist_matrix: np.ndarray, erasures: list[int], k: int
    ) -> tuple:
        """Reference signature format, ErasureCodeIsa.cc:233-248 (the
        survivor part uses the first-k-non-erased rows, matching decode_plan's
        key derivation even when isa_decode_matrix picks different rows)."""
        km = dist_matrix.shape[0]
        erased = set(erasures)
        survivors: list[int] = []
        r = 0
        for _ in range(k):
            while r in erased:
                r += 1
            if r >= km:
                raise EcError(EIO, f"not enough survivors for erasures {erasures}")
            survivors.append(r)
            r += 1
        sig = "".join(f"+{r}" for r in survivors) + "".join(
            f"-{e}" for e in erasures
        )
        return (dist_matrix.shape, dist_matrix.tobytes(), sig)

    def decode_coder(
        self, dist_matrix: np.ndarray, erasures: list[int], k: int
    ) -> tuple[_DeviceCoder, list[int]]:
        """Cached coding operator + survivor index for an erasure pattern."""
        key = self._decode_key(dist_matrix, erasures, k)
        bitmat, decode_index, c = self._decode_entry(dist_matrix, erasures, k, key)
        with self._lock:
            coder = self._decode_coders.get(key)
            if coder is not None:
                self._hits += 1
                self._decode_coders.move_to_end(key)
                return coder, decode_index
            self._misses += 1
        coder = self._make_coder(c, bitmat, decode=True)  # built outside the lock
        if _trace_local(coder.bm):
            return coder, decode_index
        with self._lock:
            self._lru_put_coder(key, coder)
        return coder, decode_index


PLAN_CACHE = _GlobalPlanCache()


def _coder_donatable(coder: _DeviceCoder, data_shape) -> bool:
    """Will a dispatch through `coder` at this (already >= packed-size)
    input shape actually consume a donated out= buffer?  Mirrors the
    _DeviceCoder dispatch exactly: the packed jnp kernel donates; the
    Pallas plan ignores `out`; a SHARDED launch donates only on the
    packed path with no remainder padding (a padded launch's output
    shape differs from the pooled logical-shape buffer)."""
    mesh = coder.shard_mesh_for(tuple(data_shape))
    if mesh is not None:
        if len(data_shape) != 3:
            return False  # flattened-lead launches skip donation
        if coder.plan is not None and data_shape[-1] % 128 == 0:
            return False
        from ceph_tpu.parallel.sharded import _stripe_shards

        return data_shape[0] % _stripe_shards(mesh) == 0
    return not (coder.plan is not None and data_shape[-1] % 128 == 0)


# The aggregation engine (ISSUE 20): AggTicket / DonationPool /
# _PadBuckets / _AggGroup / LaunchAggregator and the process-wide
# aggregator set moved to the service-agnostic offload runtime.  They
# are re-exported here verbatim — every existing import path
# (`from ceph_tpu.codec.matrix_codec import LaunchAggregator, ...`)
# keeps working, and the EC aggregators below are now plain service
# subclasses of the shared engine.
from ceph_tpu.ops.offload_runtime import (  # noqa: F401  (re-exports)
    _AGGREGATORS,
    AggTicket,
    DonationPool,
    LaunchAggregator,
    _AggGroup,
    _next_pow2,
    _PadBuckets,
    drain_all_aggregators,
    drop_donation_retention,
)



class EncodeAggregator(LaunchAggregator):
    """Cross-write launch aggregation: concurrent stripe encodes of one
    (matrix, chunk-size) geometry coalesce into one padded device launch
    (knobs `ec_tpu_aggregate_window` / `ec_tpu_aggregate_max_bytes`)."""

    PERF_NAME = "ec_aggregator"
    WHAT = "encode"

    def submit(self, ec: "MatrixCodecMixin", shaped: np.ndarray) -> AggTicket:
        """Queue one (stripes, k, L) uint8 encode; returns its ticket."""
        return self._submit(
            (ec.distribution_matrix().tobytes(), shaped.shape[-1]), ec, None, shaped
        )

    def _dispatch(self, g: _AggGroup, data: np.ndarray, donate):
        return g.ec.encode_array(data, out=donate)

    def _dispatch_host(self, g: _AggGroup, data: np.ndarray) -> np.ndarray:
        return g.ec.encode_array_host(data)

    def _out_shape(self, g: _AggGroup, data_shape) -> tuple:
        return (
            data_shape[0],
            g.ec.get_chunk_count() - data_shape[1],
            data_shape[2],
        )

    def _donate_ok(self, g: _AggGroup, data_shape) -> bool:
        check = getattr(g.ec, "encode_donatable", None)
        return bool(check(data_shape)) if check is not None else False


class DecodeAggregator(LaunchAggregator):
    """Cross-op DECODE launch aggregation — the recovery/degraded-read
    twin of EncodeAggregator (knobs `ec_tpu_decode_aggregate_window` /
    `ec_tpu_decode_aggregate_max_bytes`).

    Submissions are (stripes, k, L) survivor batches in decode_index
    order, keyed by the cached decode-plan signature + chunk length: the
    common case during recovery/backfill is ONE erasure pattern repeating
    across every object in the PG, so per-object decodes coalesce into
    one padded launch exactly like concurrent writes do on the encode
    side.  Tickets resolve to (stripes, len(erasures), L) reconstructed
    chunks, rows in erasure order; a failed launch is sticky on its group
    and reported at every co-rider's reap."""

    PERF_NAME = "ec_decode_aggregator"
    WHAT = "decode"
    SCHED_CLASS = "recovery"

    def submit(
        self, ec: "MatrixCodecMixin", erasures: list[int], survivors: np.ndarray
    ) -> AggTicket:
        """Queue one (stripes, k, L) uint8 survivor batch (decode_index
        order); returns its ticket.  Co-riders share a group only when
        their decode-plan signature matches, so every ticket in a group
        agrees on the erasure row order."""
        erasures = list(erasures)
        key = PLAN_CACHE._decode_key(
            ec.distribution_matrix(), erasures, ec.k
        ) + (survivors.shape[-1],)
        return self._submit(key, ec, tuple(erasures), survivors)

    def _dispatch(self, g: _AggGroup, data: np.ndarray, donate):
        return g.ec.decode_array(list(g.ctx), data, out=donate)

    def _dispatch_host(self, g: _AggGroup, data: np.ndarray) -> np.ndarray:
        return g.ec.decode_array_host(list(g.ctx), data)

    def _out_shape(self, g: _AggGroup, data_shape) -> tuple:
        return (data_shape[0], len(g.ctx), data_shape[2])

    def _donate_ok(self, g: _AggGroup, data_shape) -> bool:
        check = getattr(g.ec, "decode_donatable", None)
        return bool(check(list(g.ctx), data_shape)) if check is not None else False


class VerifyAggregator(LaunchAggregator):
    """Cross-object VERIFY launch aggregation (ISSUE 9): deep-scrub
    parity recomputes from one (matrix, chunk-length) geometry coalesce
    into one compare-only device launch (knobs
    `ec_tpu_verify_aggregate_window` / `ec_tpu_verify_aggregate_max_bytes`).

    Submissions are (stripes, k+m, L) full-codeword batches — data rows
    in encode order followed by the stored parity rows — and tickets
    resolve to a (stripes,) uint8 per-stripe mismatch bitmap (bit j set
    = parity row j inconsistent).  Padding stripes are all-zero
    codewords, whose recomputed parity is zero = their stored parity,
    so a padded launch's bitmap is exact.  Launches dispatch under the
    `background` QoS lane: a scrub chunk's verify never preempts a
    queued client encode, and the host-oracle fallback keeps scrub
    byte-identical while the backend is DEGRADED."""

    PERF_NAME = "ec_verify_aggregator"
    WHAT = "verify"
    SCHED_CLASS = "background"
    MEM_POOL = "verify"

    def submit(self, ec: "MatrixCodecMixin", codewords: np.ndarray) -> AggTicket:
        """Queue one (stripes, k+m, L) uint8 codeword batch; the ticket
        resolves to its (stripes,) mismatch bitmap."""
        return self._submit(
            (ec.distribution_matrix().tobytes(), "#verify",
             codewords.shape[-1]),
            ec, None, codewords,
        )

    def _dispatch(self, g: _AggGroup, data: np.ndarray, donate):
        return g.ec.verify_array(data)

    def _dispatch_host(self, g: _AggGroup, data: np.ndarray) -> np.ndarray:
        return g.ec.verify_array_host(data)

    def _out_shape(self, g: _AggGroup, data_shape) -> tuple:
        return (data_shape[0],)

    def _donate_ok(self, g: _AggGroup, data_shape) -> bool:
        return False  # the bitmap output is tiny; pooling buys nothing




_DEFAULT_AGGREGATOR: EncodeAggregator | None = None


def default_encode_aggregator() -> EncodeAggregator:
    """Process-wide aggregator shared by every ECBackend that isn't handed
    its own — the sharing is what coalesces encodes ACROSS PGs.  Built
    from the option-table defaults (common/options.py); daemons with a
    live Config can construct and inject their own."""
    global _DEFAULT_AGGREGATOR
    if _DEFAULT_AGGREGATOR is None:
        from ceph_tpu.common.options import OPTIONS

        _DEFAULT_AGGREGATOR = EncodeAggregator(
            window=int(OPTIONS["ec_tpu_aggregate_window"].default),
            max_bytes=int(OPTIONS["ec_tpu_aggregate_max_bytes"].default),
        )
    return _DEFAULT_AGGREGATOR


_DEFAULT_DECODE_AGGREGATOR: DecodeAggregator | None = None


def default_decode_aggregator() -> DecodeAggregator:
    """Process-wide decode aggregator shared by every ECBackend that isn't
    handed its own, so recovery/degraded-read decodes coalesce ACROSS PGs
    on one OSD (the backfill case: one erasure pattern, many objects)."""
    global _DEFAULT_DECODE_AGGREGATOR
    if _DEFAULT_DECODE_AGGREGATOR is None:
        from ceph_tpu.common.options import OPTIONS

        _DEFAULT_DECODE_AGGREGATOR = DecodeAggregator(
            window=int(OPTIONS["ec_tpu_decode_aggregate_window"].default),
            max_bytes=int(OPTIONS["ec_tpu_decode_aggregate_max_bytes"].default),
        )
    return _DEFAULT_DECODE_AGGREGATOR


_DEFAULT_VERIFY_AGGREGATOR: VerifyAggregator | None = None


def default_verify_aggregator() -> VerifyAggregator:
    """Process-wide verify aggregator shared by every scrubber on one
    OSD, so concurrent deep scrubs of different PGs coalesce their
    parity recomputes into shared compare-only launches.  The default
    window is open (unlike encode/decode): scrub is a throughput
    workload with no commit barrier, so batching is pure win — the
    scrubber's per-chunk reap is the flush."""
    global _DEFAULT_VERIFY_AGGREGATOR
    if _DEFAULT_VERIFY_AGGREGATOR is None:
        from ceph_tpu.common.options import OPTIONS

        _DEFAULT_VERIFY_AGGREGATOR = VerifyAggregator(
            window=int(OPTIONS["ec_tpu_verify_aggregate_window"].default),
            max_bytes=int(OPTIONS["ec_tpu_verify_aggregate_max_bytes"].default),
        )
    return _DEFAULT_VERIFY_AGGREGATOR


# The EC trio are the offload runtime's first three service entries
# (ISSUE 20): same singletons, same knobs, same perf names — the
# registry only ADDS a uniform by-name surface (service_aggregator /
# offload_perf_dump) on top of the existing factories.
from ceph_tpu.ops.offload_runtime import register_service as _register_service

_register_service(
    "encode", default_encode_aggregator, lane="client",
    oracle="MatrixCodecMixin.encode_array_host",
    doc="EC stripe encode (parity generation)",
)
_register_service(
    "decode", default_decode_aggregator, lane="recovery",
    oracle="MatrixCodecMixin.decode_array_host",
    doc="EC reconstruct decode (recovery / degraded reads)",
)
_register_service(
    "verify", default_verify_aggregator, lane="background",
    oracle="MatrixCodecMixin.verify_array_host",
    doc="EC deep-scrub compare-only verify",
)


class EncodePipeline:
    """Asynchronous chunk-encode hand-off — the completion queue behind
    the synchronous `encode_chunks` interface (SURVEY §7's hard part).

    `submit` stages the host->device transfer and LAUNCHES the encode
    immediately (JAX dispatch is asynchronous: the call returns while the
    device works), so consecutive submissions overlap compute with the
    host-side gather of the next batch — the double-buffering the
    reference gets from queued librados AIO in front of `ec_encode_data`.
    Completions copy parity back into the caller's chunk buffers exactly
    like `encode_chunks`; `poll()` reaps only finished launches
    (non-blocking), `flush()` drains everything.  `depth` bounds
    device-side in-flight work the way an AIO queue depth does.
    """

    def __init__(self, codec: "MatrixCodecMixin", depth: int = 4):
        self.codec = codec
        self.depth = max(1, depth)
        self._tickets = 0
        # in-flight: (ticket, caller chunk dict, device parity array)
        self._inflight: list[tuple[int, Mapping[int, np.ndarray], object]] = []
        # tickets completed inside submit's backpressure path: the next
        # poll()/flush() reports them — a completed ticket is NEVER lost
        self._reaped: list[int] = []

    def submit(self, chunks: Mapping[int, np.ndarray]) -> int:
        """Launch one stripe's encode; returns its ticket.  Blocks only
        when `depth` launches are already in flight (backpressure)."""
        parity_dev = self.codec.encode_array(self.codec._gather(chunks))
        self._tickets += 1
        self._inflight.append((self._tickets, chunks, parity_dev))
        while len(self._inflight) > self.depth:
            self._reaped += self._complete(*self._inflight.pop(0))
        return self._tickets

    def _complete(self, ticket: int, chunks, parity_dev) -> list[int]:
        parity = np.asarray(parity_dev)  # blocks until the launch finishes
        self.codec._scatter(chunks, parity)
        return [ticket]

    def poll(self) -> list[int]:
        """Reap FINISHED launches without blocking (completion queue)."""
        done, self._reaped = self._reaped, []
        while self._inflight:
            ticket, chunks, dev = self._inflight[0]
            ready = getattr(dev, "is_ready", None)
            # unknown readiness means NOT ready: popping would block in
            # _complete and silently defeat the non-blocking contract
            if ready is None or not ready():
                break  # still computing; keep submission order
            self._inflight.pop(0)
            done += self._complete(ticket, chunks, dev)
        return done

    def flush(self) -> list[int]:
        """Drain every in-flight encode (the barrier before a commit)."""
        done, self._reaped = self._reaped, []
        while self._inflight:
            done += self._complete(*self._inflight.pop(0))
        return done


class MatrixCodecMixin:
    """Chunk-level + device-level coding for matrix-defined codecs.

    Host contract: the concrete class provides `self.k`, `self.m`,
    `chunk_index()` (from ErasureCode) and `build_matrix() -> (k+m, k)`
    systematic uint8 distribution matrix.
    """

    _dist_matrix: np.ndarray | None = None

    def build_matrix(self) -> np.ndarray:  # pragma: no cover - abstract
        raise NotImplementedError

    def invalidate_matrix(self) -> None:
        """Drop the cached distribution matrix; call on (re)parse so a
        second init() with new geometry cannot serve the stale matrix."""
        self._dist_matrix = None

    def distribution_matrix(self) -> np.ndarray:
        if self._dist_matrix is None:
            mat = np.asarray(self.build_matrix(), dtype=np.uint8)
            k, m = self.k, self.m
            assert mat.shape == (k + m, k), mat.shape
            assert np.array_equal(mat[:k], np.eye(k, dtype=np.uint8)), (
                "distribution matrix must be systematic"
            )
            self._dist_matrix = mat
        return self._dist_matrix

    def _xor_row_available(self) -> bool:
        """True when parity row 0 is all ones (enables XOR fast paths)."""
        mat = self.distribution_matrix()
        return bool((mat[self.k] == 1).all())

    # -- device-native bulk paths ------------------------------------------

    def encode_array(self, data, out=None) -> jnp.ndarray:
        """(..., k, L) uint8 -> (..., m, L) parity, stays on device.

        Dispatches through the cached _DeviceCoder, so on a TPU backend this
        IS the fused Pallas kernel — the production analog of the reference
        plugin's `ec_encode_data` hot call (isa/ErasureCodeIsa.cc:83-91).

        `out`: optional dead device buffer of the parity's shape, donated
        into the packed kernel so recurring aggregated launches reuse the
        allocation (ignored on paths that cannot donate)."""
        mat = self.distribution_matrix()
        if self.m == 1 and self._xor_row_available():
            arr = jnp.asarray(data)
            lead = arr.shape[:-2]
            record_launch(int(np.prod(lead)) if lead else 1, int(np.prod(arr.shape)))
            return xor_reduce(arr)[..., None, :]
        # host batches pass through un-placed: the coder's sharded mode
        # does ONE sharded device_put (a premature jnp.asarray would
        # commit to device 0 and pay a second reshard copy)
        arr = data if isinstance(data, np.ndarray) else jnp.asarray(data)
        return PLAN_CACHE.encode_coder(mat[self.k :])(arr, out=out)

    def encode_donatable(self, data_shape) -> bool:
        """True when encode_array(data, out=...) at this input shape will
        actually consume a donated parity buffer — i.e. the dispatch lands
        on the packed jnp kernel.  The EncodeAggregator gates its donation
        pool on this so it never hoards dead device memory for paths
        (xor_reduce, Pallas, small-matmul) that ignore `out`."""
        mat = self.distribution_matrix()
        if self.m == 1 and self._xor_row_available():
            return False
        if int(np.prod(data_shape)) < PACKED_MIN_BYTES:
            return False
        coder = PLAN_CACHE.encode_coder(mat[self.k :])
        return _coder_donatable(coder, data_shape)

    def decode_array(self, erasures: list[int], survivors, out=None) -> jnp.ndarray:
        """survivors (..., k, L) in decode_index order -> (..., nerrs, L).

        The decode twin of encode_array: dispatches through the cached
        erasure-pattern _DeviceCoder (Pallas on TPU-aligned chunks, packed
        planes for bulk work, bitsliced matmul for small one-off
        patterns).  `out`: optional dead device buffer of the
        reconstruction's shape, donated into the packed kernel so
        recurring aggregated recovery launches reuse the allocation."""
        coder, _ = PLAN_CACHE.decode_coder(self.distribution_matrix(), erasures, self.k)
        arr = survivors if isinstance(survivors, np.ndarray) else jnp.asarray(survivors)
        return coder(arr, out=out)

    def decode_donatable(self, erasures: list[int], data_shape) -> bool:
        """True when decode_array(erasures, data, out=...) at this input
        shape will actually consume a donated output buffer — the decode
        twin of encode_donatable, gating the DecodeAggregator's pool."""
        if int(np.prod(data_shape)) < PACKED_MIN_BYTES:
            return False
        coder, _ = PLAN_CACHE.decode_coder(
            self.distribution_matrix(), list(erasures), self.k
        )
        return _coder_donatable(coder, data_shape)

    def verify_array(self, codewords) -> jnp.ndarray:
        """(..., k+m, L) uint8 full codewords (data rows in encode order,
        then the stored parity rows) -> (...,) uint8 per-stripe mismatch
        bitmap, bit j set iff stored parity row j differs from the
        recompute.  The deep-scrub compare-only path (ISSUE 9): one
        fused kernel per matrix, batch-shaped exactly like encode_array
        so scrub rides the same aggregation machinery."""
        mat = self.distribution_matrix()
        return PLAN_CACHE.verify_coder(mat[self.k :])(jnp.asarray(codewords))

    def verify_array_host(self, codewords) -> np.ndarray:
        """Byte-identical HOST oracle of verify_array (pure numpy end to
        end): the DEGRADED-mode fallback the VerifyAggregator re-runs
        scrub verifies on — same bit-matrix parity recompute, same
        bitmap packing, and it can never hang on a wedged runtime."""
        mat = self.distribution_matrix()
        return packed_verify_host(
            mat[self.k :], np.asarray(codewords, dtype=np.uint8)
        )

    def encode_array_host(self, data) -> np.ndarray:
        """Byte-identical HOST oracle of encode_array: pure numpy end to
        end, so a wedged device runtime can never hang it.  This is the
        DEGRADED-mode fallback the launch watchdog (ops/guard.py) re-runs
        aggregated encodes on — same xor fast path gate, and since
        ISSUE 11 the SAME reduced plane program the device kernel
        compiles (ops/packed_gf.packed_code_host), so the oracle is
        derived from the schedule rather than re-derived from the
        matrix — the two paths cannot drift, and the fallback runs the
        reduced XOR count too (plus an 8x smaller working set than the
        bit-plane expansion)."""
        mat = self.distribution_matrix()
        arr = np.asarray(data, dtype=np.uint8)
        if self.m == 1 and self._xor_row_available():
            return np.bitwise_xor.reduce(arr, axis=-2)[..., None, :]
        from ceph_tpu.ops.packed_gf import packed_code_host

        return packed_code_host(mat[self.k :], arr)

    def encode_delta_device(
        self, old_bufs, new_bufs, parity_bufs, chunk: int
    ) -> jnp.ndarray:
        """RMW parity delta, fully on device (ISSUE 18): k + k + m FLAT
        per-shard device buffers (the chunk cache's native layout) ->
        (stripes, m, chunk) NEW parity in ONE fused launch.  The code is
        GF(2)-linear, so parity_new = parity_old ^ Encode(old ^ new)
        with Encode the SAME reduced plane program `encode_array`'s
        packed path compiles — the delta path cannot drift byte-wise
        from a full re-encode.  Counts exactly one dispatch on the
        launch gauges (`devices_per_launch` stays consistent)."""
        from ceph_tpu.ops.packed_gf import _packed_delta_flat, best_program

        mat = self.distribution_matrix()
        prog = best_program(mat[self.k :])
        stripes = int(old_bufs[0].size) // int(chunk)
        nbytes = sum(
            int(b.size)
            for bufs in (old_bufs, new_bufs, parity_bufs)
            for b in bufs
        )
        record_launch(stripes, nbytes)
        return _packed_delta_flat(
            tuple(old_bufs), tuple(new_bufs), tuple(parity_bufs),
            sched=prog, k=self.k, m=self.m, chunk=int(chunk),
        )

    def encode_delta_host(
        self, old_data, new_data, old_parity
    ) -> np.ndarray:
        """Byte-identical HOST oracle of encode_delta_device (pure
        numpy): same chosen program via packed_delta_host, same xor
        composition — the anchor the delta-path byte-identity tests pin
        the device bytes against.  (S, k, L) old/new data + (S, m, L)
        old parity -> (S, m, L) new parity."""
        from ceph_tpu.ops.packed_gf import packed_delta_host

        mat = self.distribution_matrix()
        return packed_delta_host(
            mat[self.k :], old_data, new_data, old_parity
        )

    def decode_array_host(self, erasures: list[int], survivors) -> np.ndarray:
        """Byte-identical HOST oracle of decode_array (pure numpy): the
        decode plan is built with the same isa_decode_matrix Gaussian
        the cached coder was built from, so reconstruction through the
        fallback path matches the device result bit for bit.  Plans are
        memoized host-side (never through the jnp-backed PLAN_CACHE —
        a wedged runtime can hang any jnp call): degraded-mode recovery
        repeats ONE erasure pattern across many launches and must not
        pay the O(k^3) inversion each time."""
        from ceph_tpu.gf.bitslice import xor_matmul_host_batch

        dist = self.distribution_matrix()
        key = (dist.shape, dist.tobytes(), tuple(erasures))
        with _HOST_DECODE_LOCK:
            bm = _HOST_DECODE_PLANS.get(key)
            if bm is not None:
                _HOST_DECODE_PLANS.move_to_end(key)
        if bm is None:
            plan = isa_decode_matrix(dist, list(erasures), self.k)
            if plan is None:
                raise EcError(
                    EIO, f"singular decode matrix for erasures {erasures}"
                )
            c, _idx = plan
            bm = expand_matrix(c)
            with _HOST_DECODE_LOCK:
                _HOST_DECODE_PLANS[key] = bm
                _HOST_DECODE_PLANS.move_to_end(key)
                while len(_HOST_DECODE_PLANS) > _HOST_DECODE_CAPACITY:
                    _HOST_DECODE_PLANS.popitem(last=False)
        return xor_matmul_host_batch(bm, np.asarray(survivors, dtype=np.uint8))

    def decode_index(self, erasures: list[int]) -> list[int]:
        _, idx = PLAN_CACHE.decode_plan(self.distribution_matrix(), erasures, self.k)
        return idx

    # -- chunk-level interface ---------------------------------------------

    @staticmethod
    def _as_u8(buf) -> np.ndarray:
        """Normalize one chunk buffer to uint8 WITHOUT copying when
        avoidable: contiguous uint8 arrays (every ECBackend call site)
        pass through untouched, raw byte containers map zero-copy via
        frombuffer, and everything else goes through np.asarray — views
        stay views, so np.stack in the caller pays the gather's only
        copy (ascontiguousarray here would copy a second time)."""
        if type(buf) is np.ndarray and buf.dtype == np.uint8:
            return buf
        if isinstance(buf, (bytes, bytearray, memoryview)):
            return np.frombuffer(buf, dtype=np.uint8)
        return np.asarray(buf, dtype=np.uint8)

    def _gather(self, chunks: Mapping[int, np.ndarray]) -> np.ndarray:
        """Stack the k data chunks in encode order (shared by the sync
        interface and the EncodePipeline so the paths cannot drift)."""
        return np.stack(
            [self._as_u8(chunks[self.chunk_index(i)]) for i in range(self.k)]
        )

    def _scatter(self, chunks: Mapping[int, np.ndarray], parity: np.ndarray) -> None:
        for i in range(self.m):
            np.copyto(chunks[self.chunk_index(self.k + i)], parity[i])

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        self._scatter(chunks, np.asarray(self.encode_array(self._gather(chunks))))

    def _use_xor_decode(self, erasures: list[int]) -> bool:
        """Single-erasure XOR path: first k+1 chunks + all-ones parity row 0
        (generalizes ErasureCodeIsa.cc:196-216)."""
        return (
            len(erasures) == 1
            and erasures[0] < self.k + 1
            and self._xor_row_available()
        )

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m = self.k, self.m
        raw_of = self.chunk_index
        erasures = [i for i in range(k + m) if raw_of(i) not in chunks]
        if not erasures:
            return
        if len(erasures) > m:
            raise EcError(EIO, f"{len(erasures)} erasures > m={m}")
        if self._use_xor_decode(erasures):
            sources = [i for i in range(k + m) if raw_of(i) in chunks][:k]
            stack = np.stack([self._as_u8(decoded[raw_of(i)]) for i in sources])
            np.copyto(decoded[raw_of(erasures[0])], np.asarray(xor_reduce(stack)))
            return
        idx = self.decode_index(erasures)
        survivors = np.stack([self._as_u8(decoded[raw_of(i)]) for i in idx])
        rec = np.asarray(self.decode_array(erasures, survivors))
        for p, e in enumerate(erasures):
            np.copyto(decoded[raw_of(e)], rec[p])
