"""Generic GF(2^8) matrix-codec machinery shared by every matrix technique.

Any systematic code defined by a (k+m, k) distribution matrix (RS, Cauchy,
jerasure variants, SHEC, LRC layers) gets its chunk-level and device-level
paths from this mixin; concrete codecs supply geometry + `build_matrix()`.

Caching mirrors the reference's two-level table cache
(/root/reference/src/erasure-code/isa/ErasureCodeIsaTableCache.{h,cc}):
encode plans per matrix, decode plans in a signature-keyed LRU (capacity 2516,
"sufficient up to (12,4)", ErasureCodeIsaTableCache.h:48) — but a cached
"table" here is a device bit-matrix operand for the shared XOR-matmul kernel,
so any erasure pattern reuses one compiled kernel per shape.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Mapping

import jax.numpy as jnp
import numpy as np

from ceph_tpu.gf import expand_matrix, isa_decode_matrix
from ceph_tpu.ops.xor_mm import xor_matmul, xor_reduce

from .base import EIO
from .interface import EcError

DECODE_LRU_CAPACITY = 2516


class _GlobalPlanCache:
    """Process-wide encode/decode plan cache keyed by matrix content."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._encode: dict[bytes, jnp.ndarray] = {}
        self._decode: OrderedDict[tuple[bytes, str], tuple[jnp.ndarray, list[int]]] = (
            OrderedDict()
        )

    def encode_bit_matrix(self, coding_rows: np.ndarray) -> jnp.ndarray:
        """Per-geometry encode matrices: one entry per codec instance's
        matrix, unbounded like the reference's per-(k,m) encode tables."""
        key = (coding_rows.shape, coding_rows.tobytes())
        with self._lock:
            bm = self._encode.get(key)
        if bm is not None:
            return bm
        bm = jnp.asarray(expand_matrix(coding_rows), dtype=jnp.uint8)
        with self._lock:
            self._encode.setdefault(key, bm)
            return self._encode[key]

    def lru_bit_matrix(self, matrix: np.ndarray) -> jnp.ndarray:
        """Bit-matrix for a decode-time matrix, bounded by the decode LRU.

        For codecs whose decode matrices vary per erasure pattern but don't
        go through decode_plan (SHEC's searched inverses) — stored alongside
        the signature-keyed plans so total decode-table memory stays within
        DECODE_LRU_CAPACITY, as the reference's cache guarantees.
        """
        key = (matrix.shape, matrix.tobytes(), "#raw")
        with self._lock:
            cached = self._decode.get(key)
            if cached is not None:
                self._decode.move_to_end(key)
                return cached[0]
        bm = jnp.asarray(expand_matrix(matrix), dtype=jnp.uint8)
        with self._lock:
            self._decode[key] = (bm, [])
            self._decode.move_to_end(key)
            while len(self._decode) > DECODE_LRU_CAPACITY:
                self._decode.popitem(last=False)
        return bm

    def gf2_decode_plan(
        self, bitmatrix: np.ndarray, k: int, w: int, erasures: list[int]
    ) -> tuple[np.ndarray, list[int]]:
        """Decode plan for a packetized GF(2) bit-matrix RAID-6 code
        (liberation family): (decode matrix (len(erasures)*w, k*w),
        decode_index).  Shares the one decode LRU so total decode-table
        memory stays within DECODE_LRU_CAPACITY."""
        from ceph_tpu.gf.gf2 import gf2_inv, gf2_matmul

        n = k + bitmatrix.shape[0] // w
        erased = set(erasures)
        decode_index = [c for c in range(n) if c not in erased][:k]
        if len(decode_index) < k:
            raise EcError(EIO, f"not enough survivors for erasures {erasures}")
        key = (bitmatrix.shape, bitmatrix.tobytes(), "#gf2", tuple(erasures))
        with self._lock:
            cached = self._decode.get(key)
            if cached is not None:
                self._decode.move_to_end(key)
                return cached
        # full generator: data identity rows then the coding rows (the
        # bitmatrix already carries both the P-identity and Q blocks)
        full = np.zeros((n * w, k * w), dtype=np.uint8)
        full[: k * w] = np.eye(k * w, dtype=np.uint8)
        full[k * w :] = bitmatrix
        survivors = np.vstack([full[c * w : (c + 1) * w] for c in decode_index])
        inv = gf2_inv(survivors)
        if inv is None:
            raise EcError(EIO, f"singular decode matrix for erasures {erasures}")
        erased_rows = np.vstack([full[c * w : (c + 1) * w] for c in erasures])
        plan = (gf2_matmul(erased_rows, inv), decode_index)
        with self._lock:
            self._decode[key] = plan
            self._decode.move_to_end(key)
            while len(self._decode) > DECODE_LRU_CAPACITY:
                self._decode.popitem(last=False)
        return plan

    def decode_plan(
        self, dist_matrix: np.ndarray, erasures: list[int], k: int
    ) -> tuple[jnp.ndarray, list[int]]:
        km = dist_matrix.shape[0]
        erased = set(erasures)
        decode_index: list[int] = []
        r = 0
        for _ in range(k):
            while r in erased:
                r += 1
            if r >= km:
                raise EcError(EIO, f"not enough survivors for erasures {erasures}")
            decode_index.append(r)
            r += 1
        # Reference signature format, ErasureCodeIsa.cc:233-248.
        sig = "".join(f"+{r}" for r in decode_index) + "".join(
            f"-{e}" for e in erasures
        )
        key = (dist_matrix.shape, dist_matrix.tobytes(), sig)
        with self._lock:
            cached = self._decode.get(key)
            if cached is not None:
                self._decode.move_to_end(key)
                return cached
        plan = isa_decode_matrix(dist_matrix, erasures, k)
        if plan is None:
            raise EcError(EIO, f"singular decode matrix for erasures {erasures}")
        c, decode_index = plan
        bitmat = jnp.asarray(expand_matrix(c), dtype=jnp.uint8)
        with self._lock:
            self._decode[key] = (bitmat, decode_index)
            self._decode.move_to_end(key)
            while len(self._decode) > DECODE_LRU_CAPACITY:
                self._decode.popitem(last=False)
        return bitmat, decode_index


PLAN_CACHE = _GlobalPlanCache()


class MatrixCodecMixin:
    """Chunk-level + device-level coding for matrix-defined codecs.

    Host contract: the concrete class provides `self.k`, `self.m`,
    `chunk_index()` (from ErasureCode) and `build_matrix() -> (k+m, k)`
    systematic uint8 distribution matrix.
    """

    _dist_matrix: np.ndarray | None = None

    def build_matrix(self) -> np.ndarray:  # pragma: no cover - abstract
        raise NotImplementedError

    def invalidate_matrix(self) -> None:
        """Drop the cached distribution matrix; call on (re)parse so a
        second init() with new geometry cannot serve the stale matrix."""
        self._dist_matrix = None

    def distribution_matrix(self) -> np.ndarray:
        if self._dist_matrix is None:
            mat = np.asarray(self.build_matrix(), dtype=np.uint8)
            k, m = self.k, self.m
            assert mat.shape == (k + m, k), mat.shape
            assert np.array_equal(mat[:k], np.eye(k, dtype=np.uint8)), (
                "distribution matrix must be systematic"
            )
            self._dist_matrix = mat
        return self._dist_matrix

    def _xor_row_available(self) -> bool:
        """True when parity row 0 is all ones (enables XOR fast paths)."""
        mat = self.distribution_matrix()
        return bool((mat[self.k] == 1).all())

    # -- device-native bulk paths ------------------------------------------

    def encode_array(self, data) -> jnp.ndarray:
        """(..., k, L) uint8 -> (..., m, L) parity, stays on device."""
        mat = self.distribution_matrix()
        if self.m == 1 and self._xor_row_available():
            return xor_reduce(jnp.asarray(data))[..., None, :]
        bm = PLAN_CACHE.encode_bit_matrix(mat[self.k :])
        return xor_matmul(bm, jnp.asarray(data))

    def decode_array(self, erasures: list[int], survivors) -> jnp.ndarray:
        """survivors (..., k, L) in decode_index order -> (..., nerrs, L)."""
        bm, _ = PLAN_CACHE.decode_plan(self.distribution_matrix(), erasures, self.k)
        return xor_matmul(bm, jnp.asarray(survivors))

    def decode_index(self, erasures: list[int]) -> list[int]:
        _, idx = PLAN_CACHE.decode_plan(self.distribution_matrix(), erasures, self.k)
        return idx

    # -- chunk-level interface ---------------------------------------------

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        data = np.stack(
            [np.asarray(chunks[self.chunk_index(i)], dtype=np.uint8) for i in range(k)]
        )
        parity = np.asarray(self.encode_array(data))
        for i in range(m):
            np.copyto(chunks[self.chunk_index(k + i)], parity[i])

    def _use_xor_decode(self, erasures: list[int]) -> bool:
        """Single-erasure XOR path: first k+1 chunks + all-ones parity row 0
        (generalizes ErasureCodeIsa.cc:196-216)."""
        return (
            len(erasures) == 1
            and erasures[0] < self.k + 1
            and self._xor_row_available()
        )

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m = self.k, self.m
        raw_of = self.chunk_index
        erasures = [i for i in range(k + m) if raw_of(i) not in chunks]
        if not erasures:
            return
        if len(erasures) > m:
            raise EcError(EIO, f"{len(erasures)} erasures > m={m}")
        if self._use_xor_decode(erasures):
            sources = [i for i in range(k + m) if raw_of(i) in chunks][:k]
            stack = np.stack(
                [np.asarray(decoded[raw_of(i)], dtype=np.uint8) for i in sources]
            )
            np.copyto(decoded[raw_of(erasures[0])], np.asarray(xor_reduce(stack)))
            return
        idx = self.decode_index(erasures)
        survivors = np.stack(
            [np.asarray(decoded[raw_of(i)], dtype=np.uint8) for i in idx]
        )
        rec = np.asarray(self.decode_array(erasures, survivors))
        for p, e in enumerate(erasures):
            np.copyto(decoded[raw_of(e)], rec[p])
