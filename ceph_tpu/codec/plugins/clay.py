"""The `clay` plugin — coupled-layer MSR regenerating codes.

Plugin shell analog of /root/reference/src/erasure-code/clay/
ErasureCodePluginClay.cc.
"""

from ceph_tpu.codec.clay import ErasureCodeClay
from ceph_tpu.codec.registry import EC_VERSION, ErasureCodePlugin

__erasure_code_version__ = EC_VERSION


def _factory(profile):
    ec = ErasureCodeClay()
    ec.init(profile)
    return ec


def __erasure_code_init__(registry):
    registry.add("clay", ErasureCodePlugin("clay", _factory))
