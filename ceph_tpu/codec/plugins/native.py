"""The `native` plugin — isa-style RS coding in C++ (libec_native.so).

Plugin shell analog of /root/reference/src/erasure-code/isa/
ErasureCodePluginIsa.cc: technique selection reed_sol_van|cauchy
(:40-57), the compute engine dlopen-loaded with the reference's
entry-point contract through registry.load_dynamic.
"""

import pathlib

from ceph_tpu.codec.registry import EC_VERSION, ErasureCodePlugin, load_dynamic

__erasure_code_version__ = EC_VERSION

# libec_native.so lives in the repo's native/ build directory (the
# erasure_code_dir role, global.yaml.in:431).
_NATIVE_DIR = str(pathlib.Path(__file__).resolve().parents[3] / "native")

_lib = None


def _get_lib():
    global _lib
    if _lib is None:
        import subprocess

        try:  # build on demand like utils/native.py
            subprocess.run(
                ["make", "-s", "libec_native.so"],
                cwd=_NATIVE_DIR, check=False, capture_output=True, timeout=120,
            )
        except (subprocess.SubprocessError, FileNotFoundError):
            pass
        _lib = load_dynamic("native", _NATIVE_DIR)
    return _lib


def _factory(profile):
    from ceph_tpu.codec.native_codec import ErasureCodeNative
    from ceph_tpu.codec.tracing import instrument_codec

    technique = profile.get("technique") or "reed_sol_van"
    ec = ErasureCodeNative(_get_lib(), technique=technique)
    ec.init(profile)
    # chunk-path calls (the C kernel) get a single `kernel` span; the
    # inherited device paths get h2d/kernel_launch like the tpu plugin
    return instrument_codec(ec, "native")


def __erasure_code_init__(registry):
    registry.add("native", ErasureCodePlugin("native", _factory))
