"""Trivial XOR example plugin (k data + 1 parity).

Mirror of the reference's example codec used by registry tests
(/root/reference/src/test/erasure-code/ErasureCodeExample.h).
"""

import numpy as np

from ceph_tpu.codec.base import ErasureCode
from ceph_tpu.codec.interface import Profile
from ceph_tpu.codec.registry import EC_VERSION, ErasureCodePlugin
from ceph_tpu.ops.xor_mm import xor_reduce

__erasure_code_version__ = EC_VERSION


class ErasureCodeXorExample(ErasureCode):
    def __init__(self) -> None:
        super().__init__()
        self.k = 2

    def parse(self, profile: Profile) -> None:
        super().parse(profile)
        self.k = self.to_int("k", profile, "2")
        self.sanity_check_k_m(self.k, 1)

    def get_chunk_count(self) -> int:
        return self.k + 1

    def get_data_chunk_count(self) -> int:
        return self.k

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        data = np.stack(
            [np.asarray(chunks[self.chunk_index(i)], dtype=np.uint8) for i in range(self.k)]
        )
        np.copyto(chunks[self.chunk_index(self.k)], np.asarray(xor_reduce(data)))

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        raw_of = self.chunk_index
        erasures = [i for i in range(self.k + 1) if raw_of(i) not in chunks]
        if not erasures:
            return
        assert len(erasures) == 1, "XOR codec tolerates exactly one erasure"
        sources = [i for i in range(self.k + 1) if raw_of(i) in chunks][: self.k]
        stack = np.stack(
            [np.asarray(decoded[raw_of(i)], dtype=np.uint8) for i in sources]
        )
        np.copyto(decoded[raw_of(erasures[0])], np.asarray(xor_reduce(stack)))


def _factory(profile):
    ec = ErasureCodeXorExample()
    ec.init(profile)
    return ec


def __erasure_code_init__(registry):
    registry.add("xor", ErasureCodePlugin("xor", _factory))
