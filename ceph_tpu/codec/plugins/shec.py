"""The `shec` plugin — shingled erasure codes.

Plugin shell analog of /root/reference/src/erasure-code/shec/
ErasureCodePluginShec.cc: technique single|multiple, default multiple (:45-52).
"""

from ceph_tpu.codec.registry import EC_VERSION, ErasureCodePlugin
from ceph_tpu.codec.shec import MULTIPLE, ErasureCodeShec

__erasure_code_version__ = EC_VERSION


def _factory(profile):
    technique = profile.get("technique") or MULTIPLE
    ec = ErasureCodeShec(technique=technique)
    ec.init(profile)
    return ec


def __erasure_code_init__(registry):
    registry.add("shec", ErasureCodePlugin("shec", _factory))
