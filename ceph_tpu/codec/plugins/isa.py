"""The `isa` plugin name — a drop-in alias for the flagship `tpu` codec.

The reference's profiles say `plugin=isa`
(/root/reference/src/erasure-code/isa/ErasureCodePluginIsa.cc); this
framework's equivalent codec is byte-identical to ISA-L's output
(tests/test_isal_golden.py proves it three ways), so existing pool
profiles port verbatim: `plugin=isa` loads the same class the `tpu`
name does.
"""

from ceph_tpu.codec.registry import EC_VERSION, ErasureCodePlugin
from ceph_tpu.codec.plugins.tpu import _factory

__erasure_code_version__ = EC_VERSION


def __erasure_code_init__(registry):
    registry.add("isa", ErasureCodePlugin("isa", _factory))
