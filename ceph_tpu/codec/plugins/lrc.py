"""The `lrc` plugin — layered locally-repairable codes.

Plugin shell analog of /root/reference/src/erasure-code/lrc/
ErasureCodePluginLrc.cc.
"""

from ceph_tpu.codec.lrc import ErasureCodeLrc
from ceph_tpu.codec.registry import EC_VERSION, ErasureCodePlugin

__erasure_code_version__ = EC_VERSION


def _factory(profile):
    ec = ErasureCodeLrc()
    ec.init(profile)
    return ec


def __erasure_code_init__(registry):
    registry.add("lrc", ErasureCodePlugin("lrc", _factory))
