"""The `jerasure` plugin — jerasure-compatible techniques on TPU kernels.

Plugin shell analog of /root/reference/src/erasure-code/jerasure/
ErasureCodePluginJerasure.cc: technique selection via the `technique` profile
key (default reed_sol_van).
"""

from ceph_tpu.codec.jerasure import (
    BITMATRIX_TECHNIQUES,
    ErasureCodeJerasure,
    ErasureCodeJerasureBitmatrix,
)
from ceph_tpu.codec.registry import EC_VERSION, ErasureCodePlugin

__erasure_code_version__ = EC_VERSION


def _factory(profile):
    technique = profile.get("technique") or "reed_sol_van"
    if technique in BITMATRIX_TECHNIQUES:
        ec = ErasureCodeJerasureBitmatrix(technique)
    else:
        ec = ErasureCodeJerasure(technique=technique)
    ec.init(profile)
    return ec


def __erasure_code_init__(registry):
    registry.add("jerasure", ErasureCodePlugin("jerasure", _factory))
