"""The `tpu` plugin — registers the flagship RS codec.

Reference plugin shell analog: /root/reference/src/erasure-code/isa/
ErasureCodePluginIsa.cc (technique selection :40-57) rebuilt for the TPU
codec.  Profile keys: k, m, technique in {reed_sol_van, cauchy}.
"""

from ceph_tpu.codec.registry import EC_VERSION, ErasureCodePlugin
from ceph_tpu.codec.rs import CAUCHY, VANDERMONDE, ErasureCodeTpuRs
from ceph_tpu.codec.tracing import instrument_codec

__erasure_code_version__ = EC_VERSION


def _factory(profile):
    technique = profile.get("technique") or VANDERMONDE
    ec = ErasureCodeTpuRs(technique=technique)
    ec.init(profile)
    # H2D / kernel_launch sub-spans on the device paths when an op trace
    # is active (codec/tracing.py); free when tracing is off
    return instrument_codec(ec, "tpu")


def __erasure_code_init__(registry):
    registry.add("tpu", ErasureCodePlugin("tpu", _factory))
