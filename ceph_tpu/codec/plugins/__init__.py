"""Loadable codec plugins (the `libec_<name>.so` analog set).

Each module here is one plugin: it declares `__erasure_code_version__` and an
`__erasure_code_init__(registry)` entry point, mirroring the reference's
dlopen contract (/root/reference/src/erasure-code/ErasureCodePlugin.cc:126-163).
"""
