"""Codec stack: interface, base scaffolding, plugin registry, codecs."""

from .base import ErasureCode
from .interface import EcError, ErasureCodeInterface, Profile
from .jerasure import ErasureCodeJerasure
from .matrix_codec import MatrixCodecMixin
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry, instance
from .rs import CAUCHY, VANDERMONDE, ErasureCodeTpuRs

__all__ = [
    "ErasureCode", "EcError", "ErasureCodeInterface", "Profile",
    "ErasureCodePlugin", "ErasureCodePluginRegistry", "instance",
    "CAUCHY", "VANDERMONDE", "ErasureCodeTpuRs", "ErasureCodeJerasure",
    "MatrixCodecMixin",
]
