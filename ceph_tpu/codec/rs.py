"""`tpu` Reed-Solomon codec — ISA-L-compatible techniques on the MXU.

The flagship codec: the TPU-native re-design of the reference `isa` plugin
(/root/reference/src/erasure-code/isa/ErasureCodeIsa.{h,cc}).  Same math
contract — techniques `reed_sol_van` (Vandermonde, default) and `cauchy`
(gf_gen_cauchy1), defaults k=7/m=3, Vandermonde MDS safety envelope
(ErasureCodeIsa.cc:331-361), XOR fast paths for m==1 and single erasures
(:125-131, :196-216), LRU-cached decode plans keyed by the same
"+survivor...-erasure..." signature strings (:227-303) — but the hot loop is a
bitsliced XOR-matmul on the TPU (ceph_tpu.ops.xor_mm) instead of AVX table
lookups, and the "decode table cache" caches device bit-matrices (operands),
not code: one compiled kernel per shape serves every erasure pattern.

Byte parity: chunks produced here are byte-identical to the reference `isa`
plugin's because the distribution matrices reproduce ISA-L's
gf_gen_rs_matrix/gf_gen_cauchy1_matrix over the same field (gf/matrix.py) and
decode inverts the identical survivor submatrix.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Mapping

import jax.numpy as jnp
import numpy as np

from ceph_tpu.gf import (
    expand_matrix,
    isa_cauchy_matrix,
    isa_decode_matrix,
    isa_rs_vandermonde_matrix,
)
from ceph_tpu.ops.xor_mm import xor_matmul, xor_reduce

from .base import EINVAL, EIO, ErasureCode
from .interface import EcError, Profile

VANDERMONDE = "reed_sol_van"
CAUCHY = "cauchy"

# Reference LRU capacity: "sufficient up to (12,4)"
# (isa/ErasureCodeIsaTableCache.h:48).
DECODE_LRU_CAPACITY = 2516


class _PlanCache:
    """Per-(technique, k, m) encode plans + LRU of decode plans.

    The analog of `ErasureCodeIsaTableCache` (isa/ErasureCodeIsaTableCache.cc):
    encode coefficients/tables computed once per geometry; decode tables LRU'd
    by erasure signature.  Here a "table" is the GF(2) bit-matrix living on
    device, ready to be fed to the shared xor_matmul kernel.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._encode: dict[tuple[str, int, int], tuple[np.ndarray, jnp.ndarray]] = {}
        self._decode: OrderedDict[tuple[str, int, int, str], tuple[jnp.ndarray, list[int]]] = OrderedDict()

    def encode_plan(self, technique: str, k: int, m: int) -> tuple[np.ndarray, jnp.ndarray]:
        """(distribution matrix (k+m, k) uint8, device parity bit-matrix)."""
        key = (technique, k, m)
        with self._lock:
            plan = self._encode.get(key)
        if plan is not None:
            return plan
        if technique == VANDERMONDE:
            coeff = isa_rs_vandermonde_matrix(k, m)
        else:
            coeff = isa_cauchy_matrix(k, m)
        if m == 1:
            # The reference encodes m==1 as a pure region XOR regardless of
            # technique (ErasureCodeIsa.cc:125-127), so the parity actually
            # stored is the all-ones row; the distribution matrix must say so
            # or decode-by-inversion would disagree with the stored parity.
            coeff[k:] = 1
        bitmat = jnp.asarray(expand_matrix(coeff[k:]), dtype=jnp.uint8)
        with self._lock:
            self._encode.setdefault(key, (coeff, bitmat))
            return self._encode[key]

    def decode_plan(
        self, technique: str, k: int, m: int, erasures: list[int]
    ) -> tuple[jnp.ndarray, list[int]]:
        """(device decode bit-matrix (8*nerrs, 8k), decode_index survivors).

        Signature format mirrors ErasureCodeIsa.cc:233-248 ("+r" per survivor
        row then "-e" per erasure); like the reference, the cache is consulted
        *before* the O(k^3) matrix inversion so steady-state rebuilds skip it.
        """
        # decode_index = first k surviving rows (ErasureCodeIsa.cc:233-242).
        erased = set(erasures)
        decode_index: list[int] = []
        r = 0
        for _ in range(k):
            while r in erased:
                r += 1
            if r >= k + m:
                raise EcError(EIO, f"not enough survivors for erasures {erasures}")
            decode_index.append(r)
            r += 1
        sig = "".join(f"+{r}" for r in decode_index) + "".join(f"-{e}" for e in erasures)
        key = (technique, k, m, sig)
        with self._lock:
            cached = self._decode.get(key)
            if cached is not None:
                self._decode.move_to_end(key)
                return cached
        coeff, _ = self.encode_plan(technique, k, m)
        plan = isa_decode_matrix(coeff, erasures, k)
        if plan is None:
            raise EcError(EIO, f"singular decode matrix for erasures {erasures}")
        c, decode_index = plan
        bitmat = jnp.asarray(expand_matrix(c), dtype=jnp.uint8)
        with self._lock:
            self._decode[key] = (bitmat, decode_index)
            self._decode.move_to_end(key)
            while len(self._decode) > DECODE_LRU_CAPACITY:
                self._decode.popitem(last=False)
        return bitmat, decode_index


_CACHE = _PlanCache()


class ErasureCodeTpuRs(ErasureCode):
    """RS(k, m) over GF(2^8), ISA-L-compatible, bitsliced on TPU."""

    DEFAULT_K = "7"  # ErasureCodeIsa.cc:46
    DEFAULT_M = "3"  # ErasureCodeIsa.cc:47

    def __init__(self, technique: str = VANDERMONDE) -> None:
        super().__init__()
        if technique not in (VANDERMONDE, CAUCHY):
            raise EcError(EINVAL, f"unknown technique {technique}")
        self.technique = technique
        self.k = 0
        self.m = 0

    # -- init ---------------------------------------------------------------

    def parse(self, profile: Profile) -> None:
        super().parse(profile)
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.sanity_check_k_m(self.k, self.m)
        if self.technique == VANDERMONDE:
            # MDS safety envelope, ErasureCodeIsa.cc:331-361.
            if self.k > 32:
                raise EcError(EINVAL, f"Vandermonde: k={self.k} must be <= 32")
            if self.m > 4:
                raise EcError(EINVAL, f"Vandermonde: m={self.m} must be <= 4 for MDS")
            if self.m == 4 and self.k > 21:
                raise EcError(EINVAL, f"Vandermonde: k={self.k} must be <= 21 with m=4")

    def init(self, profile: Profile) -> None:
        self.parse(profile)
        # Warm the encode plan (reference `prepare()`, ErasureCodeIsa.cc:369).
        _CACHE.encode_plan(self.technique, self.k, self.m)
        self._profile = dict(profile)

    # -- geometry -----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    # -- device paths -------------------------------------------------------

    def encode_array(self, data) -> jnp.ndarray:
        """Device-native encode: (..., k, L) uint8 -> (..., m, L) parity.

        Stays on device; the batched bulk path the benchmark and the sharded
        scrub/rebuild pipeline use (no host round-trip per stripe — this is
        what replaces the reference's per-stripe loop at ECUtil.cc:139).
        """
        _, bitmat = _CACHE.encode_plan(self.technique, self.k, self.m)
        if self.m == 1:
            return xor_reduce(jnp.asarray(data))[..., None, :]
        return xor_matmul(bitmat, jnp.asarray(data))

    def decode_array(self, erasures: list[int], survivors) -> jnp.ndarray:
        """Device-native decode: survivors (..., k, L) in decode_index order
        -> (..., nerrs, L) reconstructed chunks (erasures order)."""
        bitmat, _ = _CACHE.decode_plan(self.technique, self.k, self.m, erasures)
        return xor_matmul(bitmat, jnp.asarray(survivors))

    def decode_index(self, erasures: list[int]) -> list[int]:
        """First-k-survivors order used by decode_array (ErasureCodeIsa.cc:233)."""
        _, idx = _CACHE.decode_plan(self.technique, self.k, self.m, erasures)
        return idx

    # -- chunk-level interface ---------------------------------------------

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        # Logical position i lives at raw position chunk_index(i) when a
        # `mapping=` profile is set (ErasureCode.cc:260-279).
        data = np.stack(
            [np.asarray(chunks[self.chunk_index(i)], dtype=np.uint8) for i in range(k)]
        )
        parity = np.asarray(self.encode_array(data))
        for i in range(m):
            np.copyto(chunks[self.chunk_index(k + i)], parity[i])

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m = self.k, self.m
        # Work in logical chunk space; raw positions go through chunk_index.
        raw_of = self.chunk_index
        erasures = [i for i in range(k + m) if raw_of(i) not in chunks]
        if not erasures:
            return
        if len(erasures) > m:
            raise EcError(EIO, f"{len(erasures)} erasures > m={m}")

        # XOR fast paths (ErasureCodeIsa.cc:196-216): single parity, or a
        # Vandermonde single erasure within the first k+1 chunks — the missing
        # chunk is the XOR of the first k survivors because parity row 0 is
        # all-ones.
        use_xor = (m == 1) or (
            self.technique == VANDERMONDE
            and len(erasures) == 1
            and erasures[0] < k + 1
        )
        if use_xor:
            sources = [i for i in range(k + m) if raw_of(i) in chunks][:k]
            stack = np.stack(
                [np.asarray(decoded[raw_of(i)], dtype=np.uint8) for i in sources]
            )
            np.copyto(decoded[raw_of(erasures[0])], np.asarray(xor_reduce(stack)))
            return

        idx = self.decode_index(erasures)
        survivors = np.stack(
            [np.asarray(decoded[raw_of(i)], dtype=np.uint8) for i in idx]
        )
        rec = np.asarray(self.decode_array(erasures, survivors))
        for p, e in enumerate(erasures):
            np.copyto(decoded[raw_of(e)], rec[p])
