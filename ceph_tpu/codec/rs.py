"""`tpu` Reed-Solomon codec — ISA-L-compatible techniques on the MXU.

The flagship codec: the TPU-native re-design of the reference `isa` plugin
(/root/reference/src/erasure-code/isa/ErasureCodeIsa.{h,cc}).  Same math
contract — techniques `reed_sol_van` (Vandermonde, default) and `cauchy`
(gf_gen_cauchy1), defaults k=7/m=3, Vandermonde MDS safety envelope
(ErasureCodeIsa.cc:331-361), XOR fast paths for m==1 and single erasures
(:125-131, :196-216), LRU-cached decode plans keyed by the same
"+survivor...-erasure..." signature strings (:227-303) — but the hot loop is a
bitsliced XOR-matmul on the TPU (ceph_tpu.ops) instead of AVX table lookups;
the shared machinery lives in MatrixCodecMixin.

Byte parity: chunks produced here are byte-identical to the reference `isa`
plugin's because the distribution matrices reproduce ISA-L's
gf_gen_rs_matrix/gf_gen_cauchy1_matrix over the same field (gf/matrix.py),
m==1 encodes as the same pure XOR, and decode inverts the identical survivor
submatrix.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.gf import isa_cauchy_matrix, isa_rs_vandermonde_matrix

from .base import EINVAL, ErasureCode
from .interface import EcError, Profile
from .matrix_codec import MatrixCodecMixin

VANDERMONDE = "reed_sol_van"
CAUCHY = "cauchy"


class ErasureCodeTpuRs(MatrixCodecMixin, ErasureCode):
    """RS(k, m) over GF(2^8), ISA-L-compatible, bitsliced on TPU."""

    DEFAULT_K = "7"  # ErasureCodeIsa.cc:46
    DEFAULT_M = "3"  # ErasureCodeIsa.cc:47

    def __init__(self, technique: str = VANDERMONDE) -> None:
        super().__init__()
        if technique not in (VANDERMONDE, CAUCHY):
            raise EcError(EINVAL, f"unknown technique {technique}")
        self.technique = technique
        self.k = 0
        self.m = 0

    # -- init ---------------------------------------------------------------

    def parse(self, profile: Profile) -> None:
        super().parse(profile)
        self.invalidate_matrix()
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.sanity_check_k_m(self.k, self.m)
        if self.technique == VANDERMONDE:
            # MDS safety envelope, ErasureCodeIsa.cc:331-361.
            if self.k > 32:
                raise EcError(EINVAL, f"Vandermonde: k={self.k} must be <= 32")
            if self.m > 4:
                raise EcError(EINVAL, f"Vandermonde: m={self.m} must be <= 4 for MDS")
            if self.m == 4 and self.k > 21:
                raise EcError(EINVAL, f"Vandermonde: k={self.k} must be <= 21 with m=4")

    def init(self, profile: Profile) -> None:
        self.parse(profile)
        # Warm the encode plan (reference `prepare()`, ErasureCodeIsa.cc:369).
        self.distribution_matrix()
        self._profile = dict(profile)

    # -- geometry / matrix --------------------------------------------------

    def build_matrix(self) -> np.ndarray:
        if self.technique == VANDERMONDE:
            coeff = isa_rs_vandermonde_matrix(self.k, self.m)
        else:
            coeff = isa_cauchy_matrix(self.k, self.m)
        if self.m == 1:
            # The reference encodes m==1 as a pure region XOR regardless of
            # technique (ErasureCodeIsa.cc:125-127), so the parity actually
            # stored is the all-ones row; the distribution matrix must say so
            # or decode-by-inversion would disagree with the stored parity.
            coeff[self.k :] = 1
        return coeff

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k
